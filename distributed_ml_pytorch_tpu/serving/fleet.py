"""Fleet serving: N engine replicas behind one router, degrading instead of
dying (ISSUE 6 tentpole).

PR 1's serving plane was one engine behind one frontend: a single engine
death killed every in-flight stream, and overload had no answer beyond a
bounded queue. This module is the serving analogue of the elastic PS plane
(``coord/``): the frontend becomes a **router** over N
:class:`EngineMember` replicas, each a lease-holding fleet member, and the
three failure answers compose:

- **Routing.** New requests go to the healthy engine with the most free
  KV-slot capacity (occupancy = busy slots + queued, the same pressure
  signal the overload plane sheds on); a ``session`` hint in the V2 submit
  frame pins a session's requests to one engine while it stays healthy
  (prefix locality — the cheapest cache-aware policy that needs no cache
  introspection).
- **Health.** The router probes members the way ``HeartbeatSender`` probes
  shards: every sweep checks each member's serve-loop heartbeat, marks it
  down after ``probe_timeout`` of silence, logs the up↔down transition,
  and REVIVES it on the next beat — a live view, not a one-shot flag. A
  coordinator adds the second detection path: members renew leases
  (occupancy/TTFT ride the renewals), the ``FleetState`` broadcast carries
  the live engine ranks, and a rank that vanishes from it (lease expiry)
  is treated exactly like a failed probe.
- **Migration.** The router already holds every stream's full token
  history (PR 2's resume source). When an engine dies, each of its
  in-flight routes is resubmitted on a survivor as ``prompt +
  tokens-so-far`` with ``gen_offset = len(tokens)`` — the engine continues
  the request's own sampling-key schedule (``fold_in(key(seed), g)`` is
  position-in-stream, not position-on-engine), so the resumed stream is
  token-identical to one the dead engine would have produced, greedy or
  sampled. The dead attempt's engine key is retired under the route lock,
  so a straggler callback from a not-quite-dead engine cannot corrupt the
  stream. Clients see latency, never an error.

Overload (shed/brownout/deadline) is inherited from
:class:`~distributed_ml_pytorch_tpu.serving.frontend.ServingFrontend` with
``_pressure()`` aggregated over healthy members only — a half-dead fleet
sheds sooner, which is the point.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from distributed_ml_pytorch_tpu.serving.engine import (
    QueueFullError,
    ServingEngine,
)
from distributed_ml_pytorch_tpu.serving.frontend import (
    ORPHANED_ENGINE,
    ServingFrontend,
    _Route,
)
from distributed_ml_pytorch_tpu.utils import codecs
from distributed_ml_pytorch_tpu.utils.compress import (
    CODEC_DENSE,
    CompressionError,
    body_crc,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    Transport,
    _join16,
    _split16,
)


class EngineMember:
    """One engine replica of the serving fleet.

    Owns the engine's scheduling thread (``engine.step()`` loop) and,
    optionally, a :class:`~distributed_ml_pytorch_tpu.coord.member.
    CoordClient` lease: the member joins the coordination star as an
    ``engine`` and piggybacks its occupancy/TTFT on every renewal
    (``report(occupancy_pct, queue_depth, ttft_ms)`` — the coordinator's
    engine-scaling advisory reads exactly these numbers).

    ``crash()`` is the chaos hook: the serve loop halts at the next block
    boundary and lease renewals STOP without a leave — the coordinator must
    detect the death by lease expiry, and the router by its probe.
    """

    def __init__(self, engine_id: int, engine: ServingEngine, *,
                 coord=None, report_interval: float = 0.25,
                 idle_sleep: float = 0.002, throttle: float = 0.0):
        self.engine_id = int(engine_id)
        self.engine = engine
        self.coord = coord
        self.report_interval = float(report_interval)
        self.idle_sleep = float(idle_sleep)
        #: seconds slept after every WORKED scheduling round — a chaos/
        #: bench hook that emulates a slower accelerator (deterministic
        #: load shaping for overload and lease-expiry scenarios)
        self.throttle = float(throttle)
        self._stop = threading.Event()
        self._crashed = False
        #: serve-loop heartbeat the router's probe reads: monotonic stamp
        #: of the last completed scheduling round (GIL-atomic float store)
        self.last_beat = time.monotonic()
        self._thread: Optional[threading.Thread] = None

    @property
    def coord_rank(self) -> Optional[int]:
        return None if self.coord is None else self.coord.transport.rank

    @property
    def alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._crashed)

    def start(self) -> "EngineMember":
        if self.coord is not None:
            self.coord.join(timeout=5.0)
        self._thread = threading.Thread(
            target=self._serve, name=f"engine-{self.engine_id}", daemon=True)
        self._thread.start()
        return self

    def _serve(self) -> None:
        next_report = time.monotonic()
        while not self._stop.is_set():
            worked = self.engine.step()
            now = time.monotonic()
            self.last_beat = now
            if self.coord is not None and now >= next_report:
                busy, slots, queued = self.engine.pressure()
                occ_pct = int(100 * (busy + queued) / max(1, slots))
                self.coord.report(min(occ_pct, 10_000), queued,
                                  self.engine.recent_ttft_ms())
                next_report = now + self.report_interval
            if not worked:
                time.sleep(self.idle_sleep)
            elif self.throttle:
                time.sleep(self.throttle)

    def pressure(self) -> Tuple[int, int, int]:
        return self.engine.pressure()

    def stop(self) -> None:
        """Clean shutdown: stop serving and leave the coordination star."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.coord is not None:
            self.coord.close()

    def crash(self) -> None:
        """Silent scripted death (no leave, no further renewals): the
        lease-expiry detection path, like ``ElasticShardServer.crash``."""
        self._crashed = True
        self._stop.set()
        if self.coord is not None:
            self.coord.stop()


class FleetRouter(ServingFrontend):
    """The frontend as a router over N :class:`EngineMember` replicas.

    Same wire protocol and client as :class:`ServingFrontend` (submit/
    stream/reject/cancel/resume, ``MessageCode`` 5-8/11-12/23); the engine
    behind a request is a routing decision, re-made on engine death.

    ``fleet`` (optional) is the control-plane view: anything with
    ``engine_up()`` (hold-and-readmit, inherited) and optionally
    ``live_engine_ranks()`` — the per-engine generalization: a member
    whose coordinator rank disappears from the live set (lease expiry) is
    marked down and its streams migrate, even if the local probe has not
    fired yet. ``fleet=None`` fails open: the router serves on its own
    probe alone.

    ``serve_forever`` only sweeps (readmit, reap, probe, migrate) — the
    members' own threads drive decoding, so N replicas decode in parallel.
    """

    def __init__(self, transport: Transport, members: List[EngineMember], *,
                 probe_timeout: float = 2.0, session_affinity: bool = True,
                 **kw):
        if not members:
            raise ValueError("FleetRouter needs at least one EngineMember")
        self.members: Dict[int, EngineMember] = {}
        for m in members:
            if m.engine_id in self.members:
                raise ValueError(f"duplicate engine_id {m.engine_id}")
            if m.engine.on_tokens is not None:
                raise ValueError(
                    f"engine {m.engine_id} already has an on_tokens consumer")
            self.members[m.engine_id] = m
        self.probe_timeout = float(probe_timeout)
        self.session_affinity = bool(session_affinity)
        #: engine_id -> router's health verdict (True = routable); member
        #: down-markings self-heal on the next good probe, like
        #: ``HeartbeatSender.peer_down``
        self._member_up: Dict[int, bool] = {
            m.engine_id: True for m in members}
        #: coord ranks ever seen live by the fleet view — lease expiry is
        #: "was there, now is not", never "has not joined yet"
        self._seen_ranks: set = set()
        #: engines the gray plane (ISSUE 20) put on PROBATION: still
        #: routable (the member is alive — degrading beats killing) but
        #: scored with a capacity penalty so fresh streams bend away from
        #: the suspect while it recovers. Wired by the harness from
        #: ``GrayHealth(on_probation=...)`` / ``on_clear``.
        self._gray_penalized: set = set()
        self._affinity: Dict[Tuple[int, int], int] = {}
        self.migrations = 0          # streams moved across an engine death
        self.migration_failures = 0  # a healthy survivor refused the stream
        #: optional flight recorder (``utils/obs.SpanRecorder``, ISSUE 12):
        #: migration windows land on the serving timeline alongside the
        #: engines' queue/prefill/decode spans. Observational only.
        self.recorder = None
        self.parked = 0              # submits parked awaiting ANY engine
        #: per-death seconds (detect -> resumed); ring — a router that
        #: survives many deaths must not keep every sample forever
        self._mttr = collections.deque(maxlen=256)
        # --- codec plane (ISSUE 18): KvMigrate handoffs ------------------
        #: decoded handoffs parked by the loopback receiver, keyed by the
        #: dying stream's old route key: (token ids, kv lane or None)
        self._handoffs: Dict[int, Tuple[List[int], Optional[np.ndarray]]] = {}
        self.handoffs = 0            # KvMigrate frames shipped + decoded
        self.handoff_drops = 0       # malformed/failed handoff frames
        #: raw float32 the handoff bodies WOULD have cost dense, vs what
        #: the coded wire actually carried (head + body floats)
        self.handoff_dense_floats = 0
        self.handoff_wire_floats = 0
        for m in members:
            m.engine.on_tokens = self._on_tokens
        super().__init__(None, transport, **kw)

    # --------------------------------------------------------------- routing
    def note_gray(self, engine_id: int) -> None:
        """Gray-plane probation actuator (ISSUE 20): penalize this engine
        in routing scores without marking it down. Idempotent; undone by
        :meth:`clear_gray`."""
        self._gray_penalized.add(int(engine_id))

    def clear_gray(self, engine_id: int) -> None:
        self._gray_penalized.discard(int(engine_id))

    def _healthy_members(self) -> List[EngineMember]:
        return [m for eid, m in sorted(self.members.items())
                if self._member_up.get(eid, False)]

    def _pick_engine(self, route: _Route) -> Optional[EngineMember]:
        """Most free KV-slot capacity among healthy members, with session
        affinity when the pinned engine is healthy and has room."""
        healthy = self._healthy_members()
        if not healthy:
            return None
        scored = []
        for m in healthy:
            busy, slots, queued = m.pressure()
            free = slots - busy - queued
            if m.engine_id in self._gray_penalized:
                # gray probation (ISSUE 20): the suspect scores as if its
                # free capacity were halved (floored at a strict loss so a
                # tie always routes elsewhere) — route-around, not removal:
                # with every other engine full it still takes the stream
                free = min(free - 1, free // 2)
            scored.append((free, -m.engine_id, m))
        scored.sort(reverse=True)
        best = scored[0][2]
        if self.session_affinity and route.session:
            pin = self._affinity.get((route.rank, route.session))
            if pin is not None and self._member_up.get(pin, False):
                m = self.members[pin]
                busy, slots, queued = m.pressure()
                if busy + queued < slots:  # pinned engine has a free slot
                    return m
            if len(self._affinity) > 65536:
                self._affinity.clear()  # bounded: re-pinned on next use
            self._affinity[(route.rank, route.session)] = best.engine_id
        return best

    def _submit_route(self, key: int, route: _Route) -> bool:
        """Route a fresh OR resumed route. ``route.prompt``/``route.kwargs``
        always hold the ORIGINAL request; the effective submission derives
        from the tokens already streamed, so a stream can migrate any
        number of times and the arithmetic stays anchored to the origin."""
        member = self._pick_engine(route)
        if member is None:
            # no healthy engine RIGHT NOW (probe blip or fleet-wide
            # outage): PARK instead of reject — the sweep resubmits when a
            # member revives, so a transient blip costs latency, not the
            # stream. (Deadline/overload shedding still applies to parked
            # work, and a request the fleet never recovers for is reaped
            # by the client-silence sweep — parking is bounded.)
            route.engine_id = ORPHANED_ENGINE
            route.req = None
            self.parked += 1
            return True
        # stable without a lock: a fresh route has no engine yet, and a
        # migrating route was RETIRED first (_take_routes_where), so no
        # callback can be appending while this snapshot is taken
        had = list(route.tokens)
        kwargs = dict(route.kwargs)
        kwargs["max_new_tokens"] = int(kwargs["max_new_tokens"]) - len(had)
        if had:
            kwargs["gen_offset"] = len(had)
            prompt = np.concatenate(
                [np.asarray(route.prompt, np.int32),
                 np.asarray(had, np.int32)])
        else:
            prompt = route.prompt
        try:
            route.req = member.engine.submit(
                prompt, request_id=key, **kwargs)
        except (QueueFullError, ValueError):
            return False
        route.engine_id = member.engine_id
        return True

    def _cancel_route(self, key: int, route: _Route) -> None:
        member = self.members.get(route.engine_id)
        if member is not None:
            member.engine.cancel(key)

    # --------------------------------------------------------- overload plane
    def _pressure(self) -> float:
        busy = queued = slots = 0
        for m in self._healthy_members():
            b, s, q = m.pressure()
            busy, slots, queued = busy + b, slots + s, queued + q
        if slots == 0:
            return 1.0  # no healthy engine: maximally loaded
        # wire backpressure folds in exactly as on the base frontend: a
        # saturated client-facing transport counts against fleet capacity
        return max((busy + queued) / slots, self._wire_pressure())

    def _ttft_now_ms(self) -> float:
        samples = [m.engine.recent_ttft_ms() for m in self._healthy_members()]
        samples = [s for s in samples if s > 0]
        return float(np.mean(samples)) if samples else 0.0

    # ------------------------------------------------------- health + probes
    def _probe(self, now: float) -> None:
        """HeartbeatSender-style liveness over the members: serve-loop
        beats (local probe) + coordinator lease view (fleet probe)."""
        lease_live = None
        ranks = getattr(self.fleet, "live_engine_ranks", None)
        if ranks is not None:
            lease_live = ranks()
            if lease_live is not None:
                self._seen_ranks |= set(lease_live)
        for eid, m in sorted(self.members.items()):
            up = m.alive and (now - m.last_beat) <= self.probe_timeout
            if up and lease_live is not None and m.coord_rank is not None \
                    and m.coord_rank not in lease_live \
                    and m.coord_rank in self._seen_ranks:
                # the coordinator expired this member's lease: trust it —
                # the probe may still see beats (e.g. a member that can
                # compute but lost its control-plane life)
                up = False
            was = self._member_up.get(eid, True)
            if up != was:
                print(f"fleet: engine {eid} state "
                      f"{'down->up' if up else 'up->down'}", file=sys.stderr)
                self._member_up[eid] = up
            if not up:
                # EVERY sweep, not just the transition: a submit racing
                # the up->down edge can land a route on the dead engine
                # AFTER the transition's migration snapshot — rescuing on
                # each sweep makes that window self-healing (idempotent:
                # no matching routes, no work)
                self._migrate_from(eid, now)

    def _migrate_from(self, dead_id: int, now: float) -> None:
        """Move every in-flight stream off a dead engine: retire the old
        engine keys under the route lock (a straggler callback from a
        not-quite-dead engine must find nothing), then resubmit each route
        under a FRESH key — ``_submit_route`` re-prefills prompt +
        generated-so-far with the matching ``gen_offset``."""
        moving = self._take_routes_where(
            lambda r: r.engine_id == dead_id and not r.done)
        dead = self.members.get(dead_id)
        resumed = 0
        for old_key, route in moving:
            # ship the stream's state over the KvMigrate wire FIRST (the
            # engine's slot still holds the KV lane until the cancel), so
            # the resubmission below re-prefills from the DECODED tokens —
            # any number of migrations stays token-identical because the
            # tok16 packing is exact (ISSUE 18)
            self._ship_handoff(old_key, route, dead)
            if dead is not None:
                dead.engine.cancel(old_key)  # free state if it ever revives
            handoff = self._handoffs.pop(old_key, None)
            if handoff is not None:
                route.tokens = handoff[0]
            new_key = next(self._route_ids)
            if not route.service_lost_at:
                route.service_lost_at = now  # MTTR anchors at DETECTION
            # retired above: the token history is frozen, no lock needed
            n_had = len(route.tokens)
            if n_had >= int(route.kwargs["max_new_tokens"]):
                # everything was generated; only the done frame is owed
                route.done = True
                route.done_at = now
                self._install_route(new_key, route)
                self._send_frame(route, start=n_had, tokens=[], done=True)
                continue
            self._install_route(new_key, route)
            if not self._submit_route(new_key, route):
                # a healthy survivor refused it: explicit reject, never
                # silence (no healthy survivor at all PARKS instead — the
                # retry sweep resumes it and closes its MTTR sample then)
                self.migration_failures += 1
                self._drop_route(new_key)
                self._send_to(route.rank, MessageCode.ServeReject,
                              np.asarray([route.rid], np.float32))
            elif route.engine_id != ORPHANED_ENGINE:
                resumed += 1
                self._note_resumed(route)
        if self.recorder is not None and moving:
            self.recorder.event(
                "migrate", corr=0, dead_engine=dead_id,
                moved=len(moving), resumed=resumed,
                window_ms=round((time.monotonic() - now) * 1e3, 3))
        if resumed:
            print(f"fleet: migrated {resumed}/{len(moving)} stream(s) off "
                  f"engine {dead_id} in "
                  f"{(time.monotonic() - now) * 1e3:.1f} ms",
                  file=sys.stderr)

    # ------------------------------------------------------ KvMigrate wire
    def _ship_handoff(self, old_key: int, route: _Route,
                      dead: Optional[EngineMember]) -> None:
        """Encode one dying stream's resumable state as a ``KvMigrate``
        frame and put it on the loopback wire (ISSUE 18): the token
        history rides the exact tok16 packing (two u16 ids per float — it
        is what the resubmission re-prefills from, so the codec is
        load-bearing), and the dead engine's KV lane rides the registry's
        ``kv_quant`` rung (int8 per-block absmax) for pricing + bound
        verification. The codec head field names the KV rung."""
        tokens = np.asarray(route.tokens, np.float32)
        try:
            tok_body = (codecs.Tok16Codec().encode(tokens)
                        if tokens.size else np.zeros(0, np.float32))
        except (CompressionError, ValueError):
            self.handoff_drops += 1
            return
        kv = None
        if dead is not None:
            try:
                kv = dead.engine.kv_lane(old_key)
            except Exception:  # noqa: BLE001 — a dying engine may throw
                kv = None
        if kv is not None and kv.size and np.isfinite(kv).all():
            cid, kv_body = codecs.encode_body(MessageCode.KvMigrate, kv)
            n_kv = int(kv.size)
        else:
            cid, kv_body, n_kv = CODEC_DENSE, np.zeros(0, np.float32), 0
        body = np.concatenate([tok_body, kv_body])
        crc = body_crc(body)
        head = np.asarray(
            [float(cid), *_split16(old_key), *_split16(int(tokens.size)),
             *_split16(n_kv), *_split16(crc)], np.float32)
        self.handoff_dense_floats += int(tokens.size) + n_kv
        self.handoff_wire_floats += int(head.size) + int(body.size)
        self._send_handoff(MessageCode.KvMigrate,
                           np.concatenate([head, body]))

    def _send_handoff(self, code: MessageCode, frame: np.ndarray) -> None:
        """The handoff 'wire' is an in-process loopback — migrations stay
        inside the router — but the frame is real: everything the resumed
        stream needs crosses this boundary encoded, so the codec plane is
        on the hook for token identity, not just pricing."""
        self._on_handoff(0, code, frame)

    def _on_handoff(self, sender: int, code: MessageCode,
                    payload: np.ndarray) -> None:
        if code == MessageCode.KvMigrate and payload.size >= 10:
            if not np.isfinite(payload[:9]).all():
                self.handoff_drops += 1
                return
            cid = int(payload[0])
            key = _join16(payload[1], payload[2])
            n_tok = _join16(payload[3], payload[4])
            n_kv = _join16(payload[5], payload[6])
            crc = _join16(payload[7], payload[8])
            body = payload[9:]
            # integrity-gate on the stamp BEFORE paying for a decode
            if body_crc(body) != crc:
                self.handoff_drops += 1
                return
            tw = codecs.Tok16Codec().wire_floats(n_tok)
            try:
                toks = codecs.Tok16Codec().decode(body[:tw], n_tok, 0)
                kv = (codecs.decode_body(
                    MessageCode.KvMigrate, cid, body[tw:], n_kv)
                    if n_kv else None)
            except CompressionError:
                self.handoff_drops += 1
                return
            if kv is not None and not np.isfinite(kv).all():
                self.handoff_drops += 1
                return
            self._handoffs[key] = ([int(t) for t in toks], kv)
            self.handoffs += 1

    def _note_resumed(self, route: _Route) -> None:
        """Close one stream's outage window: count the migration and record
        detection -> back-in-service as its MTTR sample."""
        self.migrations += 1
        if route.service_lost_at:
            self._mttr.append(time.monotonic() - route.service_lost_at)
            route.service_lost_at = 0.0

    def mttr_s(self) -> Optional[float]:
        """Mean seconds from death detection to every stream resubmitted
        (None until a migration happened) — the bench's migration MTTR."""
        return float(np.mean(self._mttr)) if self._mttr else None

    def _retry_parked(self) -> None:
        """Resubmit routes parked while no engine was healthy. A park that
        stays parked (still no healthy member) waits for the next sweep; a
        HEALTHY engine refusing the work (queue full / unfittable) is a
        real reject."""
        if not self._healthy_members():
            return
        parked = self._routes_where(
            lambda r: r.engine_id == ORPHANED_ENGINE and not r.done)
        for key, route in parked:
            in_flight = bool(route.tokens)
            if not self._submit_route(key, route):
                self.migration_failures += 1
                self._drop_route(key)
                self._send_to(route.rank, MessageCode.ServeReject,
                              np.asarray([route.rid], np.float32))
            elif in_flight and route.engine_id != ORPHANED_ENGINE:
                # an in-flight stream is back in service: its MTTR sample
                # spans the WHOLE outage (death detection -> this resume)
                self._note_resumed(route)

    # ----------------------------------------------------- elastic membership
    def add_member(self, member: EngineMember) -> None:
        """Admit a NEW replica mid-run (the autoscaler's scale-up path,
        ISSUE 16): same wiring the constructor does — unique id, the
        router owns ``on_tokens``, healthy until a probe says otherwise.
        The member should already be started (its serve thread beats)."""
        if member.engine_id in self.members:
            raise ValueError(f"duplicate engine_id {member.engine_id}")
        if member.engine.on_tokens is not None:
            raise ValueError(
                f"engine {member.engine_id} already has an on_tokens consumer")
        member.engine.on_tokens = self._on_tokens
        self.members[member.engine_id] = member
        self._member_up[member.engine_id] = True
        print(f"fleet: engine {member.engine_id} admitted (scale-up)",
              file=sys.stderr)

    def remove_member(self, engine_id: int) -> Optional[EngineMember]:
        """Retire a replica mid-run (the scale-down / slot-revoke path):
        mark it down, migrate its in-flight streams to survivors FIRST,
        then stop it cleanly. Returns the removed member (None if
        unknown)."""
        member = self.members.get(engine_id)
        if member is None:
            return None
        now = time.monotonic()
        self._member_up[engine_id] = False
        self._migrate_from(engine_id, now)
        member.stop()
        del self.members[engine_id]
        self._member_up.pop(engine_id, None)
        print(f"fleet: engine {engine_id} retired (scale-down)",
              file=sys.stderr)
        return member

    # ------------------------------------------------------------------ loop
    def _sweep(self, now: float) -> None:
        self._probe(now)
        self._retry_parked()
        super()._sweep(now)

    def serve_forever(self, idle_sleep: float = 0.02,
                      sweep_every: float = 0.1) -> None:
        """Sweep loop only — decoding runs on the members' own threads."""
        while not self._stop.is_set():
            self._sweep(time.monotonic())
            time.sleep(min(idle_sleep, sweep_every))

    def stop(self) -> None:
        super().stop()
        for m in self.members.values():
            if m.alive:
                m.stop()

    def fleet_summary(self) -> dict:
        """Router-level stats for benches and the CLI exit report."""
        return {
            "engines": {
                eid: {"up": self._member_up.get(eid, False),
                      "alive": m.alive,
                      "pressure": m.pressure()}
                for eid, m in sorted(self.members.items())
            },
            "migrations": self.migrations,
            "migration_failures": self.migration_failures,
            "handoffs": self.handoffs,
            "handoff_drops": self.handoff_drops,
            "handoff_dense_floats": self.handoff_dense_floats,
            "handoff_wire_floats": self.handoff_wire_floats,
            "parked": self.parked,
            "mttr_s": self.mttr_s(),
            "shed": self.shed,
            "brownouts": self.brownouts,
            "reaped": self.reaped,
            "held_peak": self.held_peak,
        }


class FleetAutoscaler:
    """The coordinator's serving-side ACTUATOR (ISSUE 16): closes the
    ``check_engine_scaling`` advisory loop.

    Before this, scale advice was an event + callback the harness had to
    act on by hand. Wire :meth:`on_scale` as the coordinator's
    ``on_scale`` callback (or call it from a node agent's ``SlotGrant``
    handler) and the fleet actually changes shape: **up** spawns a fresh
    replica via ``member_factory`` (an ``EngineMember`` with its own
    engine + optional coord lease), starts it and admits it to the
    router; **down** retires the emptiest replica (streams migrate to
    survivors first). ``min_engines``/``max_engines`` bound the fleet.

    Scale-up MTTR — advice fired -> the new replica's serve loop beating
    — is sampled per spawn (``scale_up_mttr_s``; the bench JSON reports
    it), measured at the next :meth:`poll`.
    """

    def __init__(self, router: FleetRouter, member_factory, *,
                 min_engines: int = 1, max_engines: int = 8,
                 clock=time.monotonic):
        self.router = router
        self.member_factory = member_factory  # () -> EngineMember (unstarted)
        self.min_engines = int(min_engines)
        self.max_engines = int(max_engines)
        self._clock = clock
        self.scaled_up = 0
        self.scaled_down = 0
        self.refused = 0
        self.scale_up_mttr_s = collections.deque(maxlen=256)  # per-spawn ring
        self._pending_up: List[Tuple[float, int, float]] = []  # (t0, eid, beat0)
        self._spawning = 0  # in-flight scale-ups, counted toward max
        self._retiring = 0
        self._workers: List[threading.Thread] = []
        self._mu = threading.Lock()

    def on_scale(self, direction: str, detail: dict) -> None:
        """The coordinator's ``on_scale`` callback. It runs ON the
        coordinator's serve thread, and actually spawning a replica is
        slow (model build + warmup compile + coord join — the join waits
        on the very serve thread calling us). So this only ADMITS the
        decision under the capacity bounds; the blocking work runs on a
        short-lived worker thread. ``quiesce()`` joins stragglers."""
        with self._mu:
            n = len(self.router.members)
            if direction == "up":
                if n + self._spawning >= self.max_engines:
                    self.refused += 1
                    return
                self._spawning += 1
                worker = threading.Thread(
                    target=self._spawn, args=(self._clock(),),
                    name="fleet-scale-up", daemon=True)
            elif direction == "down":
                victim = self._emptiest()
                if n - self._retiring <= self.min_engines or victim is None:
                    self.refused += 1
                    return
                self._retiring += 1
                worker = threading.Thread(
                    target=self._retire, args=(victim.engine_id,),
                    name="fleet-scale-down", daemon=True)
            else:
                return
            self._workers.append(worker)
        worker.start()

    def _spawn(self, t0: float) -> None:
        try:
            member = self.member_factory()
            member.start()
            with self._mu:
                self.router.add_member(member)
                self.scaled_up += 1
                self._pending_up.append((t0, member.engine_id,
                                         member.last_beat))
        finally:
            with self._mu:
                self._spawning -= 1

    def _retire(self, engine_id: int) -> None:
        try:
            if self.router.remove_member(engine_id) is not None:
                with self._mu:
                    self.scaled_down += 1
        finally:
            with self._mu:
                self._retiring -= 1

    def quiesce(self, timeout: float = 10.0) -> bool:
        """Join outstanding spawn/retire workers (tests, end-of-bench)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._mu:
                self._workers = [w for w in self._workers if w.is_alive()]
                live = list(self._workers)
            if not live:
                return True
            if time.monotonic() >= deadline:
                return False
            live[0].join(timeout=0.05)

    def _emptiest(self) -> Optional[EngineMember]:
        """Least-loaded ALIVE member — retiring it migrates the fewest
        streams. Never the only member."""
        candidates = []
        for eid, m in sorted(self.router.members.items()):
            busy, _slots, queued = m.pressure()
            candidates.append((busy + queued, eid, m))
        if len(candidates) <= 1:
            return None
        candidates.sort()
        return candidates[0][2]

    def poll(self) -> None:
        """Close pending scale-up MTTR samples: a spawned member whose
        serve loop has beaten since the spawn is IN SERVICE."""
        with self._mu:
            still = []
            for t0, eid, beat0 in self._pending_up:
                m = self.router.members.get(eid)
                if m is None:
                    continue  # retired before it ever served
                if m.last_beat > beat0:
                    self.scale_up_mttr_s.append(m.last_beat - t0)
                else:
                    still.append((t0, eid, beat0))
            self._pending_up = still

    def summary(self) -> dict:
        self.poll()
        with self._mu:
            return {
                "scaled_up": self.scaled_up,
                "scaled_down": self.scaled_down,
                "refused": self.refused,
                "scale_up_mttr_s": list(self.scale_up_mttr_s),
                "n_engines": len(self.router.members),
            }
