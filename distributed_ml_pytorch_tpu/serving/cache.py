"""Slot-based KV cache pool — the serving data plane.

``models/generate.py`` decodes ONE prompt batch: every sequence starts
together, shares one cursor, and the whole cache dies with the call. A
serving engine needs the opposite: S long-lived cache *slots*, each holding
an independent sequence at its own length, all advanced by one compiled
decode step per token. This module provides that pool by **vmapping the
existing ring-buffered blocked decode module over the slot axis**:

- every slot is a batch-1 instance of the exact cache ``generate()`` uses
  (big per-layer K/V + per-layer ring + cursor/ring_base), stacked to a
  leading ``(slots, ...)`` axis;
- under ``jax.vmap`` the per-layer ``cursor``/``ring_base`` scalars become
  per-slot vectors — which is precisely the per-slot live-length tracking a
  heterogeneous batch needs, with zero changes to the attention module;
- decode steps write each slot's ring; once per block the rings merge into
  the big caches at PER-SLOT offsets (``merge_ring_caches`` vmapped with a
  traced ``live``), and ``ring_base`` advances — the same amortization
  that removed the full-cache copies from the decode scan (DESIGN.md §5b),
  minus the static live-prefix read (slot lengths differ, so reads cover
  the full allocation under the ``key_pos < ring_base`` mask).

Admission (prefill) runs per request on a FRESH zeroed lane cache and is
scattered into the pool at the target slot. That freshness is what makes
slot REUSE safe under ``kv_quant``: the int8 cache's single-prefill
contract (``init_cache``) requires the first multi-token apply to happen at
cursor 0, and a recycled slot always restarts from a zero lane rather than
the previous occupant's state. Prompts may be right-padded to a bucket
length to bound prefill compile count: padded positions write garbage K/V
past the prompt, but causal masking keeps real logits exact, the cursor is
rewound to the true length, and the ``key_pos < ring_base`` mask hides the
garbage until decode merges overwrite it.

Exactness contract (CPU): a request decoded through the pool picks
token-for-token what a standalone ``generate()`` picks for the same
``(params, prompt, rng)`` — the attention math is the same module, the
extra masked cache tail contributes exact zeros, and the sampler consumes
the same folded keys (tested in ``tests/test_serving.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ml_pytorch_tpu.models.generate import (
    DECODE_BLOCK,
    _decode_model,
    _fuse_qkv_params,
    init_cache,
    join_cache,
    merge_ring_caches,
    sample_tokens_dynamic,
    split_cache,
)


def find_cache_leaf(tree, name: str):
    """First leaf called ``name`` in a (possibly stacked) cache pytree.

    Every attention layer carries its own ``cursor``/``ring_base`` and the
    blocked decode advances them in lockstep, so any one leaf is the
    per-slot truth (deterministic traversal order for trace stability).
    """
    if isinstance(tree, dict):
        for key in sorted(tree):
            val = tree[key]
            if key == name and not isinstance(val, dict):
                return val
            if isinstance(val, dict):
                found = find_cache_leaf(val, name)
                if found is not None:
                    return found
    return None


def replace_cache_leaves(tree, mapping):
    """Rebuild a cache pytree with every leaf named in ``mapping`` replaced
    by the mapped value (cast to the leaf's dtype, broadcast to its shape).
    Used to rewind cursors after a padded prefill and to reset freed slots."""
    out = {}
    for name, val in tree.items():
        if isinstance(val, dict):
            out[name] = replace_cache_leaves(val, mapping)
        elif name in mapping:
            out[name] = jnp.broadcast_to(
                jnp.asarray(mapping[name], val.dtype), val.shape)
        else:
            out[name] = val
    return out


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _admit_jit(dec, params, pool, slot, prompt, real_len, seed,
               temperature, top_k, top_p, gen_offset):
    """Prefill ``prompt`` ([1, bucket] int32, right-padded past ``real_len``)
    on a fresh lane cache, sample the request's first token, and scatter the
    lane into ``pool`` at ``slot``. Returns ``(pool, first_token)``.

    ``gen_offset`` is the request's position in its own sampling-key
    schedule: token ``g`` is always drawn with ``fold_in(key(seed), g)``,
    so a RESUMED request (fleet migration re-prefills prompt + the tokens
    generated so far on a surviving engine) samples its next token with
    the same key the dead engine would have — stream migration stays
    token-identical even for sampled requests. A fresh admission passes 0.
    """
    lane = jax.tree.map(lambda x: jnp.zeros(x.shape[1:], x.dtype), pool)
    bucket = prompt.shape[1]
    positions = jnp.arange(bucket)[None, :]
    logits, mutated = dec.apply(
        {"params": params, "cache": lane}, prompt, positions, mutable=["cache"]
    )
    # rewind cursor/ring_base from the padded bucket end to the true prompt
    # length: the pad region's K/V is garbage the ``key_pos < ring_base``
    # mask hides until decode merges overwrite it
    lane = replace_cache_leaves(
        mutated["cache"], {"cursor": real_len, "ring_base": real_len})
    last = jax.lax.dynamic_index_in_dim(logits[0], real_len - 1, keepdims=False)
    keys = jax.vmap(
        lambda s: jax.random.fold_in(jax.random.key(s), gen_offset))(seed[None])
    tok0 = sample_tokens_dynamic(
        last[None], keys, temperature[None], top_k[None], top_p[None])[0]
    pool = jax.tree.map(
        lambda P, L: jax.lax.dynamic_update_slice(
            P, L[None], (slot,) + (0,) * L.ndim),
        pool, lane)
    return pool, tok0.astype(jnp.int32)


@partial(jax.jit, donate_argnums=(0,))
def _reset_slots_jit(pool, mask):
    """Zero the cursor/ring_base of every slot where ``mask`` is True: the
    freed slot's cache contents become invisible (``key_pos < ring_base``)
    and its live length reads 0 until the next admission overwrites it."""

    def walk(tree):
        out = {}
        for name, val in tree.items():
            if isinstance(val, dict):
                out[name] = walk(val)
            elif name in ("cursor", "ring_base"):
                out[name] = jnp.where(mask, 0, val)
            else:
                out[name] = val
        return out

    return walk(pool)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _decode_block_jit(dec, params, pool, tok, n_gen, seeds,
                      temps, top_ks, top_ps, active):
    """One decode block for the whole pool: ``decode_block`` single-token
    steps vmapped over slots, then per-slot ring merges.

    Mirrors ``_generate_blocked_jit``'s structure: the big caches cross the
    scan as constants (only the small ring state is carried), appends hit
    the per-layer rings, and the merge amortizes the big-cache write to
    once per block. Slots where ``active`` is False decode garbage from a
    zeroed state (their tokens are discarded by the scheduler) and are
    re-zeroed on exit so their cursors never creep toward the cache edge.
    Token ``g`` of a request is sampled with ``fold_in(key(seed), g)`` —
    the same per-step key schedule ``generate()`` uses, which is what makes
    engine output bit-match a standalone ``generate`` call on CPU.
    """
    T = dec.decode_block
    big, small = split_cache(pool)
    base = find_cache_leaf(small, "ring_base")  # (S,) per-slot block start

    def lane_apply(lane_cache, tok1, pos1):
        logits, mutated = dec.apply(
            {"params": params, "cache": lane_cache},
            tok1[None, None], pos1[None, None], mutable=["cache"],
        )
        return logits[0, -1], mutated["cache"]

    def step(carry, _):
        small, tok, g = carry
        cursor = find_cache_leaf(small, "cursor")  # (S,) = absolute position
        logits, cache = jax.vmap(lane_apply)(join_cache(big, small), tok, cursor)
        _, small = split_cache(cache)
        keys = jax.vmap(
            lambda s, i: jax.random.fold_in(jax.random.key(s), i))(seeds, g)
        nxt = sample_tokens_dynamic(
            logits, keys, temps, top_ks, top_ps).astype(jnp.int32)
        return (small, nxt, g + 1), nxt

    (small, _, _), toks = jax.lax.scan(
        step, (small, tok, jnp.asarray(n_gen, jnp.int32)), None, length=T)

    live = jnp.where(active, base, 0)
    big = jax.vmap(merge_ring_caches)(big, small, live)
    cursor = find_cache_leaf(small, "cursor")
    small = replace_cache_leaves(small, {
        "cursor": jnp.where(active, cursor, 0),
        "ring_base": jnp.where(active, base + T, 0),
    })
    return join_cache(big, small), jnp.moveaxis(toks, 0, 1)  # [S, T]


class SlotKVPool:
    """Fixed-capacity pool of ``slots`` independent KV cache slots, each of
    total length ``cache_size``, over the blocked decode module.

    The pool is the compiled data plane; the scheduler
    (``serving/engine.py``) owns which slot belongs to which request. All
    per-request sampling state (seed/temperature/top-k/top-p) is traced, so
    one compiled block program serves any mix of greedy and sampled
    requests.
    """

    def __init__(self, model, params, *, slots: int, cache_size: int,
                 decode_block: int = DECODE_BLOCK, kv_quant: bool = False):
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        if decode_block < 1:
            raise ValueError(
                "the slot pool rides the ring-buffered blocked cache — "
                f"decode_block must be >= 1, got {decode_block}")
        max_len = getattr(model, "max_len", None)
        if (max_len is not None and cache_size > max_len
                and getattr(model, "pos_encoding", "learned") != "rope"):
            raise ValueError(
                f"cache_size {cache_size} exceeds the model's learned "
                f"position table max_len={max_len} (RoPE models have no "
                "such bound)")
        self.slots = int(slots)
        self.cache_size = int(cache_size)
        self.decode_block = int(decode_block)
        self.kv_quant = bool(kv_quant)
        self.model = model
        self.dec = _decode_model(model, cache_size, decode_block=decode_block,
                                 kv_quant=kv_quant)
        self.params = (
            _fuse_qkv_params(params)
            if getattr(self.dec, "fused_qkv", False) else params)
        lane = jax.eval_shape(lambda: init_cache(
            model, 1, self.cache_size, decode_block=self.decode_block,
            kv_quant=self.kv_quant))
        self.cache = jax.tree.map(
            lambda s: jnp.zeros((self.slots,) + s.shape, s.dtype), lane)

    def admit(self, slot: int, prompt: np.ndarray, real_len: int, *,
              seed: int = 0, temperature: float = 0.0, top_k: int = 0,
              top_p: float = 1.0, gen_offset: int = 0) -> int:
        """Prefill a (bucketed) prompt into ``slot``; returns the request's
        first sampled token. One compiled program per bucket length.
        ``gen_offset`` resumes the sampling-key schedule at that generated-
        token index (stream migration; 0 for a fresh request)."""
        prompt = jnp.asarray(prompt, jnp.int32)[None, :]
        if prompt.shape[1] < 2:
            # s == 1 is the decode-step discriminator inside the blocked
            # module: a 1-token "prefill" would write the ring, orphaning
            # the prompt's K/V (the hazard uses_block_decode documents) —
            # callers must pad 1-token prompts (ServingEngine._bucket_len)
            raise ValueError(
                "admit() needs a prompt of length >= 2 — pad 1-token "
                "prompts (a length-1 apply is a decode step, not a prefill)")
        self.cache, tok0 = _admit_jit(
            self.dec, self.params, self.cache,
            jnp.asarray(slot, jnp.int32), prompt,
            jnp.asarray(real_len, jnp.int32),
            jnp.asarray(seed, jnp.uint32),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_k, jnp.int32),
            jnp.asarray(top_p, jnp.float32),
            jnp.asarray(gen_offset, jnp.int32))
        return int(tok0)

    def decode_block_step(self, tok, n_gen, seeds, temps, top_ks, top_ps,
                          active) -> np.ndarray:
        """Advance every slot by one ``decode_block``-token block; returns
        the sampled tokens ``[slots, decode_block]`` (host array — the
        fetch is the block's device sync point)."""
        self.cache, toks = _decode_block_jit(
            self.dec, self.params, self.cache,
            jnp.asarray(tok, jnp.int32), jnp.asarray(n_gen, jnp.int32),
            jnp.asarray(seeds, jnp.uint32), jnp.asarray(temps, jnp.float32),
            jnp.asarray(top_ks, jnp.int32), jnp.asarray(top_ps, jnp.float32),
            jnp.asarray(active, bool))
        return np.asarray(toks)

    def reset_slots(self, slot_indices) -> None:
        """Mark the given slots empty (cursor/ring_base back to 0)."""
        mask = np.zeros(self.slots, bool)
        mask[list(slot_indices)] = True
        self.cache = _reset_slots_jit(self.cache, jnp.asarray(mask))

    def slot_kv(self, slot: int) -> np.ndarray:
        """One slot's KV lane as a flat float32 vector (every floating
        cache leaf's row for ``slot``, concatenated in tree order) — the
        body a migration handoff ships on the ``KvMigrate`` wire (ISSUE
        18). With ``kv_quant`` the leaves are already the int8+scale
        recipe; the float32 view is the wire's common currency either way."""
        parts = [
            np.asarray(leaf[slot], np.float32).ravel()
            for leaf in jax.tree.leaves(self.cache)
            if jnp.issubdtype(leaf.dtype, jnp.floating)]
        if not parts:
            return np.zeros(0, np.float32)
        return np.concatenate(parts)

    def live_lengths(self) -> np.ndarray:
        """Per-slot live sequence length (prompt + generated), from the
        cache's own cursors — the observability face of slot occupancy."""
        cur = find_cache_leaf(self.cache, "cursor")
        return np.asarray(cur).reshape(self.slots)

    def blocks_needed(self, max_new_tokens: int) -> int:
        """Decode blocks a request of ``max_new_tokens`` occupies a slot for
        (its first token comes from prefill, the rest from whole blocks)."""
        return -(-(max_new_tokens - 1) // self.decode_block)

    def capacity_needed(self, prompt_len: int, bucket_len: int,
                        max_new_tokens: int) -> int:
        """Cache rows the request can touch: the padded prefill writes up to
        ``bucket_len``, and block-granular decode writes merges from the
        true prompt length through the rounded-up tail block."""
        decoded = self.blocks_needed(max_new_tokens) * self.decode_block
        return max(bucket_len, prompt_len + decoded)
