"""Continuous-batching scheduler — the serving control plane.

DownPour's shape, transposed to inference (PAPER.md; DESIGN.md §3): many
asynchronous clients feed one compiled data plane, and all coordination is
host-side Python around jitted programs. The engine owns a
:class:`~distributed_ml_pytorch_tpu.serving.cache.SlotKVPool` and runs the
classic continuous-batching loop:

1. **evict** — free the slots of finished/cancelled requests;
2. **admit** — pop queued requests into free slots (one compiled prefill
   per request, bucketed prompt lengths), emitting each request's first
   token (TTFT ends here);
3. **decode** — one compiled block advances EVERY active slot by
   ``decode_block`` tokens, regardless of how heterogeneous the batch is.

Admission only happens between decode blocks, so a request arriving while
others are mid-decode joins the very next block — no draining, no
restarts. Backpressure is explicit: ``submit`` raises
:class:`QueueFullError` once ``max_queue`` requests are waiting, which the
transport frontend maps to a reject frame (``serving/frontend.py``).

SLO observability rides ``utils/metrics.py``/``utils/tracing.py``: TTFT
and TPOT samples summarized by ``latency_summary`` percentiles, decode
block latency through a ``StepTimer``, queue depth and slot occupancy
sampled every scheduling round. Tokens stream at block granularity —
per-token latency is the block time divided by the block's tokens.

Determinism contract: with ``temperature=0`` (or any fixed sampling params
+ seed) a request's output is the same regardless of arrival order or what
shares the batch, and token-identical on CPU to ``generate(model, params,
prompt[None], max_new_tokens, rng=jax.random.key(seed))`` — slots are
independent vmap lanes over the same attention module (tested in
``tests/test_serving.py``).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Callable, Deque, List, Optional, Tuple

import numpy as np

from distributed_ml_pytorch_tpu.models.generate import DECODE_BLOCK
from distributed_ml_pytorch_tpu.serving.cache import SlotKVPool
from distributed_ml_pytorch_tpu.utils.metrics import latency_summary
from distributed_ml_pytorch_tpu.utils.tracing import StepTimer


class QueueFullError(RuntimeError):
    """Raised by :meth:`ServingEngine.submit` when the wait queue is at
    ``max_queue`` — the engine's backpressure signal."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs, same semantics as ``generate()``:
    ``temperature <= 0`` is greedy (k/p/seed ignored); otherwise categorical
    at the given temperature with optional top-k / nucleus truncation, keys
    folded per token from ``jax.random.key(seed)``."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0


@dataclasses.dataclass
class Request:
    """One inference request and its whole lifecycle (the engine mutates it
    in place; ``wait()`` blocks until completion)."""

    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    sampling: SamplingParams
    eos_token: Optional[int] = None
    #: flight-recorder correlation id (ISSUE 12): minted at submit, so the
    #: queue -> prefill -> decode -> done journey is one timeline — and a
    #: MIGRATED request's resubmission keeps the original id across engines
    corr: int = 0
    #: sampling-key schedule offset: this request's token ``g`` is drawn
    #: with ``fold_in(key(seed), gen_offset + g)`` — nonzero only for a
    #: RESUMED request (fleet migration re-prefills prompt + generated-so-
    #: far on a surviving engine and continues the schedule mid-stream)
    gen_offset: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    cancelled: bool = False
    slot: Optional[int] = None
    #: number of OTHER requests mid-flight when this one was admitted —
    #: the continuous-batching witness (tests assert it's > 0 for a
    #: late-arriving request)
    active_at_admit: int = 0
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0
    _event: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    @property
    def ttft(self) -> Optional[float]:
        return (self.t_first_token - self.t_submit) if self.t_first_token else None

    @property
    def tpot(self) -> Optional[float]:
        """Mean seconds per token after the first (block-granular stream)."""
        if not self.t_done or len(self.tokens) < 2:
            return None
        return (self.t_done - self.t_first_token) / (len(self.tokens) - 1)


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple if multiple > 1 else n


class ServingEngine:
    """Slot-based continuous-batching engine over one ``TransformerLM``.

    ``on_tokens(request, new_tokens, done)`` is invoked from the scheduling
    thread every time a request's stream advances (admission's first token,
    then each decode block's truncated share) — the transport frontend
    hangs its send path on it.
    """

    def __init__(self, model, params, *, slots: int = 4,
                 cache_size: int = 256, decode_block: int = DECODE_BLOCK,
                 kv_quant: bool = False, max_queue: int = 64,
                 prefill_bucket: int = 16,
                 on_tokens: Optional[Callable] = None,
                 recorder=None):
        self.pool = SlotKVPool(
            model, params, slots=slots, cache_size=cache_size,
            decode_block=decode_block, kv_quant=kv_quant)
        self.max_queue = int(max_queue)
        self.prefill_bucket = max(1, int(prefill_bucket))
        self.on_tokens = on_tokens
        #: optional flight recorder (``utils/obs.SpanRecorder``, ISSUE 12):
        #: queue/prefill/decode spans per request correlation id — the
        #: serving plane's side of the fleet timeline. Observational only.
        self.recorder = recorder
        self._lock = threading.Lock()
        self._queue: Deque[Request] = collections.deque()
        self._ids = itertools.count()
        S = self.pool.slots
        self._slot_req: List[Optional[Request]] = [None] * S
        # per-slot compiled-state mirror (device sees these every dispatch)
        self._tok = np.zeros(S, np.int32)
        self._n_gen = np.zeros(S, np.int32)
        self._seeds = np.zeros(S, np.uint32)
        self._temps = np.zeros(S, np.float32)
        self._top_ks = np.zeros(S, np.int32)
        self._top_ps = np.ones(S, np.float32)
        # SLO samples (seconds; summaries convert to ms). Health samples
        # are bounded deques so a long-lived server cannot grow them
        # without limit; latency samples are per-request (bounded by
        # traffic actually served) and kept whole for exact percentiles.
        self._ttft: List[float] = []
        self._tpot: List[float] = []
        self._queue_depths: collections.deque = collections.deque(maxlen=65536)
        self._occupancy: collections.deque = collections.deque(maxlen=65536)
        self._block_timer = StepTimer(skip=1)
        self._completed = 0
        self._cancelled = 0
        self._rejected = 0

    # ------------------------------------------------------------- submit
    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               seed: int = 0, eos_token: Optional[int] = None,
               request_id: Optional[int] = None,
               gen_offset: int = 0) -> Request:
        """Queue one request; returns its live :class:`Request` handle.

        ``gen_offset`` resumes the sampling-key schedule at that generated-
        token index — the stream-migration path passes the number of tokens
        already emitted by a dead engine, with ``prompt`` extended by those
        tokens and ``max_new_tokens`` reduced by the same count, and the
        resumed stream continues token-identically.

        Raises :class:`QueueFullError` at ``max_queue`` waiting requests
        (admission control) and ``ValueError`` for requests the pool can
        never hold (those would wedge the queue forever).
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket = self._bucket_len(int(prompt.size))
        need = self.pool.capacity_needed(int(prompt.size), bucket,
                                         int(max_new_tokens))
        if need > self.pool.cache_size:
            raise ValueError(
                f"request needs {need} cache rows (prompt {prompt.size} "
                f"-> bucket {bucket}, {max_new_tokens} new tokens in "
                f"{self.pool.decode_block}-token blocks) but slots hold "
                f"{self.pool.cache_size}")
        from distributed_ml_pytorch_tpu.utils import obs

        req = Request(
            request_id=(request_id if request_id is not None
                        else next(self._ids)),
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            sampling=SamplingParams(temperature, top_k, top_p, seed),
            eos_token=eos_token, gen_offset=max(0, int(gen_offset)),
            # adopt the submitting thread's active correlation id (a
            # frontend relaying an enveloped SubmitRequest, or a migration
            # resubmit) — mint a fresh one only at a true origin
            corr=obs.current_corr() or obs.next_corr(),
            t_submit=time.perf_counter())
        with self._lock:
            # cancelled entries (e.g. overload-shed work awaiting its
            # admission-pass drop) no longer hold queue room — a displacing
            # submit must be admittable the moment its victim is shed
            if sum(1 for r in self._queue
                   if not r.cancelled) >= self.max_queue:
                self._rejected += 1
                if self.recorder is not None:
                    self.recorder.event("queue-reject", corr=req.corr,
                                        id=req.request_id)
                raise QueueFullError(
                    f"queue at max_queue={self.max_queue}; retry later")
            self._queue.append(req)
        if self.recorder is not None:
            self.recorder.event("submit", corr=req.corr, id=req.request_id,
                                prompt_len=int(prompt.size))
        return req

    def _bucket_len(self, prompt_len: int) -> int:
        """Padded prefill length for a prompt. Never 1: inside the blocked
        decode module ``s == 1`` is the branch discriminator for a DECODE
        step (the same hazard ``uses_block_decode`` guards in generate()),
        so a 1-token prompt pads to 2 even at prefill_bucket=1."""
        return max(2, _round_up(prompt_len, self.prefill_bucket))

    def cancel(self, request_id: int) -> bool:
        """Flag a request cancelled. Queued requests are dropped at the next
        admission pass; an active request's slot is evicted at the next
        block boundary. Returns whether the id was found live."""
        with self._lock:
            for req in self._queue:
                if req.request_id == request_id and not req.done:
                    req.cancelled = True
                    return True
        for req in self._slot_req:
            if req is not None and req.request_id == request_id:
                req.cancelled = True
                return True
        return False

    def kv_lane(self, request_id: int) -> Optional[np.ndarray]:
        """The flat KV-cache lane behind a live request's slot, or None
        when the request holds no slot (queued / finished). The migration
        handoff (``serving/fleet.py``, ISSUE 18) ships this on the
        ``KvMigrate`` wire under the pool's ``kv_quant`` recipe."""
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.request_id == request_id:
                return self.pool.slot_kv(slot)
        return None

    # ------------------------------------------------------------ schedule
    def step(self) -> bool:
        """One scheduling round: evict → admit → decode one block. Returns
        False when there was nothing to do (caller may idle-sleep)."""
        worked = self._evict()
        worked = self._admit() or worked
        active = [r is not None for r in self._slot_req]
        if worked or any(active):
            # sample scheduler health only on rounds that do work — a
            # serve_forever loop idles at ~500 rounds/s and would both
            # grow these lists without bound and dilute the occupancy
            # stats with idle zeros (the deques bound the busy case too)
            with self._lock:
                self._queue_depths.append(len(self._queue))
            self._occupancy.append(sum(active) / len(active))
        if any(active):
            self._decode(np.asarray(active, bool))
            worked = True
        return worked

    def run_until_idle(self, max_rounds: int = 10_000) -> None:
        """Drive scheduling rounds until queue and slots are empty (the
        synchronous harness used by tests and the benchmark driver)."""
        for _ in range(max_rounds):
            if not self.step():
                with self._lock:
                    queued = len(self._queue)
                if queued == 0 and not any(
                        r is not None for r in self._slot_req):
                    return
        raise RuntimeError(f"not idle after {max_rounds} scheduling rounds")

    def _evict(self) -> bool:
        freed = []
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            if req.done or req.cancelled:
                self._finish(req)
                self._slot_req[slot] = None
                freed.append(slot)
        if freed:
            self.pool.reset_slots(freed)
        return bool(freed)

    def _admit(self) -> bool:
        admitted = False
        free = [s for s, r in enumerate(self._slot_req) if r is None]
        while free:
            with self._lock:
                while self._queue and self._queue[0].cancelled:
                    self._finish(self._queue.popleft())
                if not self._queue:
                    break
                req = self._queue.popleft()
            slot = free.pop(0)
            p = int(req.prompt.size)
            bucket = self._bucket_len(p)
            padded = np.zeros(bucket, np.int32)
            padded[:p] = req.prompt
            sp = req.sampling
            # claim the slot BEFORE the admission dispatch: between the
            # queue pop above and this point the request is in neither the
            # queue count nor the slot count, and a fleet router sampling
            # pressure() cross-thread would see a falsely idle engine and
            # stack new work onto it (prefill dispatch is a ~ms window)
            req.active_at_admit = sum(
                r is not None for r in self._slot_req)
            req.slot = slot  # with it, "slot is None" == waiting, exactly
            self._slot_req[slot] = req
            rec = self.recorder
            t0 = time.monotonic_ns() if rec is not None else 0
            tok0 = self.pool.admit(
                slot, padded, p, seed=sp.seed, temperature=sp.temperature,
                top_k=sp.top_k, top_p=sp.top_p, gen_offset=req.gen_offset)
            req.t_admit = time.perf_counter()
            if rec is not None:
                # queue wait ended here; the prefill span carries the
                # request's correlation id through slot admission
                rec.record("prefill", "prefill", t0, time.monotonic_ns(),
                           corr=req.corr,
                           meta={"id": req.request_id, "slot": slot,
                                 "bucket": bucket})
            self._tok[slot] = tok0
            # the per-slot sampling clock continues the request's OWN
            # schedule: a resumed request's next draw is fold_in(key,
            # gen_offset + 1), exactly what its first life would have drawn
            self._n_gen[slot] = req.gen_offset + 1
            self._seeds[slot] = np.uint32(sp.seed)
            self._temps[slot] = sp.temperature
            self._top_ks[slot] = sp.top_k
            self._top_ps[slot] = sp.top_p
            self._emit(req, [tok0])
            admitted = True
            if req.done:  # max_new_tokens == 1, or the first token was eos
                self._finish(req)
                self._slot_req[slot] = None
                self.pool.reset_slots([slot])  # same sweep _evict gives others
                free.insert(0, slot)
        return admitted

    def _decode(self, active: np.ndarray) -> None:
        rec = self.recorder
        t0 = time.monotonic_ns() if rec is not None else 0
        self._block_timer.start()
        toks = self.pool.decode_block_step(
            self._tok, self._n_gen, self._seeds, self._temps,
            self._top_ks, self._top_ps, active)  # [S, T] host array (syncs)
        self._block_timer.tick()
        if rec is not None:
            rec.record("decode-block", "decode", t0, time.monotonic_ns(),
                       corr=0, meta={"active": int(active.sum())})
        T = toks.shape[1]
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._tok[slot] = toks[slot, -1]
            self._n_gen[slot] += T  # sampling-step clock, even past finish
            remaining = req.max_new_tokens - len(req.tokens)
            self._emit(req, toks[slot, :remaining].tolist())

    def _emit(self, req: Request, new_tokens: List[int]) -> None:
        """Append ``new_tokens`` to a request's stream (truncating at eos),
        stamp TTFT/finish times, and fan out to ``on_tokens``."""
        if req.eos_token is not None and new_tokens:
            for i, t in enumerate(new_tokens):
                if t == req.eos_token:
                    new_tokens = new_tokens[: i + 1]
                    req.done = True
                    break
        req.tokens.extend(int(t) for t in new_tokens)
        now = time.perf_counter()
        if not req.t_first_token and req.tokens:
            req.t_first_token = now
            self._ttft.append(req.ttft)
        if len(req.tokens) >= req.max_new_tokens:
            req.done = True
        if req.done:
            req.t_done = now
            self._record_done(req)
        if self.on_tokens is not None and new_tokens:
            self.on_tokens(req, [int(t) for t in new_tokens], req.done)

    def _record_done(self, req: Request) -> None:
        """SLO accounting at the moment a stream closes (NOT at eviction —
        the last request's samples must exist before its slot is swept).
        Cancellations count separately: "completed" means served in full."""
        if req.cancelled:
            self._cancelled += 1
            return
        self._completed += 1
        if req.tpot is not None:
            self._tpot.append(req.tpot)

    def _finish(self, req: Request) -> None:
        if req.cancelled and not req.done:
            req.done = True
            req.t_done = time.perf_counter()
            self._record_done(req)
            if self.on_tokens is not None:
                self.on_tokens(req, [], True)
        req._event.set()

    # ------------------------------------------------------------- metrics
    def pressure(self) -> Tuple[int, int, int]:
        """Cheap load sample for routers and admission control:
        ``(busy_slots, total_slots, queued)``. Advisory — one scheduling
        round stale at worst, which is within the overload plane's
        contract (shed decisions are rate signals, not invariants)."""
        with self._lock:
            queued = len(self._queue)
        busy = sum(r is not None for r in self._slot_req)
        return busy, self.pool.slots, queued

    def recent_ttft_ms(self, k: int = 16) -> float:
        """Mean of the last ``k`` TTFT samples in milliseconds (0.0 when
        nothing completed yet) — the SLO-breach signal the overload plane
        and the coordinator's engine-scaling advisory consume."""
        tail = self._ttft[-k:]
        if not tail:
            return 0.0
        return float(np.mean(tail)) * 1e3

    def reset_metrics(self) -> None:
        """Drop accumulated SLO samples (e.g. after a compile warmup) while
        keeping the block timer's warmup state — mirrors
        ``StepTimer.reset_stats``."""
        self._ttft.clear()
        self._tpot.clear()
        self._queue_depths.clear()
        self._occupancy.clear()
        self._block_timer.reset_stats()
        self._completed = 0
        self._cancelled = 0
        self._rejected = 0

    def slo_summary(self) -> dict:
        """Percentile SLO report (milliseconds) over everything completed so
        far, plus scheduler health (queue depth, occupancy, rejects)."""
        to_ms = lambda xs: [x * 1e3 for x in xs]
        depths = self._queue_depths or [0]
        return {
            "completed": self._completed,
            "cancelled": self._cancelled,
            "rejected": self._rejected,
            "ttft_ms": latency_summary(to_ms(self._ttft)),
            "tpot_ms": latency_summary(to_ms(self._tpot)),
            "decode_block": self._block_timer.summary(),
            "queue_depth": {"mean": float(np.mean(depths)),
                            "max": int(np.max(depths))},
            "slot_occupancy": float(np.mean(self._occupancy or [0.0])),
        }
