"""``serve`` — the serving-side CLI (counterpart of ``training/cli.py``).

Starts a continuous-batching engine for a ``TransformerLM`` and exposes it
over a messaging transport::

    # TCP server: waits for --clients client processes on --port
    python -m distributed_ml_pytorch_tpu.serving.cli --port 29600 --clients 1

    # restore trained params (examples/train_lm.py checkpoint)
    python -m distributed_ml_pytorch_tpu.serving.cli --ckpt-dir /tmp/lm ...

    # self-contained demo: an in-process client drives N mixed
    # greedy/sampled requests through the full frontend path, prints the
    # SLO summary, exits (what the CLI tests run)
    python -m distributed_ml_pytorch_tpu.serving.cli --demo 6

Engine knobs: ``--slots`` (concurrent sequences), ``--cache-size`` (rows
per slot: prompt + padded decode blocks), ``--decode-block`` (tokens per
compiled block — admission latency vs merge amortization), ``--kv-quant``
(int8 slot caches: half the pool HBM, see the single-prefill note in
``serving/cache.py``), ``--max-queue`` (backpressure threshold),
``--prefill-bucket`` (prompt-length bucketing: compile count vs pad waste).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Continuous-batching TransformerLM serving engine")
    # model size (mirrors examples/generate_text.py)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=256)
    p.add_argument("--max-len", type=int, default=0,
                   help="learned-position table size (0 = derived from "
                        "--cache-size; checkpoint restores must match the "
                        "training run's table)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--pos-encoding", default="learned",
                   choices=["learned", "rope"])
    p.add_argument("--ckpt-dir", type=str, default="",
                   help="restore params from an examples/train_lm.py orbax "
                        "checkpoint (default: fresh random init)")
    # engine
    p.add_argument("--slots", type=int, default=4,
                   help="concurrent sequences sharing the compiled decode step")
    p.add_argument("--cache-size", type=int, default=256,
                   help="KV rows per slot (bounds prompt + generation)")
    p.add_argument("--decode-block", type=int, default=16,
                   help="tokens per compiled decode block (admission happens "
                        "between blocks)")
    p.add_argument("--kv-quant", action="store_true",
                   help="int8 slot caches with per-key scales — half the "
                        "pool footprint")
    p.add_argument("--max-queue", type=int, default=64,
                   help="queued-request cap; beyond it submissions are "
                        "rejected (backpressure)")
    p.add_argument("--prefill-bucket", type=int, default=16,
                   help="round prompt lengths up to this multiple for "
                        "prefill compilation (1 = exact lengths)")
    # transport
    p.add_argument("--port", type=str, default="29600",
                   help="TCP port the engine's rank-0 hub binds")
    p.add_argument("--master", type=str, default="localhost")
    p.add_argument("--clients", type=int, default=1,
                   help="client processes the TCP rendezvous waits for "
                        "(clients may later drop and rejoin)")
    p.add_argument("--reliable", action="store_true",
                   help="wrap the hub transport in the reliability layer "
                        "(seq + CRC + ack/retry + dedup, utils/messaging."
                        "ReliableTransport); clients must wrap too")
    p.add_argument("--client-deadline", type=float, default=30.0,
                   metavar="SEC",
                   help="cancel + free a request whose client has been "
                        "silent this long (disconnect/abandon cleanup); "
                        "streaming clients refresh liveness via StreamAck")
    p.add_argument("--coord", type=str, default="", metavar="HOST:PORT",
                   help="register this engine with an elastic control plane "
                        "(coord/cli.py): lease-based membership, and the "
                        "frontend holds submits while the coordinator "
                        "reports the engine fleet down, re-admitting them "
                        "on recovery")
    p.add_argument("--coord-rank", type=int, default=0, metavar="R",
                   help="this engine's rank in the coordination star "
                        "(0 = derive from --port; two engines MUST use "
                        "distinct ranks or the later one replaces the "
                        "earlier in the coordinator's membership)")
    p.add_argument("--demo", type=int, default=0, metavar="N",
                   help="serve N synthetic requests from an in-process "
                        "client, print the SLO summary, exit")
    # fleet serving (ISSUE 6): N engine replicas behind one router
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="run N engine replicas behind a FleetRouter "
                        "(occupancy + session-affinity routing, stream "
                        "migration across engine death, overload "
                        "shed/brownout); 0 = single-engine frontend")
    p.add_argument("--slo-ttft-ms", type=float, default=0.0,
                   help="TTFT SLO in ms (0 = off): recent TTFT above it "
                        "reads as overload and sheds lowest-priority work")
    p.add_argument("--shed-occupancy", type=float, default=0.0,
                   help="fleet pressure (busy+queued per slot) at which "
                        "new work admits only by displacing lower-priority "
                        "waiting work (0 = off); shed = explicit reject")
    p.add_argument("--brownout-occupancy", type=float, default=0.0,
                   help="pressure at which incoming max_new_tokens is "
                        "capped at --brownout-max-new (degrade before "
                        "shedding; 0 = off)")
    p.add_argument("--brownout-max-new", type=int, default=0)
    p.add_argument("--metrics-dump", type=str, default="", metavar="PATH",
                   help="write the metrics-registry snapshot JSON "
                        "(utils/metrics.get_registry, ISSUE 12) at exit — "
                        "engine SLO summary, transport counters; '-' "
                        "prints to stdout")
    p.add_argument("--seed", type=int, default=0)
    return p


def _build_model(args, parser):
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import TransformerLM

    if args.d_model % args.n_heads:
        parser.error(f"--d-model {args.d_model} must divide by --n-heads "
                     f"{args.n_heads}")
    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
        max_len=args.max_len or max(args.cache_size, 256),
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        pos_encoding=args.pos_encoding,
    )
    if not args.ckpt_dir:
        params = lm.init(
            jax.random.key(args.seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    else:
        import optax

        from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
            create_lm_train_state,
        )
        from distributed_ml_pytorch_tpu.utils.checkpoint import Checkpointer

        with Checkpointer(args.ckpt_dir) as ckpt:
            step = ckpt.latest_step()
            if step is None:
                raise SystemExit(
                    f"no checkpoint under {args.ckpt_dir} — train one with "
                    "examples/train_lm.py --ckpt-dir first")
            template = jax.eval_shape(lambda: create_lm_train_state(
                lm, jax.random.key(args.seed), optax.sgd(0.1)))
            state, step = ckpt.restore(template)
            params = state.params
            print(f"restored params from step {step} of {args.ckpt_dir}")
    return lm, params


def _make_engine(lm, params, args):
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine

    return ServingEngine(
        lm, params, slots=args.slots, cache_size=args.cache_size,
        decode_block=args.decode_block, kv_quant=args.kv_quant,
        max_queue=args.max_queue, prefill_bucket=args.prefill_bucket)


def _build_engine(args, parser):
    lm, params = _build_model(args, parser)
    engine = _make_engine(lm, params, args)
    # observability (ISSUE 12): the engine's SLO summary rides the
    # process registry, so --metrics-dump sees serving health for free
    from distributed_ml_pytorch_tpu.utils.metrics import get_registry

    get_registry().attach("engine", engine.slo_summary)
    return engine


def _build_fleet(args, parser, coord_factory=None):
    """N engine replicas as started EngineMembers (one model init, shared
    read-only params). ``coord_factory(engine_id)`` may supply a
    CoordClient per member (lease-holding fleet membership). Engines are
    WARMED (prefill buckets + decode block compiled) before their serve
    threads start, so the router's liveness probe never mistakes a
    cold-start XLA compile for a death."""
    import numpy as np

    from distributed_ml_pytorch_tpu.serving.fleet import EngineMember

    lm, params = _build_model(args, parser)
    members = []
    for i in range(args.fleet):
        engine = _make_engine(lm, params, args)
        # EVERY bucket the cache can hold: a first-of-its-size prompt
        # compiling inside the serve loop would stall heartbeats and read
        # as a death (compiled programs are shared across same-shape
        # replicas, so only replica 0 pays the XLA time)
        bucket = max(2, args.prefill_bucket)
        warmed = 0
        while warmed < 32 and engine.pool.capacity_needed(bucket, bucket, 2) \
                <= engine.pool.cache_size:
            # (capped: --prefill-bucket 1 means exact-length buckets, where
            # exhaustive warmup is unbounded — residual lazy compiles are
            # that configuration's accepted cost)
            w = engine.submit(np.zeros(bucket, np.int32), 2)
            engine.run_until_idle()
            assert w.done
            bucket += max(1, args.prefill_bucket)
            warmed += 1
        engine.reset_metrics()
        coord = coord_factory(i) if coord_factory is not None else None
        members.append(EngineMember(i, engine, coord=coord).start())
    return members


def _print_summary(engine) -> None:
    import json

    summary = engine.slo_summary()
    print("SLO summary:", json.dumps(summary, indent=2, default=float))


def _run_demo(args, engine=None, members=None) -> int:
    import threading

    import numpy as np

    from distributed_ml_pytorch_tpu.serving.frontend import (
        ServingClient,
        ServingFrontend,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import InProcessTransport

    world = InProcessTransport.create_world(2)
    if members is not None:
        from distributed_ml_pytorch_tpu.serving.fleet import FleetRouter

        frontend = FleetRouter(
            world[0], members, slo_ttft_ms=args.slo_ttft_ms,
            shed_occupancy=args.shed_occupancy,
            brownout_occupancy=args.brownout_occupancy,
            brownout_max_new=args.brownout_max_new)
        engine = members[0].engine  # SLO summary target below
    else:
        frontend = ServingFrontend(engine, world[0])
    client = ServingClient(world[1])
    server = threading.Thread(target=frontend.serve_forever, daemon=True)
    server.start()

    rng = np.random.default_rng(args.seed)
    # cap generation lengths so every demo request fits the slot capacity
    # check in ServingEngine.submit (bucketed prompt + whole decode blocks)
    budget = max(
        2, min(24, args.cache_size - args.prefill_bucket - args.decode_block))
    try:
        # submit everything up front so the engine actually batches the
        # requests together, then collect the streams
        submitted = []
        for i in range(args.demo):
            prompt = rng.integers(
                0, args.vocab, size=int(rng.integers(2, 12))).astype(np.int32)
            new = int(rng.integers(2, budget + 1))
            sampled = bool(i % 2)
            rid = client.submit(
                prompt, new,
                temperature=0.8 if sampled else 0.0,
                top_k=8 if sampled else 0, seed=int(i))
            submitted.append((rid, new))
        results = {
            rid: (new, list(client.stream(rid, timeout=120.0)))
            for rid, new in submitted
        }
        for rid, (new, toks) in results.items():
            if len(toks) != new or any(t < 0 or t >= args.vocab for t in toks):
                print(f"demo request {rid}: bad stream {toks}", file=sys.stderr)
                return 1
        print(f"served {args.demo} demo requests "
              f"({sum(len(t) for _, t in results.values())} tokens)")
        _print_summary(engine)
        if members is not None:
            import json

            print("fleet summary:",
                  json.dumps(frontend.fleet_summary(), default=str))
        print("serving demo complete")
        return 0
    finally:
        frontend.stop()
        server.join(timeout=5)
        for t in world.values():
            t.close()


def _main_fleet(args, parser) -> int:
    """N replicas behind a FleetRouter (``--fleet N``): the quickstart is
    ``make serve-fleet``; add ``--coord host:port`` for lease-holding
    membership + coordinator-driven scaling advice."""
    coord_factory = None
    coord_clients = []
    if args.coord:
        from distributed_ml_pytorch_tpu.coord.member import CoordClient
        from distributed_ml_pytorch_tpu.utils.messaging import TCPTransport

        host, _, cport = args.coord.partition(":")

        def coord_factory(i):
            # engines live in the high end of the coordination rank space
            # (see the single-engine path below); co-hosted replicas offset
            # by engine id so each holds its OWN lease
            rank = (args.coord_rank or 50 + int(args.port) % 14) + i
            if rank >= 64:
                # the coordination star validates 1 <= rank < world_size
                # (64): an overflowing derived rank would be refused at the
                # hub's hello and the replica would silently serve without
                # a lease — fail loudly instead
                parser.error(
                    f"fleet replica {i} derives coordination rank {rank} "
                    ">= 64 — pin a lower base with --coord-rank")
            client = CoordClient(
                # distcheck: ignore[DC105] same advisory control star as
                # the single-engine path — periodic, self-healing frames
                TCPTransport(rank=rank, world_size=64,
                             master=host or "localhost",
                             port=int(cport or 29700)),
                "engine")
            coord_clients.append(client)
            return client

    members = _build_fleet(args, parser, coord_factory)
    try:
        if args.demo:
            return _run_demo(args, members=members)

        from distributed_ml_pytorch_tpu.serving.fleet import FleetRouter
        from distributed_ml_pytorch_tpu.utils.messaging import (
            ReliableTransport,
            TCPTransport,
        )

        transport = TCPTransport(
            rank=0, world_size=1 + args.clients, master=args.master,
            port=int(args.port))
        if args.reliable:
            transport = ReliableTransport(transport)
        router = FleetRouter(
            transport, members,
            client_deadline=args.client_deadline,
            fleet=members[0].coord.fleet if members[0].coord else None,
            slo_ttft_ms=args.slo_ttft_ms,
            shed_occupancy=args.shed_occupancy,
            brownout_occupancy=args.brownout_occupancy,
            brownout_max_new=args.brownout_max_new)
        print(f"fleet serving on {args.master}:{args.port} "
              f"({args.fleet} engines x {args.slots} slots x "
              f"{args.cache_size} rows, block {args.decode_block})")
        try:
            router.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            router.stop()
            transport.close()
            import json

            print("fleet summary:",
                  json.dumps(router.fleet_summary(), default=str))
            _print_summary(members[0].engine)
        return 0
    finally:
        for m in members:
            if m.alive:
                m.stop()
        for c in coord_clients:
            c.transport.close()


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        return _main(args, parser)
    finally:
        # observability plane (ISSUE 12): one registry snapshot at exit
        if getattr(args, "metrics_dump", ""):
            from distributed_ml_pytorch_tpu.coord.cli import dump_metrics

            dump_metrics(args.metrics_dump)


def _main(args, parser) -> int:
    print(args)
    if args.fleet:
        return _main_fleet(args, parser)
    engine = _build_engine(args, parser)
    if args.demo:
        return _run_demo(args, engine=engine)

    from distributed_ml_pytorch_tpu.serving.frontend import ServingFrontend
    from distributed_ml_pytorch_tpu.utils.messaging import (
        ReliableTransport,
        TCPTransport,
    )

    coord_client = None
    if args.coord:
        from distributed_ml_pytorch_tpu.coord.member import CoordClient

        host, _, cport = args.coord.partition(":")
        # engines live in the high end of the coordination rank space so
        # they can never collide with training ranks (rank + 1 there);
        # deriving from the SERVING port keeps co-hosted engines distinct
        # (two engines cannot share a port) — cross-host fleets should pin
        # --coord-rank explicitly
        rank = args.coord_rank or 50 + int(args.port) % 14
        coord_client = CoordClient(
            # distcheck: ignore[DC105] coordination frames are periodic and
            # self-healing (join retries, lease renewals the reliability
            # layer exempts anyway); --reliable hardens the DATA hub below,
            # not the advisory control star
            TCPTransport(rank=rank, world_size=64,
                         master=host or "localhost",
                         port=int(cport or 29700)),
            "engine")
        coord_client.join(timeout=10)
    transport = TCPTransport(
        rank=0, world_size=1 + args.clients, master=args.master,
        port=int(args.port))
    if args.reliable:
        transport = ReliableTransport(transport)
    frontend = ServingFrontend(
        engine, transport, client_deadline=args.client_deadline,
        fleet=coord_client.fleet if coord_client is not None else None,
        slo_ttft_ms=args.slo_ttft_ms, shed_occupancy=args.shed_occupancy,
        brownout_occupancy=args.brownout_occupancy,
        brownout_max_new=args.brownout_max_new)
    print(f"serving on {args.master}:{args.port} "
          f"({args.slots} slots x {args.cache_size} rows, "
          f"block {args.decode_block}"
          + (", int8 kv" if args.kv_quant else "") + ")")
    try:
        frontend.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        frontend.stop()
        transport.close()
        if coord_client is not None:
            coord_client.close()
            coord_client.transport.close()
        _print_summary(engine)
    return 0


if __name__ == "__main__":
    sys.exit(main())
