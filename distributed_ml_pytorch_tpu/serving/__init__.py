"""Serving layer: a continuous-batching inference engine for the
TransformerLM family.

The training half of the framework ends at ``models/generate.py`` — one
prompt batch, one ``generate()`` call. This package is the inference half
the ROADMAP's "heavy traffic from millions of users" north star needs: many
concurrent requests of different lengths share ONE compiled decode step
(the paper's DownPour shape transposed to serving — many asynchronous
clients, one compiled data plane, host-side control plane).

- :mod:`serving.cache` — fixed-capacity KV **slot pool** over the
  ring-buffered blocked decode cache (``models/generate.py``), per-slot
  live-length tracking, optional int8 ``kv_quant`` storage.
- :mod:`serving.engine` — the scheduler: admit queued requests into free
  slots between decode blocks, per-request sampling params, eviction,
  admission control/backpressure, SLO metrics (TTFT/TPOT/occupancy).
- :mod:`serving.frontend` — request/response transport over the L1
  messaging layer (``utils/messaging.py``): in-process and TCP clients
  submit prompts and stream tokens back.
- :mod:`serving.cli` — the ``serve`` entrypoint.
"""

from distributed_ml_pytorch_tpu.serving.cache import SlotKVPool
from distributed_ml_pytorch_tpu.serving.engine import (
    QueueFullError,
    Request,
    SamplingParams,
    ServingEngine,
)
from distributed_ml_pytorch_tpu.serving.frontend import (
    RequestRejected,
    ServingClient,
    ServingFrontend,
)

__all__ = [
    "SlotKVPool",
    "ServingEngine",
    "Request",
    "SamplingParams",
    "QueueFullError",
    "ServingFrontend",
    "ServingClient",
    "RequestRejected",
]
