"""distributed_ml_pytorch_tpu — a TPU-native distributed training framework.

A brand-new JAX/XLA framework with the capabilities of the reference
``bkpcoding/distributed_ML_pytorch`` (a DistBelief/DownPour-SGD asynchronous
parameter-server trainer; see ``SURVEY.md``), re-designed TPU-first:

- **Sync data parallelism** over a ``jax.sharding.Mesh`` with compiled ``psum``
  gradient allreduce riding ICI (replaces the reference's out-of-tree gloo
  backend, ``example/main.py:165``).
- **Async DownPour-SGD parameter server** with ``n_push``/``n_pull`` cadence
  (reference ``asgd/optim/Asynchronous.py:42-71``) re-expressed functionally:
  jitted local steps + host-side tagged messaging between controller
  processes; the reference's Listener-thread data race becomes a race-free
  between-steps parameter swap.
- **Flax CNN models** (LeNet/AlexNet parity with ``example/models.py``, plus
  ResNet) and a CIFAR-10 pipeline.
- **p2p primitives** via ``ppermute`` (replaces ``pytorch_p2p_ex.py``).

Public API re-exports the contractual symbols recovered in SURVEY.md §2.3.
"""

from distributed_ml_pytorch_tpu.version import __version__
from distributed_ml_pytorch_tpu.utils.serialization import (
    ravel_model_params,
    unravel_model_params,
    make_unraveler,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    MessageListener,
    send_message,
)
from distributed_ml_pytorch_tpu.models import LeNet, AlexNet


def __getattr__(name):
    # contractual PS symbols (M1/M4/C1) — lazy to keep `import
    # distributed_ml_pytorch_tpu` light
    if name in ("ParameterServer", "Asynchronous", "DownpourSGD", "Listener"):
        from distributed_ml_pytorch_tpu.parallel import async_ps

        return getattr(async_ps, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "__version__",
    "ravel_model_params",
    "unravel_model_params",
    "make_unraveler",
    "MessageCode",
    "MessageListener",
    "send_message",
    "LeNet",
    "AlexNet",
    "ParameterServer",
    "Asynchronous",
    "DownpourSGD",
]
