"""distributed_ml_pytorch_tpu — a TPU-native distributed training framework.

A brand-new JAX/XLA framework with the capabilities of the reference
``bkpcoding/distributed_ML_pytorch`` (a DistBelief/DownPour-SGD asynchronous
parameter-server trainer; see ``SURVEY.md``), re-designed TPU-first:

- **Sync data parallelism** over a ``jax.sharding.Mesh`` with compiled ``psum``
  gradient allreduce riding ICI (replaces the reference's out-of-tree gloo
  backend, ``example/main.py:165``).
- **Async DownPour-SGD parameter server** with ``n_push``/``n_pull`` cadence
  (reference ``asgd/optim/Asynchronous.py:42-71``) re-expressed functionally:
  jitted local steps + host-side tagged messaging between controller
  processes; the reference's Listener-thread data race becomes a race-free
  between-steps parameter swap.
- **Flax CNN models** (LeNet/AlexNet parity with ``example/models.py``, plus
  ResNet) and a CIFAR-10 pipeline.
- **p2p primitives** via ``ppermute`` (replaces ``pytorch_p2p_ex.py``).

Public API re-exports the contractual symbols recovered in SURVEY.md §2.3.
"""

import jax as _jax

#: True when this runtime predates the graduated jax.shard_map (and its
#: varying-manual-axes type system). Code whose GRADIENTS depend on
#: transpose-time psum insertion for replicated operands (parallel/pipeline)
#: consults this to pin check_rep=False and insert those psums explicitly —
#: the old checker's false positives otherwise make strict-vs-loose (and so
#: the gradient math) depend on which body happens to trace.
LEGACY_SHARD_MAP = not hasattr(_jax, "shard_map")

if LEGACY_SHARD_MAP:
    # jax-version compatibility: shard_map graduated out of jax.experimental
    # after this runtime's jax; the framework is written against the new
    # spelling, so install it where older runtimes lack it (keyword surface
    # — mesh/in_specs/out_specs — is identical). check_rep stays ON by
    # default — it drives the transpose-time psum insertion that makes
    # gradients of replicated operands correct (round 3; DESIGN.md §4) —
    # but the experimental checker has false positives the graduated one
    # fixed (e.g. it cannot prove an optax update of psum-med grads is
    # replicated), so a callable whose TRACE fails the replication check is
    # rebuilt once with check_rep=False and remembered.
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def _shard_map_compat(f, **kwargs):
        if "check_rep" in kwargs or "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma", kwargs.get("check_rep"))
            return _shard_map(f, **kwargs)
        strict = _shard_map(f, **kwargs, check_rep=True)
        mode = {}

        def loose():
            # built once and cached: a fresh function object per call
            # would miss jax's trace caches (keyed on identity) and
            # retrace eager callers every iteration
            if "loose" not in mode:
                import warnings

                warnings.warn(
                    "shard_map compat: replication check disabled for "
                    f"{getattr(f, '__name__', f)!r} after the old checker "
                    "rejected it — if this body relies on transpose-time "
                    "psum insertion for replicated operands, verify its "
                    "gradients against an unsharded reference",
                    stacklevel=3,
                )
                mode["loose"] = _shard_map(f, **kwargs, check_rep=False)
            return mode["loose"]

        def dispatch(*args, **kw):
            if "loose" in mode:
                return loose()(*args, **kw)
            try:
                return strict(*args, **kw)
            except (ValueError, NotImplementedError) as e:
                # checker false positives only: unprovable replication
                # (ValueError) or a primitive with no replication rule,
                # e.g. pallas_call (NotImplementedError) — anything else
                # is a real error and propagates
                if "replicat" not in str(e):
                    raise
                return loose()(*args, **kw)

        return dispatch

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "pcast"):
    # pcast/pvary markers belong to the varying-manual-axes type system of
    # newer jax; the experimental shard_map used above has no vma tracking
    # (check_rep defaults off), so the marker is correctly an identity here
    _jax.lax.pcast = lambda x, *args, **kwargs: x

from distributed_ml_pytorch_tpu.version import __version__
from distributed_ml_pytorch_tpu.utils.serialization import (
    ravel_model_params,
    unravel_model_params,
    make_unraveler,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    MessageListener,
    send_message,
)
from distributed_ml_pytorch_tpu.models import LeNet, AlexNet


def __getattr__(name):
    # contractual PS symbols (M1/M4/C1) — lazy to keep `import
    # distributed_ml_pytorch_tpu` light
    if name in ("ParameterServer", "Asynchronous", "DownpourSGD", "Listener"):
        from distributed_ml_pytorch_tpu.parallel import async_ps

        return getattr(async_ps, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "__version__",
    "ravel_model_params",
    "unravel_model_params",
    "make_unraveler",
    "MessageCode",
    "MessageListener",
    "send_message",
    "LeNet",
    "AlexNet",
    "ParameterServer",
    "Asynchronous",
    "DownpourSGD",
]
