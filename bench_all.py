"""Measure every BASELINE.md config this environment can measure honestly.

``bench.py`` stays the driver's one-line headline (config #1); this harness
produces the full table — one JSON line per config on stdout, narration on
stderr — and its results are recorded in BASELINE.md.

Measurement boundaries, per config (honesty notes in each JSON record):

1. single-process example CNN (reference ``Makefile:23``): differenced
   steady-state img/s on the real chip (``bench.bench_jax``), with the torch
   CPU leg as the measured reference baseline.
2. 2-process gradient exchange (reference ``pytorch_p2p_ex.py:7-23``): a
   2-device psum allreduce of the raveled AlexNet gradient vector (the
   sync-DP collective that replaces gloo send/recv). Only one real chip is
   attached here, so this runs on 2 virtual CPU devices — a functional
   measurement of the compiled collective, not ICI bandwidth.
3. async-SGD, 4 workers (reference ``asgd/optim/Asynchronous.py:42-70``):
   the real thing — 5 localhost processes (1 server + 4 workers) over the
   TCP transport, aggregate img/s with process startup and compile INCLUDED
   (the reference's own launch pattern pays the same costs).
4. ResNet-18 8-way data-parallel: single-chip TPU throughput (the per-chip
   number that an 8-way ICI allreduce scales, per the sync-DP exactness
   tests), plus an 8-virtual-device functional run of the actual sharded
   step.
5. ResNet-50 ImageNet-shaped (north star): single-chip TPU throughput at
   224x224. Pod-scale (v4-32) ICI needs hardware this environment lacks;
   the sharded program itself is validated by ``__graft_entry__`` /
   ``dryrun_multichip``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

from bench import (
    BATCH,
    LARGE_BATCH,
    LEG_NOTES,
    LR,
    bench_jax,
    bench_torch_cpu,
    log,
    make_batch,
    run_headline_legs,
)

RESULTS: list = []


def emit(config: int, metric: str, value: float, unit: str, hardware: str,
         note: str, extra: dict = None) -> None:
    rec = {
        "config": config,
        "metric": metric,
        "value": round(float(value), 1),
        "unit": unit,
        "hardware": hardware,
        "note": note,
    }
    # VERDICT r1 #1: every leg carries its FLOPs story when the harness
    # measured one (bench.Rate) — model FLOPs/step, achieved TFLOP/s, MFU
    from bench import Rate

    if isinstance(value, Rate) and value.tflops is not None:
        rec.update(value.record_fields())
        rec["note"] = f"{note}; {value.mfu_note()}"
    if extra:
        # structured side-channel fields (e.g. mpmd_phase's
        # bubble_attribution, ISSUE 12) — schema-checked by the caller
        rec.update(extra)
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


#: the exclusive serve-loop states a bubble_attribution record may name
#: (utils/obs.StateClock vocabulary for the mpmd plane)
BUBBLE_STATES = ("compute", "wait-act", "wait-grad", "wire-blocked", "ckpt",
                 "idle")


def check_bubble_attribution(attr: dict) -> dict:
    """Schema gate for ``mpmd_phase``'s ``bubble_attribution`` JSON field
    (ISSUE 12; the ``test_bench_gate.py``-style check): fractions over the
    known exclusive states, summing to ~1, with ``bubble_fraction``
    consistent with ``1 - compute``. Raises ``ValueError`` on any breach —
    a malformed attribution must not ship in the bench record."""
    if not isinstance(attr, dict):
        raise ValueError(f"bubble_attribution must be a dict, got "
                         f"{type(attr).__name__}")
    fractions = attr.get("fractions")
    if not isinstance(fractions, dict) or not fractions:
        raise ValueError("bubble_attribution.fractions missing/empty")
    unknown = sorted(k for k in fractions if k not in BUBBLE_STATES)
    if unknown:
        raise ValueError(f"bubble_attribution names unknown state(s) "
                         f"{unknown} (known: {list(BUBBLE_STATES)})")
    total = sum(float(v) for v in fractions.values())
    if not 0.95 <= total <= 1.05:
        raise ValueError(
            f"bubble_attribution fractions sum to {total:.4f}, not ~1 — "
            "the exclusive-state clock contract is broken")
    bubble = attr.get("bubble_fraction")
    if not isinstance(bubble, (int, float)) or not 0.0 <= bubble <= 1.0:
        raise ValueError(f"bubble_fraction {bubble!r} not in [0, 1]")
    if abs((1.0 - float(fractions.get("compute", 0.0))) - float(bubble)) \
            > 1e-3:
        raise ValueError("bubble_fraction != 1 - compute fraction")
    stages = attr.get("stages")
    if not isinstance(stages, int) or stages < 1:
        raise ValueError(f"bubble_attribution.stages {stages!r} invalid")
    return attr


def tpu_phase() -> None:
    import jax

    platform = jax.devices()[0].platform
    hw = f"1x {platform}"

    # config 1 — flagship AlexNet, all three headline legs (identical to
    # bench.py's record: parity recipe, large-batch ceiling, grad-accum)
    legs = run_headline_legs()

    def leg_note(name: str) -> str:
        # off-TPU run_headline_legs shrinks the big legs to validation
        # shapes; the emitted note must describe what actually ran, not
        # the TPU recipe (bench.py's own record does this via leg_batch)
        note = LEG_NOTES[name]
        expected = BATCH if name == "parity_b64" else LARGE_BATCH
        actual = getattr(legs[name], "leg_batch", None)
        if actual is not None and actual != expected:
            note = (f"MEASURED at batch {actual} on a shrunk off-TPU "
                    f"validation workload (structure check, not the TPU "
                    f"recipe); leg description: {note}")
        return note

    emit(1, "alexnet_cifar10_train_throughput", legs["parity_b64"],
         "images/sec/chip", hw, leg_note("parity_b64"))
    emit(1, "alexnet_cifar10_train_throughput_large_batch",
         legs["large_batch_b1024"], "images/sec/chip", hw,
         leg_note("large_batch_b1024"))
    emit(1, "alexnet_cifar10_train_throughput_grad_accum",
         legs["grad_accum_b1024"], "images/sec/chip", hw,
         leg_note("grad_accum_b1024"))
    base = bench_torch_cpu()
    if base:
        emit(1, "alexnet_cifar10_train_throughput_torch_reference", base,
             "images/sec", "cpu",
             "reference `make single` recipe re-measured in torch")

    # config 1 (MXU-native leg) — the same flagship with bf16 activations
    # (f32 params; the framework's --dtype bfloat16 path)
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import AlexNet

    ips_bf16 = bench_jax(model=AlexNet(num_classes=10, dtype=jnp.bfloat16))
    emit(1, "alexnet_cifar10_train_throughput_bf16", ips_bf16,
         "images/sec/chip", hw,
         "same recipe with bfloat16 activations feeding the MXU natively")

    # config 1 (north-star metric #2) — steps to target accuracy, both
    # frameworks, identical batch stream
    jax_steps, torch_steps, torch_status, _jacc, _tacc, _curves = bench_steps_to_accuracy()
    if jax_steps is None:
        emit(1, "steps_to_99pct_test_accuracy", -1, "steps", hw,
             "did NOT reach the target within the 2000-step cap — "
             "investigate before trusting other rows (-1 = cap hit)")
    else:
        torch_part = {
            "measured": f"torch on the identical batch stream took "
                        f"{torch_steps} steps",
            "cap": "torch on the identical batch stream did NOT reach the "
                   "target within the 2000-step cap (its default kaiming "
                   "init plateaus at this lr; flax's lecun-normal escapes "
                   "early — init is part of each framework's recipe)",
            "unavailable": "torch leg unavailable in this environment "
                           "(not a measured outcome)",
        }[torch_status]
        emit(1, "steps_to_99pct_test_accuracy", jax_steps, "steps", hw,
             f"reference recipe on the deterministic synthetic set; {torch_part}")

    # config 1 (identical-init leg, VERDICT r3 #3) — the cross-framework
    # steps ratio needs a target BOTH frameworks reach; torch's default init
    # never learns at this lr (chance accuracy at the cap), so this leg
    # installs the identical flax init into the torch model and compares
    # steps-to-60% — isolating the training machinery from init luck
    mj, mt, mstat, mjacc, mtacc, _ = bench_steps_to_accuracy(
        target=0.60, torch_init="matched")
    if mj is not None and mt is not None:
        emit(1, "steps_to_60pct_matched_init_ratio", mt / mj, "torch/jax steps",
             hw, f"identical init + identical batch stream: jax {mj} vs "
             f"torch {mt} steps to 60%; final acc delta "
             f"{abs(mjacc - mtacc):.4f} (north-star parity bar is 0.001)")
    else:
        emit(1, "steps_to_60pct_matched_init_ratio", -1, "torch/jax steps",
             hw, f"matched-init leg incomplete: jax {mj}, torch {mt} "
             f"({mstat}); -1 = no finite ratio")

    from distributed_ml_pytorch_tpu.models import TransformerLM, get_resnet

    # config 4 (per-chip leg) — ResNet-18, CIFAR shapes, batch 64
    r18 = bench_jax(model=get_resnet("resnet18"), k=20, n_long=11, trials=3)
    emit(4, "resnet18_cifar10_train_throughput", r18, "images/sec/chip", hw,
         "single-chip leg of the 8-way DP config; the sync-DP step is "
         "numerically validated on an 8-device mesh (tests/test_resnet.py)")

    # config 4 (MXU-native leg) — ResNet-18 in bf16 at a batch that fills
    # the MXU (the f32/batch-64 leg above keeps the reference-recipe shape)
    r18bf = bench_jax(model=get_resnet("resnet18", dtype=jnp.bfloat16),
                      batch=256, k=10, n_long=8, trials=3)
    emit(4, "resnet18_cifar10_train_throughput_bf16", r18bf,
         "images/sec/chip", hw,
         "bf16 activations + f32 master params, batch 256, device-resident "
         "input")

    # config 5 (per-chip leg) — ResNet-50, ImageNet shapes (224x224, 1000-way)
    r50 = bench_jax(model=get_resnet("resnet50", num_classes=1000), batch=32,
                    input_shape=(224, 224, 3), n_classes=1000, k=4,
                    n_long=6, trials=3)
    emit(5, "resnet50_imagenet_shape_train_throughput", r50, "images/sec/chip",
         hw, "224x224 synthetic, batch 32, f32; pod-scale ICI requires a "
         "v4-32 this environment lacks — sharded program validated by "
         "dryrun_multichip")

    # config 5 (MXU-native leg, VERDICT r1 #1) — ResNet-50 in bf16 at a
    # batch that fills the MXU; this is the MFU-judged leg
    r50bf = bench_jax(
        model=get_resnet("resnet50", num_classes=1000, dtype=jnp.bfloat16),
        batch=256, input_shape=(224, 224, 3), n_classes=1000, k=2,
        n_long=6, trials=3,
    )
    emit(5, "resnet50_imagenet_shape_train_throughput_bf16", r50bf,
         "images/sec/chip", hw,
         "224x224 synthetic, batch 256, bf16 activations + f32 master params, "
         "device-resident input (compute ceiling)")

    # config 5 (host-fed leg) — same step with every batch starting in host
    # RAM, double-buffered device_put overlapping the previous step
    r50h = bench_hostfed_resnet50()
    if r50h is not None:
        emit(5, "resnet50_hostfed_overlapped_input_throughput", r50h,
             "images/sec/chip", hw,
             "batch 256 bf16, each step's input device_put from host while "
             "the prior step runs; on this rig the host link is the axon "
             "tunnel — a real TPU VM's local PCIe link is far faster, so "
             "this is the pipeline floor, not the typical deployment number")

    # config 6 (capability extension, no reference counterpart) — long-context
    # Transformer-LM training throughput at seq 8192
    tok_s = bench_lm(tag="lm-512d-seq8192")
    emit(6, "transformer_lm_seq8192_train_throughput", tok_s, "tokens/sec/chip",
         hw, "default TransformerLM (512d/8h/6L), bf16 activations, per-block "
         "remat, RoPE, batch 1 x seq 8192; capability extension — the "
         "reference has no sequence models (SURVEY.md §5.7)")

    # config 6 (MFU-judged leg, VERDICT r1 #1) — GPT-2-small-scale LM
    # (162M params incl. untied embeddings; vocab padded to a multiple of
    # 128 for MXU-aligned logits). remat=False measured faster than
    # remat=True at both shapes (flash attention removed the S² temps that
    # made remat necessary: 88.1k vs 65.9k tok/s at b8/s2048). The flash
    # kernel's FLOPs are invisible to cost_analysis and are added
    # analytically inside bench_lm (utils/flops.flash_attention_train_flops).
    gpt2 = TransformerLM(
        vocab_size=50304, d_model=768, n_heads=12, n_layers=12, d_ff=3072,
        dtype=jnp.bfloat16, remat=False, pos_encoding="rope",
    )
    tok_s2 = bench_lm(gpt2, batch=8, seq=2048, n_long=6, tag="gpt2-small-seq2048")
    emit(6, "gpt2_small_seq2048_train_throughput", tok_s2, "tokens/sec/chip",
         hw, "GPT-2-small config (768d/12h/12L, padded vocab 50304), bf16, "
         "RoPE, Pallas flash attention, batch 8 x seq 2048; kernel FLOPs "
         "counted analytically on top of the XLA count")
    tok_s3 = bench_lm(gpt2, batch=1, seq=8192, n_long=6, tag="gpt2-small-seq8192")
    emit(6, "gpt2_small_seq8192_train_throughput", tok_s3, "tokens/sec/chip",
         hw, "same GPT-2-small config at long context, batch 1 x seq 8192; "
         "attention dominates at this S (the analytic kernel count is most "
         "of the numerator)")

    # config 6 (extreme-length leg) — full model at 32k context via the
    # sequence-chunked loss
    bench_lm_32k()

    # config 6 (MoE family leg) — Switch-MoE at GPT-2-small dims
    moe_tok = bench_moe_lm()
    emit(6, "moe_lm_4expert_seq2048_train_throughput", moe_tok,
         "tokens/sec/chip", hw,
         "Switch-MoE (768d/12L, 4 experts top-1, 2.0 capacity), bf16, batch "
         "8 x seq 2048 — single-chip leg of the dp x ep sharding "
         "(dryrun_multichip runs the sharded step)")

    # config 8 (inference) — KV-cache autoregressive decode, with the HBM
    # roofline that judges it (decode reads all params + the live KV cache
    # per step; utilization column per VERDICT r2 #4)
    dec_rate, dec_frac, dec_bytes = bench_decode()
    emit(8, "gpt2_small_decode_throughput", dec_rate, "tokens/sec/chip", hw,
         f"batch 32, 128-token prompt prefill + 256 generated tokens per "
         f"call, ring-buffered block decode (models/generate.py: per-step "
         f"ring appends, static live-prefix cache reads, once-per-block "
         f"merges); greedy, device-true timing. "
         f"{dec_bytes / 1e6:.0f} MB/step of mandatory HBM traffic → "
         f"{100 * dec_frac:.0f}% of the measured streaming roofline")
    emit(8, "gpt2_small_decode_hbm_utilization", 100 * dec_frac,
         "percent of measured HBM roofline", hw,
         "mandatory traffic (bf16 params + average live K/V read) per step "
         "x steps/s, judged against the bandwidth a pure streaming read "
         "actually sustains on this chip (~715 GB/s, 87% of the 819 GB/s "
         "nameplate) — decode's MFU-equivalent, a lower bound on achieved "
         "bandwidth. Remaining gap: weight-DMA latency stalls between "
         "small per-layer matmuls (measured as async copy/slice waits)")

    # config 8 (capacity knob) — int8 KV cache: halves the cache's HBM
    # footprint (2x decode batch or context per chip); measured here so the
    # throughput-neutrality claim stays current
    q_rate, _, _ = bench_decode(kv_quant=True)
    emit(8, "gpt2_small_decode_throughput_int8_kv", q_rate, "tokens/sec/chip",
         hw, "same leg with kv_quant=True (int8 cache + per-key f32 scales, "
         "quantized at block merges; prefill attends with exact K/V). A "
         "CAPACITY knob, not a speed knob on this runtime: bytes halve but "
         "the fused convert+dequantize read runs at ~half the bf16 GB/s, "
         "so read time is ~flat")


def install_flax_alexnet_init(tmodel, flax_params) -> None:
    """Copy a flax AlexNet init into the torch AlexNet (the inverse of
    ``utils/interop``'s torch→flax direction, specialized to the one
    architecture the steps-to-target comparison uses): conv kernels
    (kH, kW, I, O) → (O, I, kH, kW), the classifier (in, out) → (out, in),
    biases as-is. Layer order is structural (conv1..conv5, classifier), so
    no shape-matching heuristics are needed."""
    import torch

    convs = [m for m in tmodel if isinstance(m, torch.nn.Conv2d)]
    linears = [m for m in tmodel if isinstance(m, torch.nn.Linear)]
    names = [f"conv{i}" for i in range(1, len(convs) + 1)]
    with torch.no_grad():
        # np.array(copy=True): jax exports read-only buffers and
        # torch.from_numpy warns on non-writable sources
        as_t = lambda a: torch.from_numpy(np.array(a, np.float32, copy=True))
        for name, m in zip(names, convs):
            m.weight.copy_(as_t(
                np.asarray(flax_params[name]["kernel"]).transpose(3, 2, 0, 1)))
            m.bias.copy_(as_t(flax_params[name]["bias"]))
        (lin,) = linears
        lin.weight.copy_(as_t(np.asarray(flax_params["classifier"]["kernel"]).T))
        lin.bias.copy_(as_t(flax_params["classifier"]["bias"]))


def bench_steps_to_accuracy(target: float = 0.99, max_steps: int = 2000,
                            eval_every: int = 25, n_eval: int = 2000,
                            synthetic: bool = True, root: str = "./data",
                            torch_init: str = "default"):
    """North-star metric #2: steps to reach ``target`` test accuracy with the
    reference recipe (AlexNet, batch 64, SGD lr 0.008) — measured for BOTH
    frameworks on the IDENTICAL batch stream (same sampled indices), so the
    comparison isolates the framework, not the data order. Inits differ
    (torch default vs flax lecun), which is part of each framework's
    recipe. ``synthetic=False`` runs on real CIFAR-10 under ``root``
    (``verify_real_data.py``'s path — raises if absent). Returns
    ``(jax_steps, torch_steps, torch_status, jax_acc, torch_acc, curves)``
    — steps are None on a cap-hit, accs are the FINAL evaluated accuracies
    either way (the parity bar's ingredients), and ``curves`` holds each
    framework's per-eval accuracy trajectory so a caller can derive any
    target's first crossing from ONE run; ``torch_status`` is one of
    ``"measured" | "cap" | "unavailable" | "skipped"`` — a cap-hit is a
    *measured outcome*, an exception is not, and the caller must not
    conflate them.
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        create_train_state,
        make_eval_fn,
        make_scan_train_step,
    )

    x, y, xt, yt, _ = load_cifar10(root=root, synthetic=synthetic)
    xe, ye = xt[:n_eval], yt[:n_eval]
    idx = np.random.default_rng(0).integers(
        0, len(x), size=(max_steps // eval_every, eval_every, BATCH)
    )

    model = AlexNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=LR)
    # snapshot the init to host BEFORE training: the scan donates the state,
    # so the initial device buffers will be reused
    init_np = jax.tree.map(np.asarray, state.params)
    scan = make_scan_train_step(model, tx)
    ev = make_eval_fn(model)
    rng = jax.random.key(1)
    jax_steps, jax_acc = None, 0.0
    jax_curve, torch_curve = [], []  # per-eval accs (steps = (i+1)*eval_every)
    xe_j = jnp.asarray(xe)
    for chunk, sel in enumerate(idx):
        state, _losses = scan(state, jnp.asarray(x[sel]), jnp.asarray(y[sel]), rng)
        _, preds = ev(state.params, xe_j, jnp.asarray(ye))
        jax_acc = float((np.asarray(preds) == ye).mean())
        jax_curve.append(jax_acc)
        if jax_steps is None and jax_acc >= target:
            jax_steps = (chunk + 1) * eval_every
            if synthetic:
                break  # real-data runs continue to the cap for the parity acc
    log(f"steps-to-{target:.0%}: jax {jax_steps} (final acc {jax_acc:.4f})")
    if jax_steps is None and synthetic:
        # the comparison leg is moot (and minutes of CPU) when the primary
        # leg missed the target — report the cap-hit instead of discarding
        return None, None, "skipped", jax_acc, None, {
            "jax": jax_curve, "torch": [], "eval_every": eval_every}

    torch_steps, torch_status, torch_acc = None, "cap", None
    try:
        import torch
        import torch.nn.functional as F

        from bench import make_torch_alexnet

        torch.manual_seed(0)
        tmodel = make_torch_alexnet()
        if torch_init == "matched":
            # identical-init leg (VERDICT r3 #3): torch's default kaiming
            # init never escapes its plateau at this lr on the synthetic
            # stream (measured: 9.1% after 2000 steps — chance), so no
            # target yields a finite cross-framework ratio. Installing the
            # IDENTICAL flax init isolates what the row is about — the
            # training machinery — instead of init luck.
            install_flax_alexnet_init(tmodel, init_np)
        elif torch_init != "default":
            raise ValueError(f"torch_init must be 'default' or 'matched', "
                             f"got {torch_init!r}")
        opt = torch.optim.SGD(tmodel.parameters(), lr=LR, momentum=0.0)
        xe_t = torch.from_numpy(xe.transpose(0, 3, 1, 2).copy())
        for chunk, sel in enumerate(idx):
            for step_idx in sel:
                bx = torch.from_numpy(x[step_idx].transpose(0, 3, 1, 2).copy())
                by = torch.from_numpy(y[step_idx].astype(np.int64))
                opt.zero_grad()
                loss = F.cross_entropy(tmodel(bx), by)
                loss.backward()
                opt.step()
            with torch.no_grad():
                torch_acc = float((tmodel(xe_t).argmax(1).numpy() == ye).mean())
            torch_curve.append(torch_acc)
            if torch_steps is None and torch_acc >= target:
                torch_steps = (chunk + 1) * eval_every
                torch_status = "measured"
                if synthetic:
                    break
    except Exception as e:
        torch_status = "unavailable"
        log(f"torch steps-to-accuracy unavailable: {e}")
    log(f"steps-to-{target:.0%}: torch {torch_steps} ({torch_status}, "
        f"final acc {torch_acc if torch_acc is not None else float('nan'):.4f})")
    return (jax_steps, torch_steps, torch_status, jax_acc, torch_acc,
            {"jax": jax_curve, "torch": torch_curve,
             "eval_every": eval_every})


def bench_lm(lm=None, batch: int = 1, seq: int = 8192, n_long: int = 11,
             cross_check: bool = True,
             trials: int = 3, tag: str = "lm"):
    """Differenced steady-state tokens/sec (+ FLOPs/MFU) of one LM train step
    on the default device (chained through the donated state: each dispatch's
    params feed the next, so the final scalar fetch forces the whole chain)."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import optax

    from bench import Rate
    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.parallel.fsdp import lm_loss_builder
    from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
        create_lm_train_state,
        next_token_targets,
    )
    from distributed_ml_pytorch_tpu.utils.flops import compiled_flops

    if lm is None:
        # remat=False: with flash attention the S² temporaries are gone, so
        # at this scale rematerialization only adds recompute — measured
        # 184.5k vs 154.9k tok/s at b1×S8192 (remat stays the right call
        # where activations genuinely exceed HBM, e.g. the 32k leg)
        lm = TransformerLM(dtype=jnp.bfloat16, remat=False, pos_encoding="rope")
    lr = 1e-3  # ONE recipe for both step builders below: plain SGD at lr
    tx = optax.sgd(lr)
    state = create_lm_train_state(lm, jax.random.key(0), tx)
    tokens = np.random.default_rng(0).integers(
        0, lm.vocab_size, size=(batch, seq)
    ).astype(np.int32)
    targets = jnp.asarray(next_token_targets(tokens))
    tokens = jnp.asarray(tokens)
    loss_builder = lm_loss_builder(lm)  # the shared masked-LM loss convention

    if getattr(lm, "head", None) is True:
        # detachable-head models take the restructured lm_head step
        # (ops/fused_head.py): same function as the AD step below (tested),
        # one lse for loss+backward+update — measured +2.1% tokens/s at
        # GPT-2-small b1×S8192 together with the S=8192 flash backward
        # blocking (121.57 → 119.11 ms/step, device-true). It implements
        # plain SGD at `lr` — exactly the tx above; change them together.
        from distributed_ml_pytorch_tpu.ops.fused_head import (
            make_fused_head_sgd_step,
        )

        step = make_fused_head_sgd_step(lm, lr)
    else:
        @partial(jax.jit, donate_argnums=(0,))
        def step(state, tokens, targets):
            loss, grads = jax.value_and_grad(
                loss_builder(state, tokens, targets))(state.params)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(params=params, opt_state=opt_state,
                                 step=state.step + 1), loss

    step_flops = compiled_flops(step, state, tokens, targets)
    # the Pallas flash kernels' FLOPs are invisible to cost_analysis; when
    # this leg runs them (TPU + blockable shape + model-default attention),
    # add the analytic kernel count so MFU is real, not a floor
    from distributed_ml_pytorch_tpu.ops.attention import flash_block_choice

    uses_flash = (
        step_flops is not None
        and jax.default_backend() == "tpu"
        and getattr(lm, "attn_fn", None) is None
        and flash_block_choice(seq, seq) is not None
    )
    if uses_flash:
        from distributed_ml_pytorch_tpu.utils.flops import (
            flash_attention_train_flops,
        )

        step_flops += flash_attention_train_flops(
            batch, lm.n_heads, seq, lm.d_model // lm.n_heads, lm.n_layers,
            causal=True, remat=bool(getattr(lm, "remat", False)),
        )

    # audit cross-check (VERDICT r2 #8): the hybrid numerator must agree
    # with an independent scaling-book 6ND count within 15%
    from distributed_ml_pytorch_tpu.utils.flops import (
        check_flops_agreement,
        lm_train_flops_6nd,
    )

    n_params = sum(p.size for p in jax.tree.leaves(state.params))
    embed_params = sum(
        leaf.size
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
        if any("embed" in str(getattr(k, "key", k)).lower() for k in path)
    )
    if cross_check:
        analytic = lm_train_flops_6nd(
            n_params - embed_params, batch, seq, lm.n_heads,
            lm.d_model // lm.n_heads, lm.n_layers,
            causal=True, remat=bool(getattr(lm, "remat", False)))
        warn = check_flops_agreement(step_flops, analytic)
        if warn:
            log(f"{tag}: {warn}")
    else:
        warn = None

    from distributed_ml_pytorch_tpu.utils.devtime import device_time

    holder = {"s": state}

    def one_step():
        holder["s"], loss = step(holder["s"], tokens, targets)
        return loss

    t = device_time(one_step, calls=max(2, n_long), warmup=2)
    per_step = t.per_call_s
    rate = Rate.make(batch * seq / per_step, step_flops, per_step)
    log(f"{tag} ({n_params / 1e6:.0f}M params): {per_step * 1e3:.1f} ms/step at "
        f"batch {batch} x seq {seq} → {rate:.0f} tokens/s ({rate.mfu_note()}, "
        f"device-true; 6ND cross-check "
        f"{'skipped' if not cross_check else 'ok' if warn is None else 'FAILED'})")
    return rate


def bench_lm_32k() -> None:
    """Config 6, extreme-length leg: a FULL GPT-2-small train step at
    S=32768 on one chip — possible only because the loss is sequence-
    chunked (``training/trainer.chunked_lm_loss``: the (1, 32768, 50304)
    logits tensor alone is 6.6 GB f32, which OOM'd the dense loss; the
    flash kernel handles the attention, remat the block activations)."""
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
    from distributed_ml_pytorch_tpu.training.trainer import chunked_lm_loss
    from distributed_ml_pytorch_tpu.utils.devtime import device_time
    from distributed_ml_pytorch_tpu.utils.flops import lm_train_flops_6nd

    S = 32768
    lm = TransformerLM(
        vocab_size=50304, d_model=768, n_heads=12, n_layers=12, d_ff=3072,
        max_len=S, dtype=jnp.bfloat16, pos_encoding="rope", remat=True)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 50304, (1, S)),
                         jnp.int32)
    targets = jnp.asarray(np.random.default_rng(1).integers(0, 50304, (1, S)),
                          jnp.int32)
    params = lm.init(jax.random.key(0), tokens[:, :128])["params"]
    tx = optax.sgd(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: chunked_lm_loss(lm, p, tokens, targets, chunk=2048)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    holder = {"p": params, "o": opt_state}

    def call():
        holder["p"], holder["o"], loss = step(
            holder["p"], holder["o"], tokens, targets)
        return loss

    t = device_time(call, calls=2, warmup=2)
    n_params = sum(p.size for p in jax.tree.leaves(holder["p"]))
    embed_params = sum(
        leaf.size
        for path, leaf in jax.tree_util.tree_flatten_with_path(holder["p"])[0]
        if any("embed" in str(getattr(k, "key", k)).lower() for k in path)
    )
    fl = lm_train_flops_6nd(
        n_params - embed_params, 1, S, lm.n_heads,
        lm.d_model // lm.n_heads, lm.n_layers, remat=True)
    from bench import Rate

    rate = Rate.make(S / t.per_call_s, fl, t.per_call_s)
    emit(6, "gpt2_small_seq32768_train_throughput", rate, "tokens/sec/chip",
         "1x tpu",
         "FULL-model single-chip training at 32k context (bf16, RoPE, "
         "remat, sequence-chunked loss — the dense loss OOMs on the 6.6 GB "
         "logits tensor); numerator is the analytic 6ND count incl. remat "
         "recompute (cost_analysis path not used for this leg)")


def bench_moe_lm(batch: int = 8, seq: int = 2048, n_long: int = 4,
                 trials: int = 2):
    """Single-chip Switch-MoE LM leg: same measurement discipline as
    bench_lm, on the MoE model family (GPT-2-small dims, 4 experts, top-1
    routing — ~4x the FFN params of the dense model at ~the dense FLOPs,
    the MoE bargain the EP sharding distributes)."""
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models.moe import MoETransformerLM

    moe = MoETransformerLM(
        vocab_size=50304, d_model=768, n_heads=12, n_layers=12, d_ff=3072,
        n_experts=4, max_len=seq, dtype=jnp.bfloat16,
    )
    # cross_check=False: 6·N·D over ALL experts' params overcounts top-1
    # routed execution ~2-3x — an activated-params 6ND for MoE is future work
    return bench_lm(moe, batch=batch, seq=seq, n_long=n_long, trials=trials,
                    cross_check=False,
                    tag=f"moe-4e-seq{seq}")


def bench_decode(batch: int = 32, prompt_len: int = 128,
                 new_tokens: int = 256, kv_quant: bool = False):
    """Autoregressive decode of the GPT-2-small model — tokens/s plus the
    roofline that judges it (VERDICT r2 #4): each single-token step must
    read every parameter once (batch-amortized) and each sequence's K/V
    cache, so the decode ceiling is HBM bandwidth, not FLOPs. Reports
    bytes/step from the actual param dtypes + the average live cache
    length, and the achieved fraction of the chip's 819 GB/s. Timing is
    device-true (utils/devtime): the profiler's device spans for the
    prefill + scanned-generation programs, immune to the tunnel RTT that
    host-differenced decode timing is hostage to."""
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import TransformerLM, generate
    from distributed_ml_pytorch_tpu.utils.devtime import device_time

    lm = TransformerLM(
        vocab_size=50304, d_model=768, n_heads=12, n_layers=12, d_ff=3072,
        max_len=prompt_len + new_tokens, dtype=jnp.bfloat16,
    )
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompts = [
        jnp.asarray(np.random.default_rng(s).integers(
            0, lm.vocab_size, size=(batch, prompt_len)), jnp.int32)
        for s in range(8)
    ]
    calls = {"i": 0}

    def one_call():  # rotate prompts: identical dispatches can be memoized
        calls["i"] += 1
        return generate(lm, params, prompts[calls["i"] % len(prompts)],
                        new_tokens, kv_quant=kv_quant)

    # single-call traces: the 256-iteration scan emits thousands of inner
    # spans per call, and a multi-call window overflows the profiler buffer
    # (observed: 4 forced calls, one surviving top-level span)
    t1 = device_time(one_call, calls=1, warmup=2)
    t2 = device_time(one_call, calls=1, warmup=0)
    per_call = (t1.per_call_s + t2.per_call_s) / 2
    rate = batch * new_tokens / per_call

    # --- roofline: MANDATORY bytes per step, a lower bound on achieved
    # HBM bandwidth. Weights count at the compute dtype (XLA hoists the
    # one-time f32→bf16 conversion out of the scanned loop, so steady-state
    # steps read the bf16 copies — counting stored-f32 bytes measured an
    # impossible 111% at batch 8); K/V counts the average live cache read.
    n_params = sum(leaf.size for leaf in jax.tree.leaves(params))
    param_bytes = n_params * jnp.dtype(lm.dtype).itemsize
    d_model, n_layers = lm.d_model, lm.n_layers
    avg_len = prompt_len + new_tokens / 2  # cache grows as tokens emit
    if kv_quant:
        # int8 values + one f32 scale per (head, position) per K and V
        kv_bytes_per_step = batch * 2 * n_layers * avg_len * (
            d_model * 1 + lm.n_heads * 4)
    else:
        kv_bytes_per_step = batch * 2 * n_layers * d_model * avg_len * 2  # bf16 K+V
    bytes_per_step = param_bytes + kv_bytes_per_step
    steps_per_s = rate / batch
    achieved_bw = bytes_per_step * steps_per_s
    frac = achieved_bw / 819e9

    # the MEASURED roofline: what a pure streaming read actually sustains on
    # this chip (nameplate 819 GB/s is never reachable — measured 714-720
    # GB/s on 256 MB-1 GB sums, ~87% of nameplate). Decode efficiency is
    # judged against what the memory system demonstrably delivers.
    stream = jnp.ones((128 * 1024 * 1024,), jnp.bfloat16)  # 256 MB
    t_read = device_time(
        jax.jit(lambda x: jnp.sum(x, dtype=jnp.float32)), stream,
        calls=6, warmup=2)
    measured_bw = stream.size * 2 / t_read.per_call_s
    frac_measured = achieved_bw / measured_bw
    log(f"decode: {per_call * 1e3:.1f} ms per {new_tokens}-token generation "
        f"(batch {batch}, device-true) → {rate:.0f} tokens/s; "
        f"{bytes_per_step / 1e6:.0f} MB/step mandatory "
        f"({param_bytes / 1e6:.0f} bf16 params + {kv_bytes_per_step / 1e6:.0f} KV) "
        f"→ ≥{achieved_bw / 1e9:.0f} GB/s = {100 * frac:.0f}% of 819 GB/s "
        f"nameplate, {100 * frac_measured:.0f}% of the measured "
        f"{measured_bw / 1e9:.0f} GB/s streaming roofline")
    return rate, frac_measured, bytes_per_step


def bench_hostfed_resnet50(batch: int = 256, steps: int = 8, trials: int = 3):
    """Overlapped-input leg (VERDICT r1 #1): every step's batch starts in
    host RAM and is ``device_put`` while the device runs the previous step —
    the per-step trainer path a real data loader feeds. jax's async dispatch
    does the overlap: the host loop enqueues transfer(i+1) + step(i+1)
    before step(i) finishes; the closing loss fetch forces the chain.
    Returns None when the host link makes the leg meaningless (< 1 img/s).
    """
    import jax
    import jax.numpy as jnp

    from bench import Rate
    from distributed_ml_pytorch_tpu.models import get_resnet
    from distributed_ml_pytorch_tpu.training.trainer import (
        create_train_state,
        make_train_step,
    )
    from distributed_ml_pytorch_tpu.utils.flops import compiled_flops

    model = get_resnet("resnet50", num_classes=1000, dtype=jnp.bfloat16)
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05,
                                   sample_shape=(1, 224, 224, 3))
    step = make_train_step(model, tx)
    rng = jax.random.key(1)
    # distinct host batches, pre-cast to bf16 on the host (what a real
    # loader would ship: half the bytes of f32 over the link)
    host = [np.random.default_rng(s).normal(
                size=(batch, 224, 224, 3)).astype(jnp.bfloat16)
            for s in range(4)]
    labels = jax.device_put(np.arange(batch, dtype=np.int32) % 1000)

    flops = compiled_flops(step, state, jax.ShapeDtypeStruct(
        (batch, 224, 224, 3), jnp.bfloat16), labels, rng)

    def run(n):
        nonlocal state
        t0 = time.perf_counter()
        loss = None
        for i in range(n):
            bx = jax.device_put(host[i % len(host)])
            state, loss = step(state, bx, labels, rng)
        float(loss)
        return time.perf_counter() - t0

    try:
        run(2)  # compile + warm
    except Exception as e:
        log(f"host-fed resnet50 leg failed: {e}")
        return None
    short = min(run(1) for _ in range(trials))
    long_ = min(run(steps) for _ in range(trials))
    per_step = (long_ - short) / (steps - 1)
    rate = Rate.make(batch / per_step, flops, per_step)
    log(f"host-fed resnet50: {per_step * 1e3:.1f} ms/step incl. host→device "
        f"batch transfer → {rate:.0f} img/s ({rate.mfu_note()})")
    if rate < 1.0:  # host link so slow the leg measures nothing but it
        log("host-fed leg suppressed (< 1 img/s — link-bound, not a "
            "framework measurement)")
        return None
    return rate


def ps_phase() -> None:
    # config 3 — 1 server + 4 workers, real processes, TCP transport
    from distributed_ml_pytorch_tpu.launch import launch_world

    n_workers = 4
    per_worker = 512  # this box exposes 1 core; 5 processes contend for it
    t0 = time.perf_counter()
    code = launch_world(
        n_workers + 1,
        ["--epochs", "1", "--synthetic-data",
         "--synthetic-train-size", str(per_worker),
         "--synthetic-test-size", "64",
         "--log-interval", "100000"],  # no mid-epoch eval in the timed window
    )
    dt = time.perf_counter() - t0
    if code != 0:
        log(f"config 3 FAILED with exit code {code}")
        return
    agg = n_workers * per_worker / dt
    emit(3, "async_ps_4worker_aggregate_throughput", agg, "images/sec",
         "5 cpu processes",
         f"{n_workers} workers x {per_worker} images in {dt:.1f}s wall, "
         "startup+compile included (the reference's launch pattern)")


_SHARD_RTT_SERVER_SRC = """
import sys
import numpy as np
from distributed_ml_pytorch_tpu.parallel.sharded_ps import make_shard_server
from distributed_ml_pytorch_tpu.utils.messaging import make_transport

shard, k, n, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
t = make_transport(0, 2, port=port, kind="python", connect_timeout=120)
try:
    server = make_shard_server(params=np.zeros(n, np.float32), shard=shard,
                               n_shards=k, transport=t, n_workers=1)
    server.run()
finally:
    t.close()
"""


def bench_sharded_push_rtt(k: int, flat: "np.ndarray", rounds: int = 20,
                           warmup: int = 3):
    """Mean end-to-end push+pull round trip against ``k`` real TCP shard
    server processes (VERDICT r3 #7): one timed round = send every shard its
    slice of the full lr-pre-scaled gradient, request every slice back, and
    block until all ``k`` replies arrive. Returns seconds/roundtrip or None
    if a server process fails."""
    import subprocess
    import sys as _sys

    from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env
    from distributed_ml_pytorch_tpu.parallel.async_ps import Listener
    from distributed_ml_pytorch_tpu.parallel.sharded_ps import shard_ranges
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode,
        make_transport,
        send_message,
    )

    n = flat.shape[0]
    ranges = shard_ranges(n, k)
    ports = [_free_port() for _ in range(k)]
    env = cpu_platform_env()
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [_sys.executable, "-c", _SHARD_RTT_SERVER_SRC,
             str(s), str(k), str(n), str(ports[s])],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for s in range(k)
    ]
    transports, listeners = [], []
    grad = np.full(n, -1e-3, np.float32)
    times = []
    try:
        transports = [
            make_transport(1, 2, port=p, kind="python", connect_timeout=120)
            for p in ports
        ]
        listeners = [Listener(transport=t) for t in transports]
        for listener in listeners:
            listener.start()
        for s, (lo, hi) in enumerate(ranges):  # install central params
            send_message(MessageCode.ParameterUpdate, flat[lo:hi],
                         transport=transports[s])
        for r in range(warmup + rounds):
            t0 = time.perf_counter()
            for s, (lo, hi) in enumerate(ranges):
                send_message(MessageCode.GradientUpdate, grad[lo:hi],
                             transport=transports[s])
            for s in range(k):
                send_message(MessageCode.ParameterRequest,
                             np.zeros(0, np.float32), transport=transports[s])
            deadline = time.perf_counter() + 120.0
            for s, listener in enumerate(listeners):
                while listener.take_latest() is None:
                    if time.perf_counter() > deadline:
                        raise TimeoutError(f"shard {s} reply never arrived")
                    time.sleep(0.0005)
            if r >= warmup:
                times.append(time.perf_counter() - t0)
        for s in range(k):
            send_message(MessageCode.WorkerDone, np.zeros(0, np.float32),
                         transport=transports[s])
    except (TimeoutError, OSError, ConnectionError) as e:
        log(f"sharded push-rtt k={k} FAILED: {e}")
        for p in procs:
            p.kill()
        return None
    finally:
        for listener in listeners:
            listener.stop()
        for t in transports:
            t.close()
    for p in procs:
        try:
            p.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()
    rtt = float(np.mean(times))
    log(f"sharded-PS e2e push round-trip, k={k}: {rtt * 1e3:.1f} ms mean "
        f"over {rounds} rounds ({n * 4 / 1e6:.1f} MB gradient split into "
        f"{k} slice(s); min {min(times) * 1e3:.1f} / max {max(times) * 1e3:.1f})")
    return rtt


def sharded_ps_phase() -> None:
    """Config 3, sharded-PS leg (VERDICT r2 #7): quantify the 1/k design
    claim of ``sharded_ps.py`` — per-shard server bandwidth and apply cost
    scale as 1/k — and measure the end-to-end world at k ∈ {1, 2, 4}.

    Two measurements, because this 1-core host confounds them when mixed:
    (a) real-process worlds (k shard servers + 2 workers over TCP):
        aggregate worker img/s — k+2 processes CONTEND for one core, so
        this validates the composed topology at each k rather than showing
        server-relief speedups (which need k hosts);
    (b) an in-process microbench of exactly the per-shard server work: the
        ``central += payload`` apply on an AlexNet-sized slice (N/k f32)
        — the bytes/push and apply seconds that each shard host is
        relieved of, the measurable substance of the 1/k claim.
    """
    from distributed_ml_pytorch_tpu.launch import launch_world
    from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer
    from distributed_ml_pytorch_tpu.parallel.sharded_ps import shard_ranges

    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import get_model

    model = get_model("alexnet")
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    flat = np.asarray(ravel_model_params(params), np.float32)
    n = flat.shape[0]

    # (b) per-shard apply microbench
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    for k in (1, 2, 4):
        lo, hi = shard_ranges(n, k)[0]
        slice_vec = flat[lo:hi].copy()
        payload = np.random.default_rng(0).normal(size=hi - lo).astype(np.float32)
        server = ParameterServer(params=slice_vec)
        reps = 50
        t0 = time.perf_counter()
        for _ in range(reps):
            server.handle(1, MessageCode.GradientUpdate, payload)
        per_apply = (time.perf_counter() - t0) / reps
        emit(3, f"sharded_ps_per_shard_apply_k{k}", per_apply * 1e6,
             "microseconds/push", "1 cpu core",
             f"server-side `central += payload` on the {hi - lo:,}-element "
             f"slice ({(hi - lo) * 4 / 1e6:.1f} MB/push wire payload) — "
             f"the per-shard-host cost the 1/k design divides")

    # (c) END-TO-END push round-trip latency, k=1 vs k=2, same worker
    # (VERDICT r3 #7): one real worker process measures
    # push(GradientUpdate slices to all k shards) + pull(ParameterRequest)
    # + wait(all k replies) as one timed round trip over real TCP server
    # processes. This is the system-level form of the 1/k claim: each
    # shard serializes/applies/replies half the bytes at k=2. CAVEAT: all
    # k+1 processes share ONE core here, so server-side apply overlap
    # (the actual multi-host win) cannot show; what CAN show is the wire
    # + apply pipeline on half-size payloads per shard.
    for k in (1, 2):
        rtt = bench_sharded_push_rtt(k, flat)
        if rtt is not None:
            emit(3, f"sharded_ps_e2e_push_rtt_k{k}", rtt * 1e3,
                 "milliseconds/roundtrip", f"{k + 1} cpu processes, TCP",
                 f"mean steady-state push+pull round trip of the full "
                 f"{n * 4 / 1e6:.1f} MB gradient against {k} real shard "
                 f"server process(es); one shared core — see (b) for the "
                 "uncontended per-shard substance")

    # (a) real-process worlds
    per_worker = 384
    batch = 16
    for k in (1, 2, 4):
        t0 = time.perf_counter()
        code = launch_world(
            k + 2,
            ["--epochs", "1", "--synthetic-data",
             "--synthetic-train-size", str(per_worker),
             "--synthetic-test-size", "64",
             "--batch-size", str(batch),
             "--log-interval", "100000"],
            n_servers=k,
        )
        dt = time.perf_counter() - t0
        if code != 0:
            log(f"sharded_ps k={k} FAILED with exit code {code}")
            continue
        agg = 2 * per_worker / dt
        emit(3, f"sharded_ps_k{k}_aggregate_throughput", agg, "images/sec",
             f"{k + 2} cpu processes",
             f"2 workers x {per_worker} images against {k} shard server(s) "
             f"in {dt:.1f}s wall (startup+compile included); all processes "
             "share ONE core, so cross-k deltas here are contention, not "
             "server relief — see sharded_ps_per_shard_apply_k* for the "
             "1/k substance")


def elastic_phase() -> None:
    """Config 3, elastic-control-plane leg (ISSUE 3): steady-state worker
    throughput BEFORE / DURING / AFTER a coordinator-driven shard
    rebalance. One in-process fleet (coordinator + 2 elastic shard servers
    + 2 DownPour workers on LeNet); shard server 2 is silently crashed
    mid-run, the coordinator detects it by lease expiry and pushes a new
    map, workers drain + cut over + install the moved range. Windows are
    split on worker 1's step timeline: [warmup, crash), [crash, cutover),
    [cutover, end) — the DURING window prices what a rebalance costs the
    data plane (stale-map drops + the cutover drain), and AFTER shows
    throughput recovered with the fleet one server smaller."""
    import time as _time

    from distributed_ml_pytorch_tpu.coord.demo import elastic_scenario

    batch = 16
    crash_at = 24
    times: dict = {}
    cut: dict = {}

    def hook(j, step, opt):
        if j == 1:
            times[step] = _time.perf_counter()
            if opt.map_version >= 3 and "step" not in cut:
                cut["step"] = step  # first step on the post-crash map

    out = elastic_scenario(
        steps=72, n_workers=2, n_shards=2, crash_shard_at=crash_at,
        lease=0.4, step_hook=hook)
    if not out["ok"] or "step" not in cut:
        log(f"elastic_phase incomplete: ok={out['ok']} cutover={cut} "
            f"events={out['events'][-5:]}")
        return

    def rate(a, b):
        ts = [times[s] for s in range(a, b) if s in times]
        if len(ts) < 3:
            return None
        return batch * (len(ts) - 1) / (ts[-1] - ts[0])

    before = rate(4, crash_at)  # skip warmup/compile steps
    during = rate(crash_at, cut["step"] + 1)
    after = rate(cut["step"] + 1, 72)
    for name, value, win in (
        ("before", before, f"steps 4-{crash_at}"),
        ("during", during, f"steps {crash_at}-{cut['step']} (crash -> "
                           "lease expiry -> map adopted)"),
        ("after", after, f"steps {cut['step'] + 1}-72, 1 shard left"),
    ):
        if value is None:
            log(f"elastic_phase: window {name} too short to rate")
            continue
        emit(3, f"elastic_rebalance_throughput_{name}", value,
             "images/sec/worker", "in-process fleet, 1 core",
             f"worker-1 steady state {win}; coordinator lease 0.4s; "
             "LeNet batch 16, cadence 2/2 (coord/demo.elastic_scenario)")
    log(f"elastic_phase: map v{out['map_version']}, cutover at worker step "
        f"{cut['step']}, server stats {out['stats']}")


def recovery_phase() -> None:
    """Config 3, durability-plane leg (ISSUE 5): the full disaster-recovery
    drill — coordinator-aligned snapshot barrier, ALL shard servers killed
    silently mid-epoch, fleet restored from FleetManifest + per-shard WALs —
    priced as MTTR (kill → every restored shard serving pulls again), pure
    restore time (manifest load + checkpoint restore + WAL replay), and the
    replayed-update count, with the acked-vs-applied sequence accounting
    reported as the loss-freedom check."""
    import tempfile

    from distributed_ml_pytorch_tpu.coord.drill import (
        default_drill_plan,
        recovery_drill,
    )

    out = recovery_drill(
        base_dir=tempfile.mkdtemp(prefix="bench_drill_"), seed=0,
        plan=default_drill_plan(0))
    if not out["ok"] or out["mttr_s"] is None:
        log(f"recovery_phase incomplete: ok={out['ok']} "
            f"errors={out['errors']} events={out['events'][-5:]}")
        return
    acked = sum(sum(d.values()) for d in out["acked"].values())
    applied = sum(sum(d.values()) for d in out["applied"].values())
    emit(3, "recovery_mttr", out["mttr_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         "kill ALL shards mid-epoch -> manifest+WAL restore -> every shard "
         f"serving pulls again; {out['replayed_updates']} WAL update(s) "
         f"replayed; acked={acked} <= applied={applied} (zero acked loss); "
         "2 workers + 2 shards, LeNet, coord/drill.recovery_drill")
    emit(3, "recovery_restore", out["restore_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         "manifest load + checkpoint restore + WAL replay + dedup reseed "
         "for both shards (the MTTR component the durability plane owns)")
    log(f"recovery_phase: mttr {out['mttr_s'] * 1e3:.0f} ms, restore "
        f"{out['restore_s'] * 1e3:.0f} ms, replayed "
        f"{out['replayed_updates']}, chaos {out['chaos_counts']}")


def coordfail_phase() -> None:
    """Config 3, control-plane durability leg (ISSUE 17): the
    kill-the-COORDINATOR drill — snapshot barrier broadcast, arbiter
    crashed before the dones land, restarted from its own checkpoint+WAL
    — priced as control-plane MTTR (kill → every member re-attached to
    the successor epoch, grace window closed by traffic), the durable
    restore time (epoch bump + ckpt load + WAL replay), and the
    steps/tokens the fleet lost to the outage (zero is the claim:
    workers train fail-open on the last shard map throughout)."""
    import tempfile

    from distributed_ml_pytorch_tpu.coord.drill import coordfail_drill

    steps, n_workers, batch = 20, 2, 16
    out = coordfail_drill(
        base_dir=tempfile.mkdtemp(prefix="bench_coordfail_"), seed=0,
        steps=steps, kill_during="snapshot")
    if not out["ok"] or out["mttr_s"] is None:
        log(f"coordfail_phase incomplete: ok={out['ok']} "
            f"errors={out['errors']} violations={out['violations']} "
            f"events={out['events2'][-5:]}")
        return
    steps_done = sum(len(l) for l in out["losses"].values())
    steps_lost = steps * n_workers - steps_done
    tokens_lost = steps_lost * batch
    emit(3, "coordfail_mttr", out["mttr_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         "kill the coordinator mid-snapshot-barrier -> restart from its "
         f"ckpt+WAL (epoch {out['epochs'][0]} -> {out['epochs'][1]}) -> "
         f"every member re-attached; {out['restored_members']} member(s) "
         f"restored, {len(out['evictions'])} evicted during the grace "
         f"window; {steps_lost} of {steps * n_workers} worker steps "
         f"({tokens_lost} samples) lost to the outage (fail-open); "
         "2 workers + 2 shards, LeNet, coord/drill.coordfail_drill")
    emit(3, "coordfail_restore", out["restore_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         "persisted-epoch bump + checkpoint load + control-plane WAL "
         "replay (member table, map/snapshot clocks, park table, "
         "scheduler ledger) — the MTTR component the durable "
         "coordinator owns")
    log(f"coordfail_phase: mttr {out['mttr_s'] * 1e3:.0f} ms, restore "
        f"{out['restore_s'] * 1e3:.0f} ms, outage "
        f"{out['outage_s'] * 1e3:.0f} ms, steps lost {steps_lost}, "
        f"chaos {out['chaos_counts']}")


def gray_phase() -> None:
    """Config 3, gray-failure leg (ISSUE 20): the SAME windowed one-way
    partition (workers' pull requests toward shard 0 vanish, its renewals
    keep flowing) run twice — containment ON (GrayHealth detects on the
    workers' renew-tail link evidence, parks the victim, resumes it
    bit-identically) vs OFF (nobody acts; the episode drains only through
    retransmit back-off). Priced as goodput over the identical fixed
    script, detection latency (gray onset -> PROBATION), and containment
    MTTR (PROBATION -> parked). Detection latency is gated against
    ``gray_detection_latency_ceiling_s`` in bench_floors.json — a slower
    detector widens the window in which a gray node poisons the fleet."""
    import tempfile

    from distributed_ml_pytorch_tpu.coord.drill import gray_drill

    steps, n_workers = 170, 2
    on = gray_drill(
        base_dir=tempfile.mkdtemp(prefix="bench_gray_on_"), seed=0,
        steps=steps, n_workers=n_workers)
    if (not on["ok"] or on["detect_latency_s"] is None
            or on["containment_mttr_s"] is None
            or on["fixed_wall_s"] is None):
        log(f"gray_phase incomplete (containment leg): ok={on['ok']} "
            f"errors={on['errors']} violations={on['violations']}")
        return
    off = gray_drill(
        base_dir=tempfile.mkdtemp(prefix="bench_gray_off_"), seed=0,
        steps=steps, n_workers=n_workers, contain=False)
    if not off["ok"] or off["fixed_wall_s"] is None:
        log(f"gray_phase incomplete (unmanaged leg): ok={off['ok']} "
            f"errors={off['errors']} violations={off['violations']}")
        return
    fixed = steps * n_workers
    goodput_on = fixed / on["fixed_wall_s"]
    goodput_off = fixed / off["fixed_wall_s"]
    # raw steps/s barely moves either way — the workers fail OPEN to
    # purely-local SGD on a downed slice and keep stepping. What
    # containment protects is CENTRAL aggregation on the gray slice:
    # worker deltas the victim shard actually applied, per second.
    central_on = sum(on["applied"][0].values()) / on["wall_s"]
    central_off = sum(off["applied"][0].values()) / off["wall_s"]
    emit(3, "gray_victim_slice_goodput_contained", central_on,
         "applied updates/s", "in-process fleet, 1 core",
         "central aggregation rate on the GRAY slice with the ladder "
         "live — the PRICE of containment: the park window trades some "
         "episode throughput for a BOUNDED recovery (victim on "
         f"PROBATION in {on['detect_latency_s'] * 1e3:.0f} ms, parked, "
         f"resumed bit_identical={on['bit_identical']}, ladder cleared, "
         f"evictions={on['gray']['evictions']}) vs {central_off:.1f} "
         "applied/s unmanaged, where the grind is open-ended and the "
         "slice's pull freshness is gone for the whole episode; raw "
         f"worker steps/s {goodput_on:.1f} vs {goodput_off:.1f} over "
         f"the identical {fixed}-step fixed script (fail-open local SGD "
         "keeps raw stepping alive either way) — coord/drill.gray_drill")
    emit(3, "gray_victim_slice_goodput_unmanaged", central_off,
         "applied updates/s", "in-process fleet, 1 core",
         "the comparison leg: identical gray episode, suspicion pinned "
         "off — the victim slice grinds on retransmit back-off + open "
         "circuits for the whole episode while its deltas drift "
         "local-only")
    emit(3, "gray_detect_latency", on["detect_latency_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         "gray onset (first chaos-matched pull) -> victim on PROBATION, "
         "confirmed over 2 suspicious ticks of renew-tail link evidence "
         "from both workers")
    emit(3, "gray_containment_mttr", on["containment_mttr_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         "PROBATION -> checkpoint-parked via the gray-granted preempt "
         "path (snapshot barrier + WAL'd park ticket); the victim never "
         "lease-expires and is never revoked")
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_floors.json")) as fh:
        ceiling = json.load(fh)["gray_detection_latency_ceiling_s"]
    log(f"gray_phase: victim-slice {central_on:.1f} vs {central_off:.1f} "
        f"applied/s, raw {goodput_on:.1f} vs {goodput_off:.1f} steps/s "
        f"(contained vs unmanaged), detect "
        f"{on['detect_latency_s'] * 1e3:.0f} ms (ceiling {ceiling}s), "
        f"mttr {on['containment_mttr_s'] * 1e3:.0f} ms, chaos "
        f"{on['chaos_counts']}")
    if on["detect_latency_s"] > ceiling:
        raise RuntimeError(
            f"gray detection latency {on['detect_latency_s']:.2f}s "
            f"exceeds the {ceiling}s ceiling in bench_floors.json — the "
            "suspicion plane got slow enough to let a gray node poison "
            "the fleet for whole episodes")


def _serving_slot_rate() -> tuple:
    """Tokens/s one engine slot serves (a real ``ServingEngine`` burst,
    compile outside the timed window) plus its p50 TTFT — the measured
    rate ``sched_phase`` prices borrowed-slot serving goodput with."""
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine

    lm = TransformerLM(vocab_size=128, d_model=64, n_heads=2, n_layers=2,
                       d_ff=128, max_len=256)
    params = lm.init(jax.random.key(0),
                     jnp.zeros((1, 8), jnp.int32))["params"]
    engine = ServingEngine(lm, params, slots=4, cache_size=128)
    w = engine.submit(np.zeros(16, np.int32), 10)
    engine.run_until_idle()
    assert w.done
    engine.reset_metrics()
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    handles = [
        engine.submit(rng.integers(0, 128, size=16).astype(np.int32), 24)
        for _ in range(8)]
    engine.run_until_idle()
    burst = time.perf_counter() - t0
    tokens = sum(len(h.tokens) for h in handles)
    ttft = engine.slo_summary().get("ttft_ms") or {}
    return tokens / max(burst, 1e-9), ttft.get("p50")


def sched_phase() -> None:
    """Config 3, scheduler-plane leg (ISSUE 16): the multi-tenant
    day-in-the-life under seeded wire chaos. The ``FleetScheduler``
    preempts a LIVE training shard at the serving peak (snapshot barrier
    -> park under the FleetManifest), lends its slot to the serving
    tenant, and resumes it bit-for-bit off-peak (checkpoint +
    exactly-once WAL replay, rejoining as a newer incarnation). Priced as
    preempt/resume MTTR plus AGGREGATE GOODPUT — training steps in the
    loss corridor + serving tokens in SLO — for the shared-scheduler
    fleet vs two statically partitioned half-fleets over the same
    measured day."""
    import tempfile

    from distributed_ml_pytorch_tpu.coord.drill import (
        default_drill_plan,
        sched_drill,
    )

    out = sched_drill(base_dir=tempfile.mkdtemp(prefix="bench_sched_"),
                      seed=0, plan=default_drill_plan(0))
    s = out["sched"]
    if not out["ok"] or not s["preempt_mttr_s"] or not s["resume_mttr_s"]:
        log(f"sched_phase incomplete: ok={out['ok']} "
            f"violations={out['violations']} errors={out['errors']}")
        return
    preempt_mttr = s["preempt_mttr_s"][0]
    resume_mttr = s["resume_mttr_s"][0]
    emit(3, "sched_preempt_mttr", preempt_mttr * 1e3, "ms",
         "in-process fleet, 1 core",
         "serving demand spike -> snapshot barrier -> PreemptRequest -> "
         "live training shard parks under the FleetManifest and its slot "
         "is granted to the serving tenant; 2 workers + 2 shards under "
         "seeded wire chaos (coord/sched.FleetScheduler via "
         "coord/drill.sched_drill)")
    emit(3, "sched_resume_mttr", resume_mttr * 1e3, "ms",
         "in-process fleet, 1 core",
         "off-peak revoke -> ResumeRequest -> fresh server restores the "
         f"manifest checkpoint + replays {out['replayed_updates']} WAL "
         f"record(s) exactly once (bit-identical: {out['bit_identical']}) "
         "and rejoins as a newer incarnation of the same rank")

    # ---- aggregate goodput: shared scheduler vs static half-fleets ----
    wall = out["wall_s"]
    peak = out["peak_window_s"] or 0.0
    in_corridor = all(np.mean(l[-4:]) < np.mean(l[:4])
                      for l in out["losses"].values())
    train_steps = sum(len(l) for l in out["losses"].values())
    serve_rate, ttft_p50 = _serving_slot_rate()
    # shared day: both slots train off-peak; one is lent for the peak
    # window, and the transitions cost the measured MTTRs
    shared_train_slot_s = 2 * wall - peak
    shared_serve_s = max(0.0, peak - preempt_mttr)
    # static halves: one slot trains all day, one serves all day — but
    # serving only has live demand during the peak window, so the
    # dedicated slot's off-peak seconds produce no goodput
    static_train_slot_s = wall
    static_serve_s = peak
    shared_tokens = serve_rate * shared_serve_s
    static_tokens = serve_rate * static_serve_s
    # static training steps: linear-in-slot-seconds extrapolation from
    # the measured shared day (stated as such in the record)
    static_train_steps = (
        train_steps * static_train_slot_s / max(shared_train_slot_s, 1e-9))
    shared_useful = shared_train_slot_s + shared_serve_s - resume_mttr
    static_useful = static_train_slot_s + static_serve_s
    emit(3, "sched_goodput_uplift", shared_useful / static_useful, "x",
         "derived",
         "demand-weighted useful slot-seconds, shared FleetScheduler vs "
         "two statically partitioned half-fleets over the SAME measured "
         "day: static dedicates one slot to serving that only has live "
         "demand during the peak window, shared lends the training slot "
         "at peak (preempt) and takes it back off-peak (resume), paying "
         "only the measured MTTRs; serving tokens priced at a real "
         "ServingEngine's measured burst rate",
         extra={
             "day_s": round(wall, 2),
             "peak_window_s": round(peak, 2),
             "shared": {
                 "train_steps": train_steps,
                 "train_in_loss_corridor": bool(in_corridor),
                 "serve_tokens_in_slo": int(shared_tokens),
                 "useful_slot_s": round(shared_useful, 2),
             },
             "static": {
                 "train_steps_extrapolated": int(static_train_steps),
                 "serve_tokens_in_slo": int(static_tokens),
                 "useful_slot_s": round(static_useful, 2),
             },
             "serve_tokens_per_s": round(serve_rate, 1),
             "serve_ttft_p50_ms": ttft_p50,
         })
    log(f"sched_phase: preempt {preempt_mttr * 1e3:.0f} ms, resume "
        f"{resume_mttr * 1e3:.0f} ms, day {wall:.1f}s (peak {peak:.1f}s), "
        f"goodput uplift {shared_useful / static_useful:.2f}x, replayed "
        f"{out['replayed_updates']}, chaos {out['chaos_counts']}")


def mpmd_phase() -> None:
    """Config 3, MPMD-pipeline-plane leg (ISSUE 10): a 4-stage pipeline of
    fleet members over the reliable in-process wire. Leg 1 (steady state):
    tokens/s through the fault-free fleet plus the measured BUBBLE
    fraction (1 - sum of per-stage busy seconds / (stages x wall)). Leg 2
    (stage kill): the middle stage is killed mid-schedule and restarted
    from its per-stage checkpoint — stage-restart MTTR (vacancy ->
    replacement StageReady) with throughput before/during/after, and the
    applied-microbatch accounting reported as the no-double-apply check."""
    import tempfile

    from distributed_ml_pytorch_tpu.coord.stages import mpmd_scenario

    # all shape knobs passed EXPLICITLY so the rates below can never skew
    # against a changed scenario default
    steps, n_stages, M, mb, seq = 16, 4, 4, 4, 8
    shape = dict(n_stages=n_stages, n_microbatches=M, mb=mb, seq=seq)
    warm = mpmd_scenario(base_dir=tempfile.mkdtemp(prefix="bench_mpmd_"),
                         seed=0, steps=4, **shape)
    if not warm["ok"]:
        log(f"mpmd_phase warmup incomplete: {warm['errors']}")
        return
    out = mpmd_scenario(base_dir=tempfile.mkdtemp(prefix="bench_mpmd_"),
                        seed=0, steps=steps, **shape)
    if not out["ok"] or out["wall_s"] is None:
        log(f"mpmd_phase steady leg incomplete: ok={out['ok']} "
            f"errors={out['errors']}")
        return
    tok_per_step = M * mb * seq
    steady = tok_per_step * (steps - 1) / out["wall_s"]
    bubble = max(0.0, 1.0 - out["busy_s"] / (n_stages * out["wall_s"]))
    emit(3, "mpmd_pipeline_steady", steady, "tokens/sec",
         "in-process fleet, 1 core",
         f"{n_stages}-stage MPMD pipeline (per-stage compiled programs "
         f"over ReliableTransport), M={M} microbatches of {mb}x{seq} "
         "tokens; driver step cadence, fault-free "
         "(coord/stages.mpmd_scenario)")
    # the flight-recorder decomposition of that bubble (ISSUE 12): merge
    # the run's per-member dumps and attribute each stage's wall clock to
    # its exclusive serve-loop states — schema-gated so a malformed
    # attribution can never ship in the record
    attribution = None
    try:
        from distributed_ml_pytorch_tpu.analysis import timeline

        report = timeline.analyze(out["obs_dir"])
        attribution = check_bubble_attribution(
            report["bubble_attribution"])
        log(f"mpmd_phase: flight-recorder dumps in {out['obs_dir']} "
            f"(analyze anytime: make timeline TIMELINE_DIR={out['obs_dir']})")
    except (ValueError, OSError, KeyError) as e:
        log(f"mpmd_phase: bubble attribution unavailable: {e!r}")
    emit(3, "mpmd_bubble_fraction", bubble * 100.0, "%",
         "in-process fleet, 1 core",
         "1 - sum(stage busy s) / (stages x wall s) over the steady run — "
         "idle share of stage-seconds (schedule bubble + wire wait); "
         "bubble_attribution decomposes it per flight-recorder state "
         "(analysis/timeline.py over the run's obs dumps)",
         extra=({"bubble_attribution": attribution}
                if attribution is not None else None))

    kill_at = 6
    out = mpmd_scenario(base_dir=tempfile.mkdtemp(prefix="bench_mpmd_"),
                        seed=0, steps=steps, kill_stage=1,
                        kill_at_step=kill_at, snapshot_at_step=2, **shape)
    if not out["ok"] or out["stage_mttr_s"] is None:
        log(f"mpmd_phase kill leg incomplete: ok={out['ok']} "
            f"errors={out['errors']} events={out['events'][-5:]}")
        return
    emit(3, "mpmd_stage_restart_mttr", out["stage_mttr_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         f"middle stage killed at its step {kill_at} (silent; lease "
         "expiry detection) -> checkpoint restart -> StageReady; "
         "watermark-bounded replay refilled the in-flight microbatches "
         f"(applied accounting {'OK' if out['applied_ok'] else 'BROKEN'}: "
         "no microbatch applied twice)")

    # throughput before/during/after on the driver's step-completion
    # timeline (step_times[i] = completion instant of step i)
    ts = out["step_times"]

    def rate(a, b):
        if b - a < 2 or b > len(ts):
            return None
        return tok_per_step * (b - 1 - a) / (ts[b - 1] - ts[a])

    for name, value, win in (
        ("before", rate(1, kill_at), f"steps 1-{kill_at}"),
        ("during", rate(kill_at, kill_at + 4),
         f"steps {kill_at}-{kill_at + 4} (kill -> lease expiry -> "
         "restart -> replay)"),
        ("after", rate(kill_at + 4, steps), f"steps {kill_at + 4}-{steps}"),
    ):
        if value is None:
            log(f"mpmd_phase: window {name} too short to rate")
            continue
        emit(3, f"mpmd_stage_kill_throughput_{name}", value, "tokens/sec",
             "in-process fleet, 1 core",
             f"driver step-completion rate {win}; 4-stage pipeline, "
             "middle stage killed and restarted from its checkpoint")
    log(f"mpmd_phase: kill leg driver stats {out['driver_stats']}, "
        f"events {out['events'][-3:]}")


def health_phase() -> None:
    """Config 3, numerical-health leg (ISSUE 8): the immune-system scenario
    — 2 workers + 2 WAL'd shards behind the admission gate, one worker's
    push channel under seeded SDC (gate-slipping scale corruption, then
    NaN) — priced as the quarantine reject rate, the worker-observed nack
    round-trips, and the coordinator auto-rollback MTTR (watchdog trigger
    -> every shard restored + reported), alongside the drill's recovery
    numbers."""
    import tempfile

    from distributed_ml_pytorch_tpu.coord.health import health_scenario

    out = health_scenario(
        base_dir=tempfile.mkdtemp(prefix="bench_health_"), seed=0)
    if not out["ok"] or out["rollbacks"] < 1 or out["rollback_mttr_s"] is None:
        log(f"health_phase incomplete: ok={out['ok']} "
            f"rollbacks={out['rollbacks']} errors={out['errors']} "
            f"events={out['events'][-5:]}")
        return
    applied = sum(sum(d.values()) for d in out["applied"].values())
    quarantined = out["quarantined_total"]
    seen = applied + quarantined
    reject_rate = quarantined / seen if seen else 0.0
    nacks_heard = sum(out["worker_nacks"].values())
    emit(3, "health_reject_rate", 100.0 * reject_rate, "%",
         "in-process fleet, 1 core",
         f"admission gate: {quarantined} of {seen} arriving updates "
         f"quarantined (finiteness + per-worker EWMA z-score), every one "
         f"explicitly nacked ({out['nacks_sent_total']} UpdateNacks), zero "
         "in any WAL; 2 workers + 2 shards, one poisoned push channel, "
         "coord/health.health_scenario")
    emit(3, "health_nack_roundtrips", float(nacks_heard), "nacks",
         "in-process fleet, 1 core",
         "UpdateNacks that completed the round trip (server reject -> "
         "worker heard it, resynced by pulling fresh params and held its "
         f"in-flight update); {out['revoked_workers']} worker(s) "
         "reputation-revoked by the coordinator")
    emit(3, "health_rollback_mttr", out["rollback_mttr_s"] * 1e3, "ms",
         "in-process fleet, 1 core",
         "coordinator watchdog detects fleet loss divergence -> "
         "RollbackRequest barrier -> both shards restore the last good "
         "FleetManifest (ckpt + WAL capped at its apply seq) -> all "
         "RollbackDone reports in; workers drop accumulators and pull")
    log(f"health_phase: reject rate {100 * reject_rate:.1f}%, "
        f"{nacks_heard} nack round-trips, rollback mttr "
        f"{out['rollback_mttr_s'] * 1e3:.0f} ms, "
        f"revoked {out['revoked_workers']}, chaos {out['chaos_counts']}")


def _steady_rate_from_csv(path: str, batch: int):
    """Steady-state img/s from a trainer CSV's per-iteration timestamps:
    MEAN inter-step gap over the second half of the run (warmup/compile
    excluded by construction). Mean, not median: chunk-dispatched workers
    log a burst of per-step records at each chunk boundary, so the gap
    distribution is bimodal (≈0 within a burst, chunk-time at boundaries)
    and a median would see only the zeros; the tail mean is exactly
    (t_end − t_mid)/steps either way. Returns (img_per_sec, n_steps) or
    None."""
    import pandas as pd

    if not os.path.isfile(path):
        return None
    df = pd.read_csv(path)
    if len(df) < 8:
        return None
    gaps = pd.to_datetime(df["timestamp"]).diff().dt.total_seconds().iloc[1:]
    tail = gaps.iloc[len(gaps) // 2:]
    per_step = float(tail.mean())
    if per_step <= 0:
        return None
    return batch / per_step, len(df)


def ps_tpu_phase() -> None:
    """Config 3 (TPU leg, VERDICT r1 #2): the DownPour core with the real
    chip in the loop — CPU server + rank-1 worker pinned to the TPU — against
    the same recipe in single mode on the same chip. Both rates come from
    per-iteration CSV timestamps (``_steady_rate_from_csv``), so startup and
    compile are excluded and the delta isolates push/pull overhead (device→
    host ravel at the push cadence + install between steps; the per-step
    dispatch cost is identical in both legs)."""
    import tempfile

    import jax

    from distributed_ml_pytorch_tpu.launch import launch_world

    if jax.devices()[0].platform != "tpu":
        log("ps_tpu_phase skipped: no TPU attached")
        return
    batch = 64
    data_args = [
        "--batch-size", str(batch),  # rate math below derives from this
        "--epochs", "2", "--synthetic-data",
        "--synthetic-train-size", "16384", "--synthetic-test-size", "64",
        "--log-interval", "100000",
    ]
    ps_rate = single_rate = None
    with tempfile.TemporaryDirectory() as td:
        code = launch_world(2, data_args + ["--log-dir", td], tpu_worker_rank=1)
        if code != 0:
            log(f"ps-with-tpu-worker FAILED with exit code {code}")
        else:
            got = _steady_rate_from_csv(os.path.join(td, "node1.csv"), batch)
            if got:
                ps_rate, n = got
                emit(3, "async_ps_tpu_worker_throughput", ps_rate,
                     "images/sec/chip", "cpu server + 1x tpu worker",
                     f"steady-state from {n} per-step CSV timestamps; "
                     "DownPour cadence 10/10 with chunked dispatch (one "
                     "compiled scan per between-comm run, VERDICT r2 #2)")
    with tempfile.TemporaryDirectory() as td:
        code = subprocess.run(
            [sys.executable, "-m", "distributed_ml_pytorch_tpu.training.cli",
             "--no-distributed", "--steps-per-dispatch", "10",
             "--log-dir", td] + data_args,
            env=dict(os.environ),
        ).returncode
        if code != 0:
            log(f"single-mode comparison leg FAILED with exit code {code}")
        else:
            got = _steady_rate_from_csv(os.path.join(td, "tpu.csv"), batch)
            if got:
                single_rate, n = got
                emit(3, "single_mode_scanned_throughput", single_rate,
                     "images/sec/chip", "1x tpu",
                     f"same recipe at --steps-per-dispatch 10 (the chunk "
                     f"size the PS cadence implies), {n} per-step records "
                     "— the PS delta is protocol cost, not dispatch")
    if ps_rate and single_rate:
        emit(3, "async_ps_push_pull_overhead", 100 * (1 - ps_rate / single_rate),
             "percent", "derived",
             "throughput cost of the PS protocol for a TPU worker vs the "
             "same-chunk-size scanned single-mode recipe; on THIS rig both "
             "legs are bounded by the tunnel's ~0.4-1s per device->host "
             "fetch (one 9.9 MB accum fetch per push cadence), not by "
             "DownPour itself — see async_ps_chunked_device_cycle")
    _ps_device_cycle_phase(batch)


def _ps_device_cycle_phase(batch: int) -> None:
    """The DownPour worker's device-side ceiling: one cadence cycle of
    chunked dispatches (lengths 1+9 at cadence 10/10) with NO host fetch —
    what the chunk-dispatch rework actually bought, measured without the
    tunnel's per-fetch cost (a TPU-VM pays ~2 ms for the 9.9 MB push fetch
    this rig pays ~1 s for)."""
    import time

    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import get_model
    from distributed_ml_pytorch_tpu.parallel.async_ps import (
        default_downpour_tx,
        init_downpour_accumulator,
        make_downpour_chunk_step,
    )

    model = get_model("alexnet")
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    _, n, pad, accum = init_downpour_accumulator(params)
    tx = default_downpour_tx(0.008)
    opt_state = tx.init(params)
    chunk_step = make_downpour_chunk_step(model, tx, pad)
    rng = jax.random.key(1)
    rnd = np.random.default_rng(0)

    def mkbatch(length):
        return (
            np.asarray(rnd.normal(size=(length, batch, 32, 32, 3)), np.float32),
            np.asarray(rnd.integers(0, 10, (length, batch))),
        )

    bxs1, bys1 = mkbatch(1)
    bxs9, bys9 = mkbatch(9)
    dx1, dy1 = jax.device_put(bxs1), jax.device_put(bys1)
    dx9, dy9 = jax.device_put(bxs9), jax.device_put(bys9)
    losses = None
    for _ in range(2):  # compile both scan lengths + warm
        params, opt_state, accum, losses = chunk_step(
            params, opt_state, accum, dx1, dy1, rng, 0)
        params, opt_state, accum, losses = chunk_step(
            params, opt_state, accum, dx9, dy9, rng, 1)
    float(losses[-1])

    def cycle_rate(x1, y1, x9, y9, reps=10):
        nonlocal params, opt_state, accum, losses
        t0 = time.perf_counter()
        for _ in range(reps):
            params, opt_state, accum, losses = chunk_step(
                params, opt_state, accum, x1, y1, rng, 0)
            params, opt_state, accum, losses = chunk_step(
                params, opt_state, accum, x9, y9, rng, 1)
        float(losses[-1])  # trailing fetch forces the chain
        return (time.perf_counter() - t0) / reps

    per_cycle = cycle_rate(dx1, dy1, dx9, dy9)
    with_xfer = cycle_rate(bxs1, bys1, bxs9, bys9)
    emit(3, "async_ps_chunked_device_cycle", 10 * batch / per_cycle,
         "images/sec/chip",
         "1x tpu, device-resident input",
         f"one 10-step DownPour cadence cycle as two compiled chunk "
         f"dispatches, forced completion ({per_cycle * 1e3:.1f} ms/cycle); "
         f"with per-cycle host batch upload it is "
         f"{10 * batch / with_xfer:.0f} img/s ({with_xfer * 1e3:.0f} ms) — "
         "this rig's tunnel moves host<->device data at ~15-50 MB/s, so "
         "the end-to-end PS row is transport-bound, not protocol-bound; "
         "round 2's per-step dispatch managed 669 img/s on the same rig")


def transport_phase() -> None:
    """Config 7 (native-runtime evidence): PS control-plane round-trip rate
    of the in-tree C++ transport vs the Python one, same wire format, same
    AlexNet-gradient-sized payload, echo server in a real separate process."""
    import subprocess
    import sys as _sys

    from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode, make_transport

    payload = np.zeros(2_472_266, np.float32)  # raveled AlexNet size
    n_iter = 30
    server_src = (
        "import sys\n"
        "from distributed_ml_pytorch_tpu.utils.messaging import make_transport\n"
        "t = make_transport(0, 2, port=int(sys.argv[1]), kind=sys.argv[2])\n"
        f"for _ in range({n_iter} + 2):\n"
        "    sender, code, payload = t.recv(timeout=120)\n"
        "    t.send(code, payload, dst=sender)\n"
        "t.close()\n"
    )
    for kind in ("native", "python"):
        port = _free_port()
        srv = subprocess.Popen(
            [_sys.executable, "-c", server_src, port, kind],
            env=cpu_platform_env(),
        )
        t = None
        try:
            t = make_transport(1, 2, port=int(port), kind=kind, connect_timeout=120)
            for _ in range(2):  # warm both directions
                t.send(MessageCode.GradientUpdate, payload)
                t.recv(timeout=120)
            t0 = time.perf_counter()
            for _ in range(n_iter):
                t.send(MessageCode.GradientUpdate, payload)
                t.recv(timeout=120)
            dt = time.perf_counter() - t0
            rate = n_iter / dt
            mbps = 2 * payload.nbytes * rate / 1e6
            emit(7, f"ps_transport_roundtrip_{kind}", rate, "roundtrips/sec",
                 "2 processes, localhost TCP",
                 f"9.9 MB gradient payload echo ({mbps:.0f} MB/s both ways); "
                 "capability-extension evidence for the in-tree C++ transport")
        except Exception as e:
            log(f"transport bench ({kind}) failed: {e}")
        finally:
            if t is not None:
                t.close()
            if srv.poll() is None:
                srv.kill()
            srv.wait()


def reliability_phase() -> None:
    """Config 7, reliability-overhead leg (ISSUE 2 satellite): the same
    Python-TCP echo as ``transport_phase`` with the reliability layer on vs
    off — what the seq+CRC envelope, the ack frames and receiver dedup cost
    on the PS wire. The ack timeout is set well above one 9.9 MB transfer
    time on this rig so the measurement is protocol overhead, not spurious
    retransmits."""
    import subprocess
    import sys as _sys

    from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode,
        ReliableTransport,
        make_transport,
    )

    payload = np.zeros(2_472_266, np.float32)  # raveled AlexNet size
    n_iter = 20
    server_src = (
        "import sys\n"
        "from distributed_ml_pytorch_tpu.utils.messaging import (\n"
        "    ReliableTransport, make_transport)\n"
        "t = make_transport(0, 2, port=int(sys.argv[1]), kind='python')\n"
        "if sys.argv[2] != 'off':\n"
        "    t = ReliableTransport(t, ack_timeout=5.0, max_backoff=10.0,\n"
        "                          batched_acks=(sys.argv[2] == 'on'),\n"
        "                          legacy_envelope=(sys.argv[2] == 'legacy'))\n"
        f"for _ in range({n_iter} + 2):\n"
        "    sender, code, payload = t.recv(timeout=120)\n"
        "    t.send(code, payload, dst=sender)\n"
        "t.close()\n"
    )
    rates: dict = {}
    # interleaved best-of-4: on this 2-core rig one round's rate swings
    # 20-50% with background load, so each mode keeps its BEST round
    # (least interference) and the derived overhead compares bests
    for _round in range(4):
        for acks in ("off", "legacy", "on"):
            port = _free_port()
            srv = subprocess.Popen(
                [_sys.executable, "-c", server_src, port, acks],
                env=cpu_platform_env(),
            )
            t = None
            try:
                t = make_transport(1, 2, port=int(port), kind="python",
                                   connect_timeout=120)
                if acks != "off":
                    t = ReliableTransport(
                        t, ack_timeout=5.0, max_backoff=10.0,
                        batched_acks=(acks == "on"),
                        legacy_envelope=(acks == "legacy"))
                for _ in range(2):  # warm both directions
                    t.send(MessageCode.GradientUpdate, payload)
                    t.recv(timeout=120)
                iters = []
                for _ in range(n_iter):
                    t0 = time.perf_counter()
                    t.send(MessageCode.GradientUpdate, payload)
                    t.recv(timeout=120)
                    iters.append(time.perf_counter() - t0)
                # median-per-roundtrip: this shared 2-core host injects
                # 20-40 ms scheduler spikes into a handful of iterations;
                # a mean (total/n) would price the SCHEDULER, not the wire
                rates[acks] = max(rates.get(acks, 0.0),
                                  1.0 / float(np.median(iters)))
            except Exception as e:
                log(f"reliability bench (acks {acks}) failed: {e}")
            finally:
                if t is not None:
                    t.close()
                if srv.poll() is None:
                    srv.kill()
                srv.wait()
    for acks, rate in rates.items():
        mbps = 2 * payload.nbytes * rate / 1e6
        desc = {
            "off": "no reliability layer",
            "legacy": "the ISSUE-2 wire faithfully reproduced: full-frame "
                      "concatenate, tobytes+crc32 checksums, one ack per "
                      "frame (legacy_envelope=True)",
            "on": "ISSUE 7 adaptive wire: zero-copy checksums, "
                  "scatter/gather envelope, batched cumulative acks",
        }[acks]
        emit(7, f"ps_transport_roundtrip_python_acks_{acks}", rate,
             "roundtrips/sec", "2 processes, localhost TCP",
             f"9.9 MB gradient payload echo ({mbps:.0f} MB/s both "
             f"ways), median roundtrip, best of 4 rounds, {desc} "
             "(utils/messaging.ReliableTransport)")
    if "on" in rates and "off" in rates and "legacy" in rates:
        overhead = 100 * (1 - rates["on"] / rates["off"])
        before = 100 * (1 - rates["legacy"] / rates["off"])
        emit(7, "ps_reliability_layer_overhead", overhead,
             "percent", "derived",
             "roundtrip-rate cost of acks+checksum+dedup on the 9.9 MB PS "
             "echo (positive = reliability slower); the exactly-once apply "
             "guarantee under drop/dup/corrupt is what it buys "
             "(tests/test_chaos.py)")
        # ISSUE 7 acceptance: >= half of the ack tax recovered. Before =
        # the ISSUE-2 envelope measured TODAY on this rig (the wire got
        # ~5x faster since the 36.5% record, which makes the same absolute
        # CPU tax a LARGER fraction — same-day legs keep the comparison
        # honest); after = the adaptive wire.
        emit(7, "ps_reliability_ack_tax_recovered",
             100 * (before - overhead) / max(1e-9, before),
             "percent of legacy overhead", "derived",
             f"before/after on this rig today: legacy envelope costs "
             f"{before:.1f}% of raw rt/s (ISSUE-2 record: 36.5% on the "
             f"then-slower wire), adaptive wire costs {overhead:.1f}% — "
             "recovered by zero-copy u64-sum bulk checksums, sendv "
             "scatter/gather framing and batched cumulative acks")


def transport_microbench_phase() -> None:
    """Config 7, wire cost ladder (ISSUE 7 satellite): every layer of the
    unified transport stack priced on the same in-process echo — raw
    mailboxes, the reliability envelope with legacy per-frame acks, the
    adaptive batched-cumulative-ack path, WAL-style deferred acks released
    at a group boundary, and the chaos wrapper's bookkeeping (empty plan).
    One JSON line per rung, so a regression in any layer's overhead is a
    diffable number, not a feeling."""
    import threading

    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode,
        ReliableTransport,
        make_world,
    )

    payload = np.zeros(2_472_266, np.float32)  # raveled AlexNet size
    n_iter = 20
    group_n = 8  # WAL-deferred leg: acks released every `group_n` applies

    def echo_run(make):
        """Round-trip rate through a 2-rank world built by ``make()``."""
        world, _ = make()
        a, b = world[0], world[1]
        stop = threading.Event()

        def server():
            applied = 0
            while not stop.is_set():
                msg = a.recv(timeout=0.5)
                if msg is None:
                    continue
                applied += 1
                commit = getattr(a, "ack_delivered", None)
                if commit is not None and not a.ack_on_delivery \
                        and applied % group_n == 0:
                    commit()  # the group-fsync boundary releases acks
                a.send(msg[1], msg[2], dst=1)

        t = threading.Thread(target=server, daemon=True)
        t.start()
        # the CLIENT defers acks too on the wal rung (both ends share
        # reliable_opts): release them at the same group cadence, or the
        # server's echo sends would hit their window once n_iter outgrows
        # it and wedge the bench
        b_commit = getattr(b, "ack_delivered", None)
        if b_commit is not None and getattr(b, "ack_on_delivery", True):
            b_commit = None
        echoes = 0

        def pump_once():
            nonlocal echoes
            assert b.recv(timeout=30) is not None
            echoes += 1
            if b_commit is not None and echoes % group_n == 0:
                b_commit()

        try:
            for _ in range(2):  # warm
                b.send(MessageCode.GradientUpdate, payload)
                pump_once()
            t0 = time.perf_counter()
            for _ in range(n_iter):
                b.send(MessageCode.GradientUpdate, payload)
                pump_once()
            return n_iter / (time.perf_counter() - t0)
        finally:
            stop.set()
            t.join(timeout=5)
            for side in (a, b):
                commit = getattr(side, "ack_delivered", None)
                if commit is not None:
                    commit()  # release any tail behind the group boundary
            for tr in world.values():
                tr.close()

    ladder = [
        ("raw", "in-process mailboxes, no wrapping",
         lambda: make_world(2)),
        ("reliable_per_frame_ack", "seq+checksum envelope, one ack/frame",
         lambda: make_world(2, reliable=True, reliable_opts={
             "ack_timeout": 5.0, "max_backoff": 10.0,
             "batched_acks": False})),
        ("reliable_batched_ack", "adaptive wire: cumulative acks + credit",
         lambda: make_world(2, reliable=True, reliable_opts={
             "ack_timeout": 5.0, "max_backoff": 10.0})),
        ("wal_deferred_ack", "acks withheld to a group boundary "
         f"(n={group_n}), cumulative release",
         lambda: make_world(2, reliable=True, reliable_opts={
             "ack_timeout": 5.0, "max_backoff": 10.0,
             "ack_on_delivery": False})),
        ("chaos_wrapped", "reliable+batched under FaultyTransport with an "
         "empty plan (pure wrapper cost)",
         lambda: make_world(2, reliable=True, plan=ChaosPlan(),
                            reliable_opts={"ack_timeout": 5.0,
                                           "max_backoff": 10.0})),
    ]
    base = None
    for name, desc, make in ladder:
        try:
            rate = echo_run(make)
        except Exception as e:  # noqa: BLE001 — one rung must not kill the rest
            log(f"transport microbench ({name}) failed: {e}")
            continue
        if base is None:
            base = rate
        emit(7, f"wire_ladder_{name}", rate, "roundtrips/sec",
             "1 process, in-process transport",
             f"9.9 MB echo; {desc}; "
             f"{100 * (1 - rate / base):.1f}% below the raw rung")


def compute_microbench_phase() -> None:
    """Per-fusion cost ladder for the conv epilogues (ISSUE 9): the fused
    Pallas ``relu_pool2`` / ``bias_relu`` kernels vs the unfused XLA chain,
    standalone, on the AlexNet conv-output shapes at the large-batch leg's
    scale — the compute-plane analog of ``transport_microbench_phase``.

    Off-TPU the fused entry points lower to the same XLA chain (recorded
    as ``xla-fallback``), so the phase still runs everywhere and prices
    the chain; the fused-vs-unfused comparison is only meaningful on the
    TPU rows. Timing is device-true on TPU (``utils/devtime``), wallclock
    elsewhere; repeat dispatches reuse one input (elementwise programs
    have not shown the tunnel's memoization, devtime.py caveat).
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.ops import fused_conv as fc
    from distributed_ml_pytorch_tpu.utils.devtime import device_time

    platform = jax.devices()[0].platform
    hw = f"1x {platform}"
    path = "pallas" if platform == "tpu" else "xla-fallback"
    on_tpu = platform == "tpu"
    calls = 10 if on_tpu else 3
    b = 256
    rng = np.random.default_rng(0)
    shapes = {  # AlexNet conv outputs feeding a relu->pool tail
        "conv1_tail": (b, 8, 8, 64),
        "conv2_tail": (b, 4, 4, 192),
        "conv5_tail": (b, 2, 2, 256),
    }

    def us(t):
        return t.per_call_s * 1e6

    for name, shape in shapes.items():
        x = jnp.asarray(rng.normal(size=shape).astype(np.float32))
        ct = jnp.asarray(rng.normal(
            size=(shape[0], shape[1] // 2, shape[2] // 2, shape[3])
        ).astype(np.float32))
        variants = {
            "unfused": lambda v: fc.max_pool_2x2(jax.nn.relu(v)),
            "fused": fc.relu_pool2,
        }
        costs = {}
        for tag, fn in variants.items():
            fwd = jax.jit(fn)
            fwdbwd = jax.jit(lambda v, g, f=fn: jax.vjp(f, v)[1](g)[0])
            t_f = device_time(fwd, x, calls=calls, warmup=1)
            t_fb = device_time(fwdbwd, x, ct, calls=calls, warmup=1)
            costs[tag] = (us(t_f), us(t_fb))
            emit(1, f"conv_epilogue_{name}_{tag}_fwdbwd", us(t_fb),
                 "us/call", hw,
                 f"{name} {shape} relu->2x2pool {tag} "
                 f"({'pallas kernel' if tag == 'fused' and on_tpu else 'xla'}): "
                 f"fwd {us(t_f):.1f} us, fwd+bwd {us(t_fb):.1f} us "
                 f"({t_f.source}); fused path on this backend = {path}")
        log(f"  {name}: unfused fwd+bwd {costs['unfused'][1]:.1f} us vs "
            f"fused {costs['fused'][1]:.1f} us")

    # the elementwise bias+relu epilogue (conv3/conv4-shaped tail)
    x = jnp.asarray(rng.normal(size=(b * 4 * 4, 384)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(384,)).astype(np.float32))
    ct = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))
    for tag, fn in {
        "unfused": lambda v, bb: jax.nn.relu(v + bb),
        "fused": fc.bias_relu,
    }.items():
        fwdbwd = jax.jit(
            lambda v, bb, g, f=fn: jax.vjp(f, v, bb)[1](g)[0])
        t_fb = device_time(fwdbwd, x, bias, ct, calls=calls, warmup=1)
        emit(1, f"conv_epilogue_bias_relu_{tag}_fwdbwd", us(t_fb), "us/call",
             hw, f"bias+relu on (4096, 384) {tag}: fwd+bwd {us(t_fb):.1f} us "
             f"({t_fb.source}); fused path on this backend = {path}")


def cpu_mesh_phase() -> None:
    """Virtual-device measurements — runs LAST (re-initializing the backend
    onto CPU is one-way within a process)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_ml_pytorch_tpu.models import AlexNet, get_resnet
    from distributed_ml_pytorch_tpu.parallel.sync import (
        make_sync_train_step,
        replicate,
        shard_batch,
    )
    from distributed_ml_pytorch_tpu.runtime.mesh import force_cpu_devices, make_mesh
    from distributed_ml_pytorch_tpu.training.trainer import create_train_state
    from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

    force_cpu_devices(8)

    # config 2 — 2-device allreduce of the raveled AlexNet gradient vector
    mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
    model = AlexNet()
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    flat = np.asarray(ravel_model_params(params))
    n_elems = flat.size
    per_device = np.stack([flat, -0.5 * flat])  # distinct values: real comms

    allreduce = jax.jit(
        jax.shard_map(
            lambda g: jax.lax.psum(g[0], "data"),
            mesh=mesh2, in_specs=P("data"), out_specs=P(),
        )
    )
    g = jax.device_put(per_device)
    jax.block_until_ready(allreduce(g))  # compile
    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(g)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    emit(2, "allreduce_2way_gradient_exchange_rate", iters / dt,
         "exchanges/sec", "2 virtual cpu devices",
         f"psum of the {n_elems}-elem raveled AlexNet gradient "
         f"({n_elems * 4 / 1e6:.1f} MB) — functional collective measurement; "
         "no second chip for an ICI number")

    # config 4 (8-way leg) — the actual sharded ResNet-18 sync-DP step
    mesh8 = make_mesh({"data": 8})
    r18 = get_resnet("resnet18")
    state, tx = create_train_state(r18, jax.random.key(0), lr=0.05)
    state = replicate(mesh8, state)
    step = make_sync_train_step(r18, tx, mesh8)
    rng = replicate(mesh8, jax.random.key(1))
    images, labels = make_batch(64)
    bx, by = shard_batch(mesh8, images, labels)
    state, loss = step(state, bx, by, rng)
    jax.block_until_ready(state.params)  # compile + first step
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, bx, by, rng)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    emit(4, "resnet18_8way_dp_step_throughput", iters * 64 / dt, "images/sec",
         "8 virtual cpu devices",
         f"global batch 64 over 8-way psum DP, loss={float(loss):.3f} — "
         "functional validation of the sharded step, not TPU perf")


def multiprocess_psum_phase(n: int = 4, rounds: int = 20) -> None:
    """Config 2 at REAL-process scale (VERDICT r4 #7): n localhost processes
    psum the raveled AlexNet gradient vector over gloo — the cross-process
    analog of the in-process `allreduce_2way_gradient_exchange_rate` row.
    Subprocess-isolated so the phase runs under any parent backend."""
    import subprocess
    import sys as _sys
    import textwrap

    from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env

    worker = textwrap.dedent('''
        import sys, time
        proc, n, port, rounds = (int(sys.argv[1]), int(sys.argv[2]),
                                 sys.argv[3], int(sys.argv[4]))
        import jax
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        from distributed_ml_pytorch_tpu.runtime.mesh import (
            initialize_distributed)
        initialize_distributed(f"localhost:{port}", num_processes=n,
                               process_id=proc)
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        from distributed_ml_pytorch_tpu.models import AlexNet
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh
        from distributed_ml_pytorch_tpu.utils.serialization import (
            ravel_model_params)

        model = AlexNet()
        params = model.init(jax.random.key(0),
                            jnp.zeros((1, 32, 32, 3)))["params"]
        flat = np.asarray(ravel_model_params(params))
        mesh = make_mesh({"data": n})
        # each process contributes a DISTINCT vector: real traffic, and the
        # psum result checks the collective actually reduced across ranks
        per = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data")),
            ((proc + 1) * flat)[None, :])
        allreduce = jax.jit(jax.shard_map(
            lambda g: jax.lax.psum(g[0], "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P()))
        out = allreduce(per)
        jax.block_until_ready(out)
        want = flat * (n * (n + 1) / 2)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)
        t0 = time.perf_counter()
        for _ in range(rounds):
            out = allreduce(per)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        print(f"PSUM-OK proc={proc} n_elems={flat.size} "
              f"rate={rounds / dt:.3f}", flush=True)
    ''')
    port = _free_port()
    env = cpu_platform_env()
    procs = [
        subprocess.Popen(
            [_sys.executable, "-c", worker, str(rank), str(n), port,
             str(rounds)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for rank in range(n)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    rates, n_elems = [], 0
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 or f"PSUM-OK proc={rank}" not in out:
            log(f"multiprocess psum rank {rank} failed:\n{out[-2000:]}")
            return
        rates.append(float(out.split("rate=")[1].split()[0]))
        n_elems = int(out.split("n_elems=")[1].split()[0])
    # one exchange completes when the SLOWEST rank finishes its round
    rate = min(rates)
    emit(2, f"allreduce_{n}process_gloo_exchange_rate", rate,
         "exchanges/sec", f"{n} real processes, 1 core",
         f"psum of the {n_elems}-elem raveled AlexNet gradient "
         f"({n_elems * 4 / 1e6:.1f} MB) across {n} localhost processes over "
         "gloo, result verified = sum of all ranks; min-rank rate over "
         f"{rounds} rounds — the real-process analog of the in-process "
         "2-device row")


def wire_bytes_phase() -> None:
    """Config 7, compressed-wire ladder (ISSUE 14, ``--only wire_bytes``,
    ``make bench-wire-bytes``): dense vs int8 vs top-k bytes-on-wire per
    push and acked push round-trips/s on the real raveled-AlexNet PS push
    path — in-process transports + the reliability envelope + a real
    ``ParameterServer`` decoding every frame, so the codec's encode AND
    decode CPU are inside the measured loop (the honest per-push cost,
    labelled in-process; the 9.9 MB echo baseline for the same payload
    over real TCP is ``reliability_phase``). Bytes are exact frame
    arithmetic, not estimates."""
    import threading

    from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer
    from distributed_ml_pytorch_tpu.utils.compress import (
        CompressingEncoder,
        make_codec,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode,
        make_world,
    )

    n = 2_472_266  # raveled AlexNet size — the 9.9 MB dense frame
    rng = np.random.default_rng(0)
    n_iter = 12
    rates: dict = {}
    bytes_per_push: dict = {}
    for mode in ("dense", "int8", "topk"):
        world, t, stop = None, None, None
        try:
            # setup rides INSIDE the try: a failed rung (construction
            # included) logs and yields to the next mode, never kills
            # the whole table
            world, _log = make_world(
                2, reliable=True,
                reliable_opts={"ack_timeout": 5.0, "max_backoff": 10.0})
            ps = ParameterServer(params=np.zeros(n, np.float32),
                                 transport=world[0])
            stop = threading.Event()

            def serve():
                while not stop.is_set():
                    msg = world[0].recv(timeout=0.2)
                    if msg is None:
                        continue
                    ps.handle(msg[0], msg[1], msg[2])

            t = threading.Thread(target=serve, daemon=True)
            t.start()
            enc = (None if mode == "dense" else CompressingEncoder(
                n, make_codec(mode, block=1024, k_frac=0.01)))
            vec = rng.normal(scale=0.01, size=n).astype(np.float32)

            def push():
                if enc is None:
                    world[1].send(MessageCode.GradientUpdate, vec, dst=0)
                    return n * 4
                head, body = enc.encode_range(vec, 0, n)
                world[1].sendv(MessageCode.CompressedUpdate, (head, body),
                               dst=0)
                return int((head.size + body.size) * 4)
            push()  # warm both directions (+ the server's first decode)
            world[1].flush(timeout=60)
            t0 = time.perf_counter()
            nbytes = 0
            for _ in range(n_iter):
                nbytes = push()
                # flush per push: the rate includes the ack round trip,
                # matching the dense echo baseline's send+reply discipline
                world[1].flush(timeout=60)
            dt = time.perf_counter() - t0
            rates[mode] = n_iter / dt
            bytes_per_push[mode] = nbytes
            emit(7, f"ps_wire_bytes_per_push_{mode}", nbytes, "bytes",
                 "in-process, reliable envelope",
                 f"exact frame bytes of one {mode} push of the "
                 f"{n}-param vector (envelope header excluded: +36 B "
                 "either way); decoded server-side inside the loop")
            emit(7, f"ps_push_roundtrips_{mode}", rates[mode],
                 "pushes/sec", "in-process, reliable envelope",
                 f"acked {mode} pushes/s incl. encode + decode + apply "
                 f"({nbytes * rates[mode] / 1e6:.1f} MB/s on-wire); "
                 "dense TCP echo baseline: reliability_phase")
        except Exception as e:  # noqa: BLE001 — a failed rung must not
            log(f"wire_bytes bench ({mode}) failed: {e}")  # kill the table
        finally:
            if stop is not None:
                stop.set()
            if t is not None:
                t.join(timeout=10)
            for tr in (world or {}).values():
                tr.close()
    for mode in ("int8", "topk"):
        if mode in bytes_per_push and "dense" in bytes_per_push:
            emit(7, f"ps_wire_compression_ratio_{mode}",
                 bytes_per_push["dense"] / bytes_per_push[mode],
                 "x fewer bytes", "derived",
                 f"dense / {mode} bytes-on-wire per push (error-feedback "
                 "encoder, utils/compress.py); the acceptance bar is "
                 ">= 3x with convergence in the fault-free corridor "
                 "(tests/test_compress.py)")

    # --- ISSUE 18: the codec plane's OTHER hot wires, same discipline —
    # exact frame arithmetic from the registry, encode AND decode CPU
    # inside every timed loop. Rows: activations (pipeline codes 30/31),
    # delta pull replies (the real server's _reply_delta path), and the
    # serving migration's quantized KV lane.
    from distributed_ml_pytorch_tpu.utils import codecs
    from distributed_ml_pytorch_tpu.utils.compress import (
        CODEC_DENSE,
        CODEC_INT8,
    )

    def _codec_ladder(tag, code, x, head_floats, note, iters=20):
        """Price one plane's dense-vs-int8 rungs: exact bytes/frame and
        encode+decode frames/s; returns {mode: bytes}."""
        out = {}
        for mode, cid in (("dense", CODEC_DENSE), ("int8", CODEC_INT8)):
            try:
                t0 = time.perf_counter()
                for _ in range(iters):
                    got, body = codecs.encode_body(code, x, cid)
                    codecs.decode_body(code, got, body, x.size)
                dt = time.perf_counter() - t0
                nbytes = int((head_floats + body.size) * 4)
                out[mode] = nbytes
                emit(7, f"{tag}_wire_bytes_per_frame_{mode}", nbytes,
                     "bytes", "registry encode_body/decode_body",
                     f"exact frame bytes ({head_floats}-float head + "
                     f"body) of one {mode} {code.name} frame of "
                     f"{x.size} floats; {note}")
                emit(7, f"{tag}_codec_frames_per_s_{mode}", iters / dt,
                     "frames/sec", "registry encode_body/decode_body",
                     f"{mode} encode + decode round trips/s on one core "
                     f"({iters / dt * nbytes / 1e6:.1f} MB/s on-wire)")
            except Exception as e:  # noqa: BLE001 — one rung, one row
                log(f"wire_bytes codec ladder ({tag}/{mode}) failed: {e}")
        return out

    act = rng.normal(scale=2.0, size=8 * 128 * 256).astype(np.float32)
    act_bytes = _codec_ladder(
        "act", MessageCode.ActivationShip, act, 8,
        "the MPMD corridor acceptance holds the loss trajectory within "
        "tolerance of the uncompressed pipeline (tests/test_mpmd.py)")
    if {"dense", "int8"} <= set(act_bytes):
        emit(7, "act_wire_compression_ratio_int8",
             act_bytes["dense"] / act_bytes["int8"], "x fewer bytes",
             "derived", "dense / int8 bytes per activation frame "
             "(codes 30/31, parallel/mpmd.py); acceptance bar is >= 3x "
             "with the loss corridor held")

    kv = rng.normal(scale=0.5, size=1024 * 128).astype(np.float32)
    kv_bytes = _codec_ladder(
        "kv_migrate", MessageCode.KvMigrate, kv, 9,
        "the token lane of the same frame rides tok16 (exact), so "
        "migrated-stream token identity never depends on this rung")
    if {"dense", "int8"} <= set(kv_bytes):
        emit(7, "kv_migrate_compression_ratio_int8",
             kv_bytes["dense"] / kv_bytes["int8"], "x fewer bytes",
             "derived", "dense / int8 bytes per migrated KV lane "
             "(serving/fleet.py handoff; kv_quant recipe)")

    # delta pull replies: the REAL server reply path (ParameterRequest
    # with a held stamp -> _reply_delta -> Listener install), so the
    # bytes are what the server actually put on the wire
    world = None
    try:
        from distributed_ml_pytorch_tpu.parallel.async_ps import (
            Listener,
        )
        from distributed_ml_pytorch_tpu.utils.messaging import (
            InProcessTransport,
        )

        world = InProcessTransport.create_world(2)
        ps = ParameterServer(
            params=rng.normal(scale=0.01, size=n).astype(np.float32),
            transport=world[0])
        lst = Listener(transport=world[1])

        def delta_pull():
            before = ps.delta_reply_wire_floats
            ps.handle(1, MessageCode.ParameterRequest, lst.held_stamp())
            msg = world[1].recv(timeout=5.0)
            assert msg is not None
            lst.receive(msg[0], msg[1], msg[2])
            return (ps.delta_reply_wire_floats - before) * 4

        full_bytes = delta_pull()  # first pull: full dense install
        upd = rng.normal(scale=1e-4, size=n).astype(np.float32)
        n_pulls, delta_bytes, spent = 6, 0, 0.0
        for _ in range(n_pulls):
            ps.handle(1, MessageCode.GradientUpdate, upd)
            t0 = time.perf_counter()
            delta_bytes = delta_pull()
            spent += time.perf_counter() - t0
        emit(7, "pull_reply_bytes_full", full_bytes, "bytes",
             "in-process, real _reply_delta path",
             f"exact wire bytes of the full dense fallback install of "
             f"the {n}-param vector (version miss / restore / rebalance "
             "path)")
        emit(7, "pull_reply_bytes_delta_steady", delta_bytes, "bytes",
             "in-process, real _reply_delta path",
             "exact wire bytes of one steady-state top-k delta reply "
             "(server tracks the worker's last-pulled view; "
             "per-worker error feedback keeps the tracked mirror "
             "bitwise equal to the installed view)")
        emit(7, "pull_reply_roundtrips_delta", n_pulls / spent,
             "pulls/sec", "in-process, real _reply_delta path",
             f"steady-state delta pulls/s incl. encode + decode + "
             f"install ({delta_bytes * n_pulls / spent / 1e6:.1f} MB/s "
             "on-wire)")
        emit(7, "pull_reply_compression_ratio_delta",
             full_bytes / max(delta_bytes, 1), "x fewer bytes",
             "derived", "full / steady-state delta reply bytes "
             "(parallel/async_ps.py); acceptance bar is >= 4x with "
             "drill restores bit-exact (full fallback re-fences)")
    except Exception as e:  # noqa: BLE001 — one ladder, one table leg
        log(f"wire_bytes pull-reply ladder failed: {e}")
    finally:
        for tr in (world or {}).values():
            tr.close()


def lint_phase() -> None:
    """Price the static-analysis pass itself (ISSUE 19): one full
    distcheck run — parse plus every checker family, the interprocedural
    distflow pass included — raw (pre-suppression) findings counted.
    `make test` fronts tier-1 with `make lint`, so the pass staying cheap
    IS a product property; gated against ``lint_wall_clock_ceiling_s``
    in bench_floors.json (a ceiling, not a floor: slower regresses)."""
    from distributed_ml_pytorch_tpu.analysis import cli
    from distributed_ml_pytorch_tpu.analysis.core import load_package

    t0 = time.perf_counter()
    pkg = load_package(cli.default_root())
    parse_s = time.perf_counter() - t0
    raw = []
    for check in cli.CHECKERS:
        raw.extend(check(pkg))
    total_s = time.perf_counter() - t0
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_floors.json")) as fh:
        ceiling = json.load(fh)["lint_wall_clock_ceiling_s"]
    emit(8, "lint_full_pass_wall_clock", total_s, "s", "1-core host",
         f"full distcheck: parse {parse_s:.2f}s + {len(cli.CHECKERS)} "
         f"checker families over {len(pkg.files)} modules, {len(raw)} "
         f"raw findings pre-suppression; ceiling {ceiling}s")
    if total_s > ceiling:
        raise RuntimeError(
            f"lint wall clock {total_s:.2f}s exceeds the "
            f"{ceiling}s ceiling in bench_floors.json — a checker "
            "got expensive enough to tax every `make test` run")


#: phases addressable via ``--only`` (``make bench-wire`` runs the wire
#: legs without paying for the full table)
PHASES = {
    "tpu": lambda: tpu_phase(),
    "ps": lambda: ps_phase(),
    "sharded_ps": lambda: sharded_ps_phase(),
    "elastic": lambda: elastic_phase(),
    "recovery": lambda: recovery_phase(),
    "coordfail": lambda: coordfail_phase(),
    "gray": lambda: gray_phase(),
    "sched": lambda: sched_phase(),
    "health": lambda: health_phase(),
    "mpmd": lambda: mpmd_phase(),
    "ps_tpu": lambda: ps_tpu_phase(),
    "transport": lambda: transport_phase(),
    "reliability": lambda: reliability_phase(),
    "transport_microbench": lambda: transport_microbench_phase(),
    "wire_bytes": lambda: wire_bytes_phase(),
    "compute_microbench": lambda: compute_microbench_phase(),
    "lint": lambda: lint_phase(),
    "cpu_mesh": lambda: cpu_mesh_phase(),
    "multiprocess_psum": lambda: multiprocess_psum_phase(),
}


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--only", action="append", choices=sorted(PHASES),
        help="run only the named phase(s), in the given order (repeatable)")
    args = ap.parse_args(argv)
    if args.only:
        for name in args.only:
            PHASES[name]()
        log(f"bench_all: {len(RESULTS)} measurements")
        return
    tpu_phase()
    ps_phase()
    sharded_ps_phase()
    elastic_phase()
    recovery_phase()
    coordfail_phase()
    gray_phase()
    sched_phase()
    health_phase()
    mpmd_phase()
    ps_tpu_phase()
    transport_phase()
    reliability_phase()
    transport_microbench_phase()
    wire_bytes_phase()
    compute_microbench_phase()
    cpu_mesh_phase()
    # LAST: the 4 gloo subprocesses leave the 1-core host briefly saturated
    # as they tear down — running this before cpu_mesh_phase measured the
    # in-process 2-way psum at 0.8 exchanges/s vs 88.5 standalone
    multiprocess_psum_phase()
    log(f"bench_all: {len(RESULTS)} measurements")


if __name__ == "__main__":
    main()
