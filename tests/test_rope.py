"""Rotary position embeddings: relative-position property, sharding
transparency (RoPE must be exact under ring/Ulysses sequence sharding because
rotation uses global positions before any exchange), and cached decode."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.models.generate import init_cache
from distributed_ml_pytorch_tpu.models.transformer import TransformerLM, apply_rope
from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
    create_lm_train_state,
    make_sp_train_step,
    next_token_targets,
    shard_lm_batch,
)
from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh


def rope_lm(**kw):
    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               max_len=128, pos_encoding="rope")
    cfg.update(kw)
    return TransformerLM(**cfg)


def test_rope_scores_depend_only_on_relative_position():
    """q·k after rotation must be invariant to shifting both positions by a
    constant — the property that makes RoPE extrapolate."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 2, 8, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 2, 8, 16)).astype(np.float32))
    pos = jnp.arange(8)[None, :]
    scores = jnp.einsum("bhsd,bhtd->bhst", apply_rope(q, pos), apply_rope(k, pos))
    shifted = jnp.einsum(
        "bhsd,bhtd->bhst", apply_rope(q, pos + 37), apply_rope(k, pos + 37)
    )
    np.testing.assert_allclose(np.asarray(scores), np.asarray(shifted),
                               rtol=1e-4, atol=1e-4)


def test_rope_requires_even_head_dim():
    q = jnp.zeros((1, 1, 4, 5))
    with pytest.raises(ValueError, match="even head_dim"):
        apply_rope(q, jnp.arange(4)[None, :])


def test_rope_model_has_no_position_table():
    lm = rope_lm()
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    assert "pos_embed" not in params
    # and the learned variant does have one
    learned = TransformerLM(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=128)
    lparams = learned.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    assert "pos_embed" in lparams


def test_rope_sp_training_matches_single_device():
    """Ring-attention SP over a rope model == unsharded training: each chunk
    rotates by its global offsets, so the sharded math is identical."""
    mesh = make_mesh({"data": 2, "seq": 4})
    lm = rope_lm()
    tx = optax.sgd(0.05)
    state_p = create_lm_train_state(lm, jax.random.key(0), tx)
    state_s = create_lm_train_state(lm, jax.random.key(0), tx)

    tokens = np.random.default_rng(1).integers(0, 64, size=(4, 64)).astype(np.int32)
    targets = next_token_targets(tokens)
    tok, tgt = shard_lm_batch(mesh, tokens, targets)
    sp_step = make_sp_train_step(lm, tx, mesh)

    @jax.jit
    def single_step(state, tokens, targets):
        def loss_fn(params):
            logits = lm.apply({"params": params}, tokens)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
            mask = jnp.ones_like(ce).at[:, -1].set(0.0)
            return jnp.sum(ce * mask) / jnp.sum(mask)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(params=params, opt_state=opt_state,
                             step=state.step + 1), loss

    for _ in range(2):
        state_s, loss_s = single_step(state_s, tokens, targets)
        state_p, loss_p = sp_step(state_p, tok, tgt)
        np.testing.assert_allclose(float(loss_s), float(loss_p), rtol=1e-5)

    for a, b in zip(jax.tree.leaves(state_s.params), jax.tree.leaves(state_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_rope_generate_extends_past_max_len():
    """RoPE has no position table, so decoding past max_len is legal (the
    learned-embedding guard in generate() must not fire)."""
    from distributed_ml_pytorch_tpu.models.generate import generate

    model = rope_lm(max_len=16)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 64, size=(1, 12)), jnp.int32
    )
    out = generate(model, params, prompt, max_new_tokens=8)  # total 20 > 16
    assert out.shape == (1, 20)


def test_rope_incremental_decode_matches_full_forward():
    """Cached decode stores ROTATED keys; step-by-step logits must equal the
    full causal forward at every position."""
    model = rope_lm(max_len=64)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 10)), jnp.int32
    )
    full_logits = model.apply({"params": params}, tokens)

    dec = model.clone(decode=True, cache_size=10, attn_fn=None)
    cache = init_cache(model, 2, 10)
    got = []
    for t in range(10):
        logits, mutated = dec.apply(
            {"params": params, "cache": cache},
            tokens[:, t : t + 1],
            jnp.full((2, 1), t, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        got.append(logits[:, 0])
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full_logits), rtol=2e-4, atol=2e-5
    )
