"""Checkpoint/resume subsystem tests (utils/checkpoint.py).

The reference has no persistence at all (SURVEY.md §5.4), so these tests
define the contract fresh: state round-trips bit-exactly (including sharded
arrays), retention honors max_to_keep, save_interval_steps gates saves, and a
resumed run continues from the exact batch and reaches the same final state
as an uninterrupted run (determinism of the (seed, epoch)-keyed data order).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils.checkpoint import (
    Checkpointer,
    maybe_restore,
    resume_position,
)


def _tiny_state():
    from distributed_ml_pytorch_tpu.models import get_model
    from distributed_ml_pytorch_tpu.training.trainer import create_train_state

    model = get_model("lenet")
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    return model, state, tx


def test_round_trip_exact(tmp_path):
    _, state, _ = _tiny_state()
    with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
        assert ckpt.save(3, state)
        ckpt.wait()
        restored, step = ckpt.restore(state)
    assert step == 3
    leaves_a = jax.tree_util.tree_leaves(state)
    leaves_b = jax.tree_util.tree_leaves(restored)
    assert len(leaves_a) == len(leaves_b)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_missing_raises(tmp_path):
    _, state, _ = _tiny_state()
    with Checkpointer(str(tmp_path / "empty")) as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore(state)
        st, step = maybe_restore(ckpt, state)
        assert step == 0 and st is state


def test_retention_and_interval(tmp_path):
    _, state, _ = _tiny_state()
    with Checkpointer(
        str(tmp_path / "ckpt"), max_to_keep=2, save_interval_steps=10
    ) as ckpt:
        assert ckpt.save(0, state)
        assert not ckpt.save(5, state)  # below interval → rejected
        assert ckpt.save(10, state)
        assert ckpt.save(20, state)
        ckpt.wait()
        assert ckpt.latest_step() == 20
    # re-open fresh and confirm only the newest 2 survive
    with Checkpointer(str(tmp_path / "ckpt")) as ckpt2:
        assert ckpt2.latest_step() == 20
        restored, step = ckpt2.restore(state, step=10)
        assert step == 10
        with pytest.raises(Exception):
            ckpt2.restore(state, step=0)


def test_sharded_round_trip(tmp_path, mesh8):
    """A state sharded over the 8-device mesh restores with its sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh8, P("data"))
    x = jax.device_put(jnp.arange(32, dtype=jnp.float32).reshape(8, 4), sharding)
    state = {"w": x, "step": jnp.int32(7)}
    with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
        ckpt.save(1, state)
        ckpt.wait()
        template = {
            "w": jax.ShapeDtypeStruct((8, 4), jnp.float32, sharding=sharding),
            "step": jax.ShapeDtypeStruct((), jnp.int32),
        }
        restored, _ = ckpt.restore(template)
    assert restored["w"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(x))


def test_resume_position():
    assert resume_position(0, 100) == (0, 0)
    assert resume_position(99, 100) == (0, 99)
    assert resume_position(100, 100) == (1, 0)
    assert resume_position(250, 100) == (2, 50)
    with pytest.raises(ValueError):
        resume_position(5, 0)


def _args(tmp_path, epochs, **over):
    import argparse

    d = dict(
        batch_size=16,
        test_batch_size=64,
        epochs=epochs,
        lr=0.05,
        log_interval=1000,
        seed=3,
        synthetic_data=True,
        synthetic_train_size=64,
        synthetic_test_size=64,
        model="lenet",
        log_dir=str(tmp_path / "log"),
        ckpt_dir=str(tmp_path / "ckpt"),
        ckpt_every=1,
        ckpt_keep=10,
        resume=False,
    )
    d.update(over)
    return argparse.Namespace(**d)


def test_resume_matches_uninterrupted(tmp_path):
    """Train 2 epochs straight vs. 1 epoch + resume for the 2nd: same params."""
    from distributed_ml_pytorch_tpu.training.trainer import train_single

    straight, _ = train_single(_args(tmp_path, 2, ckpt_dir=str(tmp_path / "a")))

    interrupted, _ = train_single(_args(tmp_path, 1, ckpt_dir=str(tmp_path / "b")))
    resumed, _ = train_single(_args(tmp_path, 2, ckpt_dir=str(tmp_path / "b"), resume=True))

    assert int(resumed.step) == int(straight.step)
    for a, b in zip(
        jax.tree_util.tree_leaves(straight.params), jax.tree_util.tree_leaves(resumed.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_fsdp_resume_matches_uninterrupted(tmp_path, mesh8):
    """Same interrupt/resume contract for --mode fsdp: restore happens
    host-side, then the state is re-laid-out sharded — the sharded and
    uninterrupted trajectories must agree."""
    from distributed_ml_pytorch_tpu.parallel.fsdp import train_fsdp

    common = dict(batch_size=2, lr=0.05, mode="fsdp",
                  log_interval=1000, prefetch=0)

    straight, _ = train_fsdp(
        _args(tmp_path, 2, ckpt_dir=str(tmp_path / "fa"), **common), mesh8
    )
    train_fsdp(_args(tmp_path, 1, ckpt_dir=str(tmp_path / "fb"), **common), mesh8)
    resumed, _ = train_fsdp(
        _args(tmp_path, 2, ckpt_dir=str(tmp_path / "fb"), resume=True, **common),
        mesh8,
    )

    assert int(resumed.step) == int(straight.step)
    for a, b in zip(
        jax.tree_util.tree_leaves(straight.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)
