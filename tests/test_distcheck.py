"""distcheck (analysis/) — the static-analysis suite's own test corpus.

Three layers (ISSUE 4 acceptance):

1. **Seeded-bug twins** — for every checker code, a fixture package with a
   planted defect and a clean twin: the checker must fire on the seeded
   file and stay silent on the clean one. Fixtures are source TEXT (the
   analyzer is pure-AST), so the broken twins never need to import.
2. **Suppression semantics** — inline ignores silence exactly their code,
   a reasonless ignore is itself a finding (DC001), a stale ignore is
   flagged (DC002).
3. **The real tree** — the installed package runs clean against the
   checked-in baseline (tests/distcheck_baseline.txt), and the runtime
   lock-order witness (analysis/witness.py) cross-validates the static
   lock model on a live reliable-transport scenario.

Plus regression tests for the genuine defects the tool surfaced (ISSUE 4
satellite): the frontend's route-table callback, the elastic server's
resize-vs-reader race, the reliable transport's dead-peer reads, the TCP
peer-table rewiring, and the coord client's progress tuple.
"""

import os
import textwrap
import threading
import time
import types

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.analysis import analyze_path
from distributed_ml_pytorch_tpu.analysis.core import read_baseline

HERE = os.path.dirname(os.path.abspath(__file__))


def _run(tmp_path, files):
    """Write a fixture package and analyze it; returns (active, suppressed)."""
    root = tmp_path / "fixturepkg"
    for rel, text in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    return analyze_path(str(root), rel_base=str(tmp_path))


def _codes(findings):
    return sorted(f.code for f in findings)


# ---------------------------------------------------------------- fixtures

_MINI_MESSAGING = """
    import enum

    class MessageCode(enum.IntEnum):
        Ping = 0
        Pong = 1

    class PayloadSchema:
        def __init__(self, fields=(), rest=None, rest_min=0, handled_by=()):
            self.fields = fields
            self.rest = rest
            self.rest_min = rest_min
            self.handled_by = handled_by

    WIRE_SCHEMAS = {
        MessageCode.Ping: PayloadSchema(
            fields=("a", "b"), handled_by=("ps",)),
        MessageCode.Pong: PayloadSchema(
            fields=("x",), rest="data", handled_by=("serving",)),
    }
"""

_PING_HANDLER = """
    from fixturepkg.utils.messaging import MessageCode

    def serve(transport):
        msg = transport.recv()
        sender, code, payload = msg
        if code == MessageCode.Ping and payload.size >= 2:
            return payload[0] + payload[1]
"""

_PONG_ROUNDTRIP = """
    import numpy as np
    from fixturepkg.utils.messaging import MessageCode

    def push(transport):
        transport.send(MessageCode.Pong,
                       np.concatenate([np.asarray([7.0], np.float32),
                                       np.zeros(3, np.float32)]))

    def serve(transport):
        sender, code, payload = transport.recv()
        if code == MessageCode.Pong and payload.size >= 1:
            return payload[0], payload[1:]
"""


def _wire_files(**overrides):
    files = {
        "utils/messaging.py": _MINI_MESSAGING,
        "parallel/worker.py": """
            import numpy as np
            from fixturepkg.utils.messaging import MessageCode

            def push(transport):
                transport.send(MessageCode.Ping,
                               np.asarray([1.0, 2.0], np.float32))
        """,
        "parallel/server.py": _PING_HANDLER,
        "serving/stream.py": _PONG_ROUNDTRIP,
    }
    files.update(overrides)
    return files


# ----------------------------------------------------------- DC1xx: wire

def test_dc101_code_collision_fires_and_clean_twin_silent(tmp_path):
    broken = _wire_files(**{"utils/messaging.py": _MINI_MESSAGING.replace(
        "Pong = 1", "Pong = 0")})
    active, _ = _run(tmp_path, broken)
    assert "DC101" in _codes(active)
    clean, _ = _run(tmp_path, _wire_files())
    assert not clean, [f.render() for f in clean]


def test_dc102_send_without_handler_on_plane(tmp_path):
    # the Ping handler lives on the wrong plane → DC102 names the plane
    broken = _wire_files()
    broken["serving/misplaced.py"] = broken.pop("parallel/server.py")
    active, _ = _run(tmp_path, broken)
    assert "DC102" in _codes(active)
    assert any("ps" in f.message for f in active if f.code == "DC102")


def test_dc103_handler_for_never_sent_code(tmp_path):
    broken = _wire_files()
    broken["serving/stream.py"] = """
        from fixturepkg.utils.messaging import MessageCode

        def serve(transport):
            sender, code, payload = transport.recv()
            if code == MessageCode.Pong and payload.size >= 1:
                return payload[0], payload[1:]
    """
    active, _ = _run(tmp_path, broken)
    assert "DC103" in _codes(active)


def test_dc104_send_head_arity_drift(tmp_path):
    broken = _wire_files(**{"parallel/worker.py": """
        import numpy as np
        from fixturepkg.utils.messaging import MessageCode

        def push(transport):
            transport.send(MessageCode.Ping,
                           np.asarray([1.0, 2.0, 3.0], np.float32))
    """})
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC104"]
    assert "3 field(s)" in active[0].message


def test_dc104_handler_guard_and_subscript_drift(tmp_path):
    broken = _wire_files(**{"parallel/server.py": """
        from fixturepkg.utils.messaging import MessageCode

        def serve(transport):
            sender, code, payload = transport.recv()
            if code == MessageCode.Ping and payload.size >= 5:
                return payload[4]
    """})
    active, _ = _run(tmp_path, broken)
    codes = _codes(active)
    assert codes.count("DC104") == 2  # wrong guard K AND out-of-head read


def test_dc104_rest_sliced_at_wrong_offset(tmp_path):
    broken = _wire_files(**{"serving/stream.py": _PONG_ROUNDTRIP.replace(
        "payload[0], payload[1:]", "payload[0], payload[2:]")})
    active, _ = _run(tmp_path, broken)
    assert "DC104" in _codes(active)
    assert any("payload[2:]" in f.message for f in active)


def test_dc105_raw_transport_in_reliable_module(tmp_path):
    client = """
        import numpy as np
        from fixturepkg.utils.messaging import MessageCode
        from fixturepkg.utils.transports import ReliableTransport, TCPTransport

        def dial(reliable):
            t = TCPTransport(0, 2)
            return t
    """
    files = _wire_files(**{
        "utils/transports.py": """
            class TCPTransport:
                def __init__(self, rank, world_size):
                    self.rank = rank

            class ReliableTransport:
                def __init__(self, inner):
                    self.inner = inner
        """,
        "training/client.py": client,
    })
    active, _ = _run(tmp_path, files)
    assert "DC105" in _codes(active)
    fixed = dict(files)
    fixed["training/client.py"] = client.replace(
        "t = TCPTransport(0, 2)", "t = ReliableTransport(TCPTransport(0, 2))")
    active, _ = _run(tmp_path, fixed)
    assert "DC105" not in _codes(active)


def test_dc106_schema_table_must_be_total(tmp_path):
    broken = _wire_files(**{"utils/messaging.py": _MINI_MESSAGING.replace(
        'MessageCode.Pong: PayloadSchema(\n            fields=("x",), rest="data", handled_by=("serving",)),\n',
        "")})
    active, _ = _run(tmp_path, broken)
    assert "DC106" in _codes(active)


_MINI_DURABILITY = """
    import os

    def atomic_write(path, data):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
"""


def test_dc107_raw_persistence_in_durability_opted_module(tmp_path):
    writer = """
        import os
        from fixturepkg.utils.durability import atomic_write

        def save_meta(path, data):
            atomic_write(path + ".meta", data)

        def save_vector(path, data):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
    """
    files = _wire_files(**{
        "utils/durability.py": _MINI_DURABILITY,
        "training/state.py": writer,
    })
    active, _ = _run(tmp_path, files)
    assert "DC107" in _codes(active)
    assert any("save_vector" in f.message for f in active)
    # clean twin: every persistent write rides the helper
    fixed = dict(files)
    fixed["training/state.py"] = """
        from fixturepkg.utils.durability import atomic_write

        def save_meta(path, data):
            atomic_write(path + ".meta", data)

        def save_vector(path, data):
            atomic_write(path, data)
    """
    active, _ = _run(tmp_path, fixed)
    assert "DC107" not in _codes(active)


def test_dc107_defining_module_and_unopted_module_are_exempt(tmp_path):
    files = _wire_files(**{
        # the helper's own open+replace IS the raw path: exempt
        "utils/durability.py": _MINI_DURABILITY,
        # a module that never opted in (no atomic_write reference) is
        # out of scope for the discipline — DC107 is opt-in like DC105
        "training/state.py": """
            import os

            def save(path, data):
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
        """,
    })
    active, _ = _run(tmp_path, files)
    assert "DC107" not in _codes(active)


def test_dc107_append_mode_wal_writes_are_exempt(tmp_path):
    files = _wire_files(**{
        "utils/durability.py": _MINI_DURABILITY,
        "training/state.py": """
            import os
            from fixturepkg.utils.durability import atomic_write

            def rotate(path, keep):
                atomic_write(path, keep)
                handle = open(path, "ab")  # append-only WAL style
                os.replace(path, path + ".bak")
                return handle
        """,
    })
    active, _ = _run(tmp_path, files)
    assert "DC107" not in _codes(active)


# ------------------------------------------------ DC108: retry backoff

_MINI_BACKOFF = """
    import random
    import time

    class Backoff:
        def __init__(self, base, cap, jitter=0.25, seed=None):
            self.base = base
            self.cap = cap
            self._rng = random.Random(seed)

        def delay(self, attempt):
            return min(self.base * (2 ** attempt), self.cap)

        def sleep(self, attempt):
            # the defining module's own literal sleep IS the policy plumbing
            while attempt > 0:
                time.sleep(0.01)
                attempt -= 1

        def attempts(self):
            k = 0
            while True:
                yield k
                self.sleep(k)
                k += 1
"""


def test_dc108_literal_retry_sleep_in_backoff_opted_module(tmp_path):
    """Seeded bug: a module imports the shared backoff policy yet still
    hard-codes a flat retry sleep in its dial loop; the clean twin drives
    the loop through the policy."""
    files = _wire_files(**{
        "utils/backoff.py": _MINI_BACKOFF,
        "utils/net.py": """
            import time
            from fixturepkg.utils.backoff import Backoff

            def connect(dial):
                policy = Backoff(0.05, 1.0)
                while True:
                    try:
                        return dial()
                    except OSError:
                        time.sleep(0.3)
        """,
    })
    active, _ = _run(tmp_path, files)
    assert "DC108" in _codes(active)
    fixed = dict(files)
    fixed["utils/net.py"] = """
        from fixturepkg.utils.backoff import Backoff

        def connect(dial):
            policy = Backoff(0.05, 1.0)
            for _attempt in policy.attempts():
                try:
                    return dial()
                except OSError:
                    pass
    """
    active, _ = _run(tmp_path, fixed)
    assert "DC108" not in _codes(active)


def test_dc108_defining_and_unopted_modules_exempt(tmp_path):
    files = _wire_files(**{
        # defines Backoff: its own plumbing is the raw path — exempt
        "utils/backoff.py": _MINI_BACKOFF,
        # never references the helper: out of scope (opt-in like DC105/107)
        "utils/net.py": """
            import time

            def connect(dial):
                while True:
                    try:
                        return dial()
                    except OSError:
                        time.sleep(0.3)
        """,
    })
    active, _ = _run(tmp_path, files)
    assert "DC108" not in _codes(active)


def test_dc108_non_literal_and_non_loop_sleeps_are_clean(tmp_path):
    files = _wire_files(**{
        "utils/backoff.py": _MINI_BACKOFF,
        "utils/net.py": """
            import time
            from fixturepkg.utils.backoff import Backoff

            def settle(policy, quiet):
                time.sleep(0.5)  # one-shot settle, not a retry loop
                while quiet():
                    time.sleep(policy.delay(1))  # policy-derived: fine
                return Backoff
        """,
    })
    active, _ = _run(tmp_path, files)
    assert "DC108" not in _codes(active)


# ----------------------------------------------------- DC2xx: concurrency

_GUARDED_BOX = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []

        def put(self, x):
            with self._lock:
                self.items.append(x)

        def put_front(self, x):
            with self._lock:
                self.items.insert(0, x)

        def drop(self):
            {drop_body}
"""


def test_dc201_mutation_outside_owning_lock(tmp_path):
    broken = {"box.py": _GUARDED_BOX.format(drop_body="self.items.clear()")}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC201"]
    clean = {"box.py": _GUARDED_BOX.format(
        drop_body="with self._lock:\n                self.items.clear()")}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc204_unguarded_read_of_lock_owned_attr(tmp_path):
    broken = {"box.py": _GUARDED_BOX.format(
        drop_body="with self._lock:\n                self.items.clear()")
        + "\n        def peek(self):\n            return len(self.items)\n"}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC204"]


def test_dc202_lock_order_cycle_including_transitive(tmp_path):
    broken = {"ab.py": """
        import threading

        class AB:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def f(self):
                with self.a:
                    self._tail()

            def _tail(self):
                with self.b:
                    pass

            def g(self):
                with self.b:
                    with self.a:
                        pass
    """}
    active, _ = _run(tmp_path, broken)
    assert "DC202" in _codes(active)
    clean = {"ab.py": """
        import threading

        class AB:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def f(self):
                with self.a:
                    self._tail()

            def _tail(self):
                with self.b:
                    pass

            def g(self):
                with self.a:
                    with self.b:
                        pass
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc203_thread_without_daemon_or_join(tmp_path):
    broken = {"spawn.py": """
        import threading

        def work():
            pass

        def go():
            t = threading.Thread(target=work)
            t.start()
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC203"]
    clean = {"spawn.py": """
        import threading

        def work():
            pass

        def go():
            t = threading.Thread(target=work, daemon=True)
            t.start()
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc203_not_masked_by_str_join(tmp_path):
    """A ``", ".join(names)`` in the creating scope must NOT count as
    joining the thread (review fix: str.join masked real findings)."""
    broken = {"spawn.py": """
        import threading

        def work():
            pass

        def go(names):
            t = threading.Thread(target=work)
            t.start()
            return ", ".join(names)
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC203"]
    clean = {"spawn.py": """
        import threading

        def work():
            pass

        def go(names):
            t = threading.Thread(target=work)
            t.start()
            t.join(timeout=5)
            return ", ".join(names)
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc205_cross_thread_attr_without_lock(tmp_path):
    broken = {"srv.py": """
        import threading

        class Srv:
            def __init__(self):
                self.count = 0
                self._t = threading.Thread(target=self.run, daemon=True)

            def run(self):
                self.count += 1

            def read(self):
                return self.count
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC205"]
    clean = {"srv.py": """
        import threading

        class Srv:
            def __init__(self):
                self.count = 0
                self._mu = threading.Lock()
                self._t = threading.Thread(target=self.run, daemon=True)

            def run(self):
                with self._mu:
                    self.count += 1

            def read(self):
                with self._mu:
                    return self.count
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_function_local_class_does_not_crash_analyzer(tmp_path):
    """A Thread target on a function-LOCAL class must not crash the run
    (review fix: the class table only holds top-level classes); the
    thread-discipline check still applies."""
    files = {"local.py": """
        import threading

        def make():
            class Worker:
                def run(self):
                    pass

                def go(self):
                    t = threading.Thread(target=self.run)
                    t.start()

            return Worker()
    """}
    active, _ = _run(tmp_path, files)
    assert [f.code for f in active] == ["DC203"]


def test_dc205_bool_flags_are_exempt(tmp_path):
    files = {"srv.py": """
        import threading

        class Srv:
            def __init__(self):
                self.closed = False
                self._t = threading.Thread(target=self.run, daemon=True)

            def run(self):
                while not self.closed:
                    pass

            def close(self):
                self.closed = True
    """}
    active, _ = _run(tmp_path, files)
    assert not active, [f.render() for f in active]


# ------------------------------------------------------- DC3xx: tracing

def test_dc301_branch_on_traced_value(tmp_path):
    broken = {"step.py": """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC301"]
    clean = {"step.py": """
        import jax

        @jax.jit
        def f(x):
            s = x.shape[0]
            if s > 1:
                return x
            return -x
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc302_host_state_read_in_traced_fn(tmp_path):
    broken = {"step.py": """
        import time
        import jax

        @jax.jit
        def f(x):
            t = time.time()
            return x * t
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC302"]
    clean = {"step.py": """
        import time
        import jax

        @jax.jit
        def f(x, t):
            return x * t

        def call(x):
            return f(x, time.time())
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc303_key_reuse_without_split(tmp_path):
    broken = {"sample.py": """
        import jax

        @jax.jit
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))
            return a + b
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC303"]
    clean = {"sample.py": """
        import jax

        @jax.jit
        def f(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (2,))
            b = jax.random.uniform(k2, (2,))
            return a + b
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc304_donated_buffer_reused_after_call(tmp_path):
    broken = {"donate.py": """
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def loop(state, xs):
            out = step(state, xs)
            return out + state
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC304"]
    clean = {"donate.py": """
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def loop(state, xs):
            state = step(state, xs)
            return state
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc304_fires_inside_loop_bodies(tmp_path):
    """Real training loops donate inside ``for``/``if`` bodies — the scan
    must descend into compound statements (review fix) without cross-
    matching exclusive branches."""
    broken = {"donate.py": """
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def loop(state, xs):
            for x in xs:
                out = step(state, x)
            return out + state
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC304"]
    # donation in one branch, use in the OTHER branch: exclusive paths,
    # not a reuse — must stay silent
    clean = {"donate.py": """
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def run(state, x, flag):
            if flag:
                return step(state, x)
            else:
                return state * 2.0
    """}
    active, _ = _run(tmp_path, clean)
    assert not active


def test_dc305_host_device_syncs_in_traced_fns(tmp_path):
    """ISSUE 9 satellite: block_until_ready / .item() / np.asarray on a
    traced value inside a jit (or scan-body) function is a host-device
    sync in the step hot path — the perf twin of DC301-304."""
    broken = {"step.py": """
        import numpy as np
        import jax

        @jax.jit
        def f(x):
            y = x * 2
            y.block_until_ready()
            return y
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC305"]
    broken = {"step.py": """
        import numpy as np
        import jax

        @jax.jit
        def f(x):
            host = np.asarray(x)
            return host.sum()
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC305"]
    # scan bodies are nested defs inside a traced fn: taint flows in, and
    # subscripted receivers (losses[-1].item()) are still caught
    broken = {"step.py": """
        import jax

        @jax.jit
        def train(state, batches):
            def body(st, b):
                loss = st + b
                return st, loss.item()

            return jax.lax.scan(body, state, batches)
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC305"]


def test_dc305_fires_in_unjitted_scan_body(tmp_path):
    """A ``lax.scan`` body traces even when the enclosing function is not
    jitted — the finder marks bodies handed to scan/fori_loop/while_loop
    directly (fori_loop's body is its THIRD argument)."""
    broken = {"step.py": """
        import numpy as np
        import jax

        def drive(state, batches):
            def body(st, b):
                host = np.asarray(b)
                return st + host.sum(), st

            return jax.lax.scan(body, state, batches)
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC305"]
    broken = {"step.py": """
        import jax

        def drive(x):
            def body(i, acc):
                acc.block_until_ready()
                return acc + i

            return jax.lax.fori_loop(0, 10, body, x)
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC305"]


def test_scan_body_name_collision_resolves_lexically(tmp_path):
    """``def body`` is the convention for scan bodies AND host-only
    helpers; the finder must resolve the callback name from the call
    site's scope chain, not a file-wide first-def-wins map. Here the
    FIRST ``body`` is host-only (its np.asarray is fine) and the SECOND,
    inside another function, is the real scan body with the sync bug."""
    broken = {"step.py": """
        import numpy as np
        import jax

        def host_prep(rows):
            def body(row):
                return np.asarray(row).sum()

            return [body(r) for r in rows]

        def drive(state, batches):
            def body(st, b):
                host = np.asarray(b)
                return st + host.sum(), st

            return jax.lax.scan(body, state, batches)
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC305"]
    assert active[0].line > 10, active[0].render()  # the scan body, not host_prep's


def test_scan_call_inside_lambda_still_marks_body(tmp_path):
    """Lambda bodies are transparent to the traced-fn finder: a
    ``lax.scan(body, …)`` sited inside a lambda (the PHASES-table idiom)
    must still mark ``body`` — a coverage hole the scope-aware rewrite
    briefly opened."""
    broken = {"step.py": """
        import numpy as np
        import jax

        def drive(state, batches):
            def body(st, b):
                host = np.asarray(b)
                return st + host.sum(), st

            run = lambda: jax.lax.scan(body, state, batches)
            return run()
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC305"]


def test_scan_body_in_jitted_fn_keeps_outer_taint(tmp_path):
    """Regression for the direct scan-body marking: a body nested inside a
    jitted fn must STILL see the outer function's traced params as taint
    (branching on a closed-over traced value is DC301 even though the body
    is also handed to lax.scan directly)."""
    broken = {"step.py": """
        import jax

        @jax.jit
        def train(state, flag, batches):
            def body(st, b):
                if flag:
                    return st + b, st
                return st, st

            return jax.lax.scan(body, state, batches)
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC301"]


def test_dc305_clean_twins_stay_silent(tmp_path):
    # the correct shape: fetch AFTER the jitted call returns, np.asarray
    # on host values, and jnp ops inside the traced fn
    clean = {"step.py": """
        import numpy as np
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.asarray(x) * 2

        def drive(x, host_cfg):
            scale = np.asarray(host_cfg)  # host value: no finding
            y = f(x * scale)
            y.block_until_ready()         # outside the traced fn: fine
            return float(np.asarray(y))
    """}
    active, _ = _run(tmp_path, clean)
    assert not active, [f.render() for f in active]


def test_traced_detection_covers_shard_map_wrapping(tmp_path):
    broken = {"sharded.py": """
        import time
        import jax

        def make_step(mesh):
            def step(x):
                t = time.monotonic()
                return x * t

            return jax.jit(jax.shard_map(step, mesh=mesh))
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC302"]


# ------------------------------------------------------- suppressions

def test_suppression_silences_with_reason_and_flags_without(tmp_path):
    body = _GUARDED_BOX.format(
        drop_body="self.items.clear()  # distcheck: ignore[DC201] {reason}")
    active, suppressed = _run(
        tmp_path, {"box.py": body.format(reason="drop() is init-only")})
    assert not active and [f.code for f in suppressed] == ["DC201"]
    active, _ = _run(tmp_path, {"box.py": body.format(reason="")})
    assert "DC001" in _codes(active)  # reasonless ignore is itself flagged
    assert "DC201" in _codes(active)  # ... and does NOT silence the finding


def test_dc105_prose_mention_does_not_opt_in(tmp_path):
    """A comment/docstring mentioning ReliableTransport (e.g. a DC105
    suppression's own text) must not opt a module into reliability
    (review fix: the opt-in is AST-only)."""
    files = _wire_files(**{"training/client.py": """
        import numpy as np
        from fixturepkg.utils.messaging import MessageCode

        # this demo deliberately does not use ReliableTransport
        def dial(TCPTransport):
            t = TCPTransport(0, 2)
            return t
    """})
    active, _ = _run(tmp_path, files)
    assert "DC105" not in _codes(active)


def test_baseline_keys_number_duplicate_findings(tmp_path):
    """Two identical-message findings in one file get distinct baseline
    keys, so a parked entry covers exactly one occurrence (review fix)."""
    from distributed_ml_pytorch_tpu.analysis.core import baseline_keys

    broken = {"spawn.py": """
        import threading

        def work():
            pass

        def go():
            a = threading.Thread(target=work)
            a.start()
            b = threading.Thread(target=work)
            b.start()
    """}
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC203", "DC203"]
    keys = baseline_keys(active)
    assert len(set(keys)) == 2 and keys[1].endswith("| #2")


def test_unused_suppression_is_flagged(tmp_path):
    files = {"ok.py": """
        X = 1  # distcheck: ignore[DC201] nothing here needs this
    """}
    active, _ = _run(tmp_path, files)
    assert [f.code for f in active] == ["DC002"]


def test_multiline_suppression_covers_next_code_line(tmp_path):
    files = {"box.py": _GUARDED_BOX.format(
        drop_body="# distcheck: ignore[DC201] drop is only called after\n"
                  "            # every worker thread has been joined\n"
                  "            self.items.clear()")}
    active, suppressed = _run(tmp_path, files)
    assert not active and [f.code for f in suppressed] == ["DC201"]


# ------------------------------------------------ DC4xx: protocol model

_PROTO_MESSAGING = """
    import enum

    class MessageCode(enum.IntEnum):
        Push = 0
        Join = 1
        Fleet = 2
        Act = 3

    class PayloadSchema:
        def __init__(self, fields=(), rest=None, rest_min=0, handled_by=(),
                     dedup_key=None, durability="none", delivery="reliable",
                     rest_sections=(), rest_separator=None):
            self.fields = fields
            self.rest = rest
            self.handled_by = handled_by
            self.dedup_key = dedup_key

    WIRE_SCHEMAS = {
        MessageCode.Push: PayloadSchema(
            rest="params", handled_by=("ps",),
            dedup_key="env_seq", durability="wal_before_ack"),
        MessageCode.Join: PayloadSchema(
            fields=("inc",), handled_by=("coord",),
            dedup_key="incarnation"),
        MessageCode.Fleet: PayloadSchema(
            fields=("v",), rest="tail", handled_by=("coord",),
            dedup_key="version",
            rest_sections=("ranks", "metrics"), rest_separator=-1.0),
        MessageCode.Act: PayloadSchema(
            fields=("codec",), rest="body", rest_min=1,
            handled_by=("ps",), dedup_key="idempotent"),
    }
"""

_PROTO_SERVER = """
    from fixturepkg.utils.messaging import MessageCode

    class Shard:
        def handle(self, code, payload, delta):
            if code == MessageCode.Push:
                self.wal.append(self.seq, delta)
                self.central += delta
            if code == MessageCode.Act and payload.size >= 2:
                self.acts = self.codec.decode(payload[1:])

        def commit(self):
            self.wal.sync()
            self.transport.ack_delivered()
"""

_PROTO_HUB = """
    from fixturepkg.utils.messaging import MessageCode

    def decode_fleet(payload):
        tail = payload[1:]
        split = [i for i, v in enumerate(tail) if v < 0]
        ranks = tail[:split[0]] if split else tail
        metrics = tail[split[0] + 1:] if split else []
        return {"ranks": list(ranks), "metrics": list(metrics)}

    class Hub:
        def handle(self, sender, code, payload):
            if code == MessageCode.Join and payload.size >= 1:
                inc = payload[0]
                if inc < self.member_inc:
                    return
                self.member_inc = inc
            if code == MessageCode.Fleet and payload.size >= 1:
                self.view = decode_fleet(payload)
"""

_PROTO_SENDERS = """
    import numpy as np
    from fixturepkg.utils.messaging import MessageCode

    def push(transport, grad):
        transport.send(MessageCode.Push, grad)

    def announce(transport, inc, frame):
        transport.send(MessageCode.Join,
                       np.asarray([float(inc)], np.float32))
        transport.send(MessageCode.Fleet, frame)

    def ship_acts(transport, codec, acts):
        cid, body = codec.encode_body(acts)
        transport.send(MessageCode.Act,
                       np.concatenate([np.asarray([float(cid)],
                                                  np.float32), body]))
"""


_PROTO_COORD = """
    class Arbiter:
        def admit(self, sender, kind, inc):
            self._wal_record(op="join", rank=sender, kind=kind, inc=inc)
            self.members[sender] = (kind, inc)

        def expire(self, rank):
            self._wal_record(op="expire", rank=rank)
            self.members.pop(rank, None)

        def park(self, rank, ticket):
            self._wal_record(op="park", rank=rank, parked=ticket)
            self._parked_durable[rank] = dict(ticket)

        def _apply_wal_op(self, op):
            # the restore path reconstructs FROM the log and never logs —
            # it carries no _wal_record call, so DC406 leaves it unscoped
            if op["op"] == "join":
                self.members[op["rank"]] = op["inc"]
"""


def _proto_files(**overrides):
    files = {
        "utils/messaging.py": _PROTO_MESSAGING,
        "parallel/server.py": _PROTO_SERVER,
        "coord/hub.py": _PROTO_HUB,
        "parallel/worker.py": _PROTO_SENDERS,
        "coord/arbiter.py": _PROTO_COORD,
    }
    files.update(overrides)
    return files


def test_proto_clean_twin_is_silent(tmp_path):
    active, _ = _run(tmp_path, _proto_files())
    assert not active, [f.render() for f in active]


def test_dc401_reliable_send_without_dedup_key(tmp_path):
    broken = _proto_files(**{"utils/messaging.py": _PROTO_MESSAGING.replace(
        'dedup_key="env_seq", durability="wal_before_ack"',
        'durability="wal_before_ack"')})
    active, _ = _run(tmp_path, broken)
    assert "DC401" in _codes(active)
    assert any("no dedup_key" in f.message for f in active)


def test_dc401_vocabulary_and_delivery_mismatch(tmp_path):
    broken = _proto_files(**{"utils/messaging.py": _PROTO_MESSAGING.replace(
        'dedup_key="env_seq"', 'dedup_key="vibes"')})
    active, _ = _run(tmp_path, broken)
    assert "DC401" in _codes(active)
    assert any("vocabulary" in f.message for f in active)


def test_dc402_apply_before_wal_append(tmp_path):
    broken = _proto_files(**{"parallel/server.py": _PROTO_SERVER.replace(
        """self.wal.append(self.seq, delta)
                self.central += delta""",
        """self.central += delta
                self.wal.append(self.seq, delta)""")})
    active, _ = _run(tmp_path, broken)
    assert _codes(active) == ["DC402"]
    assert "BEFORE the WAL append" in active[0].message


def test_dc403_ack_released_before_group_fsync(tmp_path):
    broken = _proto_files(**{"parallel/server.py": _PROTO_SERVER.replace(
        """self.wal.sync()
            self.transport.ack_delivered()""",
        """self.transport.ack_delivered()
            self.wal.sync()""")})
    active, _ = _run(tmp_path, broken)
    assert _codes(active) == ["DC403"]
    assert "BEFORE the WAL group-fsync" in active[0].message


def test_dc404_incarnation_update_without_gate(tmp_path):
    broken = _proto_files(**{"coord/hub.py": _PROTO_HUB.replace(
        """inc = payload[0]
                if inc < self.member_inc:
                    return
                self.member_inc = inc""",
        """self.member = payload[0]""")})
    active, _ = _run(tmp_path, broken)
    assert _codes(active) == ["DC404"]
    assert "incarnation" in active[0].message


def test_dc405_multi_section_tail_needs_separator(tmp_path):
    broken = _proto_files(**{"utils/messaging.py": _PROTO_MESSAGING.replace(
        "rest_sections=(\"ranks\", \"metrics\"), rest_separator=-1.0",
        "rest_sections=(\"ranks\", \"metrics\")")})
    active, _ = _run(tmp_path, broken)
    assert "DC405" in _codes(active)
    assert any("without a rest_separator" in f.message for f in active)


def test_dc405_decoder_must_split_on_declared_separator(tmp_path):
    broken = _proto_files(**{"coord/hub.py": _PROTO_HUB.replace(
        """        tail = payload[1:]
        split = [i for i, v in enumerate(tail) if v < 0]
        ranks = tail[:split[0]] if split else tail
        metrics = tail[split[0] + 1:] if split else []
        return {"ranks": list(ranks), "metrics": list(metrics)}""",
        """        tail = payload[1:]
        return {"ranks": list(tail), "metrics": []}""")})
    active, _ = _run(tmp_path, broken)
    assert _codes(active) == ["DC405"]
    assert "splits on it" in active[0].message


def test_dc406_member_table_mutation_above_durable_log(tmp_path):
    broken = _proto_files(**{"coord/arbiter.py": _PROTO_COORD.replace(
        """self._wal_record(op="join", rank=sender, kind=kind, inc=inc)
            self.members[sender] = (kind, inc)""",
        """self.members[sender] = (kind, inc)
            self._wal_record(op="join", rank=sender, kind=kind, inc=inc)""")})
    active, _ = _run(tmp_path, broken)
    assert _codes(active) == ["DC406"]
    assert "the restart replay never sees" in active[0].message


def test_dc406_expiry_pop_and_park_ledger_above_durable_log(tmp_path):
    """Both mutation shapes the coordinator actually uses — the
    ``members.pop`` eviction and the parked-ledger subscript write — are
    flagged when hoisted above their log records."""
    broken = _proto_files(**{"coord/arbiter.py": _PROTO_COORD.replace(
        """self._wal_record(op="expire", rank=rank)
            self.members.pop(rank, None)""",
        """self.members.pop(rank, None)
            self._wal_record(op="expire", rank=rank)""").replace(
        """self._wal_record(op="park", rank=rank, parked=ticket)
            self._parked_durable[rank] = dict(ticket)""",
        """self._parked_durable[rank] = dict(ticket)
            self._wal_record(op="park", rank=rank, parked=ticket)""")})
    active, _ = _run(tmp_path, broken)
    assert _codes(active) == ["DC406", "DC406"]
    attrs = sorted(f.message.split()[3] for f in active)
    assert attrs == ["self._parked_durable", "self.members"]


def test_dc407_codec_frame_sent_around_the_registry(tmp_path):
    """A send site that stamps a codec id on the frame head without any
    registry encoder call in the enclosing function is flagged — the
    body bypassed the codec plane."""
    broken = _proto_files(**{"parallel/worker.py": _PROTO_SENDERS.replace(
        """    def ship_acts(transport, codec, acts):
        cid, body = codec.encode_body(acts)
        transport.send(MessageCode.Act,
                       np.concatenate([np.asarray([float(cid)],
                                                  np.float32), body]))""",
        """    def ship_acts(transport, codec, acts):
        transport.send(MessageCode.Act,
                       np.concatenate([np.asarray([1.0],
                                                  np.float32), acts]))""")})
    active, _ = _run(tmp_path, broken)
    assert _codes(active) == ["DC407"]
    assert "bypassed the codec plane" in active[0].message


def test_dc407_exempts_the_messaging_layer(tmp_path):
    """The layer that IS the plumbing may forward codec-bearing frames
    without re-encoding (retransmits, envelope relays)."""
    broken = _proto_files(**{"utils/messaging.py": _PROTO_MESSAGING + """

    def relay(transport, frame):
        transport.send(MessageCode.Act, frame)
"""})
    active, _ = _run(tmp_path, broken)
    assert "DC407" not in _codes(active), [f.render() for f in active]


def test_dc4xx_silent_without_protocol_annotations(tmp_path):
    """The opt-in discipline (DC105/107/108 precedent): a schema table
    with NO protocol-model annotations — the DC1xx fixture corpora, any
    third-party tree — must see no DC4xx findings at all, even with a
    reliable send and no dedup keys anywhere."""
    active, _ = _run(tmp_path, _wire_files())
    assert not [f for f in active if f.code.startswith("DC4")]


# -------------------------------------------- analysis/ self-analysis

def test_analysis_package_self_clean(tmp_path):
    """The ISSUE 13 satellite: distcheck over the analyzer package
    ITSELF (concurrency + tracing + protocol rules all apply to the
    checker's own code) must be clean — no findings, no stale
    suppressions."""
    root = os.path.join(_package_root(), "analysis")
    active, _ = analyze_path(root, rel_base=os.path.dirname(_package_root()))
    assert not active, [f.render() for f in active]


# ------------------------------------------------- the real package

def _package_root():
    import distributed_ml_pytorch_tpu

    return os.path.dirname(os.path.abspath(distributed_ml_pytorch_tpu.__file__))


@pytest.fixture(scope="module")
def real_pkg():
    """The installed package, parsed once for the whole module (parsing
    ~60 files dominates the analyzer's wall time)."""
    from distributed_ml_pytorch_tpu.analysis.core import load_package

    return load_package(_package_root())


def test_real_package_has_no_findings_beyond_baseline(real_pkg):
    from distributed_ml_pytorch_tpu.analysis import analyze
    from distributed_ml_pytorch_tpu.analysis.core import baseline_keys

    active, suppressed = analyze(real_pkg)
    baseline = read_baseline(os.path.join(HERE, "distcheck_baseline.txt"))
    new = [f for f, k in zip(active, baseline_keys(active))
           if k not in baseline]
    assert not new, "new distcheck findings:\n" + "\n".join(
        f.render() for f in new)
    # the acceptance bar: every live suppression carries a reason (a
    # reasonless one would have surfaced as an active DC001 above)
    assert all(f.code.startswith("DC") for f in suppressed)


# --------------------------------------------------- runtime witness

def test_witness_detects_cyclic_acquisition_order():
    from distributed_ml_pytorch_tpu.analysis.witness import LockOrderWitness

    w = LockOrderWitness().install()
    try:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:  # the reverse order — latent deadlock
                pass
    finally:
        w.uninstall()
    assert w.cycles(), w.report()


def test_witness_cross_validates_static_lock_model(real_pkg):
    """A live reliable-transport scenario under the witness: every lock it
    observes in the package maps to a statically known creation site, and
    the observed acquisition order is acyclic."""
    from distributed_ml_pytorch_tpu.analysis import concurrency
    from distributed_ml_pytorch_tpu.analysis.witness import LockOrderWitness

    pkg_root = _package_root()
    static_sites = concurrency.collect_lock_sites(real_pkg)
    static_lines = {(os.path.basename(p), line) for p, line in static_sites}

    w = LockOrderWitness(package_root=pkg_root).install()
    try:
        from distributed_ml_pytorch_tpu.utils.messaging import (
            InProcessTransport,
            MessageCode,
            ReliableTransport,
        )

        world = InProcessTransport.create_world(2)
        server = ReliableTransport(world[0], ack_timeout=0.05)
        worker = ReliableTransport(world[1], ack_timeout=0.05)
        got = []

        def serve():
            while len(got) < 8:
                msg = server.recv(timeout=0.2)
                if msg is None:
                    continue
                got.append(msg)
                server.send(MessageCode.ParameterUpdate, msg[2], dst=msg[0])

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        for i in range(8):
            worker.send(MessageCode.GradientUpdate,
                        np.full(4, float(i), np.float32))
        deadline = time.monotonic() + 10
        while len(got) < 8 and time.monotonic() < deadline:
            worker.recv(timeout=0.05)
        t.join(timeout=5)
        worker.close()
        server.close()
    finally:
        w.uninstall()
    assert len(got) == 8
    observed = w.package_sites()
    assert observed, "witness saw no package locks — is it installed?"
    unknown = {(os.path.basename(p), line) for p, line in observed} - static_lines
    assert not unknown, f"locks unknown to the static model: {unknown}"
    assert not w.cycles(), w.report()


# --------------------------------- regression tests for the fixed defects

def test_frontend_on_tokens_takes_route_lock():
    """The engine-thread stream callback must hold the route-table lock
    (the DC204 fix): with the lock held elsewhere, the callback blocks."""
    from distributed_ml_pytorch_tpu.serving.frontend import ServingFrontend

    fe = ServingFrontend.__new__(ServingFrontend)
    fe._routes_lock = threading.Lock()
    fe._routes = {}
    req = types.SimpleNamespace(request_id=1)
    done = threading.Event()

    def cb():
        fe._on_tokens(req, [1], False)
        done.set()

    with fe._routes_lock:
        t = threading.Thread(target=cb, daemon=True)
        t.start()
        assert not done.wait(0.25), "_on_tokens ignored _routes_lock"
    assert done.wait(2.0)


def test_elastic_server_snapshot_is_lock_consistent():
    """The DC205 fix: resize/apply and external readers share one mutex,
    and a snapshot always sees matching (lo, hi, central)."""
    from distributed_ml_pytorch_tpu.coord.elastic import ElasticShardServer
    from distributed_ml_pytorch_tpu.coord.shardmap import ShardEntry, ShardMap
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    world = InProcessTransport.create_world(2)
    coord = types.SimpleNamespace(
        report=lambda *a: None, stop=lambda: None, close=lambda: None)
    srv = ElasticShardServer(
        server_id=1, n_params=12, transport=world[0], coord=coord,
        init_params=np.arange(12, dtype=np.float32))
    srv._apply_map(ShardMap(1, 12, [ShardEntry(1, 0, 12, 0, 0)]))
    snap = srv.snapshot()
    assert snap["hi"] - snap["lo"] == snap["central"].shape[0] == 12

    done = threading.Event()

    def mutate():
        # the stamped elastic push (ISSUE 6 wire): (version, lo, hi) head
        from distributed_ml_pytorch_tpu.utils.messaging import _split16

        srv.handle(1, MessageCode.ShardPush, np.concatenate(
            [np.asarray([*_split16(1), *_split16(0), *_split16(12)],
                        np.float32), np.ones(12, np.float32)]))
        done.set()

    with srv._mu:
        t = threading.Thread(target=mutate, daemon=True)
        t.start()
        assert not done.wait(0.25), "handle() ignored the server mutex"
    assert done.wait(2.0)
    assert srv.snapshot()["central"][0] == 1.0  # the push landed once


def test_reliable_send_checks_dead_peers_under_lock():
    """The DC204 fix in ReliableTransport.send: the dead-peer check rides
    the transport lock."""
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
        ReliableTransport,
    )

    world = InProcessTransport.create_world(2)
    rt = ReliableTransport(world[0], ack_timeout=0.05)
    done = threading.Event()

    def send():
        rt.send(MessageCode.GradientUpdate, np.zeros(2, np.float32), dst=1)
        done.set()

    with rt._lock:
        t = threading.Thread(target=send, daemon=True)
        t.start()
        assert not done.wait(0.25), "send() ignored the transport lock"
    assert done.wait(2.0)
    rt.close()


def test_tcp_peer_table_is_mutex_guarded():
    """The DC205 fix in TCPTransport: the peer/send-lock tables are behind
    _peers_mu, and the per-peer serializer is stable across calls."""
    from distributed_ml_pytorch_tpu.utils.messaging import TCPTransport

    t = TCPTransport(0, 1, port=29731)  # solo server: no rendezvous wait
    try:
        assert t._send_lock_for(5) is t._send_lock_for(5)
        got = threading.Event()

        def lookup():
            t._send_lock_for(6)
            got.set()

        with t._peers_mu:
            thread = threading.Thread(target=lookup, daemon=True)
            thread.start()
            assert not got.wait(0.25), "_send_lock_for ignored _peers_mu"
        assert got.wait(2.0)
    finally:
        t.close()


def test_coord_client_progress_guarded():
    """The DC205 fix in CoordClient: report() writes the progress tuple
    under the client lock the renew thread reads it with."""
    from distributed_ml_pytorch_tpu.coord.member import CoordClient
    from distributed_ml_pytorch_tpu.utils.messaging import InProcessTransport

    world = InProcessTransport.create_world(2)
    client = CoordClient(world[1], "worker", renew_interval=30.0)
    try:
        done = threading.Event()

        def report():
            client.report(1, 2, 3.0)
            done.set()

        with client._lock:
            t = threading.Thread(target=report, daemon=True)
            t.start()
            assert not done.wait(0.25), "report() ignored the client lock"
        assert done.wait(2.0)
        with client._lock:
            assert client._progress == (1, 2, 3.0, 0, 0, 0, 0.0, 0.0)
    finally:
        client.stop()


# ------------------------------------------------- DC5xx: dataflow (ISSUE 19)
#
# One clean fixture package (codec-bearing Grad, fenced Cmd, a thread-pump
# class, a lock-holding flusher) that the whole analyzer is SILENT on; each
# seeded twin is a targeted mutation of one file, so every test pins both
# the fire and the silence.

_FLOW_MESSAGING = """
    import enum

    class MessageCode(enum.IntEnum):
        Grad = 0
        Cmd = 1

    class PayloadSchema:
        def __init__(self, fields=(), rest=None, rest_min=0, handled_by=(),
                     dedup_key=None, fenced=False):
            self.fields = fields
            self.rest = rest
            self.handled_by = handled_by
            self.dedup_key = dedup_key
            self.fenced = fenced

    WIRE_SCHEMAS = {
        MessageCode.Grad: PayloadSchema(
            fields=("codec", "crc_lo"), rest="body", rest_min=1,
            handled_by=("ps",), dedup_key="idempotent"),
        MessageCode.Cmd: PayloadSchema(
            fields=("epoch", "version"), rest="map",
            handled_by=("coord",), dedup_key="version", fenced=True),
    }
"""

_FLOW_SERVER = """
    from fixturepkg.utils.messaging import MessageCode

    class GradServer:
        def handle(self, sender, code, payload):
            if code == MessageCode.Grad and payload.size >= 3:
                if not self.check_crc(payload):
                    return
                body = self.codec.decode(payload[2:])
                self._apply(body)

        def _apply(self, body):
            self.acc += body
"""

_FLOW_HUB = """
    from fixturepkg.utils.messaging import MessageCode

    class CmdHub:
        def handle(self, sender, code, payload):
            if code == MessageCode.Cmd and payload.size >= 2:
                if payload[0] < self.cmd_epoch:
                    return
                self.version = payload[1]
                self.live_map = self.decode_map(payload[2:])
"""

_FLOW_PUMP = """
    import threading

    class Pump:
        def __init__(self):
            self.inbox = {}
            self.seen = []
            self._t = threading.Thread(target=self.run, daemon=True)

        def run(self):
            while True:
                key, body = self.poll()
                self.inbox[key] = body
                self.seen.append(key)
                self.compact()

        def compact(self):
            while len(self.seen) > 64:
                old = self.seen.pop(0)
                self.inbox.pop(old, None)
"""

_FLOW_FLUSHER = """
    import threading
    import time

    class Flusher:
        def __init__(self):
            self._mu = threading.Lock()
            self._t = threading.Thread(target=self.run, daemon=True)

        def run(self):
            while True:
                with self._mu:
                    batch = self.drain()
                self.wal.sync()
                time.sleep(0.01)
"""

_FLOW_SENDERS = """
    import numpy as np
    from fixturepkg.utils.messaging import MessageCode

    def push_grad(transport, codec, grad):
        transport.send(MessageCode.Grad, codec.encode(grad))

    def push_cmd(transport, frame):
        transport.send(MessageCode.Cmd, frame)
"""


def _flow_files(**overrides):
    files = {
        "utils/messaging.py": _FLOW_MESSAGING,
        "parallel/server.py": _FLOW_SERVER,
        "coord/hub.py": _FLOW_HUB,
        "utils/pump.py": _FLOW_PUMP,
        "utils/flusher.py": _FLOW_FLUSHER,
        "parallel/worker.py": _FLOW_SENDERS,
    }
    files.update(overrides)
    return files


@pytest.mark.distflow
def test_flow_clean_twin_is_silent(tmp_path):
    active, _ = _run(tmp_path, _flow_files())
    assert not active, [f.render() for f in active]


@pytest.mark.distflow
def test_dc501_raw_bytes_applied_before_decode(tmp_path):
    # the apply consumes the raw slice instead of the decoded body
    broken = _flow_files(**{"parallel/server.py": _FLOW_SERVER.replace(
        "self._apply(body)", "self.acc += payload[2:]")})
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC501"]
    assert "raw (undecoded) payload bytes reach self.acc" in \
        active[0].message


@pytest.mark.distflow
def test_dc501_interprocedural_raw_delegate(tmp_path):
    # the handler delegates the RAW slice one call deep; the sink is in
    # the callee — the one-level follow must carry the taint through
    broken = _flow_files(**{"parallel/server.py": _FLOW_SERVER.replace(
        "self._apply(body)", "self._apply(payload[2:])")})
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC501"]


@pytest.mark.distflow
def test_dc501_gate_wrapped_consumption_is_clean(tmp_path):
    # consuming THROUGH the gate call in one expression is the contract
    ok = _flow_files(**{"parallel/server.py": _FLOW_SERVER.replace(
        "body = self.codec.decode(payload[2:])\n                self._apply(body)",
        "self._apply(self.codec.decode(payload[2:]))")})
    active, _ = _run(tmp_path, ok)
    assert not active, [f.render() for f in active]


@pytest.mark.distflow
def test_dc502_fenced_mutation_without_epoch_gate(tmp_path):
    broken = _flow_files(**{"coord/hub.py": _FLOW_HUB.replace(
        "                if payload[0] < self.cmd_epoch:\n"
        "                    return\n", "")})
    active, _ = _run(tmp_path, broken)
    assert set(_codes(active)) == {"DC502"}
    assert all("fenced frame" in f.message for f in active)


@pytest.mark.distflow
def test_dc503_unbounded_handler_state(tmp_path):
    leaky = _FLOW_PUMP.replace("                self.compact()\n", "") \
        .replace("""
        def compact(self):
            while len(self.seen) > 64:
                old = self.seen.pop(0)
                self.inbox.pop(old, None)
""", "")
    assert "compact" not in leaky  # the seed really removed the prune
    active, _ = _run(tmp_path, _flow_files(**{"utils/pump.py": leaky}))
    assert _codes(active) == ["DC503", "DC503"]
    assert {m.split(" grows")[0].split()[-1] for m in
            (f.message for f in active)} == {"Pump.inbox", "Pump.seen"}


@pytest.mark.distflow
def test_dc503_pruned_containers_become_witness_exemptions(tmp_path):
    """The clean pump's containers are cleared by FALLIBLE evidence, so
    they must surface in bounded_exemptions() for the runtime witness."""
    from distributed_ml_pytorch_tpu.analysis import distflow
    from distributed_ml_pytorch_tpu.analysis.core import load_package

    root = tmp_path / "fixturepkg"
    for rel, text in _flow_files().items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    exempt = distflow.bounded_exemptions(
        load_package(str(root), rel_base=str(tmp_path)))
    assert {(e.cls, e.attr) for e in exempt} >= {
        ("Pump", "inbox"), ("Pump", "seen")}
    assert all(e.reason for e in exempt)


@pytest.mark.distflow
def test_dc503_bounded_ctor_is_structural_not_watched(tmp_path):
    """deque(maxlen=...) is structurally bounded: no finding AND no
    witness watch entry."""
    from distributed_ml_pytorch_tpu.analysis import distflow
    from distributed_ml_pytorch_tpu.analysis.core import load_package

    bounded = _FLOW_PUMP.replace(
        "self.seen = []", "self.seen = collections.deque(maxlen=64)") \
        .replace("import threading", "import collections\n    import threading")
    root = tmp_path / "fixturepkg"
    files = _flow_files(**{"utils/pump.py": bounded})
    for rel, text in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    pkg = load_package(str(root), rel_base=str(tmp_path))
    assert not [f for f in distflow.check(pkg) if f.code == "DC503"]
    assert ("Pump", "seen") not in {
        (e.cls, e.attr) for e in distflow.bounded_exemptions(pkg)}


@pytest.mark.distflow
def test_dc503_memo_idiom_is_exempt_but_watched(tmp_path):
    """A presence-gated insert (`if k in self.m: return` before `m[k]=`)
    is a memo keyed by a finite domain — exempt, but witness-watched."""
    from distributed_ml_pytorch_tpu.analysis import distflow
    from distributed_ml_pytorch_tpu.analysis.core import load_package

    memo = _FLOW_PUMP.replace("""
                self.inbox[key] = body
                self.seen.append(key)
                self.compact()
""", """
                if key in self.inbox:
                    continue
                self.inbox[key] = body
""").replace("""
        def compact(self):
            while len(self.seen) > 64:
                old = self.seen.pop(0)
                self.inbox.pop(old, None)
""", "").replace("            self.seen = []\n", "")
    root = tmp_path / "fixturepkg"
    for rel, text in _flow_files(**{"utils/pump.py": memo}).items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    pkg = load_package(str(root), rel_base=str(tmp_path))
    assert not [f for f in distflow.check(pkg) if f.code == "DC503"]
    assert ("Pump", "inbox") in {
        (e.cls, e.attr) for e in distflow.bounded_exemptions(pkg)}


@pytest.mark.distflow
def test_dc504_direct_block_while_holding_lock(tmp_path):
    broken = _flow_files(**{"utils/flusher.py": _FLOW_FLUSHER.replace(
        """                with self._mu:
                    batch = self.drain()
                self.wal.sync()""",
        """                with self._mu:
                    batch = self.drain()
                    self.wal.sync()""")})
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC504"]
    assert "wal.sync() (group fsync) while holding Flusher._mu" in \
        active[0].message


@pytest.mark.distflow
def test_dc504_transitive_block_through_same_class_call(tmp_path):
    broken = _flow_files(**{"utils/flusher.py": _FLOW_FLUSHER.replace(
        """                with self._mu:
                    batch = self.drain()
                self.wal.sync()
                time.sleep(0.01)""",
        """                with self._mu:
                    self.flush()

        def flush(self):
            self.wal.sync()""")})
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC504"]
    assert "transitively" in active[0].message


@pytest.mark.distflow
def test_dc504_condition_wait_on_held_lock_is_exempt(tmp_path):
    # cv.wait() releases the lock it waits on — the held-lock wait is the
    # condition-variable idiom, not a stall; waiting on a DIFFERENT
    # object while holding the lock IS the stall
    ok = _flow_files(**{"utils/flusher.py": _FLOW_FLUSHER.replace(
        "                self.wal.sync()\n",
        "                with self._mu:\n"
        "                    self._mu.wait()\n")})
    active, _ = _run(tmp_path, ok)
    assert not active, [f.render() for f in active]
    broken = _flow_files(**{"utils/flusher.py": _FLOW_FLUSHER.replace(
        "                self.wal.sync()\n",
        "                with self._mu:\n"
        "                    self.done_evt.wait()\n")})
    active, _ = _run(tmp_path, broken)
    assert [f.code for f in active] == ["DC504"]


@pytest.mark.distflow
def test_dc5xx_suppression_with_reason(tmp_path):
    leaky = _FLOW_PUMP.replace("                self.compact()\n", "") \
        .replace("""
        def compact(self):
            while len(self.seen) > 64:
                old = self.seen.pop(0)
                self.inbox.pop(old, None)
""", "").replace(
        "self.seen.append(key)",
        "self.seen.append(key)  # distcheck: ignore[DC503] audit capped by scenario length")
    active, suppressed = _run(
        tmp_path, _flow_files(**{"utils/pump.py": leaky}))
    assert _codes(active) == ["DC503"]  # inbox still fires
    assert _codes(suppressed) == ["DC503"]  # seen silenced with a reason


@pytest.mark.distflow
def test_bounded_witness_catches_wrongly_cleared_container(tmp_path):
    """The DC503 prune exemption is textual — 'a pop exists in the
    class' — so a prune that never RUNS still clears statically. The
    runtime witness is the backstop: a watched container that only ever
    grew across samples and ended past budget fails the scenario."""
    from distributed_ml_pytorch_tpu.analysis import distflow
    from distributed_ml_pytorch_tpu.analysis.core import load_package
    from distributed_ml_pytorch_tpu.analysis.witness import (
        BoundedStateWitness,
    )

    # static: the pump's containers are cleared by fallible prune
    # evidence, so they are exactly what the witness watches
    root = tmp_path / "fixturepkg"
    for rel, text in _flow_files().items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    exempt = {(e.cls, e.attr) for e in distflow.bounded_exemptions(
        load_package(str(root), rel_base=str(tmp_path)))}
    assert ("Pump", "inbox") in exempt

    # runtime: this pump's compact() guard is dead — it never prunes
    class Pump:
        def __init__(self):
            self.inbox = {}

        def on_msg(self, key, body):
            self.inbox[key] = body
            self.compact()

        def compact(self):
            while len(self.inbox) > 10 ** 9:  # wrong threshold: dead
                self.inbox.pop(next(iter(self.inbox)))

    w = BoundedStateWitness(budget=100)
    pump = Pump()
    w.watch("Pump.inbox", pump.inbox, budget=100)
    for i in range(200):
        pump.on_msg(i, i)
        if i % 20 == 0:
            w.sample()
    w.sample()
    violations = w.violations()
    assert violations and "Pump.inbox" in violations[0], violations

    # a prune that actually runs produces a dip — no violation
    w2 = BoundedStateWitness(budget=100)
    working = Pump()
    w2.watch("Pump.inbox", working.inbox, budget=100)
    for i in range(200):
        working.inbox[i] = i
        if len(working.inbox) > 150:
            working.inbox.clear()
        if i % 20 == 0:
            w2.sample()
    w2.sample()
    assert not w2.violations(), w2.violations()


@pytest.mark.distflow
def test_witness_gc_scan_flags_exempt_container_over_budget(monkeypatch):
    """The teardown hook's auto-discovery: one gc pass finds live
    instances of statically-exempt (class, attr) pairs and reports any
    container over budget."""
    from distributed_ml_pytorch_tpu.analysis import witness

    class Scanned:
        pass

    monkeypatch.setattr(
        witness, "_EXEMPT_INDEX",
        {(Scanned.__module__, "Scanned"): {"box"}})
    obj = Scanned()
    obj.box = dict.fromkeys(range(5000))
    assert ("Scanned", "box", 5000) in witness.check_exempt_budget(4096)
    obj.box = {}
    assert not witness.check_exempt_budget(4096)


# -------------------------------------- ISSUE 19 real-tree DC503 regressions

@pytest.mark.distflow
def test_mpmd_driver_retires_ship_state():
    """The DC503 fix in MpmdDriver: token/target bodies, ce reports and
    corr ids for steps past the restart-replay window are dropped — the
    driver no longer holds every (step, mb) it ever shipped."""
    from distributed_ml_pytorch_tpu.parallel.mpmd import MpmdDriver

    coord = types.SimpleNamespace(on_stage_assign=None)
    d = MpmdDriver(None, coord, n_stages=2, n_microbatches=2)
    for t in range(10):
        for mbi in range(2):
            d._tokens[(t, mbi)] = np.zeros(1, np.float32)
            d._targets[(t, mbi)] = np.zeros(1, np.float32)
            d._ce[(t, mbi)] = 0.0
            d._mb_corr[(t, mbi)] = 7
    d._retire_below(6)
    for store in (d._tokens, d._targets, d._ce, d._mb_corr):
        assert {k[0] for k in store} == {6, 7, 8, 9}
    d._retire_below(0)  # no-op floor
    assert {k[0] for k in d._tokens} == {6, 7, 8, 9}


@pytest.mark.distflow
def test_coordinator_metric_accumulators_are_rings():
    """The DC503 fixes: per-event metric lists on long-running control
    classes became rings — a long fleet lifetime cannot grow them
    without bound."""
    import collections

    from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator

    c = Coordinator(None, 100, lease=10.0, speculation=False)
    for ring in (c.rollback_mttrs, c.scale_advice):
        assert isinstance(ring, collections.deque) and ring.maxlen
        ring.extend([0.0] * (ring.maxlen + 10))
        assert len(ring) == ring.maxlen


# --------------------------------------------- analyzer totality (ISSUE 19)

def test_every_emittable_code_is_documented_and_tested():
    """Reflection over the findings engine: every DC code any checker can
    emit must appear in DESIGN.md's checker documentation AND in at least
    one corpus test — a future DC508 cannot ship undocumented/untested."""
    import ast as _ast

    repo = os.path.dirname(HERE)
    adir = os.path.join(repo, "distributed_ml_pytorch_tpu", "analysis")
    emittable = set()
    for name in sorted(os.listdir(adir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(adir, name)) as fh:
            tree = _ast.parse(fh.read())
        for node in _ast.walk(tree):
            if not (isinstance(node, _ast.Call) and (
                    getattr(node.func, "id", None) == "Finding"
                    or getattr(node.func, "attr", None) == "Finding")):
                continue
            for arg in node.args:
                if isinstance(arg, _ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value.startswith("DC") and \
                        arg.value[2:].isdigit():
                    emittable.add(arg.value)
    # sanity: reflection actually saw the engine, including this PR's
    assert {"DC001", "DC002", "DC501", "DC502", "DC503", "DC504"} <= \
        emittable, sorted(emittable)

    with open(os.path.join(repo, "DESIGN.md")) as fh:
        design = fh.read()
    undocumented = {c for c in emittable if c not in design}
    assert not undocumented, (
        f"DC codes emitted but absent from DESIGN.md: "
        f"{sorted(undocumented)}")

    corpus = ""
    for tname in ("test_distcheck.py", "test_distmodel.py"):
        with open(os.path.join(HERE, tname)) as fh:
            corpus += fh.read()
    untested = {c for c in emittable if c not in corpus}
    assert not untested, (
        f"DC codes emitted but never exercised by a corpus test: "
        f"{sorted(untested)}")
