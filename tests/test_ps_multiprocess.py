"""Integration: the full 3-process PS topology over TCP on localhost — the
reference's `make server` + `make first` + `make second` smoke pattern
(Makefile:13-20), driven through the real CLI."""

import os
import subprocess
import sys

import pytest

from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_three_process_ps_topology(tmp_path):
    port = _free_port()
    env = cpu_platform_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    common = [
        sys.executable, "-m", "distributed_ml_pytorch_tpu.training.cli",
        "--mode", "ps", "--model", "lenet", "--epochs", "1",
        "--batch-size", "16", "--test-batch-size", "64", "--lr", "0.05",
        "--num-push", "4", "--num-pull", "4", "--log-interval", "4",
        "--synthetic-data", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64",
        "--world-size", "3", "--port", port,
        "--log-dir", str(tmp_path),
    ]
    procs = [
        subprocess.Popen(
            common + ["--rank", "0", "--server"],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
    ]
    for rank in ("1", "2"):
        procs.append(
            subprocess.Popen(
                common + ["--rank", rank],
                env=env, cwd=str(tmp_path),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                outs.append(p.communicate()[0])
    assert all(p.returncode == 0 for p in procs), "\n\n".join(outs)
    assert "parameter server: all workers done" in outs[0], outs[0]
    for rank in (1, 2):
        assert "Finished Training" in outs[rank], outs[rank]
        assert os.path.exists(os.path.join(str(tmp_path), f"node{rank}.csv"))
