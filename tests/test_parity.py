"""Cross-framework training parity — the BASELINE.md acceptance bar.

BASELINE.json's north star requires the TPU backend to match the reference
run's final accuracy within 0.1% on the same recipe. This test checks the
strongest form directly: starting from IDENTICAL weights (torch→flax via
``utils/interop``) and feeding IDENTICAL batches through the reference
recipe (SGD, momentum 0, cross-entropy — ``example/main.py:44,71``), the
torch training trajectory and this framework's jitted trajectory must track
each other step for step, and the resulting classifiers must agree on a
held-out set to well within the 0.1% bar.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from distributed_ml_pytorch_tpu.models import AlexNet  # noqa: E402
from distributed_ml_pytorch_tpu.training.trainer import (  # noqa: E402
    TrainState,
    make_train_step,
)
from distributed_ml_pytorch_tpu.utils.interop import load_torch_state_dict  # noqa: E402
from tests.test_interop import torch_alexnet  # noqa: E402

N_STEPS = 20
BATCH = 32
LR = 0.05
N_EVAL = 2048


def _batches(n_steps, batch, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n_steps, batch, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, size=(n_steps, batch)).astype(np.int64)
    return images, labels


def test_same_recipe_same_weights_same_trajectory():
    tmodel = torch_alexnet()
    flax_model = AlexNet(num_classes=10)  # dropout-free: deterministic
    template = flax_model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    params = load_torch_state_dict(template, tmodel.state_dict())

    tx = optax.sgd(LR, momentum=0.0)
    state = TrainState.create(params, tx)
    jax_step = make_train_step(flax_model, tx)
    opt = torch.optim.SGD(tmodel.parameters(), lr=LR, momentum=0.0)

    images, labels = _batches(N_STEPS, BATCH)
    rng = jax.random.key(1)  # unused by the dropout-free model, API parity

    torch_losses, jax_losses = [], []
    for i in range(N_STEPS):
        opt.zero_grad()
        x = torch.from_numpy(images[i].transpose(0, 3, 1, 2).copy())
        loss = F.cross_entropy(tmodel(x), torch.from_numpy(labels[i]))
        loss.backward()
        opt.step()
        torch_losses.append(float(loss.detach()))

        state, jloss = jax_step(
            state, jnp.asarray(images[i]), jnp.asarray(labels[i].astype(np.int32)), rng
        )
        jax_losses.append(float(jloss))

    # step-for-step loss tracking: float32 reduction-order drift only
    np.testing.assert_allclose(torch_losses, jax_losses, rtol=5e-3, atol=5e-4)

    # the 0.1% accuracy bar, measured on a held-out set with both finals
    ev_images, ev_labels = _batches(1, N_EVAL, seed=99)
    with torch.no_grad():
        t_pred = (
            tmodel(torch.from_numpy(ev_images[0].transpose(0, 3, 1, 2).copy()))
            .argmax(1)
            .numpy()
        )
    j_pred = np.asarray(
        flax_model.apply(
            {"params": state.params}, jnp.asarray(ev_images[0]), train=False
        ).argmax(1)
    )
    t_acc = float((t_pred == ev_labels[0]).mean())
    j_acc = float((j_pred == ev_labels[0]).mean())
    assert abs(t_acc - j_acc) <= 0.001, (
        f"accuracy parity violated: torch {t_acc:.4f} vs jax {j_acc:.4f}"
    )
    # and prediction-level agreement should be near-total
    agree = float((t_pred == j_pred).mean())
    assert agree > 0.995, f"prediction agreement only {agree:.4f}"


def test_flax_init_installs_into_torch_with_identical_forward():
    """The flax→torch direction (``bench_all.install_flax_alexnet_init``,
    the matched-init steps-to-target leg): installing a flax init into the
    torch AlexNet must give the same classifier function."""
    from bench import make_torch_alexnet
    from bench_all import install_flax_alexnet_init

    flax_model = AlexNet(num_classes=10)
    params = flax_model.init(jax.random.key(3), jnp.zeros((1, 32, 32, 3)))[
        "params"
    ]
    tmodel = make_torch_alexnet()
    install_flax_alexnet_init(
        tmodel, jax.tree.map(np.asarray, params)
    )

    images, _ = _batches(1, 64, seed=7)
    with torch.no_grad():
        t_out = tmodel(
            torch.from_numpy(images[0].transpose(0, 3, 1, 2).copy())
        ).numpy()
    j_out = np.asarray(
        flax_model.apply({"params": params}, jnp.asarray(images[0]), train=False)
    )
    np.testing.assert_allclose(t_out, j_out, rtol=2e-4, atol=2e-5)
