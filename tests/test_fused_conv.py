"""Numerics of the Pallas-fused conv epilogues (ops/fused_conv.py, ISSUE 9).

The contract under test (the round-5 pool test pattern, extended):

- FORWARD is bit-identical to the unfused XLA lowering on both code paths
  (the Pallas kernel via ``force_pallas_interpret`` and the off-TPU XLA
  fallback) — same adds/maxima in the same order;
- BACKWARD matches the unfused chain element-for-element, including the
  pool vjp's first-max tie contract (window row-major order, the torch
  MaxPool2d behavior) and relu's gradient-at-0 = 0;
- the AlexNet ``fused_epilogue`` flag changes kernels, never numerics or
  the parameter tree.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_ml_pytorch_tpu.ops import fused_conv as fc


def _rand(shape, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape).astype(np.float32))


def _unfused(x, bias=None):
    z = x if bias is None else x + bias
    return fc.max_pool_2x2(jax.nn.relu(z))


@pytest.mark.parametrize("shape,with_bias", [
    ((3, 8, 8, 64), False),    # conv1 tail shape (C < 128 lanes)
    ((2, 4, 4, 192), True),    # conv2 tail (C not a lane multiple)
    ((4, 2, 2, 256), True),    # conv5 tail (lane-aligned C)
])
def test_relu_pool2_forward_bit_identical_both_paths(shape, with_bias):
    x = _rand(shape)
    bias = _rand((shape[-1],), seed=1) if with_bias else None
    ref = _unfused(x, bias)
    # XLA fallback path (CPU backend, no interpret): the exact chain
    assert bool(jnp.all(fc.relu_pool2(x, bias) == ref))
    # Pallas kernel path (interpret mode on CPU)
    with fc.force_pallas_interpret():
        assert bool(jnp.all(fc.relu_pool2(x, bias) == ref))


@pytest.mark.parametrize("with_bias", [False, True])
def test_relu_pool2_backward_matches_unfused_chain(with_bias):
    x = _rand((3, 8, 8, 64), seed=2)
    bias = _rand((64,), seed=3) if with_bias else None
    g = _rand((3, 4, 4, 64), seed=4)
    dref = jax.vjp(lambda *a: _unfused(*a), x, bias)[1](g)
    with fc.force_pallas_interpret():
        dfused = jax.vjp(lambda *a: fc.relu_pool2(*a), x, bias)[1](g)
    # dx: the one-kernel backward equals the unfused select chain exactly
    assert bool(jnp.all(dfused[0] == dref[0]))
    if with_bias:
        # db is reduced outside the kernel from the same dz: tight, and in
        # practice exact on CPU (identical summation tree)
        np.testing.assert_allclose(
            np.asarray(dfused[1]), np.asarray(dref[1]), rtol=1e-6, atol=1e-6)


def test_relu_pool2_tie_behavior_preserved():
    """The first-max tie contract survives fusion: all-negative windows
    (pool of relu ties at 0 → no gradient through relu), exactly-tied
    positive values (first slot in window row-major order wins), and a
    zero-max window with an exact 0 input (relu'(0) = 0)."""
    x = _rand((2, 4, 4, 8), seed=5)
    x = x.at[0, :2, :2, :].set(-1.0)   # window all negative: m == 0
    x = x.at[0, 2:, 2:, :].set(0.0)    # window all exactly 0: m == 0
    x = x.at[1, :2, :2, :].set(3.0)    # 4-way positive tie: slot (0,0) wins
    g = _rand((2, 2, 2, 8), seed=6)
    dref = jax.vjp(lambda a: _unfused(a), x)[1](g)[0]
    with fc.force_pallas_interpret():
        dfused = jax.vjp(lambda a: fc.relu_pool2(a, None), x)[1](g)[0]
    assert bool(jnp.all(dfused == dref))
    # and the tied window really did route everything to the first slot
    win = np.asarray(dfused)[1, :2, :2, :]
    assert bool(np.all(win[0, 0] == np.asarray(g)[1, 0, 0]))
    assert not np.any(win[0, 1]) and not np.any(win[1, :])
    # the zero-max windows produce NO gradient (relu mask at 0 is 0)
    assert not np.any(np.asarray(dfused)[0, :2, :2, :])
    assert not np.any(np.asarray(dfused)[0, 2:, 2:, :])


@pytest.mark.parametrize("with_bias", [False, True])
def test_bias_relu_forward_and_backward(with_bias):
    x = _rand((5, 7, 192), seed=7)
    bias = _rand((192,), seed=8) if with_bias else None
    g = _rand((5, 7, 192), seed=9)
    ref_fn = lambda v, b: jax.nn.relu(v if b is None else v + b)
    ref = ref_fn(x, bias)
    dref = jax.vjp(ref_fn, x, bias)[1](g)
    for ctx in (fc.force_pallas_interpret, None):
        if ctx is None:
            y = fc.bias_relu(x, bias)
            d = jax.vjp(lambda *a: fc.bias_relu(*a), x, bias)[1](g)
        else:
            with ctx():
                y = fc.bias_relu(x, bias)
                d = jax.vjp(lambda *a: fc.bias_relu(*a), x, bias)[1](g)
        assert bool(jnp.all(y == ref))
        assert bool(jnp.all(d[0] == dref[0]))
        if with_bias:
            np.testing.assert_allclose(
                np.asarray(d[1]), np.asarray(dref[1]), rtol=1e-6, atol=1e-6)


def test_relu_pool2_domain_is_pool2_tiles():
    """The pooled entry point's domain IS ``max_pool_2x2``'s: no 2x2
    stride-2 pool exists for odd spatial dims or rank != 4 (fused or
    not), so those shapes raise a clear ValueError instead of crashing
    in a reshape; in-domain shapes still match the unfused chain on the
    kernel path."""
    assert not fc.pool2_tiles(_rand((2, 5, 6, 8)))
    assert not fc.pool2_tiles(_rand((2, 6, 6)))
    assert fc.pool2_tiles(_rand((2, 6, 6, 8)))
    with pytest.raises(ValueError, match="even"):
        fc.relu_pool2(_rand((2, 5, 6, 8)), None)
    with pytest.raises(ValueError, match="rank-4"):
        fc.relu_pool2(_rand((2, 6, 6)), None)
    x = _rand((2, 6, 6, 8), seed=10)
    with fc.force_pallas_interpret():
        assert bool(jnp.all(fc.relu_pool2(x, None) == _unfused(x)))


def test_alexnet_fused_epilogue_identical_numerics_and_tree():
    """The model flag is kernels-only: identical param tree (checkpoints
    interchangeable), bit-identical logits, element-identical gradients —
    on the fallback path AND the Pallas path."""
    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    base = AlexNet(num_classes=10)
    fused = AlexNet(num_classes=10, fused_epilogue=True)
    x = _rand((4, 32, 32, 3), seed=11)
    labels = jnp.asarray(np.arange(4, dtype=np.int32) % 10)
    params = base.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    pf = fused.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    assert jax.tree.structure(params) == jax.tree.structure(pf)

    def loss(model, p):
        return cross_entropy_loss(model.apply({"params": p}, x), labels)

    ref_logits = base.apply({"params": params}, x)
    ref_grads = jax.grad(lambda p: loss(base, p))(params)
    for ctx in (None, fc.force_pallas_interpret):
        if ctx is None:
            logits = fused.apply({"params": params}, x)
            grads = jax.grad(lambda p: loss(fused, p))(params)
        else:
            with ctx():
                logits = fused.apply({"params": params}, x)
                grads = jax.grad(lambda p: loss(fused, p))(params)
        assert bool(jnp.all(logits == ref_logits))
        for ga, gb in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(ga), np.asarray(gb), rtol=1e-6, atol=1e-6)
