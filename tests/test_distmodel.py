"""ISSUE 13 — the bounded protocol model checker (analysis/distmodel.py).

Four layers:

1. **Soundness of the clean protocol** — every model's invariants hold
   over the full bounded state space at the ``make distmodel`` depths.
2. **Mutation corpus** — each seeded protocol mutation (ack-before-fsync,
   dedup-key removal, dedup-seed loss on restore, incarnation-gate
   removal, watermark off-by-one, microbatch-dedup removal) yields a
   counterexample; the checker that cannot find the planted bug is not
   checking anything.
3. **Counterexample-to-chaos replay** — the PS-family counterexamples
   replay against the REAL ``ReliableTransport``/``ParameterServer``/WAL
   stack: the invariant fails under the mutated configuration and holds
   under the correct one on the SAME schedule (closing the loop between
   the abstract model and the running system).
4. **Model/code tethers** — the model's replay-cutoff rule IS
   ``parallel/mpmd.replay_covers``; the ChaosPlan JSON round-trip that
   carries counterexamples is exact.
"""

import json
import os

import pytest

from distributed_ml_pytorch_tpu.analysis import distmodel

pytestmark = pytest.mark.distmodel


# ----------------------------------------------------- clean models hold

def test_unmutated_models_hold_exhaustively():
    results = distmodel.run()
    assert [r.model for r in results] == sorted(distmodel.MODELS)
    for r in results:
        assert r.ok, f"{r.model}: {r.invariant}\n{r.trace}"
        assert r.states > 100  # exhaustive, not vacuous


# ------------------------------------------------------ mutation corpus

@pytest.mark.parametrize("mutation", sorted(distmodel.MUTATIONS))
def test_seeded_mutation_yields_counterexample(mutation):
    results = distmodel.run(mutation=mutation)
    bad = [r for r in results if not r.ok]
    assert bad, f"mutation {mutation} was not caught"
    (r,) = bad
    assert r.model == distmodel.MUTATIONS[mutation]
    assert r.mutation == mutation
    assert r.trace and r.invariant


def test_counterexample_traces_are_minimal_prefixes():
    """BFS returns SHORTEST counterexamples: the no_dedup trace needs
    exactly send + dup + two deliveries, nothing else — a bloated trace
    would make the chaos replays needlessly fragile."""
    (r,) = [x for x in distmodel.run(mutation="no_dedup") if not x.ok]
    assert len(r.trace) == 4


# ----------------------------------------------- artifacts + chaos plans

def test_counterexample_artifact_and_chaos_plan_roundtrip():
    from distributed_ml_pytorch_tpu.utils.chaos import plan_from_json

    (r,) = [x for x in distmodel.run(mutation="no_dedup") if not x.ok]
    ce = distmodel.counterexample_artifact(r)
    # JSON-clean and self-describing
    ce2 = json.loads(json.dumps(ce))
    assert ce2["model"] == "ps" and ce2["mutation"] == "no_dedup"
    assert ce2["invariant"] and ce2["trace"]
    # the embedded plan parses back into a real ChaosPlan with the dup
    # rule windowed to the duplicated frame's own channel send index
    plan = plan_from_json(ce2["chaos_plan"])
    dup_rules = [rule for rule in plan.rules if rule.dup]
    assert dup_rules and dup_rules[0].after == 0 \
        and dup_rules[0].until == 1


def test_write_counterexample_emits_json_and_pytest_stub(tmp_path):
    (r,) = [x for x in distmodel.run(mutation="ack_before_fsync")
            if not x.ok]
    json_path, stub_path = distmodel.write_counterexample(r, str(tmp_path))
    with open(json_path) as fh:
        ce = json.load(fh)
    assert ce["mutation"] == "ack_before_fsync"
    assert any(s["op"] == "crash" for s in ce["crash_script"])
    stub = open(stub_path).read()
    assert "def test_counterexample_replays" in stub
    assert os.path.basename(json_path) in stub
    compile(stub, stub_path, "exec")  # the stub is valid Python


def test_non_replayable_family_gets_model_level_stub(tmp_path):
    """Families without a real-stack harness must NOT get a stub that
    errors unconditionally — they get the model-trace validity check,
    and replay_trace_on_model confirms the recorded trace still reaches
    the recorded violation."""
    (r,) = [x for x in distmodel.run(mutation="watermark_off_by_one")
            if not x.ok]
    json_path, stub_path = distmodel.write_counterexample(r, str(tmp_path))
    stub = open(stub_path).read()
    assert "replay_trace_on_model" in stub
    assert "replay_counterexample" not in stub
    compile(stub, stub_path, "exec")
    with open(json_path) as fh:
        ce = json.load(fh)
    assert distmodel.replay_trace_on_model(ce) == [ce["invariant"]]
    # a stale artifact (model rules drifted under it) reports empty,
    # never a false confirmation
    stale = dict(ce, trace=["ship 0", "no-such-event"])
    assert distmodel.replay_trace_on_model(stale) == []


def test_state_cap_truncation_is_surfaced_not_silent():
    """An ok verdict the max_states cap truncated mid-frontier must say
    so — `complete=False` in the Result and the JSON — instead of
    reading as a full bounded proof."""
    r = distmodel.explore(distmodel.PSModel(), max_depth=12, max_states=50)
    assert r.ok and not r.complete and r.states >= 50
    assert r.to_json()["complete"] is False
    full = distmodel.explore(distmodel.PSModel(), max_depth=12)
    assert full.ok and full.complete


def test_dropped_ack_rules_are_windowed_not_blackholes():
    """A drop_ack trace event must become windowed ack-channel rules,
    never an unconditional forever-drop of the whole return channel."""
    r = distmodel.Result(
        model="ps", mutation=None, ok=False, states=1, depth=1,
        invariant="x", trace=[("send", 0, 0), ("drop_ack", 0, 0)])
    from distributed_ml_pytorch_tpu.utils.chaos import plan_from_json
    plan = plan_from_json(distmodel.counterexample_artifact(r)["chaos_plan"])
    ack_rules = [rule for rule in plan.rules if rule.src == 0]
    assert ack_rules
    for rule in ack_rules:
        assert rule.code is not None
        assert rule.until == rule.after + 1


# ------------------------------------------- replay against the real stack

@pytest.mark.chaos
@pytest.mark.parametrize("mutation", [
    "ack_before_fsync", "no_dedup", "no_seed_on_restore",
    "no_error_feedback", "decode_before_admission",
    "stale_delta_base", "no_full_fallback_on_restore",
    "park_without_manifest", "double_grant_slot",
    "no_epoch_fence", "expire_on_restart", "forget_parked",
    "no_hysteresis", "symmetric_probe_only", "evict_on_first_suspicion"])
def test_counterexample_replays_on_real_stack(mutation, tmp_path):
    """The acceptance bar: the model-level violation reproduces on the
    real transport/server stack under the mutated configuration, and the
    SAME schedule passes on the correct one."""
    (r,) = [x for x in distmodel.run(mutation=mutation) if not x.ok]
    ce = distmodel.counterexample_artifact(r)
    broken = distmodel.replay_counterexample(
        ce, str(tmp_path / "mutated"), mutated=True)
    assert broken, f"{mutation}: the real stack did not reproduce"
    clean = distmodel.replay_counterexample(
        ce, str(tmp_path / "clean"), mutated=False)
    assert not clean, f"{mutation}: the correct stack violated: {clean}"


def test_replay_refuses_unknown_family(tmp_path):
    with pytest.raises(ValueError, match="no real-stack replay"):
        distmodel.replay_counterexample(
            {"model": "lease", "mutation": "no_incarnation_gate"},
            str(tmp_path))


# ------------------------------------------------------ model/code tethers

def test_mpmd_replay_cutoff_is_the_real_predicate():
    """The model's restart-and-replay re-ships exactly the indices
    ``parallel/mpmd.replay_covers`` declares eligible — the tether that
    keeps the checked model and the shipping code the same protocol."""
    from distributed_ml_pytorch_tpu.parallel.mpmd import replay_covers

    m = distmodel.MpmdModel()  # 2 steps x 2 microbatches
    # crashed receiver: 4 produced, applied {0,1}, checkpoint watermark 2
    crashed = (4, (), frozenset({0, 1}), False, 2, False, 1, 0)
    (label, nxt), = [s for s in m.successors(crashed)
                     if s[0][0] == "restart"]
    reshipped = set(nxt[1])
    expected = {i for i in range(4)
                if replay_covers(i // m.M, i % m.M, m.M, 2)}
    assert reshipped == expected == {2, 3}


def test_lease_model_matches_coordinator_gate_semantics():
    """A clean leave then a re-join is NOT a violation (history resets,
    like the real coordinator forgetting a departed member) — only a
    transition that adopts a stale life over a live newer one is."""
    m = distmodel.LeaseModel()
    r = distmodel.explore(m, max_depth=12)
    assert r.ok


# ------------------------------------------------------------------- CLI

def test_cli_clean_run_exits_zero(capsys):
    assert distmodel.main(["--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert all(row["ok"] for row in out["results"])
    assert {row["model"] for row in out["results"]} == set(distmodel.MODELS)


def test_cli_mutated_run_writes_artifacts(tmp_path, capsys):
    rc = distmodel.main(["--mutate", "watermark_off_by_one", "--json",
                         "--out", str(tmp_path)])
    assert rc == 0  # a mutated run succeeds by FINDING the counterexample
    out = json.loads(capsys.readouterr().out)
    assert any(not row["ok"] for row in out["results"])
    names = sorted(os.listdir(tmp_path))
    assert names == ["mpmd_watermark_off_by_one.json",
                     "test_repro_mpmd_watermark_off_by_one.py"]


def test_cli_depth_zero_is_vacuous_but_honest():
    """--depth caps exploration; depth 0 visits only the initial state
    and reports ok (bounded) — the knob the Makefile gate tunes."""
    results = distmodel.run(["lease"], depth=0)
    assert results[0].ok and results[0].states == 1
