"""M2 contract tests: MessageCode enum, send_message, MessageListener dispatch,
TCP transport framing (the gap-closing unit tests SURVEY.md §4 calls for)."""

import threading
import time

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    MessageListener,
    TCPTransport,
    send_message,
    set_default_transport,
)


def test_message_code_members():
    # reference call sites use these three members and `.name` (Asynchronous.py:16,17,34,49,59)
    assert {m.name for m in MessageCode} >= {
        "ParameterUpdate",
        "ParameterRequest",
        "GradientUpdate",
    }


def test_inprocess_send_recv():
    world = InProcessTransport.create_world(3)
    payload = np.arange(5, dtype=np.float32)
    world[1].send(MessageCode.GradientUpdate, payload, dst=0)
    sender, code, got = world[0].recv(timeout=1)
    assert sender == 1 and code == MessageCode.GradientUpdate
    np.testing.assert_array_equal(got, payload)


def test_send_message_default_transport():
    world = InProcessTransport.create_world(2)
    set_default_transport(world[1])
    try:
        send_message(MessageCode.ParameterRequest, np.zeros(3, np.float32))
        msg = world[0].recv(timeout=1)
        assert msg is not None and msg[1] == MessageCode.ParameterRequest
    finally:
        set_default_transport(None)


def test_listener_dispatch():
    world = InProcessTransport.create_world(2)
    got = []
    done = threading.Event()

    class L(MessageListener):
        def receive(self, sender, message_code, parameter):
            got.append((sender, message_code, parameter))
            done.set()

    listener = L(transport=world[1])
    listener.start()
    world[0].send(MessageCode.ParameterUpdate, np.ones(4, np.float32), dst=1)
    assert done.wait(timeout=5)
    listener.stop()
    sender, code, param = got[0]
    assert sender == 0 and code == MessageCode.ParameterUpdate
    np.testing.assert_array_equal(param, np.ones(4, np.float32))


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_tcp_transport_round_trip():
    port = _free_port()
    results = {}

    def server():
        t = TCPTransport(0, 2, "localhost", port)
        msg = t.recv(timeout=10)
        results["server_got"] = msg
        t.send(MessageCode.ParameterUpdate, np.full(3, 7.0, np.float32), dst=msg[0])
        time.sleep(0.2)
        t.close()

    st = threading.Thread(target=server)
    st.start()
    w = None
    for _ in range(100):  # retry until the server thread is listening
        try:
            w = TCPTransport(1, 2, "localhost", port)
            break
        except OSError:
            time.sleep(0.05)
    assert w is not None, "worker could not reach server"
    w.send(MessageCode.GradientUpdate, np.arange(3, dtype=np.float32))
    reply = w.recv(timeout=10)
    st.join(timeout=10)
    w.close()

    sender, code, payload = results["server_got"]
    assert sender == 1 and code == MessageCode.GradientUpdate
    np.testing.assert_array_equal(payload, np.arange(3, dtype=np.float32))
    assert reply is not None
    assert reply[0] == 0 and reply[1] == MessageCode.ParameterUpdate
    np.testing.assert_array_equal(reply[2], np.full(3, 7.0, np.float32))
