"""M2 contract tests: MessageCode enum, send_message, MessageListener dispatch,
TCP transport framing (the gap-closing unit tests SURVEY.md §4 calls for)."""

import threading
import time

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    MessageListener,
    TCPTransport,
    send_message,
    set_default_transport,
)


def test_message_code_members():
    # reference call sites use these three members and `.name` (Asynchronous.py:16,17,34,49,59)
    assert {m.name for m in MessageCode} >= {
        "ParameterUpdate",
        "ParameterRequest",
        "GradientUpdate",
    }


def test_inprocess_send_recv():
    world = InProcessTransport.create_world(3)
    payload = np.arange(5, dtype=np.float32)
    world[1].send(MessageCode.GradientUpdate, payload, dst=0)
    sender, code, got = world[0].recv(timeout=1)
    assert sender == 1 and code == MessageCode.GradientUpdate
    np.testing.assert_array_equal(got, payload)


def test_send_message_default_transport():
    world = InProcessTransport.create_world(2)
    set_default_transport(world[1])
    try:
        send_message(MessageCode.ParameterRequest, np.zeros(3, np.float32))
        msg = world[0].recv(timeout=1)
        assert msg is not None and msg[1] == MessageCode.ParameterRequest
    finally:
        set_default_transport(None)


def test_listener_dispatch():
    world = InProcessTransport.create_world(2)
    got = []
    done = threading.Event()

    class L(MessageListener):
        def receive(self, sender, message_code, parameter):
            got.append((sender, message_code, parameter))
            done.set()

    listener = L(transport=world[1])
    listener.start()
    world[0].send(MessageCode.ParameterUpdate, np.ones(4, np.float32), dst=1)
    assert done.wait(timeout=5)
    listener.stop()
    sender, code, param = got[0]
    assert sender == 0 and code == MessageCode.ParameterUpdate
    np.testing.assert_array_equal(param, np.ones(4, np.float32))


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_tcp_transport_round_trip():
    port = _free_port()
    results = {}

    def server():
        t = TCPTransport(0, 2, "localhost", port)
        msg = t.recv(timeout=10)
        results["server_got"] = msg
        t.send(MessageCode.ParameterUpdate, np.full(3, 7.0, np.float32), dst=msg[0])
        time.sleep(0.2)
        t.close()

    st = threading.Thread(target=server)
    st.start()
    w = None
    for _ in range(100):  # retry until the server thread is listening
        try:
            w = TCPTransport(1, 2, "localhost", port)
            break
        except OSError:
            time.sleep(0.05)
    assert w is not None, "worker could not reach server"
    w.send(MessageCode.GradientUpdate, np.arange(3, dtype=np.float32))
    reply = w.recv(timeout=10)
    st.join(timeout=10)
    w.close()

    sender, code, payload = results["server_got"]
    assert sender == 1 and code == MessageCode.GradientUpdate
    np.testing.assert_array_equal(payload, np.arange(3, dtype=np.float32))
    assert reply is not None
    assert reply[0] == 0 and reply[1] == MessageCode.ParameterUpdate
    np.testing.assert_array_equal(reply[2], np.full(3, 7.0, np.float32))


def test_reliable_round_trip_and_dedup():
    """Reliability layer (ISSUE 2): seq+CRC envelope, ack clears pending,
    and a wire-level duplicate is re-acked but delivered once."""
    from distributed_ml_pytorch_tpu.utils.messaging import ReliableTransport

    world = InProcessTransport.create_world(2)
    a = ReliableTransport(world[0], ack_timeout=0.05)
    b = ReliableTransport(world[1], ack_timeout=0.05)
    try:
        b.send(MessageCode.GradientUpdate, np.arange(4, dtype=np.float32))
        msg = a.recv(timeout=5)
        assert msg is not None and msg[1] == MessageCode.GradientUpdate
        np.testing.assert_array_equal(msg[2], np.arange(4, dtype=np.float32))
        assert b.flush(timeout=5) and b.stats["acked"] == 1

        # replay the same envelope (a retry that crossed its ack): craft it
        # byte-correct — same incarnation, same seq 0, REAL crc — so the
        # drop can only come from the dedup path, not the CRC check
        from distributed_ml_pytorch_tpu.utils.messaging import (
            _frame_crc,
            _split16,
        )

        body = np.arange(4, dtype=np.float32)
        crc = _frame_crc(b.incarnation, 0, int(MessageCode.GradientUpdate),
                         body.tobytes())
        b.inner.send(
            MessageCode.ReliableFrame,
            np.concatenate([
                np.asarray([*_split16(b.incarnation), *_split16(0),
                            *_split16(crc),
                            float(int(MessageCode.GradientUpdate)),
                            *_split16(0)],  # corr id (ISSUE 12): none
                           np.float32),
                body]))
        assert a.recv(timeout=0.3) is None  # dropped as duplicate
        assert a.stats["dup_dropped"] == 1
    finally:
        a.close()
        b.close()


def test_tcp_reader_survives_unknown_code_and_misaligned_frame():
    """Satellite hardening: a malformed frame (unknown MessageCode, or a
    non-float32-aligned payload) is dropped and logged — the reader thread
    keeps serving subsequent well-formed frames."""
    import struct

    from distributed_ml_pytorch_tpu.utils.messaging import _HEADER

    port = _free_port()
    holder = {}

    def server():
        holder["t"] = TCPTransport(0, 2, "localhost", port)

    st = threading.Thread(target=server)
    st.start()
    w = None
    for _ in range(100):
        try:
            w = TCPTransport(1, 2, "localhost", port)
            break
        except OSError:
            time.sleep(0.05)
    st.join(timeout=10)
    assert w is not None
    t = holder["t"]
    try:
        sock = w._peers[0]
        # unknown code 99, sane length
        sock.sendall(_HEADER.pack(1, 99, 8) + b"\x00" * 8)
        # known code, misaligned 6-byte payload
        sock.sendall(_HEADER.pack(1, int(MessageCode.GradientUpdate), 6)
                     + b"\x00" * 6)
        # a well-formed frame AFTER the garbage must still arrive
        w.send(MessageCode.GradientUpdate, np.arange(3, dtype=np.float32))
        msg = t.recv(timeout=10)
        assert msg is not None and msg[1] == MessageCode.GradientUpdate
        np.testing.assert_array_equal(msg[2], np.arange(3, dtype=np.float32))
    finally:
        w.close()
        t.close()


def test_tcp_reader_drops_connection_on_insane_length():
    """A declared payload length over MAX_FRAME_BYTES cannot be resynced —
    that connection is dropped (loudly), not the process."""
    from distributed_ml_pytorch_tpu.utils.messaging import (
        _HEADER,
        MAX_FRAME_BYTES,
    )

    port = _free_port()
    holder = {}

    def server():
        holder["t"] = TCPTransport(0, 2, "localhost", port)

    st = threading.Thread(target=server)
    st.start()
    w = None
    for _ in range(100):
        try:
            w = TCPTransport(1, 2, "localhost", port)
            break
        except OSError:
            time.sleep(0.05)
    st.join(timeout=10)
    t = holder["t"]
    try:
        w._peers[0].sendall(
            _HEADER.pack(1, int(MessageCode.GradientUpdate),
                         MAX_FRAME_BYTES + 4))
        # frames after the poisoned header are never parsed: that reader
        # is gone, but the server process/transport itself stays up
        assert t.recv(timeout=0.5) is None
    finally:
        w.close()
        t.close()


def test_reliable_restarted_peer_not_blackholed_and_dead_peer_heals():
    """Peer lifecycle (ISSUE 2 review findings): (a) a restarted peer's
    fresh seq space must not be deduped against its previous life — the
    incarnation stamp resets the receiver's state; (b) a rank declared dead
    after exhausted retries is revived by any frame it sends."""
    from distributed_ml_pytorch_tpu.utils.messaging import ReliableTransport

    world = InProcessTransport.create_world(2)
    server = ReliableTransport(world[0], ack_timeout=0.02, max_backoff=0.05,
                               max_retries=2)
    # first life of rank 1: delivers seq 0
    life1 = ReliableTransport(world[1], ack_timeout=0.05)
    life1.send(MessageCode.GradientUpdate, np.full(2, 1.0, np.float32))
    msg = server.recv(timeout=5)
    assert msg is not None and int(msg[2][0]) == 1

    # rank 1 "crashes"; the server's sends to it go unacked until it is
    # declared dead
    life1._closed = True  # stop life1's retry/ack machinery
    server.send(MessageCode.ParameterUpdate, np.ones(1, np.float32), dst=1)
    deadline = time.monotonic() + 5
    while not server.stats["gave_up"] and time.monotonic() < deadline:
        time.sleep(0.02)
    assert server.stats["gave_up"] == 1

    # second life on the same rank: its seq restarts at 0, but the newer
    # incarnation resets dedup — the frame must be DELIVERED, and hearing
    # from the rank revives it for sending
    life2 = ReliableTransport(world[1], ack_timeout=0.05)
    assert life2.incarnation > life1.incarnation
    life2.send(MessageCode.GradientUpdate, np.full(2, 2.0, np.float32))
    msg = server.recv(timeout=5)
    assert msg is not None and int(msg[2][0]) == 2, (
        "restarted peer's seq 0 was blackholed as a duplicate")
    server.send(MessageCode.ParameterUpdate, np.ones(1, np.float32), dst=1)
    msg = life2.recv(timeout=5)
    assert msg is not None and msg[1] == MessageCode.ParameterUpdate
    server.close()
    life2.close()


def test_reliable_stale_incarnation_ack_does_not_clear_pending():
    """An ack echoing a PREVIOUS life's incarnation (a straggler for the
    old process's frame with the same seq) must not clear the new life's
    pending entry — that frame still needs its retransmit."""
    from distributed_ml_pytorch_tpu.utils.messaging import (
        ReliableTransport,
        _split16,
    )

    world = InProcessTransport.create_world(2)
    b = ReliableTransport(world[1], ack_timeout=0.05)
    try:
        b.send(MessageCode.GradientUpdate, np.ones(2, np.float32), dst=0)
        world[0].send(
            MessageCode.ReliableAck,
            np.asarray([*_split16(0), *_split16(b.incarnation - 1)],
                       np.float32), dst=1)
        assert not b.flush(timeout=0.4)  # stale ack ignored: still pending
        assert b.stats["acked"] == 0
        world[0].send(
            MessageCode.ReliableAck,
            np.asarray([*_split16(0), *_split16(b.incarnation)],
                       np.float32), dst=1)
        assert b.flush(timeout=2)
        assert b.stats["acked"] == 1
    finally:
        b.close()
        for t in world.values():
            t.close()


# --------------------------------------------------- ISSUE 5: durable acks

def test_deferred_ack_withheld_until_ack_delivered():
    """With ack_on_delivery=False the delivery ack is released only by
    ack_delivered() — the sender keeps retrying (and the duplicate is NOT
    re-acked early) until the receiver declares the update durable."""
    from distributed_ml_pytorch_tpu.utils.messaging import ReliableTransport

    world = InProcessTransport.create_world(2)
    a = ReliableTransport(world[0], ack_timeout=0.05, ack_on_delivery=False)
    b = ReliableTransport(world[1], ack_timeout=0.05)
    try:
        b.send(MessageCode.GradientUpdate, np.ones(2, np.float32), dst=0)
        msg = a.recv(timeout=2)
        assert msg is not None and msg[1] == MessageCode.GradientUpdate
        assert a.last_delivery is not None
        # the retry keeps landing as a dup, and the dup is not re-acked
        assert a.recv(timeout=0.3) is None
        assert a.stats["dup_dropped"] >= 1
        with b._lock:
            assert b._pending  # still unacked: durability never committed
        a.ack_delivered()
        assert b.flush(timeout=5), b.stats
        assert b.acked_count(0, MessageCode.GradientUpdate) == 1
    finally:
        a.close()
        b.close()
        for t in world.values():
            t.close()


def test_seed_dedup_survives_receiver_restart():
    """The WAL restart path: a restored receiver seeds the envelope
    identities its log recorded, so the sender's retry of an applied-but-
    unacked frame is re-acked, never re-delivered (exactly-once across
    receiver restarts)."""
    from distributed_ml_pytorch_tpu.utils.messaging import ReliableTransport

    world = InProcessTransport.create_world(2)
    a = ReliableTransport(world[0], ack_timeout=0.05, ack_on_delivery=False)
    b = ReliableTransport(world[1], ack_timeout=0.05)
    a2 = None
    try:
        b.send(MessageCode.GradientUpdate, np.ones(2, np.float32), dst=0)
        msg = a.recv(timeout=2)
        assert msg is not None
        inc, seq = a.last_delivery
        a.detach()  # the crash: applied + logged, ack never released

        a2 = ReliableTransport(world[0], ack_timeout=0.05)
        a2.seed_dedup([(1, inc, seq)])
        deadline = time.monotonic() + 5
        redelivered = None
        while time.monotonic() < deadline:
            redelivered = redelivered or a2.recv(timeout=0.1)
            with b._lock:
                if not b._pending:
                    break
        assert redelivered is None, "retry was re-applied after restart"
        assert a2.stats["dup_dropped"] >= 1
        assert b.flush(timeout=5), b.stats
        assert b.acked_count(0, MessageCode.GradientUpdate) == 1
    finally:
        if a2 is not None:
            a2.close()
        b.close()
        for t in world.values():
            t.close()


# ---------------------------------------------------------------------------
# ISSUE 7 satellites: handshake hardening, scatter/gather framing, backoff
# ---------------------------------------------------------------------------

def test_tcp_stalled_handshake_cannot_wedge_the_rendezvous():
    """A connection that dials in and then STALLS mid-handshake (partial
    hello, then silence) must be dropped after ``handshake_timeout`` — the
    accept path may not block forever, and a real worker arriving behind
    the staller must still be admitted."""
    import socket
    import struct

    from distributed_ml_pytorch_tpu.utils.messaging import _HEADER

    port = _free_port()
    holder = {}

    def server():
        # world-size 2: the rendezvous blocks for exactly ONE real worker
        holder["t"] = TCPTransport(0, 2, "localhost", port,
                                   handshake_timeout=0.5)

    st = threading.Thread(target=server)
    st.start()
    # the staller connects first and sends 4 bytes of a 16-byte header,
    # then goes silent
    staller = None
    for _ in range(100):
        try:
            staller = socket.create_connection(("localhost", port),
                                               timeout=2)
            break
        except OSError:
            time.sleep(0.05)
    assert staller is not None
    staller.sendall(struct.pack("<i", 1))
    t0 = time.monotonic()
    # the real worker dials in behind the staller; the server must shed
    # the stalled handshake within its deadline and admit this one
    w = TCPTransport(1, 2, "localhost", port, connect_timeout=30)
    st.join(timeout=30)
    assert not st.is_alive(), "rendezvous wedged behind a stalled handshake"
    assert time.monotonic() - t0 < 20
    t = holder["t"]
    try:
        w.send(MessageCode.GradientUpdate, np.arange(3, dtype=np.float32))
        msg = t.recv(timeout=10)
        assert msg is not None and msg[1] == MessageCode.GradientUpdate
    finally:
        staller.close()
        w.close()
        t.close()


def test_tcp_sendv_scatter_gather_matches_single_frame():
    """sendv (zero-copy envelope framing) must produce byte-identical
    frames to a concatenated single-part send, for both the small-frame
    (joined) and bulk (multi-sendall) paths."""
    port = _free_port()
    holder = {}

    def server():
        holder["t"] = TCPTransport(0, 2, "localhost", port)

    st = threading.Thread(target=server)
    st.start()
    w = None
    for _ in range(100):
        try:
            w = TCPTransport(1, 2, "localhost", port)
            break
        except OSError:
            time.sleep(0.05)
    st.join(timeout=10)
    t = holder["t"]
    try:
        head = np.asarray([1.0, 2.0, 3.0], np.float32)
        small_tail = np.arange(5, dtype=np.float32)
        bulk_tail = np.arange(40_000, dtype=np.float32)  # > 64 KB frame
        for tail in (small_tail, bulk_tail):
            w.sendv(MessageCode.GradientUpdate, (head, tail))
            w.send(MessageCode.GradientUpdate,
                   np.concatenate([head, tail]))
            a = t.recv(timeout=10)
            b = t.recv(timeout=10)
            assert a is not None and b is not None
            np.testing.assert_array_equal(a[2], b[2])
    finally:
        w.close()
        t.close()


def test_backoff_policy_is_seeded_capped_and_deadline_bounded():
    from distributed_ml_pytorch_tpu.utils.backoff import Backoff

    p1 = Backoff(0.1, 1.0, jitter=0.5, seed=7)
    p2 = Backoff(0.1, 1.0, jitter=0.5, seed=7)
    p3 = Backoff(0.1, 1.0, jitter=0.5, seed=8)
    d1 = [p1.delay(k) for k in range(8)]
    # pure per-seed: same seed replays, attempt k is stable on re-ask
    assert d1 == [p2.delay(k) for k in range(8)]
    assert d1 == [p1.delay(k) for k in range(8)]
    # different seeds desynchronize (the anti-retry-storm property)
    assert d1 != [p3.delay(k) for k in range(8)]
    assert all(d <= 1.0 for d in d1)       # cap holds through jitter
    assert d1[0] < d1[4]                    # growth is real
    # attempts() honors its deadline without a literal sleep at the caller
    t0 = time.monotonic()
    fast = Backoff(0.01, 0.02, seed=1)
    n = sum(1 for _ in fast.attempts(deadline=t0 + 0.15))
    assert 3 <= n <= 40
    assert time.monotonic() - t0 < 2.0
