"""Compressed gradient wire (ISSUE 14, utils/compress.py).

- codec numerics: int8 encode/decode inside the per-block bound, top-k
  exact on its support, error-feedback accumulation identity (sum of
  decoded pushes == sum of raw pushes minus the final residual, which a
  quantization bound caps);
- frame integrity: pack/unpack roundtrip with and without the elastic
  stamp, body-CRC rejection, the chaos SDC re-stamp path (silent on the
  wire, visible only to the decoded-norm admission gate);
- PS integration: a compressed push applies exactly the decoded delta,
  malformed frames drop before accounting, the WAL records carry the
  codec id, admission evaluates the DECODED norm;
- THE acceptance (``chaos`` marker): compressed DownPour — int8 + error
  feedback, 2 workers under seeded drop/dup chaos — converges in the
  fault-free corridor with >= 3x fewer bytes on the wire, a byte-
  identical chaos log across 3 runs, and zero quarantines (every
  compressed push passes the gate on decoded norms);
- the drill satellite (``drill`` marker): a shard killed mid-compressed-
  run restores from manifest + WAL with decoded deltas replayed exactly
  once and per-range optimizer state intact.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models import LeNet
from distributed_ml_pytorch_tpu.parallel.async_ps import (
    Asynchronous,
    ParameterServer,
)
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosPlan,
    FaultRule,
    FaultyTransport,
    SDCRule,
)
from distributed_ml_pytorch_tpu.utils.compress import (
    CODEC_INT8,
    CODEC_TOPK,
    HEAD_LEN,
    CompressingEncoder,
    CompressionError,
    Int8Codec,
    TopKCodec,
    decode_update,
    make_codec,
    pack_frame,
    restamp_crc,
    unpack_frame,
)
from distributed_ml_pytorch_tpu.utils.health import GradientAdmission
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
)
from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params


# ----------------------------------------------------------------- codecs

def test_int8_roundtrip_stays_inside_the_per_block_bound():
    rng = np.random.default_rng(0)
    x = (rng.normal(size=5000) * rng.choice([0.01, 1.0, 100.0], size=5000)
         ).astype(np.float32)
    c = Int8Codec(block=256)
    body = c.encode(x)
    xd = c.decode(body, x.size, 256)
    nblocks = -(-x.size // 256)
    scales = body[:nblocks]
    bound = np.repeat(scales / 2.0, 256)[:x.size] + 1e-7
    assert (np.abs(x - xd) <= bound).all()


def test_int8_wire_floats_accounting_is_exact():
    c = Int8Codec(block=1024)
    n = 2_472_266  # raveled AlexNet
    assert c.encode(np.zeros(n, np.float32)).size == c.wire_floats(n)
    # the headline claim: ~3.9x fewer floats than dense
    assert n / c.wire_floats(n) > 3.8


def test_topk_is_exact_on_its_support_and_zero_elsewhere():
    x = np.asarray([0.1, -5.0, 0.2, 4.0, -0.3, 0.0], np.float32)
    c = TopKCodec(k_frac=0.34)  # k = 2
    xd = c.decode(c.encode(x), x.size, 0)
    np.testing.assert_array_equal(xd, [0.0, -5.0, 0.0, 4.0, 0.0, 0.0])


def test_topk_rejects_out_of_range_indices():
    body = np.asarray([99.0, 1.0], np.float32)  # idx 99 for n=4
    with pytest.raises(CompressionError, match="out of range"):
        TopKCodec().decode(body, 4, 0)


@pytest.mark.parametrize("codec_name", ["int8", "topk"])
def test_error_feedback_accumulation_identity(codec_name):
    """sum(decoded) + final residual == sum(raw) — exactly for top-k,
    within float addition noise for int8 (the identity is algebraic:
    residual_t = p_t - decoded_t telescopes)."""
    rng = np.random.default_rng(3)
    n = 64
    enc = CompressingEncoder(
        n, make_codec(codec_name, block=16, k_frac=0.1))
    tot_raw = np.zeros(n, np.float64)
    tot_dec = np.zeros(n, np.float64)
    for _ in range(20):
        u = rng.normal(size=n).astype(np.float32)
        tot_raw += u
        head, body = enc.encode_range(u, 0, n)
        _, _, dec = decode_update(np.concatenate([head, body]))
        tot_dec += dec
    np.testing.assert_allclose(tot_raw, tot_dec + enc.residual, atol=1e-4)
    # and the residual itself is bounded (error deferred, not compounded)
    assert np.abs(enc.residual).max() < 10.0


def test_no_error_feedback_drops_the_identity():
    """The mutation twin's premise, pinned as a unit: without the
    residual the drift grows with the push count."""
    n = 4
    u = np.asarray([8.0, 4.0, 2.0, 1.0], np.float32)
    on = CompressingEncoder(n, make_codec("topk", k_frac=0.25))
    off = CompressingEncoder(n, make_codec("topk", k_frac=0.25),
                             error_feedback=False)
    tot_on = np.zeros(n, np.float32)
    tot_off = np.zeros(n, np.float32)
    for _ in range(8):
        for enc, tot in ((on, tot_on), (off, tot_off)):
            head, body = enc.encode_range(u, 0, n)
            _, _, dec = decode_update(np.concatenate([head, body]))
            tot += dec
    true = 8 * u
    assert np.abs(true - tot_on).max() <= 12.0
    assert np.abs(true - tot_off).max() >= 32.0


# ----------------------------------------------------------------- frames

def test_frame_roundtrip_with_and_without_stamp():
    body = Int8Codec(block=4).encode(np.arange(8, dtype=np.float32))
    head, b = pack_frame(CODEC_INT8, 8, 4, body, stamp=(7, 100, 108))
    codec_id, n, param, stamp, got = unpack_frame(np.concatenate([head, b]))
    assert (codec_id, n, param, stamp) == (CODEC_INT8, 8, 4, (7, 100, 108))
    np.testing.assert_array_equal(got.view(np.uint32), body.view(np.uint32))
    head, b = pack_frame(CODEC_TOPK, 8, 2,
                         np.asarray([1.0, 3.0, 5.0, -5.0], np.float32))
    assert unpack_frame(np.concatenate([head, b]))[3] is None


def test_body_crc_rejects_corruption_and_restamp_heals_it():
    body = Int8Codec(block=4).encode(np.ones(8, np.float32))
    head, b = pack_frame(CODEC_INT8, 8, 4, body)
    frame = np.concatenate([head, b])
    frame[HEAD_LEN] = np.float32(1e30)  # corrupt one body word
    with pytest.raises(CompressionError, match="CRC"):
        unpack_frame(frame)
    restamp_crc(frame, 0)  # the SDC injector's contract
    unpack_frame(frame)  # decodes (to poison — the gate's job, not ours)


def test_sdc_on_compressed_frame_is_wire_silent_and_decoder_visible():
    """A chaos scale-SDC on a CompressedUpdate riding the reliability
    envelope arrives CRC-clean (both the envelope and the body CRC are
    re-stamped) and decodes to a norm explosion only the admission gate
    can see — the 'silent' in silent data corruption."""
    plan = ChaosPlan(sdc=[SDCRule(
        src=1, dst=0, code=int(MessageCode.CompressedUpdate), p=1.0,
        kind="scale", factor=1e20, skip=HEAD_LEN)])
    world = InProcessTransport.create_world(2)
    chaos, log = FaultyTransport.wrap_world(world, plan)
    srv = ReliableTransport(chaos[0], ack_timeout=0.5)
    wrk = ReliableTransport(chaos[1], ack_timeout=0.5)
    enc = CompressingEncoder(8, make_codec("int8", block=4))
    head, body = enc.encode_range(np.full(8, 0.5, np.float32), 0, 8)
    wrk.sendv(MessageCode.CompressedUpdate, (head, body), dst=0)
    msg = srv.recv(timeout=5.0)
    assert msg is not None and msg[1] == MessageCode.CompressedUpdate
    assert srv.stats["crc_dropped"] == 0  # bit-perfect on the wire
    _, _, dec = decode_update(msg[2])  # body CRC passes too
    assert float(np.linalg.norm(dec)) > 1e10  # ...but the poison decodes
    assert "sdc-scale" in log.lines()
    srv.detach()
    wrk.detach()
    for t in world.values():
        t.close()


# ----------------------------------------------------------- PS integration

def test_ps_applies_exactly_the_decoded_delta_and_logs_the_codec(tmp_path):
    ps = ParameterServer(params=np.zeros(32, np.float32),
                         ckpt_dir=str(tmp_path), ckpt_every=0, wal=True)
    enc = CompressingEncoder(32, make_codec("int8", block=8))
    u = np.linspace(-1, 1, 32).astype(np.float32)
    head, body = enc.encode_range(u, 0, 32)
    frame = np.concatenate([head, body])
    _, _, expected = decode_update(frame)
    ps.handle(1, MessageCode.CompressedUpdate, frame)
    ps.commit()
    np.testing.assert_array_equal(ps.central, expected)
    recs, _ = ps.wal.replay()
    assert [r.codec for r in recs] == [CODEC_INT8]
    np.testing.assert_array_equal(recs[0].payload, expected)


def test_truncated_compressed_frames_are_counted_never_silent():
    """A frame shorter than head+1 cannot even reach the decode path —
    it must still be loudly counted, on both the plain and elastic
    handlers (review hardening: the guarded elif used to fall through)."""
    ps = ParameterServer(params=np.zeros(8, np.float32))
    ps.handle(1, MessageCode.CompressedUpdate, np.zeros(5, np.float32))
    assert ps.dropped_bad_updates == 1 and ps._apply_seq == 0


def test_ps_drops_malformed_compressed_frames_before_accounting():
    ps = ParameterServer(params=np.zeros(8, np.float32))
    head, body = pack_frame(CODEC_INT8, 8, 4,
                            Int8Codec(block=4).encode(np.ones(8)))
    frame = np.concatenate([head, body])
    frame[HEAD_LEN + 1] = 42.0  # body corruption: CRC mismatch
    ps.handle(1, MessageCode.CompressedUpdate, frame)
    assert ps.dropped_bad_updates == 1 and ps._apply_seq == 0
    # decoded-size mismatch (frame encodes 8, server holds 4)
    ps2 = ParameterServer(params=np.zeros(4, np.float32))
    head, body = pack_frame(CODEC_INT8, 8, 4,
                            Int8Codec(block=4).encode(np.ones(8)))
    ps2.handle(1, MessageCode.CompressedUpdate,
               np.concatenate([head, body]))
    assert ps2.dropped_bad_updates == 1 and ps2._apply_seq == 0


def test_admission_gate_evaluates_the_decoded_norm(tmp_path):
    """The schema contract: z-scores on the DECODED norm, so a compressed
    poison cannot slip the gate — and a clean compressed stream trains
    the same per-worker statistics a dense stream would."""
    world = InProcessTransport.create_world(2)
    gate = GradientAdmission(z_max=6.0, warmup=2)
    ps = ParameterServer(params=np.zeros(16, np.float32),
                         transport=world[0], admission=gate)
    enc = CompressingEncoder(16, make_codec("int8", block=4))
    rng = np.random.default_rng(0)
    for _ in range(4):
        head, body = enc.encode_range(
            rng.normal(size=16).astype(np.float32), 0, 16)
        ps.handle(1, MessageCode.CompressedUpdate,
                  np.concatenate([head, body]))
    assert ps.quarantined == 0 and gate.admitted == 4
    # a poison whose WIRE bytes look ordinary but whose decode explodes:
    # scale the body (scales included) like the SDC rule does
    head, body = enc.encode_range(
        rng.normal(size=16).astype(np.float32), 0, 16)
    frame = np.concatenate([head, body * np.float32(1e20)])
    restamp_crc(frame, 0)
    ps.handle(1, MessageCode.CompressedUpdate, frame)
    assert ps.quarantined == 1 and ps._apply_seq == 4
    for t in world.values():
        t.close()


# ------------------------------------------------------------ THE acceptance

_MODEL = LeNet()
_STEPS = 16
_BATCH = 16


@pytest.fixture(scope="module")
def ps_fixture():
    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.training.trainer import (
        cross_entropy_loss,
    )

    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = _MODEL.apply({"params": q}, bx, train=True,
                                  rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = _MODEL.init(jax.random.key(0),
                          jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


def _run_compressed_world(ps_fixture, plan=None, compress="int8",
                          admission=True, n_workers=2):
    """One in-process compressed-DownPour run; returns (losses, log,
    server, encoders)."""
    x, y, grad_fn, params0 = ps_fixture
    world = InProcessTransport.create_world(n_workers + 1)
    log = None
    if plan is not None:
        world, log = FaultyTransport.wrap_world(world, plan)
    gate = GradientAdmission(z_max=8.0, warmup=2) if admission else None
    server = ParameterServer(
        params=np.asarray(ravel_model_params(params0)),
        transport=world[0], n_workers=n_workers, admission=gate)
    server_thread = threading.Thread(target=server.run,
                                     kwargs={"timeout": 180})
    server_thread.start()
    results, encoders = {}, {}

    def worker(rank):
        params = jax.tree.map(jnp.asarray, params0)
        opt = Asynchronous(params, lr=0.05, n_push=4, n_pull=4,
                           transport=world[rank], compress=compress,
                           compress_opts={"block": 1024})
        encoders[rank] = opt.encoder
        rng = jax.random.key(rank)
        losses = []
        for step in range(_STEPS):
            sel = np.random.default_rng(rank * 100 + step).integers(
                0, len(x), _BATCH)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            losses.append(float(loss))
        opt.finish()
        results[rank] = losses

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(1, n_workers + 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    server_thread.join(timeout=60)
    assert not server_thread.is_alive(), "server did not shut down"
    for t in world.values():
        t.close()
    return results, log, server, encoders


_COMPRESSED_PLAN = ChaosPlan(
    [FaultRule(code=int(c), drop=0.10, dup=0.05)
     for c in (MessageCode.CompressedUpdate, MessageCode.ParameterRequest,
               MessageCode.ParameterUpdate)],
    seed=42)


@pytest.mark.chaos
def test_compressed_downpour_acceptance(ps_fixture, lock_witness):
    """THE ISSUE 14 acceptance: int8 + error feedback, 2 workers under
    seeded drop/dup chaos, 3 runs — fault-free-corridor convergence,
    >= 3x fewer bytes on the wire than dense, byte-identical chaos logs,
    and every compressed push admitted on its decoded norm (zero
    quarantines)."""
    clean, _, _, _ = _run_compressed_world(ps_fixture, plan=None,
                                           compress=None, admission=False)
    clean_final = np.mean([np.mean(l[-6:]) for l in clean.values()])

    logs, finals = [], []
    for _run in range(3):
        results, log, server, encoders = _run_compressed_world(
            ps_fixture, plan=_COMPRESSED_PLAN)
        assert np.isfinite(server.central).all()
        assert server.quarantined == 0, server.quarantine
        assert server.message_counts[MessageCode.CompressedUpdate] > 0
        assert server.message_counts[MessageCode.GradientUpdate] == 0
        for enc in encoders.values():
            assert enc.compression_ratio() >= 3.0, enc.compression_ratio()
        logs.append(log.lines())
        finals.append(np.mean([np.mean(l[-6:])
                               for l in results.values()]))
        for losses in results.values():
            assert np.mean(losses[-6:]) < np.mean(losses[:6]), losses
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "fault log not byte-identical across runs")
    assert "drop" in logs[0] and "dup" in logs[0]
    for final in finals:
        assert abs(final - clean_final) < 0.45, (final, clean_final)


# ----------------------------------------------------------------- drill

@pytest.mark.drill
def test_compressed_drill_replays_decoded_deltas_exactly_once(tmp_path):
    """The ISSUE 14 drill satellite: kill a shard mid-COMPRESSED-run
    (int8 wire, sgdm sharded optimizer), restore from manifest + WAL —
    acked => applied holds across the crash-truncation, the replayed WAL
    records carry the codec id, and the restored shards' per-range
    optimizer state is live (momentum engaged, sized to the range)."""
    from distributed_ml_pytorch_tpu.coord.drill import (
        default_drill_plan,
        recovery_drill,
    )
    from distributed_ml_pytorch_tpu.utils.compress import CODEC_INT8

    out = recovery_drill(base_dir=str(tmp_path), seed=0,
                         plan=default_drill_plan(0),
                         compress="int8", server_opt="sgdm")
    assert out["ok"], (out["errors"], out["accounting_ok"],
                       out["stuck_workers"])
    assert out["accounting_ok"], (out["acked"], out["applied"])
    assert out["replayed_updates"] > 0
    # every record surviving into the restore carried the int8 codec id —
    # captured at restore time, before the end-of-run checkpoint truncates
    assert out["replayed_codecs"], out
    assert set(out["replayed_codecs"]) == {CODEC_INT8}, (
        out["replayed_codecs"])
    for srv in out["servers"]:
        ps = srv.ps
        assert ps.optimizer is not None
        assert ps.optimizer.size == srv.hi - srv.lo
        assert np.isfinite(ps.optimizer.m).all()
        # compressed pushes really flowed on every restored shard
        assert ps.message_counts[MessageCode.CompressedUpdate] > 0
