"""Fleet serving (ISSUE 6): multi-engine routing, stream migration across
engine death (token-identical, byte-identical chaos logs), SLO-aware
overload control (shed/brownout/deadline), and the frontend fail-open /
hold-queue satellites.

THE acceptance scenario: 3 engines + router under ``FaultyTransport``
(seeded loss on stream frames, duplicated submits), one engine killed
mid-decode — every in-flight stream completes token-identical (CPU) to a
single-engine ``generate()``, three runs produce byte-identical chaos
logs, and lease expiry (a member whose renewals stop while its serve loop
keeps running) triggers the same migration as a scripted crash.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models.generate import generate
from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
from distributed_ml_pytorch_tpu.serving.engine import ServingEngine
from distributed_ml_pytorch_tpu.serving.fleet import EngineMember, FleetRouter
from distributed_ml_pytorch_tpu.serving.frontend import (
    RequestRejected,
    ServingClient,
    ServingFrontend,
)
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosLog,
    ChaosPlan,
    FaultRule,
    FaultyTransport,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)

pytestmark = pytest.mark.fleet

VOCAB = 64


@pytest.fixture(scope="module")
def lm_and_params():
    model = TransformerLM(vocab_size=VOCAB, d_model=32, n_heads=4,
                          n_layers=2, d_ff=64, max_len=256)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def make_engine(lm_and_params, warm=True, **kw):
    model, params = lm_and_params
    kw.setdefault("slots", 2)
    kw.setdefault("cache_size", 200)
    kw.setdefault("decode_block", 4)
    kw.setdefault("prefill_bucket", 8)
    engine = ServingEngine(model, params, **kw)
    if warm:
        # compile the buckets/decode block OUTSIDE the probed window — a
        # cold engine stalls seconds in XLA and reads as dead to a router
        for bucket in (8, 16):
            if engine.pool.capacity_needed(bucket, bucket, 6) \
                    <= engine.pool.cache_size:
                w = engine.submit(np.zeros(bucket, np.int32), 6)
                engine.run_until_idle()
                assert w.done
        engine.reset_metrics()
    return engine


def expected(lm_and_params, prompt, n, **kw):
    model, params = lm_and_params
    if "seed" in kw:
        kw["rng"] = jax.random.key(kw.pop("seed"))
    return np.asarray(generate(
        model, params, jnp.asarray(prompt, jnp.int32)[None], n, **kw)
    )[0, len(prompt):].tolist()


def fleet_world(lm_and_params, n_engines=3, plan=None, router_kw=None,
                member_coords=None, member_kw=None):
    """N warmed engines behind a FleetRouter on a 2-rank world (rank 0 hub,
    rank 1 client), optionally chaos-wrapped with one shared log."""
    world = InProcessTransport.create_world(2)
    log = None
    if plan is not None:
        world, log = FaultyTransport.wrap_world(world, plan)
    members = []
    for i in range(n_engines):
        coord = member_coords[i] if member_coords else None
        members.append(EngineMember(
            i, make_engine(lm_and_params), coord=coord,
            **(member_kw or {})).start())
    kw = {"probe_timeout": 0.5}
    kw.update(router_kw or {})
    router = FleetRouter(world[0], members, **kw)
    thread = threading.Thread(target=router.serve_forever, daemon=True)
    thread.start()
    return world, members, router, thread, log


def teardown_fleet(world, router, thread):
    router.stop()
    thread.join(timeout=10)
    for t in world.values():
        t.close()


def wait_for(cond, timeout=30.0, interval=0.002):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# engine-level: the gen_offset resume primitive migration rides on
# ---------------------------------------------------------------------------

def test_gen_offset_resume_is_token_identical(lm_and_params):
    """Resuming prompt + generated-so-far with the matching gen_offset
    continues the stream token-identically — greedy AND sampled (the
    sampling key schedule is position-in-stream, not position-on-engine)."""
    prompt = np.random.default_rng(1).integers(0, VOCAB, size=5)
    for kw in ({}, {"temperature": 0.8, "top_k": 8, "seed": 11}):
        want = expected(lm_and_params, prompt, 20, **dict(kw))
        engine_a = make_engine(lm_and_params)
        full = engine_a.submit(prompt, 20, **kw)
        engine_a.run_until_idle()
        assert full.tokens == want
        for cut in (1, 7, 19):
            engine_b = make_engine(lm_and_params, warm=False)
            resumed = engine_b.submit(
                np.concatenate([prompt, np.asarray(want[:cut], np.int32)]),
                20 - cut, gen_offset=cut, **kw)
            engine_b.run_until_idle()
            assert resumed.tokens == want[cut:], (kw, cut)


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_router_routes_by_occupancy_and_session_affinity(lm_and_params):
    world, members, router, thread, _ = fleet_world(lm_and_params, 2)
    try:
        client = ServingClient(world[1])
        # 4 concurrent long streams over 2 engines x 2 slots: occupancy
        # routing must spread them instead of stacking one engine
        rids = [client.submit(np.arange(4), 60) for _ in range(4)]
        assert wait_for(lambda: len(router._routes) == 4)
        with router._routes_lock:
            used = [r.engine_id for r in router._routes.values()]
        assert sorted(used).count(0) == 2 and sorted(used).count(1) == 2
        for rid in rids:
            assert len(list(client.stream(rid, timeout=120))) == 60
        # session affinity: consecutive submits of one session stick to one
        # engine while it has room (prefix locality)
        sids = [client.submit(np.arange(4), 4, session=9) for _ in range(2)]
        assert wait_for(
            lambda: len([r for r in router._routes.values()
                         if r.session == 9]) == 2)
        with router._routes_lock:
            pinned = {r.engine_id for r in router._routes.values()
                      if r.session == 9}
        assert len(pinned) == 1
        for rid in sids:
            list(client.stream(rid, timeout=60))
    finally:
        teardown_fleet(world, router, thread)


# ---------------------------------------------------------------------------
# THE acceptance: migration under chaos, byte-identical logs, 3x
# ---------------------------------------------------------------------------

def _acceptance_plan():
    # faults restricted to deterministic-index channels: the hub's first 8
    # StreamTokens frames (drops recovered by the client resume protocol —
    # retransmits are unfaulted, so their count never touches the log) and
    # the client's 3 SubmitRequest frames (dups replay, never double-submit)
    return ChaosPlan([
        FaultRule(code=int(MessageCode.StreamTokens), drop=0.4, until=8),
        FaultRule(code=int(MessageCode.SubmitRequest), dup=0.3),
    ], seed=29)


def _run_fleet_acceptance_once(lm_and_params):
    """3 engines + router under chaos, one engine killed mid-decode; returns
    (tokens per request, chaos log lines, router stats)."""
    world, members, router, thread, log = fleet_world(
        lm_and_params, 3, plan=_acceptance_plan())
    try:
        client = ServingClient(world[1], resume_after=0.25)
        prompt = np.random.default_rng(0).integers(0, VOCAB, size=5)
        specs = [dict(n=120), dict(n=100, temperature=0.8, top_k=8, seed=3),
                 dict(n=90)]
        rids = []
        for s in specs:
            s = dict(s)
            rids.append(client.submit(prompt, s.pop("n"), **s))
        # kill the first engine observed with an in-flight stream that has
        # streamed at least 2 tokens — a mid-decode death by construction
        victim = {}

        def find_victim():
            with router._routes_lock:
                for r in router._routes.values():
                    if not r.done and r.engine_id >= 0 and len(r.tokens) >= 2:
                        victim["id"] = r.engine_id
                        return True
            return False

        assert wait_for(find_victim), "no stream ever got mid-decode"
        members[victim["id"]].crash()
        streams = [list(client.stream(r, timeout=180)) for r in rids]
        assert wait_for(lambda: router.migrations >= 1, timeout=5)
        return prompt, specs, streams, log.lines(), {
            "migrations": router.migrations,
            "failures": router.migration_failures,
        }
    finally:
        teardown_fleet(world, router, thread)


def test_fleet_acceptance_migration_token_identical_3x(lm_and_params):
    """ISSUE 6 acceptance: one engine killed mid-decode under seeded chaos
    — every in-flight stream completes token-identical to a single-engine
    generate(), across THREE runs with byte-identical chaos logs."""
    logs = []
    for _run in range(3):
        prompt, specs, streams, lines, stats = \
            _run_fleet_acceptance_once(lm_and_params)
        for spec, got in zip(specs, streams):
            s = dict(spec)
            want = expected(lm_and_params, prompt, s.pop("n"), **s)
            assert got == want, f"stream diverged after migration: {spec}"
        assert stats["migrations"] >= 1 and stats["failures"] == 0
        logs.append(lines)
    assert logs[0] == logs[1] == logs[2], "chaos logs not byte-identical"
    assert logs[0], "no faults ever fired"


def test_lease_expiry_triggers_migration(lm_and_params):
    """The OTHER detection path: a member whose lease renewals stop while
    its serve loop keeps beating (control-plane death). The local probe
    sees a healthy engine; the coordinator's fleet view drops its rank —
    and that alone must trigger the same token-identical migration."""
    from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator
    from distributed_ml_pytorch_tpu.coord.member import CoordClient

    coord_world = InProcessTransport.create_world(4)
    coord = Coordinator(coord_world[0], n_params=8, lease=0.6)
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 120}, daemon=True)
    coord_thread.start()
    clients = [CoordClient(coord_world[i], "engine", renew_interval=0.1)
               for i in (1, 2, 3)]
    world, members, router, thread, _ = fleet_world(
        lm_and_params, 3,
        router_kw={"probe_timeout": 60.0, "fleet": coord},  # probe blinded
        member_coords=clients,
        # throttled decode keeps the stream in flight across one lease
        member_kw={"throttle": 0.05})
    try:
        assert wait_for(lambda: len(coord.live_engine_ranks()) == 3,
                        timeout=10)
        client = ServingClient(world[1], resume_after=0.25)
        prompt = np.random.default_rng(2).integers(0, VOCAB, size=6)
        rid = client.submit(prompt, 110)
        victim = {}

        def started():
            with router._routes_lock:
                for r in router._routes.values():
                    if not r.done and r.engine_id >= 0 and len(r.tokens) >= 2:
                        victim["id"] = r.engine_id
                        return True
            return False

        assert wait_for(started)
        # kill ONLY the control-plane life: renewals stop, serving does not
        members[victim["id"]].coord.stop()
        assert wait_for(
            lambda: victim["id"] not in
            {m.engine_id for m in router._healthy_members()}, timeout=10), \
            "lease expiry never marked the member down"
        toks = list(client.stream(rid, timeout=180))
        assert toks == expected(lm_and_params, prompt, 110)
        assert router.migrations >= 1
    finally:
        teardown_fleet(world, router, thread)
        coord.stop()
        coord_thread.join(timeout=10)
        for c in clients:
            c.stop()
        for t in coord_world.values():
            t.close()


# ---------------------------------------------------------------------------
# overload plane: shed / brownout / deadline
# ---------------------------------------------------------------------------

def overloaded_frontend(lm_and_params, **kw):
    """A 1-slot engine with a long-running occupant, so pressure >= 1."""
    engine = make_engine(lm_and_params, slots=1, cache_size=200,
                         max_queue=16)
    world = InProcessTransport.create_world(2)
    frontend = ServingFrontend(engine, world[0], **kw)
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    return engine, world, frontend, thread


def test_shed_lowest_priority_with_explicit_reject(lm_and_params):
    # shed_occupancy=2.0 on a 1-slot engine: overload begins once one
    # request runs AND one waits — the waiting one is the displacement pool
    engine, world, frontend, thread = overloaded_frontend(
        lm_and_params, shed_occupancy=2.0)
    try:
        client = ServingClient(world[1])
        occupant = client.submit(np.arange(4), 120)  # fills the only slot
        assert wait_for(lambda: engine.pressure()[0] == 1)
        mid = client.submit(np.arange(4), 8, priority=2)  # queues: now 2.0
        assert wait_for(lambda: len(frontend._waiting_routes()) == 1)
        # overloaded: a LOWER-priority submit cannot displace mid — it is
        # shed outright with an explicit reject …
        low = client.submit(np.arange(4), 8, priority=1)
        with pytest.raises(RequestRejected):
            list(client.stream(low, timeout=30))
        assert frontend.shed == 1
        # … while a HIGHER-priority one displaces mid (mid gets the reject)
        high = client.submit(np.arange(4), 8, priority=5)
        with pytest.raises(RequestRejected):
            list(client.stream(mid, timeout=30))
        assert frontend.shed == 2
        assert len(list(client.stream(occupant, timeout=180))) == 120
        assert len(list(client.stream(high, timeout=60))) == 8
    finally:
        frontend.stop()
        thread.join(timeout=10)
        for t in world.values():
            t.close()


def test_brownout_caps_max_new_before_shedding(lm_and_params):
    engine, world, frontend, thread = overloaded_frontend(
        lm_and_params, brownout_occupancy=1.0, brownout_max_new=5)
    try:
        client = ServingClient(world[1])
        occupant = client.submit(np.arange(4), 60)
        assert wait_for(lambda: engine.pressure()[0] == 1)
        # browned out, NOT shed: served, but truncated to brownout_max_new
        dim = client.submit(np.arange(4), 40, priority=1)
        toks = list(client.stream(dim, timeout=120))
        assert len(toks) == 5
        assert frontend.brownouts == 1 and frontend.shed == 0
        assert len(list(client.stream(occupant, timeout=120))) == 60
    finally:
        frontend.stop()
        thread.join(timeout=10)
        for t in world.values():
            t.close()


def test_deadline_expired_waiting_work_is_shed(lm_and_params):
    """No serve loop: the scheduling timeline is driven by hand, so the
    deadline expiry is exact (the existing silent-client test's style)."""
    engine = make_engine(lm_and_params, slots=1, max_queue=16)
    world = InProcessTransport.create_world(2)
    frontend = ServingFrontend(engine, world[0])
    try:
        client = ServingClient(world[1])
        occupant = client.submit(np.arange(4), 30)
        doomed = client.submit(np.arange(4), 8, deadline_ms=100)
        assert wait_for(lambda: len(frontend._waiting_routes()) == 2)
        time.sleep(0.15)  # the doomed deadline passes while both wait
        frontend._sweep(time.monotonic())
        assert frontend.shed == 1
        engine.run_until_idle()  # the survivor is served to completion
        with pytest.raises(RequestRejected):
            list(client.stream(doomed, timeout=30))
        assert len(list(client.stream(occupant, timeout=60))) == 30
    finally:
        frontend.stop()
        for t in world.values():
            t.close()


# ---------------------------------------------------------------------------
# satellites: fail-open without a control plane; hold-queue overflow
# ---------------------------------------------------------------------------

def test_frontend_fails_open_without_fleet(lm_and_params):
    """fleet=None (no control plane) must keep admitting — the documented
    fail-open path, previously untested."""
    engine = make_engine(lm_and_params)
    world = InProcessTransport.create_world(2)
    frontend = ServingFrontend(engine, world[0], fleet=None)
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    try:
        client = ServingClient(world[1])
        toks = client.generate(np.arange(5), 10, timeout=60)
        assert len(toks) == 10
        assert frontend.held_peak == 0  # nothing was ever held
    finally:
        frontend.stop()
        thread.join(timeout=10)
        for t in world.values():
            t.close()


class _DownFleet:
    def __init__(self):
        self.up = False

    def engine_up(self):
        return self.up


def test_hold_queue_overflow_under_down_fleet(lm_and_params):
    """With the fleet DOWN: the first hold_queue submits are held (arrival
    order), the overflow gets explicit rejects, and recovery re-admits
    every held request to completion."""
    engine = make_engine(lm_and_params)
    fleet = _DownFleet()
    world = InProcessTransport.create_world(2)
    frontend = ServingFrontend(engine, world[0], fleet=fleet, hold_queue=3)
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    try:
        client = ServingClient(world[1])
        rids = [client.submit(np.arange(4), 6) for _ in range(5)]
        assert wait_for(lambda: frontend.held_peak == 3)
        with frontend._held_lock:
            assert len(frontend._held) == 3
        rejected = 0
        for rid in rids[3:]:
            with pytest.raises(RequestRejected):
                list(client.stream(rid, timeout=30))
            rejected += 1
        assert rejected == 2
        fleet.up = True  # recovery: the sweep re-admits in arrival order
        for rid in rids[:3]:
            assert len(list(client.stream(rid, timeout=120))) == 6
        with frontend._held_lock:
            assert not frontend._held
    finally:
        frontend.stop()
        thread.join(timeout=10)
        for t in world.values():
            t.close()


# ---------------------------------------------------------------------------
# bench satellites: arrival mixes + overload soak
# ---------------------------------------------------------------------------

def test_bench_arrival_mixes_are_reproducible_and_shaped():
    import bench_serving

    p = bench_serving.build_parser()
    for mix in ("poisson", "diurnal", "bursty", "herd"):
        args = p.parse_args(["--arrival", mix, "--requests", "64",
                             "--rate", "20", "--seed", "7"])
        a1 = bench_serving.make_arrivals(args, np.random.default_rng(7))
        a2 = bench_serving.make_arrivals(args, np.random.default_rng(7))
        assert np.array_equal(a1, a2), mix  # seeded => reproducible
        assert a1.shape == (64,) and np.all(np.diff(a1) >= 0), mix
    args = p.parse_args(["--arrival", "herd", "--requests", "64",
                         "--herd-frac", "0.5"])
    herd = bench_serving.make_arrivals(args, np.random.default_rng(0))
    assert np.sum(herd == 0.0) == 32  # the thundering front


@pytest.mark.slow
@pytest.mark.soak
def test_overload_soak_2x_rate_degrades_not_dies(lm_and_params):
    """Acceptance (overload half): at 2x the baseline arrival rate the
    fleet sheds/brownouts instead of collapsing — goodput-under-SLO stays
    >= 80% of the 1x value, and every shed request got an explicit reject
    (client-side rejects == router-side shed count)."""
    import bench_serving

    def run(rate, shed_on):
        argv = [
            "--engines", "2", "--requests", "36", "--rate", str(rate),
            "--arrival", "poisson", "--deadline-ms", "8000",
            "--priority-levels", "3", "--slots", "2", "--cache-size", "96",
            "--decode-block", "4", "--prompt-len", "4", "8",
            "--new-tokens", "6", "14", "--sampled-frac", "0.3",
            "--vocab", "64", "--d-model", "32", "--n-heads", "4",
            "--n-layers", "2", "--d-ff", "64", "--seed", "5",
        ]
        if shed_on:
            argv += ["--shed-occupancy", "3.0",
                     "--brownout-occupancy", "2.0", "--brownout-max-new", "6"]
        args = bench_serving.build_parser().parse_args(argv)
        r = bench_serving.run_fleet(args)
        goodput = r["good_tokens"] / r["wall"] if r["wall"] else 0.0
        return goodput, r

    base_rate = 4.0
    goodput_1x, _ = run(base_rate, shed_on=False)
    goodput_2x, r2 = run(2 * base_rate, shed_on=True)
    assert goodput_1x > 0
    assert goodput_2x >= 0.8 * goodput_1x, (
        f"fleet collapsed under 2x load: {goodput_2x:.1f} vs "
        f"{goodput_1x:.1f} tok/s goodput")
    # every shed request was told so explicitly — no silent drops
    assert r2["rejected_client_side"] == r2["shed"]
