"""utils/metrics.py helpers: the percentile/latency-summary primitives the
serving SLO reporter builds on."""

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils.metrics import latency_summary, percentile


def test_percentile_linear_interpolation():
    values = [1.0, 2.0, 3.0, 4.0]
    assert percentile(values, 0) == 1.0
    assert percentile(values, 100) == 4.0
    assert percentile(values, 50) == 2.5  # numpy linear interpolation
    assert percentile([7.0], 99) == 7.0
    # order-independent
    assert percentile([4.0, 1.0, 3.0, 2.0], 50) == 2.5


def test_percentile_matches_numpy_on_random_sample():
    rng = np.random.default_rng(0)
    sample = rng.exponential(size=257).tolist()
    for q in (50, 90, 99, 99.9):
        assert percentile(sample, q) == pytest.approx(
            float(np.percentile(sample, q)))


def test_percentile_rejects_bad_input():
    with pytest.raises(ValueError, match="empty"):
        percentile([], 50)
    with pytest.raises(ValueError, match="0, 100"):
        percentile([1.0], 101)
    with pytest.raises(ValueError, match="0, 100"):
        percentile([1.0], -1)


def test_latency_summary_shape_and_values():
    s = latency_summary([10.0, 20.0, 30.0])
    assert s["count"] == 3
    assert s["mean"] == pytest.approx(20.0)
    assert s["max"] == 30.0
    assert s["p50"] == 20.0
    assert set(s) == {"count", "mean", "max", "p50", "p90", "p99"}
    custom = latency_summary([1.0, 2.0], percentiles=(25,))
    assert set(custom) == {"count", "mean", "max", "p25"}


def test_latency_summary_empty_is_none():
    assert latency_summary([]) is None
