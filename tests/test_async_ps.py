"""C1/C2/M1 async parameter-server tests: server message semantics, DownPour
cadence parity (push/pull every n steps, lr-pre-scaled accumulator), and a
full in-process 1-server/2-worker topology — the single-host cluster
simulation the reference does with localhost processes (SURVEY.md §4)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models import LeNet
from distributed_ml_pytorch_tpu.parallel.async_ps import (
    Asynchronous,
    DownpourSGD,
    ParameterServer,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)
from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params


def _lenet_params(seed=0):
    model = LeNet()
    return model, model.init(jax.random.key(seed), jnp.zeros((1, 32, 32, 3)))["params"]


def test_server_gradient_update_adds():
    _, params = _lenet_params()
    flat = np.asarray(ravel_model_params(params))
    server = ParameterServer(params=flat)
    delta = np.random.default_rng(0).normal(size=flat.shape).astype(np.float32)
    server.handle(1, MessageCode.GradientUpdate, delta)
    np.testing.assert_allclose(server.central, flat + delta, rtol=1e-6)


def test_server_checkpoint_cadence_and_restore(tmp_path):
    """Central params persist every ckpt_every pushes (atomic write) and a
    fresh server adopts them — PS preemption safety: workers recover by
    rejoining, only the server's state would otherwise be lost."""
    _, params = _lenet_params()
    flat = np.asarray(ravel_model_params(params))
    server = ParameterServer(params=flat, ckpt_dir=str(tmp_path), ckpt_every=2)
    delta = np.random.default_rng(1).normal(size=flat.shape).astype(np.float32)

    server.handle(1, MessageCode.GradientUpdate, delta)
    assert not (tmp_path / "ps_central.npy").exists()  # cadence not reached
    server.handle(2, MessageCode.GradientUpdate, delta)
    assert (tmp_path / "ps_central.npy").exists()

    fresh = ParameterServer(params=flat, ckpt_dir=str(tmp_path))
    assert fresh.maybe_restore()
    np.testing.assert_allclose(fresh.central, flat + 2 * delta, rtol=1e-6)


def test_server_restore_rejects_wrong_model(tmp_path):
    _, params = _lenet_params()
    flat = np.asarray(ravel_model_params(params))
    server = ParameterServer(params=flat, ckpt_dir=str(tmp_path))
    server.save_checkpoint()
    other = ParameterServer(params=flat[:100].copy(), ckpt_dir=str(tmp_path))
    with pytest.raises(ValueError, match="wrong --model"):
        other.maybe_restore()


def test_restored_server_survives_fresh_worker_install(tmp_path):
    """A resumed server must NOT be stomped by a non-rejoin worker's
    construction-time ParameterUpdate install — it answers with the
    authoritative (restored) params instead."""
    from distributed_ml_pytorch_tpu.utils.messaging import InProcessTransport

    _, params = _lenet_params()
    flat = np.asarray(ravel_model_params(params))
    saved = flat + 7.0
    writer = ParameterServer(params=saved.copy(), ckpt_dir=str(tmp_path))
    writer.save_checkpoint()

    world = InProcessTransport.create_world(2)
    server = ParameterServer(
        params=flat, transport=world[0], ckpt_dir=str(tmp_path)
    )
    assert server.maybe_restore()

    fresh_init = np.zeros_like(flat)
    server.handle(1, MessageCode.ParameterUpdate, fresh_init)
    np.testing.assert_allclose(server.central, saved, rtol=1e-6)  # not stomped
    # and the worker got the restored params back
    sender, code, payload = world[1].recv(timeout=5)
    assert code == MessageCode.ParameterUpdate
    np.testing.assert_allclose(payload, saved, rtol=1e-6)


def test_server_restore_without_checkpoint_is_noop(tmp_path):
    _, params = _lenet_params()
    flat = np.asarray(ravel_model_params(params))
    server = ParameterServer(params=flat, ckpt_dir=str(tmp_path))
    assert not server.maybe_restore()
    np.testing.assert_allclose(server.central, flat)


def test_server_parameter_request_replies():
    world = InProcessTransport.create_world(2)
    _, params = _lenet_params()
    server = ParameterServer(params=np.asarray(ravel_model_params(params)), transport=world[0])
    server.handle(1, MessageCode.ParameterRequest, np.zeros(0, np.float32))
    msg = world[1].recv(timeout=2)
    assert msg is not None
    sender, code, payload = msg
    assert sender == 0 and code == MessageCode.ParameterUpdate
    np.testing.assert_array_equal(payload, server.central)


def test_server_parameter_update_installs():
    _, params = _lenet_params()
    server = ParameterServer(params=np.asarray(ravel_model_params(params)))
    new = np.arange(server.central.size, dtype=np.float32)
    server.handle(2, MessageCode.ParameterUpdate, new)
    np.testing.assert_array_equal(server.central, new)


def test_downpour_alias():
    assert DownpourSGD is Asynchronous  # M4 contract


def test_worker_cadence_and_accumulator():
    """Message pattern parity with Asynchronous.py:42-70 for n_push=3, n_pull=2."""
    world = InProcessTransport.create_world(2)
    _, params = _lenet_params()
    opt = Asynchronous(params, lr=0.1, n_push=3, n_pull=2, transport=world[1])
    try:
        # construction sends the initial ParameterUpdate (:34)
        msg = world[0].recv(timeout=2)
        assert msg[1] == MessageCode.ParameterUpdate

        grads = jax.tree.map(jnp.ones_like, params)
        flat_ones = np.ones_like(np.asarray(ravel_model_params(params)))

        codes_per_step = []
        for _ in range(6):
            before = opt.idx
            params = opt.step(params, grads)
            codes = []
            while True:
                m = world[0].recv(timeout=0.05)
                if m is None:
                    break
                codes.append((m[1], m[2]))
            codes_per_step.append([c for c, _ in codes])
            for c, payload in codes:
                if c == MessageCode.GradientUpdate:
                    # lr-pre-scaled accumulation: pushes carry -lr * sum(grads)
                    steps_since_push = 3
                    if before == 0:
                        steps_since_push = 1  # first push fires on step 0
                    np.testing.assert_allclose(
                        payload, -0.1 * steps_since_push * flat_ones, rtol=1e-5
                    )
        # idx%2==0 → pull on steps 0,2,4; idx%3==0 → push on steps 0,3
        assert codes_per_step[0] == [MessageCode.ParameterRequest, MessageCode.GradientUpdate]
        assert codes_per_step[1] == []
        assert codes_per_step[2] == [MessageCode.ParameterRequest]
        assert codes_per_step[3] == [MessageCode.GradientUpdate]
        assert codes_per_step[4] == [MessageCode.ParameterRequest]
        assert codes_per_step[5] == []
    finally:
        opt.listener.stop()


def test_worker_installs_server_push_between_steps():
    world = InProcessTransport.create_world(2)
    _, params = _lenet_params()
    opt = Asynchronous(params, lr=0.0, n_push=100, n_pull=100, transport=world[1])
    try:
        world[0].recv(timeout=2)  # drain initial ParameterUpdate
        pushed = np.full(np.asarray(ravel_model_params(params)).size, 3.25, np.float32)
        world[1]._boxes[1].put((0, MessageCode.ParameterUpdate, pushed))
        # wait until the listener thread deposits it
        for _ in range(100):
            if opt.listener._latest is not None:
                break
            threading.Event().wait(0.02)
        grads = jax.tree.map(jnp.zeros_like, params)
        params = opt.step(params, grads)
        flat_after = np.asarray(ravel_model_params(params))
        np.testing.assert_allclose(flat_after, pushed, rtol=1e-6)
    finally:
        opt.listener.stop()


def test_full_ps_topology_in_process():
    """1 server + 2 workers training LeNet on synthetic data, in-process
    transports, real jitted steps — convergence + clean shutdown."""
    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    world = InProcessTransport.create_world(3)
    model, params0 = _lenet_params()
    server = ParameterServer(
        params=np.asarray(ravel_model_params(params0)), transport=world[0], n_workers=2
    )
    server_thread = threading.Thread(target=server.run, kwargs={"timeout": 120})
    server_thread.start()

    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)
    results = {}

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = model.apply({"params": q}, bx, train=True, rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    def worker(rank):
        _, params = _lenet_params(seed=0)
        opt = Asynchronous(params, lr=0.05, n_push=4, n_pull=4, transport=world[rank])
        rng = jax.random.key(rank)
        losses = []
        for step in range(24):
            sel = np.random.default_rng(rank * 100 + step).integers(0, len(x), 32)
            loss, grads = grad_fn(params, x[sel], y[sel], jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            losses.append(float(loss))
        opt.finish()
        results[rank] = losses

    threads = [threading.Thread(target=worker, args=(r,)) for r in (1, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    server_thread.join(timeout=30)
    assert not server_thread.is_alive(), "server did not shut down after WorkerDone x2"

    for rank in (1, 2):
        losses = results[rank]
        assert np.mean(losses[-6:]) < np.mean(losses[:6]), (rank, losses)
    assert server.message_counts[MessageCode.GradientUpdate] >= 2
    assert server.message_counts[MessageCode.ParameterRequest] >= 2
    assert np.isfinite(server.central).all()


def test_push_flusher_overlaps_order_and_drain():
    """The flusher (VERDICT r4 #5) must (a) return from enqueue without
    waiting for the send, (b) preserve FIFO order across pushes, and
    (c) complete every pending send on drain()."""
    import time

    from distributed_ml_pytorch_tpu.parallel.async_ps import PushFlusher

    sent, gate = [], threading.Event()

    def slow_send(arr):
        gate.wait(5)  # the wire is slow; enqueue must not care
        sent.append(int(arr[0]))

    fl = PushFlusher(slow_send)
    t0 = time.perf_counter()
    for i in range(fl.MAX_IN_FLIGHT):  # up to the bound: non-blocking
        fl.enqueue(jnp.full((8,), i, jnp.float32))
    enq_time = time.perf_counter() - t0
    assert enq_time < 1.0, f"enqueue blocked on the send ({enq_time:.2f}s)"
    assert sent == []  # nothing sent while the wire is blocked
    gate.set()
    fl.drain()
    assert sent == list(range(fl.MAX_IN_FLIGHT))  # FIFO, all landed
    fl.stop()


def test_push_flusher_survives_send_failure_and_still_drains():
    """A failing fetch/send must drop THAT push (degrade-never-crash, the
    _send contract) — not kill the thread and deadlock drain()/finish()."""
    from distributed_ml_pytorch_tpu.parallel.async_ps import PushFlusher

    sent, fail_first = [], [True]

    def flaky_send(arr):
        if fail_first[0]:
            fail_first[0] = False
            raise RuntimeError("wire exploded")
        sent.append(int(arr[0]))

    fl = PushFlusher(flaky_send)
    fl.enqueue(jnp.full((4,), 0, jnp.float32))  # lost to the failure
    fl.enqueue(jnp.full((4,), 1, jnp.float32))
    fl.drain()  # must NOT hang
    assert sent == [1]
    fl.stop()
