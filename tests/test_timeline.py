"""Timeline analyzer over canned multi-member flight dumps (ISSUE 12).

Fixtures live under ``tests/data/timeline/`` — four dumps covering the
contract surface: a stage with a proper attribution summary, a death dump
with spans only (fallback summation), a TORN dump (truncated line mid-
crash), and an unknown-plane dump that must be surfaced, not dropped.
Also hosts the ``bench_all.check_bubble_attribution`` schema gate tests
(the ``test_bench_gate.py``-style check for the mpmd_phase JSON field).
"""

import json
import os

import pytest

from distributed_ml_pytorch_tpu.analysis import timeline

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "data", "timeline")


@pytest.fixture(scope="module")
def report():
    return timeline.analyze(FIXTURES)


def test_loads_all_dumps_and_counts_torn_lines(report):
    assert report["n_dumps"] == 4
    # the torn fixture has exactly 2 unparseable lines (truncated json +
    # garbage); they are tolerated AND counted, never fatal
    assert report["torn_lines"] == 2
    # the valid spans AROUND the tear still load
    (d,) = [d for d in timeline.load_dir(FIXTURES)
            if d["member"] == "driver"]
    assert len(d["events"]) == 1 and len(d["spans"]) == 1
    # ring-drop accounting propagates from the meta headers
    assert report["ring_dropped_spans"] == 1


def test_unknown_plane_surfaced_not_dropped(report):
    assert report["unknown_planes"] == ["quantum"]
    mystery = [m for m in report["members"] if m["member"] == "mystery"]
    assert mystery, "unknown-plane member must still be attributed"
    # its states are attributed generically (4s of spans over 4s wall)
    assert mystery[0]["accounted"] == pytest.approx(1.0, abs=1e-6)


def test_attribution_prefers_summary_and_sums_to_one(report):
    s0 = next(m for m in report["members"] if m["member"] == "stage0")
    # the attribution event's exact numbers win over span summation
    assert s0["wall_s"] == 10.0
    assert s0["fractions"]["compute"] == pytest.approx(0.4)
    assert s0["fractions"]["wait-grad"] == pytest.approx(0.3)
    assert s0["accounted"] == pytest.approx(1.0, abs=1e-6)
    assert s0["unknown_states"] == []


def test_attribution_fallback_sums_spans_for_death_dump(report):
    s1 = next(m for m in report["members"] if m["member"] == "stage1")
    assert s1["reason"] == "death"
    # spans cover 1.5e9..11.5e9 ns -> 10 s wall, fully accounted
    assert s1["wall_s"] == pytest.approx(10.0)
    assert s1["seconds"]["compute"] == pytest.approx(4.5)
    assert s1["seconds"]["wire-blocked"] == pytest.approx(2.0)
    assert s1["accounted"] == pytest.approx(1.0, abs=1e-6)


def test_bubble_aggregates_stage_members(report):
    b = report["bubble_attribution"]
    assert b["stages"] == 2
    assert b["stage_seconds"] == pytest.approx(20.0)
    # compute = (4.0 + 4.5) / 20
    assert b["fractions"]["compute"] == pytest.approx(0.425)
    assert b["bubble_fraction"] == pytest.approx(0.575)
    assert b["wait_fraction"] + b["fractions"]["compute"] == pytest.approx(
        1.0, abs=1e-3)


def test_wire_attribution_from_wire_stats_events(report):
    w = report["wire_attribution"]
    assert w["members_reporting"] == 1
    assert w["sent"] == 100 and w["retries"] == 5
    assert w["retransmit_share"] == pytest.approx(0.05)
    assert w["ack_frames"] == 25
    assert w["acks_per_data_frame"] == pytest.approx(25 / 95)
    assert w["credit_block_s"] == pytest.approx(0.25)


def test_correlation_journeys_cross_members(report):
    j = report["journeys"]
    # corr 7 and 8 each appear on multiple members (driver + stages)
    assert j["cross_member_units"] >= 2
    longest = j["longest"][0]
    assert len(longest["members"]) >= 2


def test_render_is_human_readable(report):
    text = timeline.render(report)
    assert "bubble" in text and "stage0" in text
    assert "unknown plane" in text  # the WARNING line for 'quantum'
    assert "torn" in text


def test_cli_timeline_subcommand(capsys):
    from distributed_ml_pytorch_tpu.analysis import cli

    assert cli.main(["timeline", FIXTURES]) == 0
    out = capsys.readouterr().out
    assert "bubble" in out
    assert cli.main(["timeline", FIXTURES, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["n_dumps"] == 4


def test_missing_dir_raises_and_empty_dir_exits_nonzero(tmp_path):
    with pytest.raises(FileNotFoundError):
        timeline.analyze(str(tmp_path / "nope"))
    from distributed_ml_pytorch_tpu.analysis import cli

    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli.main(["timeline", str(empty)]) == 1


# ------------------------- bench_all bubble_attribution schema gate ------

def _good_attr():
    return {
        "stages": 4,
        "stage_seconds": 40.0,
        "fractions": {"compute": 0.12, "wait-act": 0.40, "wait-grad": 0.30,
                      "wire-blocked": 0.08, "ckpt": 0.05, "idle": 0.05},
        "bubble_fraction": 0.88,
        "wait_fraction": 0.88,
    }


def test_bench_bubble_attribution_schema_accepts_good_record():
    import bench_all

    assert bench_all.check_bubble_attribution(_good_attr()) == _good_attr()


@pytest.mark.parametrize("mutate, msg", [
    (lambda a: a.pop("fractions"), "fractions"),
    (lambda a: a["fractions"].update({"napping": 0.5}), "unknown state"),
    (lambda a: a["fractions"].update({"idle": 0.5}), "sum"),
    (lambda a: a.update(bubble_fraction=1.5), "not in [0, 1]"),
    (lambda a: a.update(bubble_fraction=0.5), "1 - compute"),
    (lambda a: a.update(stages=0), "stages"),
])
def test_bench_bubble_attribution_schema_rejects_breaches(mutate, msg):
    import bench_all

    attr = _good_attr()
    mutate(attr)
    with pytest.raises(ValueError, match=None) as exc:
        bench_all.check_bubble_attribution(attr)
    assert msg.split()[0] in str(exc.value)


def test_bench_bubble_attribution_accepts_real_analyzer_output():
    """The analyzer's own fixture-derived record passes the bench gate
    (the two halves of the pipeline agree on the schema)."""
    import bench_all

    rep = timeline.analyze(FIXTURES)
    bench_all.check_bubble_attribution(rep["bubble_attribution"])
