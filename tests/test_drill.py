"""ISSUE 5 drill suite: snapshot barrier, fleet manifests, and the
kill-and-recover acceptance scenario.

Layers:

- unit: the snapshot barrier's coordinator half under a fake clock
  (start, mixed-version abort, rebalance abort, manifest finalization)
  and FleetManifest validation (refuse incomplete / mixed / gapped);
- restore: ElasticShardServer.restore_from_manifest refuses a missing
  checkpoint or state behind the manifest's promise;
- system: THE acceptance drill — 2 workers + 2 shards under
  FaultyTransport + the reliability envelope, coordinator-aligned
  snapshot, ALL shards killed silently mid-epoch, restore from
  manifest + WAL — run 3x with identical seeds: zero acked-
  GradientUpdate loss (sequence accounting), byte-identical chaos logs,
  fault-free-corridor convergence; plus a subset-kill variant.

``make drill`` selects this module (and tests/test_wal.py) via the
``drill`` marker; the full scenarios get measured into slow_tests.txt.
"""

import os
import threading
import time

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.coord.coordinator import (
    KIND_SHARD,
    KIND_WORKER,
    Coordinator,
    encode_join,
    encode_snapshot_done,
)
from distributed_ml_pytorch_tpu.coord.drill import (
    default_drill_plan,
    recovery_drill,
)
from distributed_ml_pytorch_tpu.coord.elastic import ElasticShardServer
from distributed_ml_pytorch_tpu.coord.manifest import (
    FleetManifest,
    ManifestError,
    ShardRecord,
)
from distributed_ml_pytorch_tpu.coord.member import CoordClient
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)

pytestmark = pytest.mark.drill

# the shared lock_witness fixture (tests/conftest.py) arms the acceptance
# drill below as a concurrency validator under DISTCHECK_WITNESS=1


# ----------------------------------------------------------- manifest unit

def _manifest(**over):
    kw = dict(
        snapshot_id=3, map_version=5, n_params=100,
        shards=(ShardRecord(1, 0, 50, 5, 10, 10),
                ShardRecord(2, 50, 100, 5, 8, 8)),
        complete=True)
    kw.update(over)
    return FleetManifest(**kw)


def test_manifest_roundtrips_and_exposes_its_shard_map(tmp_path):
    path = str(tmp_path / "m.json")
    _manifest().write(path)
    m = FleetManifest.load(path)
    assert m == _manifest()
    assert m.shard_map.version == 5
    assert m.shard_map.ranges == [(0, 50), (50, 100)]
    assert m.entry_for(2).apply_seq == 8
    with pytest.raises(ManifestError, match="no record for server 9"):
        m.entry_for(9)


def test_manifest_refuses_incomplete_mixed_and_gapped(tmp_path):
    with pytest.raises(ManifestError, match="incomplete"):
        _manifest(complete=False).validate()
    with pytest.raises(ManifestError, match="MIXED"):
        _manifest(shards=(ShardRecord(1, 0, 50, 5, 10, 10),
                          ShardRecord(2, 50, 100, 4, 8, 8))).validate()
    with pytest.raises(ManifestError, match="tile"):
        _manifest(shards=(ShardRecord(1, 0, 40, 5, 10, 10),
                          ShardRecord(2, 50, 100, 5, 8, 8))).validate()
    with pytest.raises(ManifestError, match="covers"):
        _manifest(shards=(ShardRecord(1, 0, 90, 5, 10, 10),)).validate()
    with pytest.raises(ManifestError, match="more than once"):
        _manifest(shards=(ShardRecord(1, 0, 50, 5, 10, 10),
                          ShardRecord(1, 50, 100, 5, 8, 8))).validate()
    # write() refuses to publish what load() would refuse
    with pytest.raises(ManifestError):
        _manifest(complete=False).write(str(tmp_path / "bad.json"))
    with pytest.raises(ManifestError, match="unreadable"):
        FleetManifest.load(str(tmp_path / "missing.json"))


# ------------------------------------------------- barrier unit (fake clock)

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _barrier_coordinator(tmp_path, clock):
    c = Coordinator(None, 100, lease=10.0, clock=clock, speculation=False,
                    manifest_dir=str(tmp_path))
    c.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 10))
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_SHARD, 11))
    assert c.shard_map.version == 2
    return c


def test_snapshot_barrier_assembles_and_publishes_manifest(tmp_path):
    clock = _Clock()
    c = _barrier_coordinator(tmp_path, clock)
    c.trigger_snapshot()
    clock.t = 0.1
    c.tick()
    assert c._snap is not None and c._snap["id"] == 1
    (lo1, hi1), (lo2, hi2) = c.shard_map.ranges
    c.handle(1, MessageCode.SnapshotDone,
             encode_snapshot_done(1, 2, lo1, hi1, 14, 14))
    assert c.manifests_written == 0  # half a barrier is not a manifest
    c.handle(2, MessageCode.SnapshotDone,
             encode_snapshot_done(1, 2, lo2, hi2, 12, 12))
    assert c.manifests_written == 1 and c._snap is None
    m = FleetManifest.load(c.manifest_path())
    assert m.snapshot_id == 1 and m.map_version == 2
    assert m.entry_for(1).apply_seq == 14 and m.entry_for(2).apply_seq == 12
    # the next barrier gets the next id
    c.trigger_snapshot()
    clock.t = 0.2
    c.tick()
    assert c._snap["id"] == 2


def test_snapshot_barrier_refuses_mixed_version_reports(tmp_path):
    clock = _Clock()
    c = _barrier_coordinator(tmp_path, clock)
    c.trigger_snapshot()
    c.tick()
    (lo1, hi1), _ = c.shard_map.ranges
    # shard 1 reports a checkpoint taken under ANOTHER map version: the
    # barrier must abort — a manifest mixing versions is the disease
    c.handle(1, MessageCode.SnapshotDone,
             encode_snapshot_done(1, 1, lo1, hi1, 14, 14))
    assert c._snap is None and c.manifests_written == 0
    assert any("aborted" in e for e in c.events)


def test_snapshot_barrier_aborts_on_mid_barrier_rebalance(tmp_path):
    clock = _Clock()
    c = _barrier_coordinator(tmp_path, clock)
    c.trigger_snapshot()
    c.tick()
    assert c._snap is not None
    c.handle(3, MessageCode.CoordJoin, encode_join(KIND_SHARD, 12))
    assert c._snap is None  # the map moved; the frozen barrier is void
    assert any("aborted" in e for e in c.events)


def test_snapshot_interval_drives_periodic_barriers(tmp_path):
    clock = _Clock()
    c = Coordinator(None, 100, lease=10.0, clock=clock, speculation=False,
                    manifest_dir=str(tmp_path), snapshot_interval=5.0)
    c.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 10))
    clock.t = 5.1
    c.tick()
    assert c._snap is not None and c._snap["id"] == 1


def test_coordinator_restores_map_and_snapshot_clock_from_manifest():
    m = _manifest()
    c = Coordinator(None, 100, speculation=False, restore_manifest=m)
    assert c.shard_map.version == 5
    assert c.shard_map.ranges == [(0, 50), (50, 100)]
    assert c._snap_seq == 3  # the next snapshot will be #4


# --------------------------------------------------------- restore refusals

def test_restore_from_manifest_refuses_missing_checkpoint(tmp_path):
    world = InProcessTransport.create_world(2)
    client = CoordClient(world[1], "shard", renew_interval=5.0)
    try:
        srv = ElasticShardServer(
            server_id=1, n_params=100, transport=world[0], coord=client,
            ckpt_dir=str(tmp_path / "shard0"), wal=True)
        manifest = FleetManifest(
            snapshot_id=1, map_version=2, n_params=100,
            shards=(ShardRecord(1, 0, 100, 2, 5, 5),))
        with pytest.raises(ManifestError, match="nothing restorable"):
            srv.restore_from_manifest(manifest)
    finally:
        client.stop()
        for t in world.values():
            t.close()


def test_restore_from_manifest_refuses_state_behind_the_promise(tmp_path):
    world = InProcessTransport.create_world(2)
    client = CoordClient(world[1], "shard", renew_interval=5.0)
    try:
        ckpt_dir = str(tmp_path / "shard0")
        srv = ElasticShardServer(
            server_id=1, n_params=100, transport=world[0], coord=client,
            ckpt_dir=ckpt_dir, wal=True)
        with srv._mu:
            srv.lo, srv.hi = 0, 100
            srv.ps.central = np.zeros(100, np.float32)
            srv.ps.handle(1, MessageCode.GradientUpdate,
                          np.ones(100, np.float32))
            srv.ps.save_checkpoint()  # on-disk apply seq: 1
        manifest = FleetManifest(
            snapshot_id=1, map_version=2, n_params=100,
            shards=(ShardRecord(1, 0, 100, 2, 9, 9),))  # promises seq 9
        srv2 = ElasticShardServer(
            server_id=1, n_params=100, transport=world[0], coord=client,
            ckpt_dir=ckpt_dir, wal=True)
        with pytest.raises(ManifestError, match="BEHIND"):
            srv2.restore_from_manifest(manifest)
    finally:
        client.stop()
        for t in world.values():
            t.close()


# --------------------------------------------------- system: THE acceptance

_STEPS = 18


@pytest.fixture(scope="module")
def drill_fixture():
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        cross_entropy_loss,
    )

    model = LeNet()
    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = model.apply({"params": q}, bx, train=True,
                                 rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = model.init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


def test_kill_all_shards_recover_lossfree_three_runs(
        drill_fixture, tmp_path, lock_witness):
    """THE acceptance drill (ISSUE 5), 3x with identical seeds: all shards
    SIGKILL'd (scripted silent death) mid-epoch, fleet restores from
    manifest + WAL with zero acked-GradientUpdate loss (sequence
    accounting proves acked <= applied per worker/shard pair), the chaos
    log is byte-identical across runs, and every run converges into the
    fault-free corridor."""
    clean = recovery_drill(
        base_dir=str(tmp_path / "clean"), seed=7, steps=_STEPS,
        snapshot_at=None, kill_at=None, fixture=drill_fixture)
    assert clean["ok"], (clean["errors"], clean["events"])
    clean_final = np.mean(
        [np.mean(l[-4:]) for l in clean["losses"].values()])

    logs, finals = [], []
    for run in range(3):
        out = recovery_drill(
            base_dir=str(tmp_path / f"run{run}"), seed=7, steps=_STEPS,
            plan=default_drill_plan(7), fixture=drill_fixture)
        assert out["ok"], (out["errors"], out["events"])
        assert out["accounting_ok"], (out["acked"], out["applied"])
        # acked updates existed that ONLY the WALs held (post-snapshot)
        assert out["replayed_updates"] > 0
        assert out["manifest"] is not None and out["manifest"]["complete"]
        assert out["mttr_s"] is not None and out["mttr_s"] < 60
        logs.append(out["chaos_lines"])
        for losses in out["losses"].values():
            assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses
        finals.append(np.mean(
            [np.mean(l[-4:]) for l in out["losses"].values()]))
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "chaos log not byte-identical across drill runs")
    for final in finals:
        assert abs(final - clean_final) < 0.5, (final, clean_final)


def test_subset_kill_recovers_and_survivor_never_restarts(
        drill_fixture, tmp_path):
    """Killing an arbitrary shard SUBSET restores only the victims; the
    survivor keeps its live state and the accounting still closes."""
    out = recovery_drill(
        base_dir=str(tmp_path / "subset"), seed=3, steps=_STEPS,
        kill_shards=[1], plan=default_drill_plan(3), fixture=drill_fixture)
    assert out["ok"], (out["errors"], out["events"])
    assert out["accounting_ok"], (out["acked"], out["applied"])
    assert out["mttr_s"] is not None
    # only shard 2 (index 1) was restarted: exactly one rejoin event
    rejoins = [e for e in out["events"] if "rejoined" in e]
    assert len(rejoins) == 1 and "shard 2" in rejoins[0]
