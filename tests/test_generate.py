"""Autoregressive decoding: KV-cache incremental attention must match the
full causal forward, and generation must be deterministic and well-shaped."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models.generate import generate, init_cache
from distributed_ml_pytorch_tpu.models.transformer import TransformerLM


def tiny_lm():
    return TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=64
    )


def trained_ish_params(model, seed=0):
    return model.init(jax.random.key(seed), jnp.zeros((1, 8), jnp.int32))["params"]


def test_incremental_decode_matches_full_forward():
    """Prefill + token-by-token cached decode must reproduce the full causal
    forward's logits at every position."""
    model = tiny_lm()
    params = trained_ish_params(model)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 10)), jnp.int32
    )

    full_logits = model.apply({"params": params}, tokens)  # [2, 10, 64]

    dec = model.clone(decode=True, cache_size=10, attn_fn=None)
    cache = init_cache(model, 2, 10)
    got = []
    for t in range(10):
        logits, mutated = dec.apply(
            {"params": params, "cache": cache},
            tokens[:, t : t + 1],
            jnp.full((2, 1), t, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        got.append(logits[:, 0])
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full_logits), rtol=2e-4, atol=2e-5)


def test_prefill_block_matches_full_forward():
    """Multi-token prefill writes the cache identically to token-by-token."""
    model = tiny_lm()
    params = trained_ish_params(model)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(2, 8)), jnp.int32
    )
    full_logits = model.apply({"params": params}, tokens)

    dec = model.clone(decode=True, cache_size=8, attn_fn=None)
    cache = init_cache(model, 2, 8)
    logits, _ = dec.apply(
        {"params": params, "cache": cache},
        tokens,
        jnp.arange(8)[None, :],
        mutable=["cache"],
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full_logits), rtol=2e-4, atol=2e-5)


def test_greedy_generate_is_deterministic_and_shaped():
    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    out1 = generate(model, params, prompt, max_new_tokens=7)
    out2 = generate(model, params, prompt, max_new_tokens=7)
    assert out1.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :3]), np.asarray(prompt))


def test_greedy_matches_naive_rollout():
    """Cached greedy decode must pick the same tokens as re-running the full
    forward on the growing sequence each step (the O(n^2)-per-token oracle)."""
    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray([[7, 8, 9, 10]], jnp.int32)
    fast = generate(model, params, prompt, max_new_tokens=5)

    seq = prompt
    for _ in range(5):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(seq))


def test_temperature_sampling_reproducible_and_varied():
    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    a = generate(model, params, prompt, 8, temperature=1.0, rng=jax.random.key(3))
    b = generate(model, params, prompt, 8, temperature=1.0, rng=jax.random.key(3))
    c = generate(model, params, prompt, 8, temperature=1.0, rng=jax.random.key(4))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c)), "rng had no effect"


def test_zero_new_tokens_returns_prompt_unchanged():
    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_decode_rejects_injected_attn_fn():
    model = tiny_lm().clone(decode=True, cache_size=8, attn_fn=lambda q, k, v: q)
    with pytest.raises(ValueError, match="attn_fn"):
        model.init(jax.random.key(0), jnp.zeros((1, 1), jnp.int32))


def test_temperature_requires_rng_and_max_len_enforced():
    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, 4, temperature=0.7)
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, 63)  # 2 + 63 > max_len 64


def test_blocked_decode_matches_unblocked_scan():
    """Runs long enough to use the ring-buffered block path (>= DECODE_BLOCK
    steps, spanning several merge boundaries) must pick exactly the same
    greedy tokens as the plain one-token scan. Exactness is a CPU contract
    (this suite's platform): on the MXU the blocked concat-softmax and the
    fused QKV matmul reorder low-bit f32 accumulation, which legitimately
    flips near-ties of a random-init model (see generate.py's numerics
    contract)."""
    from distributed_ml_pytorch_tpu.models.generate import (
        DECODE_BLOCK,
        _decode_model,
        _generate_jit,
    )

    model = TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=128
    )
    params = trained_ish_params(model)
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 64, size=(2, 5)), jnp.int32
    )
    n = 2 * DECODE_BLOCK + 3  # crosses two merge boundaries + a padded tail
    blocked = generate(model, params, prompt, n)

    total = 5 + n
    cache = init_cache(model, 2, total)
    ref = _generate_jit(
        _decode_model(model, total), n, 0.0, 0, 1.0,
        params, cache, prompt, jax.random.key(0)
    )
    np.testing.assert_array_equal(np.asarray(blocked), np.asarray(ref))


def test_single_token_prompt_long_generation_correct():
    """A (B, 1) prompt must NOT take the blocked path (its prefill would be
    indistinguishable from a decode step and the prompt's K/V would be
    orphaned in the ring — found by review); it must match the naive
    rollout exactly."""
    model = TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=64
    )
    params = trained_ish_params(model)
    prompt = jnp.asarray([[7], [13]], jnp.int32)
    fast = generate(model, params, prompt, 20)

    seq = prompt
    for _ in range(20):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(seq))


def test_blocked_decode_cache_has_rings():
    """The blocked clone's cache carries per-layer rings; the plain clone's
    does not (the standalone one-token module contract is unchanged)."""
    model = tiny_lm()
    plain = init_cache(model, 1, 32)
    ringed = init_cache(model, 1, 32, decode_block=8)
    flat_plain = {"/".join(str(k) for k in p): v.shape
                  for p, v in jax.tree_util.tree_leaves_with_path(plain)}
    assert not any("ring" in k for k in flat_plain)
    flat_ring = {jax.tree_util.keystr(p): v.shape
                 for p, v in jax.tree_util.tree_leaves_with_path(ringed)}
    rings = [s for k, s in flat_ring.items() if "ring_k" in k]
    assert len(rings) == model.n_layers and all(s[2] == 8 for s in rings)


def test_quantize_kv_roundtrip_error_bounded():
    from distributed_ml_pytorch_tpu.models.transformer import quantize_kv

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 4, 16, 32)) * 0.3,
        jnp.bfloat16,
    )
    q8, scale = quantize_kv(x)
    assert q8.dtype == jnp.int8 and scale.shape == (2, 4, 16)
    deq = np.asarray(q8, np.float32) * np.asarray(scale)[..., None]
    err = np.abs(deq - np.asarray(x, np.float32))
    # absmax/127 per key is the quantization step; error <= half a step
    # plus bf16 rounding slack
    bound = np.asarray(scale)[..., None] * 0.51 + 1e-6
    assert (err <= bound).all()


def test_kv_quant_decode_deterministic_and_prefill_exact():
    """int8-cache decode must be deterministic, stay in-vocab, and agree
    with the exact-cache path on the FIRST generated token (the quantized
    prefill attends with the in-hand exact K/V, so prompt logits carry no
    quantization noise). Later tokens may legitimately drift on a
    random-init model whose logits have near-ties."""
    model = TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=128
    )
    params = trained_ish_params(model)
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, size=(2, 6)), jnp.int32
    )
    exact = generate(model, params, prompt, 40)
    q1 = generate(model, params, prompt, 40, kv_quant=True)
    q2 = generate(model, params, prompt, 40, kv_quant=True)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
    assert q1.shape == exact.shape
    assert int(q1.max()) < 64 and int(q1.min()) >= 0
    np.testing.assert_array_equal(np.asarray(q1[:, 6]), np.asarray(exact[:, 6]))


def test_kv_quant_fallback_to_plain_scan_warns():
    """kv_quant=True on a shape the blocked path can't take (here: too few
    new tokens to fill one block) must be AUDIBLE — the plain scan keeps
    the exact full-size cache, not the halved int8 footprint the caller
    sized for (ADVICE r4)."""
    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, size=(1, 6)), jnp.int32
    )
    with pytest.warns(UserWarning, match="kv_quant.*fall"):
        out = generate(model, params, prompt, 4, kv_quant=True)
    assert out.shape == (1, 10)


def test_fuse_qkv_params_only_rewrites_attn_named_modules():
    """The fused-QKV rewrite is anchored on the module NAME 'attn' plus the
    {q,k,v,o} child keys — a non-attention module that happens to have
    those child names must pass through untouched (ADVICE r4)."""
    from distributed_ml_pytorch_tpu.models.generate import _fuse_qkv_params

    k = jnp.ones((4, 4))
    attn = {"q": {"kernel": k}, "k": {"kernel": k}, "v": {"kernel": k},
            "o": {"kernel": k}}
    impostor = {"q": {"kernel": k}, "k": {"kernel": k}, "v": {"kernel": k},
                "o": {"kernel": k}, "extra": {"kernel": k}}
    tree = {"block_0": {"attn": attn, "lookup": impostor}}
    out = _fuse_qkv_params(tree)
    assert set(out["block_0"]["attn"]) == {"qkv", "o"}
    assert out["block_0"]["attn"]["qkv"]["kernel"].shape == (4, 12)
    assert set(out["block_0"]["lookup"]) == set(impostor)  # untouched


def test_kv_quant_cache_is_int8_with_scales():
    model = tiny_lm()
    cache = init_cache(model, 2, 32, decode_block=8, kv_quant=True)
    leaves = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(cache)}
    big = [v for k, v in leaves.items() if "cached_k" in k]
    scales = [v for k, v in leaves.items() if "scale_k" in k]
    assert big and all(v.dtype == jnp.int8 for v in big)
    assert scales and all(
        v.dtype == jnp.float32 and v.shape == (2, 4, 32) for v in scales)


def test_tp_sharded_decode_matches_single_device():
    """Greedy TP decode on a 2x4 dp x tp mesh must be bit-identical to the
    single-device path — same compiled program, shardings propagated."""
    from distributed_ml_pytorch_tpu.models.generate import generate_tp
    from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(2, 6)), jnp.int32
    )
    want = generate(model, params, prompt, 8)
    mesh = make_mesh({"data": 2, "model": 4})
    got = generate_tp(model, params, prompt, 8, mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_decode_rejects_indivisible_heads():
    from distributed_ml_pytorch_tpu.models.generate import generate_tp
    from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

    model = TransformerLM(
        vocab_size=64, d_model=30, n_heads=3, n_layers=1, d_ff=64, max_len=64
    )
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    mesh = make_mesh({"data": 1, "model": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="divisible"):
        generate_tp(model, params, jnp.zeros((1, 2), jnp.int32), 4, mesh)


def test_sample_tokens_topk_restricts_support():
    from distributed_ml_pytorch_tpu.models.generate import sample_tokens

    rng = np.random.default_rng(4)
    logits = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    topset = np.argsort(np.asarray(logits), axis=-1)[:, -5:]
    for i in range(50):
        toks = np.asarray(sample_tokens(logits, jax.random.key(i),
                                        temperature=1.0, top_k=5))
        for b in range(4):
            assert toks[b] in topset[b]


def test_sample_tokens_topk1_and_tiny_topp_equal_greedy():
    from distributed_ml_pytorch_tpu.models.generate import sample_tokens

    rng = np.random.default_rng(5)
    logits = jnp.asarray(rng.normal(size=(3, 32)), jnp.float32)
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    for i in range(10):
        k1 = np.asarray(sample_tokens(logits, jax.random.key(i),
                                      temperature=0.7, top_k=1))
        p0 = np.asarray(sample_tokens(logits, jax.random.key(i),
                                      temperature=0.7, top_p=1e-9))
        np.testing.assert_array_equal(k1, greedy)
        np.testing.assert_array_equal(p0, greedy)


def test_sample_tokens_topp_keeps_nucleus_only():
    from distributed_ml_pytorch_tpu.models.generate import sample_tokens

    # 0.5/0.3/0.1/0.1 distribution: the 0.75-nucleus is {0, 1} with a solid
    # float margin on both sides (0.5 < 0.75 ≤ 0.8 — an exact-boundary
    # threshold like 0.8 would flip on cumsum rounding across backends)
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.1, 0.1]], jnp.float32))
    seen = set()
    for i in range(100):
        seen.add(int(sample_tokens(logits, jax.random.key(i),
                                   temperature=1.0, top_p=0.75)[0]))
    assert seen == {0, 1}


def test_sample_tokens_topk_topp_combined_restricts_support():
    """top-k AND top-p together: nucleus truncation applies to the
    POST-top-k RENORMALIZED distribution, so the combined support can be
    strictly smaller than either filter alone. With p = (0.5, 0.3, 0.12,
    0.08) and all-distinct logits (ties at the k-th logit are kept by
    contract, so distinctness matters): top_k=3 alone keeps {0, 1, 2};
    top_p=0.85 alone keeps {0, 1, 2} (exclusive mass before token 2 is
    0.8 < 0.85, before token 3 is 0.92); combined, the top-3 renormalize
    to (0.543, 0.326, 0.130) and the mass before token 2 becomes
    0.870 >= 0.85 — support {0, 1}, smaller than both."""
    from distributed_ml_pytorch_tpu.models.generate import sample_tokens

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.12, 0.08]], jnp.float32))
    combined, k_only, p_only = set(), set(), set()
    for i in range(150):
        combined.add(int(sample_tokens(
            logits, jax.random.key(i), temperature=1.0, top_k=3,
            top_p=0.85)[0]))
        k_only.add(int(sample_tokens(
            logits, jax.random.key(i), temperature=1.0, top_k=3)[0]))
        p_only.add(int(sample_tokens(
            logits, jax.random.key(i), temperature=1.0, top_p=0.85)[0]))
    assert combined == {0, 1}
    assert k_only == {0, 1, 2}
    assert p_only == {0, 1, 2}


def test_generate_with_topk_topp_runs_and_stays_in_vocab():
    model = tiny_lm()
    params = trained_ish_params(model)
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(model, params, prompt, 8, temperature=0.9,
                   rng=jax.random.key(0), top_k=10, top_p=0.9)
    assert out.shape == (1, 12)
    assert int(out.max()) < 64 and int(out.min()) >= 0
    out2 = generate(model, params, prompt, 8, temperature=0.9,
                    rng=jax.random.key(0), top_k=10, top_p=0.9)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
