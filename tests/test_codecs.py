"""ISSUE 18 — the codec plane registry (utils/codecs.py).

Three layers:

1. **Totality** — the ``WIRE_PLANES`` registry and the ``WIRE_SCHEMAS``
   table agree in BOTH directions: every schema that declares a
   ``codec`` field resolves to a registered plane, no plane names a wire
   whose schema forgot the field, every admissible codec id resolves to
   a real codec class, and every stated loss contract is vocabulary.
2. **Numerics** — each contract's promise holds concretely: the int8
   per-block-absmax bound elementwise (``|x - x̂| <= scale/2``), tok16
   bit-exactness over the full id range, and the delta-reply identity
   ``base + decoded_delta == central - residual`` BITWISE on the real
   parameter server (the server's tracked base mirrors the worker by
   replaying its own encode→decode).
3. **Refusals** — a lossy rung on a wire that never admits it, a dense
   body of the wrong size, and an unregistered wire are all loud errors
   at the registry boundary, not silent corruption downstream.
"""

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils import codecs
from distributed_ml_pytorch_tpu.utils.compress import (
    CODEC_DENSE,
    CODEC_INT8,
    CODEC_NAMES,
    CODEC_TOPK,
    CompressionError,
    _CODECS_BY_ID,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    WIRE_SCHEMAS,
)

pytestmark = pytest.mark.codec


# ------------------------------------------------------------- totality

def _schema_codec_wires():
    """MessageCode names whose schema declares a ``codec`` head field."""
    return {code.name for code, schema in WIRE_SCHEMAS.items()
            if "codec" in schema.fields}


def test_every_codec_bearing_schema_resolves_to_a_plane():
    missing = _schema_codec_wires() - set(codecs.WIRE_PLANES)
    assert not missing, (
        f"schemas declare a codec field but the registry has no plane: "
        f"{sorted(missing)}")


def test_every_plane_names_a_codec_bearing_schema():
    ghosts = set(codecs.WIRE_PLANES) - _schema_codec_wires()
    assert not ghosts, (
        f"planes registered for wires whose schema declares no codec "
        f"field: {sorted(ghosts)}")
    for name, plane in codecs.WIRE_PLANES.items():
        assert plane.code_name == name
        assert hasattr(MessageCode, name)


def test_every_plane_contract_is_vocabulary_and_stated():
    for plane in codecs.WIRE_PLANES.values():
        assert plane.contract in codecs.CONTRACTS
        if plane.contract == "bounded":
            assert plane.bound, (
                f"{plane.code_name}: a bounded plane must state its "
                "bound")
        assert plane.fallback, (
            f"{plane.code_name}: every lossy plane must name what "
            "restores exactness")


def test_every_admissible_codec_id_is_registered():
    for plane in codecs.WIRE_PLANES.values():
        assert plane.default_id in plane.codec_ids
        for cid in plane.codec_ids:
            assert cid in CODEC_NAMES, (
                f"{plane.code_name} admits unnamed codec id {cid}")
            if cid != CODEC_DENSE:
                assert cid in _CODECS_BY_ID, (
                    f"{plane.code_name} admits codec id {cid} with no "
                    "registered codec class")


def test_plane_for_accepts_code_and_name():
    plane = codecs.plane_for(MessageCode.ActivationShip)
    assert plane is codecs.plane_for("ActivationShip")
    assert plane is not None and plane.contract == "bounded"
    assert codecs.plane_for(MessageCode.GradientUpdate) is None


def test_tok16_rung_is_registered_in_the_compress_tables():
    assert _CODECS_BY_ID[codecs.CODEC_TOK16] is codecs.Tok16Codec
    assert CODEC_NAMES[codecs.CODEC_TOK16] == "tok16"


# ------------------------------------------------------------- numerics

@pytest.mark.parametrize("code", [MessageCode.ActivationShip,
                                  MessageCode.ActivationGrad,
                                  MessageCode.KvMigrate])
def test_int8_bound_holds_elementwise(code):
    rng = np.random.default_rng(18)
    plane = codecs.plane_for(code)
    # mixed scales across blocks, an outlier, and a zero block
    x = (rng.standard_normal(5 * plane.param + 37)
         .astype(np.float32))
    x[: plane.param] *= 1e3
    x[plane.param: 2 * plane.param] = 0.0
    x[7] = 512.0
    cid, body = codecs.encode_body(code, x, CODEC_INT8)
    assert cid == CODEC_INT8
    x_hat = codecs.decode_body(code, cid, body, x.size)
    allow = codecs.int8_bound(x, plane.param)
    assert (np.abs(x - x_hat) <= allow).all(), (
        np.max(np.abs(x - x_hat) - allow))


def test_int8_wire_is_at_least_3x_smaller():
    n = 4 * codecs.ACT_BLOCK
    coded = codecs.wire_floats(MessageCode.ActivationShip, n, CODEC_INT8)
    assert coded * 3 <= n


def test_tok16_roundtrip_is_bit_exact_over_the_full_range():
    tok = codecs.Tok16Codec()
    for ids in ([0], [65535], [0, 1, 2], list(range(1000)),
                [65535, 0, 32768, 17]):
        x = np.asarray(ids, np.float32)
        body = tok.encode(x)
        assert body.size == tok.wire_floats(x.size) == (x.size + 1) // 2
        back = tok.decode(body, x.size, 0)
        assert back.dtype == np.float32
        assert np.array_equal(back, x)


def test_tok16_refuses_non_ids():
    tok = codecs.Tok16Codec()
    with pytest.raises(ValueError):
        tok.encode(np.asarray([1.5], np.float32))
    with pytest.raises(ValueError):
        tok.encode(np.asarray([-1.0], np.float32))
    with pytest.raises(ValueError):
        tok.encode(np.asarray([65536.0], np.float32))
    with pytest.raises(CompressionError):
        tok.decode(np.zeros(3, np.float32), 4, 0)


def test_dense_rung_is_the_identity():
    x = np.arange(9, dtype=np.float32)
    cid, body = codecs.encode_body(MessageCode.DeltaParams, x,
                                   CODEC_DENSE)
    assert cid == CODEC_DENSE and np.array_equal(body, x)
    assert np.array_equal(
        codecs.decode_body(MessageCode.DeltaParams, cid, body, 9), x)


def test_delta_reply_identity_is_bitwise_on_the_real_server(tmp_path):
    """``base + decoded_delta == central - residual`` EXACTLY: the
    server updates its tracked base by replaying its own encode→decode,
    so the tracked mirror and the worker's installed view are the same
    float32 bytes after every reply — full or lossy delta alike."""
    from distributed_ml_pytorch_tpu.parallel.async_ps import (
        Listener,
        ParameterServer,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
    )

    world = InProcessTransport.create_world(2)
    try:
        ps = ParameterServer(params=np.zeros(64, np.float32),
                             transport=world[0], ckpt_dir=str(tmp_path),
                             ckpt_every=0, wal=True)
        lst = Listener(transport=world[1])

        def exchange():
            ps.handle(1, MessageCode.ParameterRequest, lst.held_stamp())
            msg = world[1].recv(timeout=0.5)
            assert msg is not None
            lst.receive(msg[0], msg[1], msg[2])

        rng = np.random.default_rng(7)
        exchange()  # full install
        for _ in range(3):
            ps.handle(1, MessageCode.GradientUpdate,
                      rng.standard_normal(64).astype(np.float32))
            ps.commit()
            exchange()  # top-k delta installs
        assert ps.delta_replies >= 3 and lst.delta_installs >= 3
        base = ps._pull_bases[1][2]
        # the identity, rearranged: view == central - residual where
        # residual = central - view is exactly what the NEXT delta ships
        assert np.array_equal(base, lst._view)
        residual = ps.central - base
        assert np.array_equal(base + (ps.central - base) - residual, base)
        # one more pull drains the residual's representable part and the
        # mirror still matches bitwise
        exchange()
        assert np.array_equal(ps._pull_bases[1][2], lst._view)
    finally:
        for t in world.values():
            t.close()


# ------------------------------------------------------------- refusals

def test_lossy_rung_refused_on_inadmissible_wire():
    x = np.ones(8, np.float32)
    with pytest.raises(CompressionError, match="not admissible"):
        codecs.encode_body(MessageCode.ActivationShip, x, CODEC_TOPK)
    with pytest.raises(CompressionError, match="not admissible"):
        codecs.decode_body(MessageCode.KvMigrate, CODEC_TOPK, x, 8)


def test_dense_size_mismatch_is_malformed():
    with pytest.raises(CompressionError, match="dense body"):
        codecs.decode_body(MessageCode.ActivationShip, CODEC_DENSE,
                           np.ones(4, np.float32), 5)


def test_unregistered_wire_is_refused():
    x = np.ones(4, np.float32)
    with pytest.raises(CompressionError, match="not a registered"):
        codecs.encode_body(MessageCode.GradientUpdate, x)
    with pytest.raises(CompressionError, match="not a registered"):
        codecs.wire_floats(MessageCode.CumAck, 4)
