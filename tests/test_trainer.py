"""C4/C5 trainer tests: loss decreases, eval shapes, CSV schema parity."""

import os

import jax
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.data import load_cifar10
from distributed_ml_pytorch_tpu.models import AlexNet
from distributed_ml_pytorch_tpu.training.trainer import (
    create_train_state,
    evaluate,
    make_eval_fn,
    make_train_step,
    train_single,
)
from distributed_ml_pytorch_tpu.utils.metrics import MetricsLogger


class Args:
    batch_size = 32
    test_batch_size = 128
    epochs = 1
    lr = 0.01
    log_interval = 4
    seed = 0
    model = "alexnet"
    dtype = "float32"
    log_dir = "log"
    data_root = "/nonexistent"
    synthetic_data = True
    synthetic_train_size = 256
    synthetic_test_size = 128


def test_train_step_reduces_loss():
    x_train, y_train, *_ = load_cifar10(n_train=256, n_test=64, synthetic=True)
    model = AlexNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    step = make_train_step(model, tx)
    rng = jax.random.key(1)
    bx, by = x_train[:64], y_train[:64]
    losses = []
    for _ in range(30):
        state, loss = step(state, bx, by, rng)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.2, losses[::10]
    assert losses[-1] == min(losses) or losses[-1] < losses[0] * 0.95


def test_evaluate_full_set():
    x_train, y_train, x_test, y_test, _ = load_cifar10(n_train=64, n_test=100, synthetic=True)
    model = AlexNet()
    state, _ = create_train_state(model, jax.random.key(0), lr=0.01)
    eval_step = make_eval_fn(model)
    loss, acc = evaluate(eval_step, state.params, x_test, y_test, test_batch_size=32)
    assert loss > 0
    assert 0.0 <= acc <= 1.0


def test_train_single_end_to_end(tmp_path):
    args = Args()
    args.log_dir = str(tmp_path)
    state, logger = train_single(args)
    assert len(logger.records) == 256 // 32
    path = logger.to_csv("single.csv")
    assert os.path.exists(path)
    import pandas as pd

    df = pd.read_csv(path)
    # schema parity with reference example/main.py:76-84,97-105
    assert list(df.columns)[:4] == ["index", "timestamp", "iteration", "training_loss"]
    assert "test_loss" in df.columns and "test_accuracy" in df.columns
    # eval fired at iterations 4 (i % 4 == 0 and i > 0) per reference semantics
    assert not np.isnan(df.loc[df.iteration == 4, "test_loss"]).any()
    assert np.isnan(df.loc[df.iteration == 0, "test_loss"]).all()


def test_training_improves_accuracy():
    """End-to-end learnability on the synthetic set: a few epochs of AlexNet
    should beat chance by a wide margin."""
    from distributed_ml_pytorch_tpu.models import LeNet

    args = Args()
    args.model = "lenet"
    args.epochs = 3
    args.lr = 0.05
    args.synthetic_train_size = 512
    args.synthetic_test_size = 256
    state, logger = train_single(args)
    model = LeNet()
    x_train, y_train, x_test, y_test, _ = load_cifar10(
        n_train=512, n_test=256, synthetic=True
    )
    eval_step = make_eval_fn(model)
    _, acc = evaluate(eval_step, state.params, x_test, y_test, 128)
    assert acc > 0.5, f"synthetic accuracy only {acc}"


def test_scan_train_step_matches_singles():
    """K scanned steps must equal K individually dispatched steps exactly
    (same update body, same step-folded dropout stream)."""
    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.training.trainer import make_scan_train_step

    k, batch = 4, 8
    rng_np = np.random.default_rng(0)
    images = rng_np.normal(size=(k, batch, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(k * batch) % 10).astype(np.int32).reshape(k, batch)
    dropout_rng = jax.random.key(7)

    model = LeNet(num_classes=10)  # has dropout: exercises the rng stream
    state_a, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    single = make_train_step(model, tx)
    for i in range(k):
        state_a, loss_a = single(state_a, images[i], labels[i], dropout_rng)

    state_b, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    scan = make_scan_train_step(model, tx)
    state_b, losses = scan(state_b, images, labels, dropout_rng)

    assert int(state_b.step) == k
    np.testing.assert_allclose(float(losses[-1]), float(loss_a), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_chunked_dispatch_matches_per_step_training(tmp_path):
    """--steps-per-dispatch K must produce the identical CSV records and
    final params as per-step dispatch (chunks aligned to eval boundaries)."""
    import copy

    class A(Args):
        epochs = 1
        batch_size = 32
        log_interval = 4
        synthetic_train_size = 32 * 11  # 11 steps: exercises chunk remainders
        synthetic_test_size = 64
        log_dir = None

    a1, a2 = copy.deepcopy(A()), copy.deepcopy(A())
    a1.log_dir = str(tmp_path / "a")
    a2.log_dir = str(tmp_path / "b")
    a2.steps_per_dispatch = 3  # does not divide log_interval: remainders happen

    state1, logger1 = train_single(a1)
    state2, logger2 = train_single(a2)

    assert int(state1.step) == int(state2.step) == 11
    r1, r2 = logger1.records, logger2.records
    assert len(r1) == len(r2)
    for rec1, rec2 in zip(r1, r2):
        assert rec1["iteration"] == rec2["iteration"]
        np.testing.assert_allclose(rec1["training_loss"], rec2["training_loss"], rtol=1e-6)
        assert ("test_loss" in rec1) == ("test_loss" in rec2)
    for p1, p2 in zip(jax.tree.leaves(state1.params), jax.tree.leaves(state2.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-6, atol=1e-7)


def test_chunked_dispatch_still_checkpoints_on_exact_boundaries(tmp_path):
    """Chunks must flush at --ckpt-every boundaries: orbax only accepts saves
    at exact interval multiples, so K-step chunk ends that skip over the
    boundary would otherwise silently disable checkpointing."""
    import copy

    class A(Args):
        epochs = 1
        batch_size = 32
        log_interval = 100  # no eval boundaries in range
        synthetic_train_size = 32 * 10  # 10 steps
        synthetic_test_size = 64

    a = copy.deepcopy(A())
    a.log_dir = str(tmp_path / "log")
    a.ckpt_dir = str(tmp_path / "ckpt")
    a.ckpt_every = 4   # K=3 chunk ends (3, 6, 9...) never hit 4 or 8 unaided
    a.ckpt_keep = 5
    a.steps_per_dispatch = 3

    train_single(a)
    import os

    saved = {int(d) for d in os.listdir(a.ckpt_dir) if d.isdigit()}
    assert {4, 8} <= saved, f"interval saves missing: {sorted(saved)}"


def test_grad_accum_equals_full_batch_step():
    """k accumulation micro-steps must equal one step on the concatenated
    batch (grad of mean CE averages linearly over equal-size micro-batches)."""
    from distributed_ml_pytorch_tpu.models import AlexNet  # no dropout: exact

    model = AlexNet(num_classes=10)
    rng_np = np.random.default_rng(0)
    images = rng_np.normal(size=(16, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(16) % 10).astype(np.int32)
    drng = jax.random.key(1)

    state_a, tx_a = create_train_state(model, jax.random.key(0), lr=0.05, grad_accum=2)
    step_a = make_train_step(model, tx_a)
    state_a, _ = step_a(state_a, images[:8], labels[:8], drng)
    state_a, _ = step_a(state_a, images[8:], labels[8:], drng)

    state_b, tx_b = create_train_state(model, jax.random.key(0), lr=0.05)
    step_b = make_train_step(model, tx_b)
    state_b, _ = step_b(state_b, images, labels, drng)

    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_lr_schedules():
    from distributed_ml_pytorch_tpu.training.trainer import make_lr_schedule

    assert make_lr_schedule("constant", 0.1) == 0.1
    inv = make_lr_schedule("inverse-epoch", 0.1, steps_per_epoch=10)
    assert float(inv(0)) == pytest.approx(0.1)
    assert float(inv(9)) == pytest.approx(0.1)    # still epoch 0
    assert float(inv(10)) == pytest.approx(0.05)  # epoch 1 → lr/2
    assert float(inv(25)) == pytest.approx(0.1 / 3)
    cos = make_lr_schedule("cosine", 0.1, steps_per_epoch=10, total_epochs=2)
    assert float(cos(0)) == pytest.approx(0.1)
    assert float(cos(20)) == pytest.approx(0.0, abs=1e-6)
    with pytest.raises(ValueError, match="unknown lr schedule"):
        make_lr_schedule("warmup-nope", 0.1)


def test_inverse_epoch_schedule_decays_updates():
    """SGD under the schedule must take smaller steps in later epochs."""
    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import make_lr_schedule

    model = AlexNet(num_classes=10)
    images = np.random.default_rng(0).normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(8) % 10).astype(np.int32)
    drng = jax.random.key(1)
    sched = make_lr_schedule("inverse-epoch", 0.1, steps_per_epoch=1)  # lr/ (step+1)
    state, tx = create_train_state(model, jax.random.key(0), sched)
    step = make_train_step(model, tx)

    deltas = []
    # materialize to host: the step donates state, deleting old leaves
    prev = [np.asarray(l) for l in jax.tree.leaves(state.params)]
    for _ in range(3):
        state, _ = step(state, images, labels, drng)
        cur = [np.asarray(l) for l in jax.tree.leaves(state.params)]
        deltas.append(float(sum(np.abs(a - b).sum() for a, b in zip(cur, prev))))
        prev = cur
    assert deltas[0] > deltas[1] > deltas[2], deltas


def test_optimizer_registry_and_adam_learns():
    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import make_optimizer

    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer("rmsprop-nope", 0.1)

    model = AlexNet(num_classes=10)
    images = np.random.default_rng(0).normal(size=(32, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(32) % 10).astype(np.int32)
    drng = jax.random.key(1)
    state, tx = create_train_state(model, jax.random.key(0), 1e-3, optimizer="adam")
    step = make_train_step(model, tx)
    losses = []
    for _ in range(20):
        state, loss = step(state, images, labels, drng)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.2, losses[::5]


def test_prefetch_preserves_batches_and_order():
    from distributed_ml_pytorch_tpu.data import iterate_batches, prefetch_to_device

    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.int32)
    plain = list(iterate_batches(x, y, 2, shuffle=True, seed=3))
    fetched = list(
        prefetch_to_device(iterate_batches(x, y, 2, shuffle=True, seed=3), size=3)
    )
    assert len(plain) == len(fetched)
    for (ax, ay), (bx, by) in zip(plain, fetched):
        np.testing.assert_array_equal(ax, np.asarray(bx))
        np.testing.assert_array_equal(ay, np.asarray(by))


def test_prefetched_training_matches_unprefetched(tmp_path):
    import copy

    class A(Args):
        epochs = 1
        synthetic_train_size = 128
        synthetic_test_size = 64

    a1, a2 = copy.deepcopy(A()), copy.deepcopy(A())
    a1.log_dir = str(tmp_path / "a")
    a1.prefetch = 0
    a2.log_dir = str(tmp_path / "b")
    a2.prefetch = 3
    s1, l1 = train_single(a1)
    s2, l2 = train_single(a2)
    for r1, r2 in zip(l1.records, l2.records):
        np.testing.assert_allclose(r1["training_loss"], r2["training_loss"], rtol=1e-6)
    for p1, p2 in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-6)


# ------------------------------------------------ large-batch accum (ISSUE 9)

def test_accum_step_mean_mode_equals_big_batch_step():
    """``effective_update_batch=None``: the microbatch accumulation scan
    applies exactly the full-batch mean gradient — one large-batch step,
    equal to ``make_train_step`` on the same batch up to summation order."""
    from distributed_ml_pytorch_tpu.training.trainer import make_accum_train_step

    model = AlexNet(num_classes=10)  # no dropout: deterministic
    rng_np = np.random.default_rng(1)
    images = rng_np.normal(size=(32, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(32) % 10).astype(np.int32)
    drng = jax.random.key(1)

    state_a, tx_a = create_train_state(model, jax.random.key(0), lr=0.05)
    accum = make_accum_train_step(model, tx_a, microbatch=8)
    state_a, loss_a = accum(state_a, images, labels, drng)

    state_b, tx_b = create_train_state(model, jax.random.key(0), lr=0.05)
    step = make_train_step(model, tx_b)
    state_b, loss_b = step(state_b, images, labels, drng)

    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_accum_step_effective_update_preserves_small_batch_recipe():
    """``effective_update_batch=e``: the applied SGD update equals the SUM
    of the B/e batch-``e`` recipe updates at frozen params — the
    large-batch throughput leg's linear-scaling contract."""
    import optax

    from distributed_ml_pytorch_tpu.training.trainer import (
        cross_entropy_loss,
        make_accum_train_step,
    )

    model = AlexNet(num_classes=10)
    rng_np = np.random.default_rng(2)
    images = rng_np.normal(size=(32, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(32) % 10).astype(np.int32)

    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    accum = make_accum_train_step(
        model, tx, microbatch=16, effective_update_batch=8)
    got, _ = accum(jax.tree.map(jax.numpy.copy, state), images, labels,
                   jax.random.key(1))

    def loss_fn(params, bx, by):
        return cross_entropy_loss(model.apply({"params": params}, bx), by)

    gsum = None
    for j in range(4):  # B/e = 32/8 batch-8 mean grads at frozen params
        g = jax.grad(loss_fn)(
            state.params, images[j * 8:(j + 1) * 8], labels[j * 8:(j + 1) * 8])
        gsum = g if gsum is None else jax.tree.map(jax.numpy.add, gsum, g)
    upd, _ = tx.update(gsum, state.opt_state, state.params)
    want = optax.apply_updates(state.params, upd)
    for a, b in zip(jax.tree.leaves(got.params), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_scan_accum_step_matches_sequential_accum_steps():
    """The U-update scan (the bench leg's compiled program) is exactly U
    sequential accum dispatches — same params, same per-update losses,
    same step count; remat=True changes memory, not values."""
    from distributed_ml_pytorch_tpu.training.trainer import (
        make_accum_train_step,
        make_scan_accum_train_step,
    )

    model = AlexNet(num_classes=10)
    rng_np = np.random.default_rng(3)
    images = rng_np.normal(size=(3, 16, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(3 * 16) % 10).astype(np.int32).reshape(3, 16)
    drng = jax.random.key(1)

    state0, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    scan = make_scan_accum_train_step(model, tx, microbatch=4,
                                      effective_update_batch=4)
    sa, losses = scan(jax.tree.map(jax.numpy.copy, state0), images, labels, drng)

    accum = make_accum_train_step(model, tx, microbatch=4,
                                  effective_update_batch=4)
    sb = jax.tree.map(jax.numpy.copy, state0)
    seq_losses = []
    for u in range(3):
        sb, lu = accum(sb, images[u], labels[u], drng)
        seq_losses.append(float(lu))
    assert int(sa.step) == int(sb.step) == 3
    np.testing.assert_allclose(np.asarray(losses), seq_losses, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(sa.params), jax.tree.leaves(sb.params)):
        assert bool((a == b).all())

    remat = make_scan_accum_train_step(model, tx, microbatch=4,
                                       effective_update_batch=4, remat=True)
    sr, _ = remat(jax.tree.map(jax.numpy.copy, state0), images, labels, drng)
    for a, b in zip(jax.tree.leaves(sr.params), jax.tree.leaves(sa.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-8)


def test_accum_step_rejects_indivisible_batch():
    from distributed_ml_pytorch_tpu.training.trainer import make_accum_train_step

    model = AlexNet(num_classes=10)
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    accum = make_accum_train_step(model, tx, microbatch=7)
    images = np.zeros((16, 32, 32, 3), np.float32)
    labels = np.zeros((16,), np.int32)
    with pytest.raises(ValueError, match="divide"):
        accum(state, images, labels, jax.random.key(1))
