"""The MFU regression gate (bench.py --gate, ISSUE 9) — logic on canned
records, no device run (this is the tier-1 twin of `make bench-gate`).

The gate's contract: a leg below its recorded floor minus tolerance fails;
a floored leg MISSING from the record fails (a silently dropped leg must
not pass); a leg without measured MFU (CPU hosts have no peak table) is a
reported skip unless --require-mfu.
"""

import copy
import json
import os
import subprocess
import sys

import bench

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SMOKE = os.path.join(HERE, "data", "bench_gate_smoke.json")


def _smoke_record():
    with open(SMOKE) as fh:
        return json.load(fh)


def test_canned_record_passes_floors():
    floors = bench.load_floors()
    breaches, skips = bench.check_mfu_floors(_smoke_record(), floors)
    assert not breaches and not skips
    assert bench.gate(_smoke_record(), floors) == 0


def test_simulated_mfu_drop_breaches_exactly_that_leg():
    floors = bench.load_floors()
    rec = _smoke_record()
    floor = floors["legs"]["large_batch_b1024"]
    rec["legs"]["large_batch_b1024"]["mfu"] = floor - floors["tolerance"] - 0.001
    breaches, skips = bench.check_mfu_floors(rec, floors)
    assert len(breaches) == 1 and "large_batch_b1024" in breaches[0]
    assert not skips
    assert bench.gate(rec, floors) == 1
    # within tolerance of the floor: still passing (hysteresis band)
    rec["legs"]["large_batch_b1024"]["mfu"] = floor - floors["tolerance"] / 2
    breaches, _ = bench.check_mfu_floors(rec, floors)
    assert not breaches


def test_missing_leg_is_a_breach_not_a_pass():
    floors = bench.load_floors()
    rec = _smoke_record()
    del rec["legs"]["parity_b64"]
    breaches, _ = bench.check_mfu_floors(rec, floors)
    assert any("parity_b64" in b and "missing" in b for b in breaches)
    assert bench.gate(rec, floors) == 1


def test_unmeasured_mfu_skips_unless_required():
    floors = bench.load_floors()
    rec = _smoke_record()
    for leg in rec["legs"].values():
        leg.pop("mfu", None)  # the CPU-host shape of the record
    breaches, skips = bench.check_mfu_floors(rec, floors)
    assert not breaches and len(skips) == 3
    assert bench.gate(rec, floors) == 0
    assert bench.gate(rec, floors, require_mfu=True) == 1


def test_build_record_carries_floors_and_headline():
    floors = bench.load_floors()
    legs = {name: bench.Rate(v) for name, v in [
        ("parity_b64", 1.18e6), ("large_batch_b1024", 1.64e6),
        ("grad_accum_b1024", 1.52e6)]}
    rec = bench.build_record(legs, torch_base=845.0, floors=floors)
    assert rec["headline_leg"] == bench.HEADLINE_LEG
    assert rec["value"] == round(float(legs[bench.HEADLINE_LEG]), 1)
    assert rec["vs_baseline"] == round(1.64e6 / 845.0, 2)
    for name, leg in rec["legs"].items():
        assert leg["mfu_floor"] == floors["legs"][name]
    # the parity leg keeps the reference batch; the throughput legs report
    # theirs — side-by-side legs, one record
    assert rec["legs"]["parity_b64"]["batch"] == 64
    assert rec["legs"]["large_batch_b1024"]["batch"] == bench.LARGE_BATCH


def test_cli_gate_exit_codes(tmp_path):
    """`python bench.py --gate --json FILE` is the make bench-gate smoke:
    exit 0 on the canned record, non-zero on a seeded regression — with
    no jax import (the gate must stay cheap enough for `make test`)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, "bench.py", "--gate", "--json", SMOKE],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stderr
    bad = copy.deepcopy(_smoke_record())
    bad["legs"]["grad_accum_b1024"]["mfu"] = 0.01
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(bad))
    fail = subprocess.run(
        [sys.executable, "bench.py", "--gate", "--json", str(bad_path)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert fail.returncode == 1
    assert "grad_accum_b1024" in fail.stderr
