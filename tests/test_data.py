"""C6 pipeline tests: normalization, batching, sharding, synthetic determinism."""

import numpy as np

from distributed_ml_pytorch_tpu.data import (
    iterate_batches,
    load_cifar10,
    shard_for_process,
    synthetic_cifar10,
)


def test_synthetic_deterministic():
    a = synthetic_cifar10(128, 64, seed=3)
    b = synthetic_cifar10(128, 64, seed=3)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_load_normalized_range():
    x_train, y_train, x_test, y_test, is_synth = load_cifar10(
        root="/nonexistent", synthetic=None, n_train=256, n_test=64
    )
    assert is_synth
    assert x_train.shape == (256, 32, 32, 3) and x_train.dtype == np.float32
    assert x_train.min() >= -1.0 and x_train.max() <= 1.0
    assert y_train.shape == (256,) and y_train.dtype == np.int32
    assert set(np.unique(y_train)) <= set(range(10))


def test_iterate_batches_static_shapes():
    x = np.zeros((100, 32, 32, 3), np.float32)
    y = np.zeros((100,), np.int32)
    batches = list(iterate_batches(x, y, 32, shuffle=True, seed=0))
    assert len(batches) == 3  # drop_last keeps shapes static for jit
    assert all(bx.shape == (32, 32, 32, 3) for bx, _ in batches)


def test_iterate_batches_shuffles_per_epoch():
    x = np.arange(64, dtype=np.float32).reshape(64, 1, 1, 1) * np.ones((64, 32, 32, 3), np.float32)
    y = np.arange(64, dtype=np.int32)
    e0 = np.concatenate([by for _, by in iterate_batches(x, y, 16, seed=0, epoch=0)])
    e1 = np.concatenate([by for _, by in iterate_batches(x, y, 16, seed=0, epoch=1)])
    assert not np.array_equal(e0, e1)
    assert set(e0) == set(range(64))


def test_shard_for_process_partitions():
    x = np.arange(101, dtype=np.float32)[:, None]
    y = np.arange(101, dtype=np.int32)
    shards = [shard_for_process(x, y, r, 4) for r in range(4)]
    seen = np.concatenate([s[1] for s in shards])
    assert len(seen) == 100  # truncated to a multiple of process_count
    assert len(set(seen.tolist())) == 100  # disjoint coverage
    assert all(len(s[1]) == 25 for s in shards)
