"""C6 pipeline tests: normalization, batching, sharding, synthetic determinism,
and the guarded download path (exercised with a fabricated archive over
``file://`` — no network needed)."""

import hashlib
import io
import os
import pickle
import tarfile

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.data import (
    download_cifar10,
    iterate_batches,
    load_cifar10,
    shard_for_process,
    synthetic_cifar10,
)


def make_fake_archive(path, n_train_per_batch=4, n_test=4, seed=0):
    """A structurally-faithful cifar-10-python.tar.gz: 5 train pickles +
    test_batch in the real key/shape layout. Returns its md5."""
    rng = np.random.default_rng(seed)

    def entry(n):
        return {
            b"data": rng.integers(0, 256, size=(n, 3072), dtype=np.uint8)
                        .astype(np.uint8),
            b"labels": rng.integers(0, 10, size=n).astype(np.int64).tolist(),
        }

    with tarfile.open(path, "w:gz") as tf:
        names = [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]
        for name in names:
            n = n_test if name == "test_batch" else n_train_per_batch
            blob = pickle.dumps(entry(n))
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    with open(path, "rb") as f:
        return hashlib.md5(f.read()).hexdigest()


def test_download_verifies_extracts_and_loads(tmp_path):
    src = tmp_path / "src.tar.gz"
    md5 = make_fake_archive(str(src))
    root = str(tmp_path / "data")
    d = download_cifar10(root, url=src.as_uri(), md5=md5)
    assert os.path.isdir(d)
    x_train, y_train, x_test, y_test, is_synth = load_cifar10(root=root,
                                                              synthetic=False)
    assert not is_synth
    assert x_train.shape == (20, 32, 32, 3) and x_test.shape == (4, 32, 32, 3)
    assert x_train.min() >= -1.0 and x_train.max() <= 1.0


def test_download_checksum_mismatch_refuses_install(tmp_path):
    src = tmp_path / "src.tar.gz"
    make_fake_archive(str(src))
    root = str(tmp_path / "data")
    with pytest.raises(ValueError, match="checksum mismatch"):
        download_cifar10(root, url=src.as_uri(), md5="0" * 32)
    # nothing half-installed: no tarball, no .part, no batches/extract dirs
    import glob

    assert not os.path.exists(os.path.join(root, "cifar-10-python.tar.gz"))
    assert not glob.glob(os.path.join(root, "*.part"))
    assert not glob.glob(os.path.join(root, "*.extract"))
    assert not os.path.isdir(os.path.join(root, "cifar-10-batches-py"))


def test_load_real_data_downloads_when_explicit(tmp_path, monkeypatch):
    """synthetic=False + data absent triggers the download attempt
    (zero-manual-steps deployment); here it lands via a patched URL."""
    src = tmp_path / "src.tar.gz"
    md5 = make_fake_archive(str(src))
    import distributed_ml_pytorch_tpu.data.cifar10 as mod

    monkeypatch.setattr(mod, "CIFAR10_URL", src.as_uri())
    monkeypatch.setattr(mod, "CIFAR10_MD5", md5)
    root = str(tmp_path / "data")
    *_, is_synth = load_cifar10(root=root, synthetic=False)
    assert not is_synth


def test_load_download_failure_falls_back_under_autodetect(tmp_path, monkeypatch):
    import distributed_ml_pytorch_tpu.data.cifar10 as mod

    monkeypatch.setattr(mod, "CIFAR10_URL",
                        (tmp_path / "missing.tar.gz").as_uri())
    *_, is_synth = load_cifar10(root=str(tmp_path / "data"), synthetic=None,
                                download=True, n_train=64, n_test=16)
    assert is_synth  # auto-detect semantics: failed fetch → stand-in


def test_synthetic_deterministic():
    a = synthetic_cifar10(128, 64, seed=3)
    b = synthetic_cifar10(128, 64, seed=3)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_load_normalized_range():
    x_train, y_train, x_test, y_test, is_synth = load_cifar10(
        root="/nonexistent", synthetic=None, n_train=256, n_test=64
    )
    assert is_synth
    assert x_train.shape == (256, 32, 32, 3) and x_train.dtype == np.float32
    assert x_train.min() >= -1.0 and x_train.max() <= 1.0
    assert y_train.shape == (256,) and y_train.dtype == np.int32
    assert set(np.unique(y_train)) <= set(range(10))


def test_iterate_batches_static_shapes():
    x = np.zeros((100, 32, 32, 3), np.float32)
    y = np.zeros((100,), np.int32)
    batches = list(iterate_batches(x, y, 32, shuffle=True, seed=0))
    assert len(batches) == 3  # drop_last keeps shapes static for jit
    assert all(bx.shape == (32, 32, 32, 3) for bx, _ in batches)


def test_iterate_batches_shuffles_per_epoch():
    x = np.arange(64, dtype=np.float32).reshape(64, 1, 1, 1) * np.ones((64, 32, 32, 3), np.float32)
    y = np.arange(64, dtype=np.int32)
    e0 = np.concatenate([by for _, by in iterate_batches(x, y, 16, seed=0, epoch=0)])
    e1 = np.concatenate([by for _, by in iterate_batches(x, y, 16, seed=0, epoch=1)])
    assert not np.array_equal(e0, e1)
    assert set(e0) == set(range(64))


def test_shard_for_process_partitions():
    x = np.arange(101, dtype=np.float32)[:, None]
    y = np.arange(101, dtype=np.int32)
    shards = [shard_for_process(x, y, r, 4) for r in range(4)]
    seen = np.concatenate([s[1] for s in shards])
    assert len(seen) == 100  # truncated to a multiple of process_count
    assert len(set(seen.tolist())) == 100  # disjoint coverage
    assert all(len(s[1]) == 25 for s in shards)


def test_preexisting_corrupt_tarball_is_reverified_and_replaced(tmp_path):
    """A torn/corrupt tarball already sitting at the destination must be
    caught by the md5 check and silently re-downloaded — not surface later
    as an opaque tarfile/extract error (ADVICE r2)."""
    src = tmp_path / "src.tar.gz"
    md5 = make_fake_archive(str(src))
    root = str(tmp_path / "data")
    os.makedirs(root)
    # plant garbage where the tarball would live
    dest = os.path.join(root, "cifar-10-python.tar.gz")
    with open(dest, "wb") as f:
        f.write(b"this is not a gzip stream")
    d = download_cifar10(root, url=src.as_uri(), md5=md5)
    assert os.path.isdir(d)
    # the garbage was replaced by the verified archive
    with open(dest, "rb") as f:
        assert hashlib.md5(f.read()).hexdigest() == md5
