"""dp×tp tensor parallelism: Megatron-style param shardings under pjit/GSPMD
must be numerically identical to unsharded training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets
from distributed_ml_pytorch_tpu.parallel.tensor_parallel import (
    create_tp_train_state,
    make_tp_train_step,
    shard_tp_batch,
    tp_param_specs,
)
from distributed_ml_pytorch_tpu.training.trainer import TrainState


def tiny_model():
    return TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=128
    )


def dp_tp_mesh(dp=2, tp=4):
    devs = np.array(jax.devices()[: dp * tp]).reshape(dp, tp)
    return Mesh(devs, ("data", "model"))


def make_batch(batch=4, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, 64, size=(batch, seq)).astype(np.int32)
    return tokens, next_token_targets(tokens)


def test_tp_param_specs_follow_megatron_rules():
    model = tiny_model()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    specs = tp_param_specs(params)
    b0 = specs["block_0"]
    assert b0["attn"]["q"]["kernel"] == P(None, "model")
    assert b0["attn"]["o"]["kernel"] == P("model", None)
    assert b0["Dense_0"]["kernel"] == P(None, "model")
    assert b0["Dense_0"]["bias"] == P("model")
    assert b0["Dense_1"]["kernel"] == P("model", None)
    assert b0["Dense_1"]["bias"] == P()
    assert specs["lm_head"]["kernel"] == P(None, "model")
    assert specs["tok_embed"]["embedding"] == P()


def test_tp_state_is_actually_sharded():
    mesh = dp_tp_mesh()
    model = tiny_model()
    state = create_tp_train_state(
        model, jax.random.key(0), optax.sgd(0.1, momentum=0.9), mesh
    )
    qk = state.params["block_0"]["attn"]["q"]["kernel"]
    assert qk.sharding.spec == P(None, "model")
    # optimizer state (momentum trace) inherits the param sharding by
    # propagation — created sharded, never materialized replicated
    trace = state.opt_state[0].trace["block_0"]["attn"]["q"]["kernel"]
    assert trace.sharding.spec == P(None, "model")


def test_tp_training_matches_unsharded_exactly():
    model = tiny_model()
    mesh = dp_tp_mesh()
    tx = optax.sgd(0.1)
    tokens, targets = make_batch()

    # unsharded single-device reference: the SAME step code, fed unsharded
    # state and arrays (jit runs it on one device)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    ref_state = TrainState.create(params, tx)
    ref_step = make_tp_train_step(model, tx, mesh)

    tp_state = create_tp_train_state(model, jax.random.key(0), tx, mesh)
    tp_step = make_tp_train_step(model, tx, mesh)
    stok, stgt = shard_tp_batch(mesh, tokens, targets)

    ref_losses, tp_losses = [], []
    for _ in range(3):
        ref_state, rl = ref_step(ref_state, jnp.asarray(tokens), jnp.asarray(targets))
        tp_state, tl = tp_step(tp_state, stok, stgt)
        ref_losses.append(float(rl))
        tp_losses.append(float(tl))
    np.testing.assert_allclose(tp_losses, ref_losses, rtol=2e-5)
    # final params agree leaf-for-leaf (gather the sharded ones)
    flat_ref = jax.tree.leaves(ref_state.params)
    flat_tp = jax.tree.leaves(jax.device_get(tp_state.params))
    for a, b in zip(flat_ref, flat_tp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-5, atol=1e-6)


def test_tp_rejects_indivisible_dimensions():
    mesh = dp_tp_mesh(dp=2, tp=4)
    bad = TransformerLM(vocab_size=64, d_model=30, n_heads=3, n_layers=1, d_ff=60)
    with pytest.raises(ValueError, match="not divisible"):
        make_tp_train_step(bad, optax.sgd(0.1), mesh)


def test_tp_composes_with_data_parallel_batch_split():
    """Loss must be identical whichever dp×tp factorization the mesh uses."""
    model = tiny_model()
    tx = optax.sgd(0.1)
    tokens, targets = make_batch(batch=8, seq=16)
    losses = []
    for dp, tp in ((2, 4), (4, 2), (8, 1)):
        mesh = dp_tp_mesh(dp, tp)
        state = create_tp_train_state(model, jax.random.key(0), tx, mesh)
        step = make_tp_train_step(model, tx, mesh)
        stok, stgt = shard_tp_batch(mesh, tokens, targets)
        _, loss = step(state, stok, stgt)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, losses[0] * np.ones(len(losses)), rtol=2e-5)
