"""FSDP (ZeRO-3 via GSPMD) correctness on the 8-device virtual CPU mesh.

The contract: fully sharding params/grads/optimizer state over the data axis
must change *memory layout only* — the training trajectory matches unsharded
single-device SGD, and the per-device parameter footprint actually drops.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_ml_pytorch_tpu.data import load_cifar10
from distributed_ml_pytorch_tpu.models import AlexNet, TransformerLM
from distributed_ml_pytorch_tpu.parallel.fsdp import (
    create_fsdp_train_state,
    fsdp_specs,
    make_fsdp_lm_train_step,
    make_fsdp_train_step,
    param_shard_fraction,
    shard_fsdp_batch,
)
from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets
from distributed_ml_pytorch_tpu.training.trainer import (
    TrainState,
    create_train_state,
    make_train_step,
)


def test_fsdp_specs_shard_largest_divisible_dim():
    tree = {
        "conv": jnp.zeros((11, 11, 3, 64)),   # 64 is largest div-by-8 dim
        "dense": jnp.zeros((256, 10)),         # 256 div by 8; 10 is not
        "bias": jnp.zeros((6,)),               # nothing divides → replicated
        "scalar": jnp.zeros(()),
    }
    specs = fsdp_specs(tree, 8)
    assert specs["conv"] == P(None, None, None, "data")
    assert specs["dense"] == P("data", None)
    assert specs["bias"] == P()
    assert specs["scalar"] == P()


def test_fsdp_step_matches_single_device(mesh8):
    """8-way FSDP on batch 64 == single-device batch-64 SGD (ZeRO changes
    where tensors live, never what is computed)."""
    x, y, *_ = load_cifar10(n_train=64, n_test=16, synthetic=True)
    model = AlexNet()  # no dropout → deterministic comparison
    state_s, tx = create_train_state(model, jax.random.key(0), lr=0.05)

    def init_fn(rng):
        images = jnp.zeros((1, 32, 32, 3), jnp.float32)
        params = model.init(rng, images)["params"]
        return TrainState.create(params, tx)

    state_f, shardings = create_fsdp_train_state(init_fn, jax.random.key(0), mesh8)
    single_step = make_train_step(model, tx)
    fsdp_step = make_fsdp_train_step(model, tx, mesh8, shardings)

    rng = jax.random.key(7)
    bx, by = shard_fsdp_batch(mesh8, x[:64], y[:64])

    for _ in range(3):
        state_s, loss_s = single_step(state_s, x[:64], y[:64], rng)
        state_f, loss_f = fsdp_step(state_f, bx, by, rng)
        np.testing.assert_allclose(float(loss_s), float(loss_f), rtol=1e-5)

    for a, b in zip(jax.tree.leaves(state_s.params), jax.tree.leaves(state_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_fsdp_param_memory_is_actually_sharded(mesh8):
    """The per-device parameter fraction must be ≈1/8, measured from the
    devices' addressable shards — the ZeRO memory claim, verified."""
    model = AlexNet()
    tx = optax.sgd(0.05)

    def init_fn(rng):
        params = model.init(rng, jnp.zeros((1, 32, 32, 3)))["params"]
        return TrainState.create(params, tx)

    state, _ = create_fsdp_train_state(init_fn, jax.random.key(0), mesh8)
    frac = param_shard_fraction(state, mesh8)
    assert frac < 0.2, f"expected ≈1/8 of params per device, measured {frac:.3f}"


def test_fsdp_training_loop_end_to_end(mesh8, tmp_path):
    """--mode fsdp through the real loop: loss decreases, telemetry written,
    params measurably sharded (the loop prints the measured fraction)."""
    from distributed_ml_pytorch_tpu.parallel.fsdp import train_fsdp
    from distributed_ml_pytorch_tpu.training.cli import build_parser

    args = build_parser().parse_args([
        "--mode", "fsdp", "--epochs", "1", "--synthetic-data",
        "--synthetic-train-size", "128", "--synthetic-test-size", "32",
        "--batch-size", "2", "--model", "lenet", "--lr", "0.05",
        "--log-interval", "100", "--log-dir", str(tmp_path),
    ])
    state, logger = train_fsdp(args, mesh8)
    assert int(state.step) == 128 // (2 * 8)
    records = logger.records
    assert records and records[-1]["training_loss"] < records[0]["training_loss"]


def test_fsdp_chunked_dispatch_matches_per_step(mesh8, tmp_path):
    """--steps-per-dispatch K under fsdp: the scanned trajectory must equal
    K per-step dispatches (same loss builder, same rng folding)."""
    from distributed_ml_pytorch_tpu.parallel.fsdp import train_fsdp
    from distributed_ml_pytorch_tpu.training.cli import build_parser

    def run(k, tag):
        args = build_parser().parse_args([
            "--mode", "fsdp", "--epochs", "1", "--synthetic-data",
            "--synthetic-train-size", "128", "--synthetic-test-size", "32",
            "--batch-size", "2", "--model", "lenet", "--lr", "0.05",
            "--log-interval", "100", "--log-dir", str(tmp_path / tag),
            "--steps-per-dispatch", str(k),
        ])
        return train_fsdp(args, mesh8)

    per_state, per_log = run(1, "per")
    chunk_state, chunk_log = run(4, "chunk")
    assert int(per_state.step) == int(chunk_state.step)
    per_losses = [r["training_loss"] for r in per_log.records]
    chunk_losses = [r["training_loss"] for r in chunk_log.records]
    np.testing.assert_allclose(per_losses, chunk_losses, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(per_state.params),
                    jax.tree.leaves(chunk_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_fsdp_lm_matches_single_device_and_shards_momentum(mesh8):
    """Transformer FSDP with momentum: trajectory matches unsharded, and the
    optimizer's momentum buffers (the biggest ZeRO saving) are sharded."""
    lm = TransformerLM(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                       d_ff=64, max_len=64)
    tx = optax.sgd(0.05, momentum=0.9)
    tokens = np.random.default_rng(0).integers(0, 64, size=(16, 32)).astype(np.int32)
    targets = next_token_targets(tokens)

    def init_fn(rng):
        params = lm.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]
        return TrainState.create(params, tx)

    state_f, shardings = create_fsdp_train_state(init_fn, jax.random.key(1), mesh8)
    state_s = init_fn(jax.random.key(1))
    fsdp_step = make_fsdp_lm_train_step(lm, tx, mesh8, shardings)

    @jax.jit
    def single_step(state, tokens, targets):
        def loss_fn(params):
            logits = lm.apply({"params": params}, tokens)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
            mask = jnp.ones_like(ce).at[:, -1].set(0.0)
            return jnp.sum(ce * mask) / jnp.sum(mask)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(params=params, opt_state=opt_state,
                             step=state.step + 1), loss

    tok_f, tgt_f = shard_fsdp_batch(mesh8, tokens, targets)
    for _ in range(2):
        state_s, loss_s = single_step(state_s, tokens, targets)
        state_f, loss_f = fsdp_step(state_f, tok_f, tgt_f)
        np.testing.assert_allclose(float(loss_s), float(loss_f), rtol=1e-5)

    for a, b in zip(jax.tree.leaves(state_s.params), jax.tree.leaves(state_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)

    sharded_opt_leaves = [
        leaf for leaf in jax.tree.leaves(state_f.opt_state)
        if getattr(leaf, "ndim", 0) > 0 and leaf.sharding.spec != P()
        and any(s is not None for s in leaf.sharding.spec)
    ]
    assert sharded_opt_leaves, "momentum buffers should be sharded (ZeRO-2/3)"
