"""ISSUE 2 chaos suite: deterministic fault injection + reliable delivery.

Three layers of property, all in-process (real subprocess kill-tests live in
tests/test_ps_fault_injection.py):

- unit: FaultyTransport's seeded per-channel decisions (drop/dup/reorder/
  corrupt/delay/partition) and ReliableTransport's seq/CRC/ack/dedup;
- system: the ISSUE acceptance scenario — async-PS training under
  drop=0.1 + dup=0.05 on two workers converges into the fault-free loss
  corridor with a byte-identical fault log across runs; scripted crash,
  failure-detector reap, rejoin, and server crash→restart (checkpoint +
  version) all exercised without spawning processes;
- serving: streams stay token-identical to standalone ``generate()`` under
  injected frame loss (client-driven resume), silent clients are reaped.

Fast seeded cases carry the ``chaos`` marker and run in tier-1
(``make chaos`` selects just them); long soak variants are additionally
``slow``.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models import LeNet
from distributed_ml_pytorch_tpu.parallel.async_ps import (
    Asynchronous,
    ParameterServer,
)
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosLog,
    ChaosPlan,
    FaultRule,
    FaultyTransport,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
)
from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

pytestmark = pytest.mark.chaos

# the shared lock_witness fixture (tests/conftest.py) arms the acceptance
# scenario below as a concurrency validator under DISTCHECK_WITNESS=1

# ---------------------------------------------------------------------------
# unit: FaultyTransport
# ---------------------------------------------------------------------------

def _pump_all(t, n=1000):
    out = []
    while True:
        m = t.recv(timeout=0.05)
        if m is None or len(out) >= n:
            return out
        out.append(m)


def test_faulty_transport_decisions_are_seeded_and_channel_local():
    """Same plan + same per-channel send sequence → identical fault log and
    identical deliveries, run-to-run."""
    plan = ChaosPlan([FaultRule(drop=0.3, dup=0.2)], seed=11)

    def run():
        world = InProcessTransport.create_world(2)
        fw, log = FaultyTransport.wrap_world(world, plan)
        for i in range(50):
            fw[1].send(MessageCode.GradientUpdate, np.full(3, i, np.float32))
            fw[1].send(MessageCode.ParameterRequest, np.zeros(0, np.float32))
        got = [int(m[2][0]) for m in _pump_all(fw[0])
               if m[1] == MessageCode.GradientUpdate]
        return got, log.lines()

    got_a, log_a = run()
    got_b, log_b = run()
    assert log_a == log_b and log_a  # byte-identical, and faults DID fire
    assert got_a == got_b
    assert len(got_a) < 60  # drops happened
    assert "drop" in log_a and "dup" in log_a


def test_chaos_plan_json_roundtrip_is_exact():
    """ISSUE 13: counterexamples from the bounded model checker travel as
    ChaosPlan JSON — the round trip must be identity across all three
    rule families, and unknown fields must fail loudly (a typo'd field
    silently weakening a replayed counterexample is the one wrong
    answer)."""
    from distributed_ml_pytorch_tpu.utils.chaos import (
        SDCRule,
        WeatherRule,
        plan_from_json,
        plan_to_json,
    )

    plan = ChaosPlan(
        rules=[FaultRule(src=1, dst=0, code=int(MessageCode.ReliableFrame),
                         drop=1.0, after=2, until=3),
               FaultRule(dup=0.5, delay=0.01, delay_p=0.25)],
        seed=41,
        weather=[WeatherRule(src=0, latency=0.002, jitter=0.001,
                             bandwidth=1e6)],
        sdc=[SDCRule(code=int(MessageCode.GradientUpdate), p=1.0,
                     kind="scale", factor=-2.0, skip=6)])
    data = plan_to_json(plan)
    assert plan_from_json(json.loads(json.dumps(data))) == plan
    # defaults are omitted from the wire form, not round-tripped as noise
    assert "weather" not in plan_to_json(ChaosPlan(seed=7))
    with pytest.raises(ValueError, match="unknown ChaosPlan fields"):
        plan_from_json({"seed": 0, "ruels": []})
    with pytest.raises(ValueError, match="unknown FaultRule fields"):
        plan_from_json({"rules": [{"dorp": 1.0}]})


def test_fault_rule_windows_and_code_match():
    """`after`/`until` schedule a rule to a channel-index window, and a
    code-scoped rule leaves other codes untouched."""
    plan = ChaosPlan(
        [FaultRule(code=int(MessageCode.GradientUpdate), drop=1.0,
                   after=2, until=4)],
        seed=0)
    world = InProcessTransport.create_world(2)
    fw, log = FaultyTransport.wrap_world(world, plan)
    for i in range(6):
        fw[1].send(MessageCode.GradientUpdate, np.full(1, i, np.float32))
        fw[1].send(MessageCode.Heartbeat, np.zeros(0, np.float32))
    grads = [int(m[2][0]) for m in _pump_all(fw[0])
             if m[1] == MessageCode.GradientUpdate]
    assert grads == [0, 1, 4, 5]  # sends #2 and #3 dropped
    assert log.counts() == {"drop": 2}


def test_one_way_partition_and_heal():
    world = InProcessTransport.create_world(2)
    fw, log = FaultyTransport.wrap_world(world, ChaosPlan())
    fw[1].partition(0)
    fw[1].send(MessageCode.GradientUpdate, np.ones(1, np.float32))
    fw[0].send(MessageCode.ParameterUpdate, np.ones(1, np.float32), dst=1)
    assert fw[0].recv(timeout=0.1) is None      # 1→0 severed
    assert fw[1].recv(timeout=0.5) is not None  # 0→1 unaffected (one-way)
    fw[1].heal(0)
    fw[1].send(MessageCode.GradientUpdate, np.ones(1, np.float32))
    assert fw[0].recv(timeout=0.5) is not None
    assert log.counts() == {"partition-drop": 1}


def test_scripted_crash_and_restart():
    world = InProcessTransport.create_world(2)
    fw, _log = FaultyTransport.wrap_world(world, ChaosPlan())
    fw[0].crash()
    with pytest.raises(ConnectionError):
        fw[1].send(MessageCode.GradientUpdate, np.ones(1, np.float32))
    with pytest.raises(ConnectionError):
        fw[0].send(MessageCode.ParameterUpdate, np.ones(1, np.float32), dst=1)
    assert fw[0].recv(timeout=0.05) is None  # a crashed endpoint hears nothing
    fw[0].restart()
    fw[1].send(MessageCode.GradientUpdate, np.ones(1, np.float32))
    assert fw[0].recv(timeout=0.5) is not None


def test_reorder_swaps_adjacent_frames():
    plan = ChaosPlan([FaultRule(reorder=1.0, until=1)], seed=3)
    world = InProcessTransport.create_world(2)
    fw, log = FaultyTransport.wrap_world(world, plan)
    for i in range(3):
        fw[1].send(MessageCode.GradientUpdate, np.full(1, i, np.float32))
    got = [int(m[2][0]) for m in _pump_all(fw[0])]
    assert got == [1, 0, 2]  # frame #0 held, released after #1
    assert log.counts() == {"reorder-hold": 1}


def test_delay_holds_then_delivers():
    plan = ChaosPlan([FaultRule(delay=0.3, delay_p=1.0, until=1)], seed=5)
    world = InProcessTransport.create_world(2)
    fw, log = FaultyTransport.wrap_world(world, plan)
    t0 = time.monotonic()
    fw[1].send(MessageCode.GradientUpdate, np.full(1, 7, np.float32))
    msg = fw[0].recv(timeout=2)
    assert msg is not None and int(msg[2][0]) == 7
    assert time.monotonic() - t0 >= 0.25
    assert log.counts() == {"delay": 1}


def test_corrupt_changes_bytes():
    plan = ChaosPlan([FaultRule(corrupt=1.0)], seed=9)
    world = InProcessTransport.create_world(2)
    fw, log = FaultyTransport.wrap_world(world, plan)
    payload = np.arange(4, dtype=np.float32)
    fw[1].send(MessageCode.GradientUpdate, payload)
    fw[1].send(MessageCode.ParameterRequest, np.zeros(0, np.float32))
    got = _pump_all(fw[0])
    assert len(got) == 2
    assert not np.array_equal(got[0][2], payload)     # corrupted in flight
    assert got[1][2].size == 1                        # empty frame grew garbage
    assert log.counts() == {"corrupt": 2}


# ---------------------------------------------------------------------------
# unit: ReliableTransport
# ---------------------------------------------------------------------------

def test_reliable_exactly_once_under_drop_dup_corrupt():
    """The tentpole's delivery contract: under wire-level drops, duplicates
    and corruption, every frame is delivered exactly once, uncorrupted."""
    world = InProcessTransport.create_world(2)
    plan = ChaosPlan([FaultRule(drop=0.3, dup=0.2, corrupt=0.2)], seed=7)
    fw, _log = FaultyTransport.wrap_world(world, plan)
    a = ReliableTransport(fw[0], ack_timeout=0.05)
    b = ReliableTransport(fw[1], ack_timeout=0.05)
    got, stop = [], threading.Event()

    def rx():
        while not stop.is_set():
            m = a.recv(timeout=0.2)
            if m is not None:
                got.append(m)

    t = threading.Thread(target=rx)
    t.start()
    n = 40
    try:
        for i in range(n):
            b.send(MessageCode.GradientUpdate, np.full(8, i, np.float32))
        assert b.flush(timeout=60), b.stats
        deadline = time.monotonic() + 10
        while len(got) < n and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        stop.set()
        t.join(timeout=5)
    assert sorted(int(m[2][0]) for m in got) == list(range(n))
    assert all(np.all(m[2] == m[2][0]) for m in got)  # no corrupt delivery
    assert a.stats["delivered"] == n
    a.close()
    b.close()


def test_reliable_passthrough_and_heartbeat_skip():
    """Plain frames from an unwrapped peer pass through; heartbeats skip the
    envelope (no ack, no retry state)."""
    world = InProcessTransport.create_world(2)
    rel = ReliableTransport(world[0], ack_timeout=0.05)
    world[1].send(MessageCode.GradientUpdate, np.ones(2, np.float32))
    msg = rel.recv(timeout=1)
    assert msg is not None and msg[1] == MessageCode.GradientUpdate
    assert rel.stats["passthrough"] == 1

    rel2 = ReliableTransport(world[1], ack_timeout=0.05)
    rel2.send(MessageCode.Heartbeat, np.zeros(0, np.float32))
    msg = rel.recv(timeout=1)
    assert msg is not None and msg[1] == MessageCode.Heartbeat
    with rel2._lock:
        assert not rel2._pending  # heartbeats are fire-and-forget
    rel.close()
    rel2.close()


def test_reliable_declares_peer_dead_after_retries():
    world = InProcessTransport.create_world(2)
    world[0].close()  # the peer will never ack
    b = ReliableTransport(world[1], ack_timeout=0.02, max_backoff=0.05,
                          max_retries=3)
    b.send(MessageCode.GradientUpdate, np.ones(2, np.float32))
    deadline = time.monotonic() + 5
    while not b.stats["gave_up"] and time.monotonic() < deadline:
        time.sleep(0.02)
    assert b.stats["gave_up"] == 1
    with pytest.raises(ConnectionError):
        b.send(MessageCode.GradientUpdate, np.ones(2, np.float32))
    b.close()


# ---------------------------------------------------------------------------
# system: the acceptance scenario (async PS under chaos, deterministic log)
# ---------------------------------------------------------------------------

_MODEL = LeNet()
_STEPS = 16
_BATCH = 16


@pytest.fixture(scope="module")
def ps_fixture():
    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = _MODEL.apply({"params": q}, bx, train=True,
                                  rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = _MODEL.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


def _run_ps_world(ps_fixture, plan=None, n_workers=2, reliable=False,
                  n_push=4, n_pull=4):
    """One in-process 1-server/N-worker DownPour run; returns
    (per-worker losses, chaos log or None, server)."""
    x, y, grad_fn, params0 = ps_fixture
    world = InProcessTransport.create_world(n_workers + 1)
    log = None
    if plan is not None:
        world, log = FaultyTransport.wrap_world(world, plan)
    if reliable:
        world = {r: ReliableTransport(t, ack_timeout=0.05)
                 for r, t in world.items()}
    server = ParameterServer(
        params=np.asarray(ravel_model_params(params0)),
        transport=world[0], n_workers=n_workers)
    server_thread = threading.Thread(target=server.run,
                                     kwargs={"timeout": 180})
    server_thread.start()
    results = {}

    def worker(rank):
        params = jax.tree.map(jnp.asarray, params0)
        opt = Asynchronous(params, lr=0.05, n_push=n_push, n_pull=n_pull,
                           transport=world[rank])
        rng = jax.random.key(rank)
        losses = []
        for step in range(_STEPS):
            sel = np.random.default_rng(rank * 100 + step).integers(
                0, len(x), _BATCH)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            losses.append(float(loss))
        opt.finish()
        results[rank] = losses

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(1, n_workers + 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    server_thread.join(timeout=60)
    assert not server_thread.is_alive(), "server did not shut down"
    for t in world.values():
        t.close()
    return results, log, server


# the ISSUE acceptance plan: drop=0.1 + dup=0.05 on the three DownPour data
# codes; WorkerDone/Heartbeat are untouched control traffic (faulting the
# shutdown handshake tests nothing DownPour claims to tolerate)
_ACCEPTANCE_PLAN = ChaosPlan(
    [FaultRule(code=int(c), drop=0.10, dup=0.05)
     for c in (MessageCode.GradientUpdate, MessageCode.ParameterRequest,
               MessageCode.ParameterUpdate)],
    seed=42)


def test_async_ps_chaos_deterministic_and_converges(ps_fixture, lock_witness):
    """THE acceptance test (ISSUE 2): drop=0.1 + dup=0.05, 2 workers,
    in-process transport, 3 runs in a row — training reaches the fault-free
    loss corridor and the fault log is byte-identical across runs."""
    clean, _, _ = _run_ps_world(ps_fixture, plan=None)
    clean_final = np.mean([np.mean(l[-6:]) for l in clean.values()])

    logs, finals = [], []
    for _run in range(3):
        results, log, server = _run_ps_world(ps_fixture, plan=_ACCEPTANCE_PLAN)
        assert np.isfinite(server.central).all()
        logs.append(log.lines())
        finals.append(np.mean([np.mean(l[-6:]) for l in results.values()]))
        for losses in results.values():
            assert np.mean(losses[-6:]) < np.mean(losses[:6]), losses
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "fault log not byte-identical across runs")
    # at this cadence the plan must actually have fired both fault kinds
    assert "drop" in logs[0] and "dup" in logs[0]
    for final in finals:
        assert abs(final - clean_final) < 0.45, (final, clean_final)


def test_async_ps_reliable_applies_each_push_exactly_once(ps_fixture):
    """With the reliability layer negotiated on every rank, the server
    applies each GradientUpdate exactly once even though the wire drops,
    duplicates and corrupts frames (corrupt applied raw would poison the
    central vector; CRC + retry must launder it)."""
    plan = ChaosPlan([FaultRule(drop=0.15, dup=0.10, corrupt=0.10)], seed=13)
    results, _, server = _run_ps_world(ps_fixture, plan=plan, reliable=True)
    # per worker: pushes fire on idx 0,4,8,12 plus the finish() flush
    expected = 2 * (len(range(0, _STEPS, 4)) + 1)
    assert server.message_counts[MessageCode.GradientUpdate] == expected
    assert np.isfinite(server.central).all()
    for losses in results.values():
        assert np.mean(losses[-6:]) < np.mean(losses[:6])


def test_worker_crash_is_detected_reaped_and_rejoin_resumes(ps_fixture):
    """A worker that goes silent mid-epoch is declared failed (its slot no
    longer blocks termination), and a rejoining replacement adopts the
    server's central params and completes the world."""
    x, y, grad_fn, params0 = ps_fixture
    world = InProcessTransport.create_world(3)
    fw, _log = FaultyTransport.wrap_world(world, ChaosPlan())
    server = ParameterServer(
        params=np.asarray(ravel_model_params(params0)),
        transport=fw[0], n_workers=2, worker_timeout=1.0)
    server_thread = threading.Thread(target=server.run,
                                     kwargs={"timeout": 120})
    server_thread.start()

    # worker 1: healthy (heartbeats carry liveness while it waits), and it
    # finishes only after the victim is reaped — so the server's clean exit
    # genuinely required the failure detector
    from distributed_ml_pytorch_tpu.utils.failure import HeartbeatSender

    hb = HeartbeatSender(fw[1], interval=0.2)
    hb.start()
    release = threading.Event()

    def healthy():
        params = jax.tree.map(jnp.asarray, params0)
        opt = Asynchronous(params, lr=0.05, n_push=2, n_pull=2,
                           transport=fw[1])
        for step in range(6):
            sel = np.random.default_rng(step).integers(0, len(x), _BATCH)
            _loss, grads = grad_fn(params, x[sel], y[sel],
                                   jax.random.fold_in(jax.random.key(1), step))
            params = opt.step(params, grads)
        release.wait(60)
        opt.finish()

    h = threading.Thread(target=healthy)
    h.start()

    # worker 2: pushes once, then crashes (scripted — stops speaking)
    params_v = jax.tree.map(jnp.asarray, params0)
    victim = Asynchronous(params_v, lr=0.05, n_push=1, n_pull=1,
                          transport=fw[2])
    sel = np.random.default_rng(99).integers(0, len(x), _BATCH)
    _loss, grads = grad_fn(params_v, x[sel], y[sel], jax.random.key(2))
    victim.step(params_v, grads)
    victim._flusher.drain()
    victim.listener.stop()
    fw[2].crash()

    deadline = time.monotonic() + 30
    while 2 not in server.failed_workers and time.monotonic() < deadline:
        time.sleep(0.05)
    assert 2 in server.failed_workers, "silent worker never declared failed"

    # rejoin: a replacement on the victim's rank adopts the central params
    fw[2].restart()
    rejoiner = Asynchronous(jax.tree.map(jnp.asarray, params0), lr=0.05,
                            n_push=2, n_pull=2, transport=fw[2],
                            rejoin=True, install_timeout=30.0)
    assert rejoiner.listener.wait_for_update(30.0), "rejoin pull unanswered"
    params = jax.tree.map(jnp.asarray, params0)
    for step in range(4):
        sel = np.random.default_rng(7 + step).integers(0, len(x), _BATCH)
        _loss, grads = grad_fn(params, x[sel], y[sel],
                               jax.random.fold_in(jax.random.key(3), step))
        params = rejoiner.step(params, grads)
    rejoiner.finish()
    release.set()
    h.join(timeout=120)
    hb.stop()
    server_thread.join(timeout=60)
    assert not server_thread.is_alive(), "server did not exit after rejoin"
    assert 2 not in server.failed_workers  # the rejoin cleared the reap
    for t in fw.values():
        t.close()


def test_server_crash_restart_restores_vector_and_version(ps_fixture, tmp_path):
    """Satellite: the ParameterServer crash→restart path end-to-end over a
    transport — a restarted server resumes the persisted central vector AND
    version, and a rejoining worker pulls the restored state."""
    _x, _y, _grad_fn, params0 = ps_fixture
    flat = np.asarray(ravel_model_params(params0))
    world = InProcessTransport.create_world(2)
    server = ParameterServer(params=flat.copy(), transport=world[0],
                             n_workers=1, ckpt_dir=str(tmp_path),
                             ckpt_every=1)
    delta = np.random.default_rng(0).normal(size=flat.shape).astype(np.float32)
    for _ in range(3):
        server.handle(1, MessageCode.GradientUpdate, delta)
    server.save_checkpoint()
    del server  # the crash

    restarted = ParameterServer(params=flat.copy(), transport=world[0],
                                n_workers=1, ckpt_dir=str(tmp_path))
    assert restarted.maybe_restore()
    np.testing.assert_allclose(restarted.central, flat + 3 * delta,
                               rtol=1e-4, atol=1e-5)
    assert restarted.staleness.version == 3       # the version survived
    assert restarted._push_count == 3
    # a reattaching worker pulls exactly the restored vector
    restarted.handle(1, MessageCode.ParameterRequest, np.zeros(0, np.float32))
    msg = world[1].recv(timeout=5)
    assert msg is not None and msg[1] == MessageCode.ParameterUpdate
    np.testing.assert_allclose(msg[2], restarted.central, rtol=1e-6)
    # and a fresh (non-rejoin) install cannot stomp the restored state
    restarted.handle(1, MessageCode.ParameterUpdate, np.zeros_like(flat))
    np.testing.assert_allclose(restarted.central, flat + 3 * delta,
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# soak variants (slow): heavier fault mix, longer runs
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_reliable_ps_survives_heavy_chaos(ps_fixture):
    """Soak: reliability layer + heavy chaos (drop/dup/corrupt/reorder on
    every code incl. the envelope) still yields exactly-once application
    and convergence."""
    plan = ChaosPlan(
        [FaultRule(drop=0.25, dup=0.15, corrupt=0.15, reorder=0.10)],
        seed=1234)
    results, _, server = _run_ps_world(
        ps_fixture, plan=plan, reliable=True, n_push=2, n_pull=2)
    expected = 2 * (len(range(0, _STEPS, 2)) + 1)
    assert server.message_counts[MessageCode.GradientUpdate] == expected
    assert np.isfinite(server.central).all()
    for losses in results.values():
        assert np.mean(losses[-6:]) < np.mean(losses[:6])


@pytest.mark.slow
def test_soak_chaos_log_three_seeds_deterministic(ps_fixture):
    """Soak: determinism is a property of the machinery, not one lucky
    seed — three different plans each produce byte-identical logs twice."""
    for seed in (1, 2, 3):
        plan = ChaosPlan(
            [FaultRule(code=int(c), drop=0.2, dup=0.1)
             for c in (MessageCode.GradientUpdate,
                       MessageCode.ParameterRequest,
                       MessageCode.ParameterUpdate)],
            seed=seed)
        _, log_a, _ = _run_ps_world(ps_fixture, plan=plan)
        _, log_b, _ = _run_ps_world(ps_fixture, plan=plan)
        assert log_a.lines() == log_b.lines() and log_a.lines()


# ---------------------------------------------------------------------------
# serving: streams under chaos (the acceptance test's serving half)
# ---------------------------------------------------------------------------

SERVE_VOCAB = 64


@pytest.fixture(scope="module")
def lm_and_params():
    from distributed_ml_pytorch_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=SERVE_VOCAB, d_model=32, n_heads=4,
                          n_layers=2, d_ff=64, max_len=128)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _serve_world(lm_and_params, plan=None, **frontend_kw):
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine
    from distributed_ml_pytorch_tpu.serving.frontend import ServingFrontend

    model, params = lm_and_params
    engine = ServingEngine(model, params, slots=2, cache_size=64,
                           decode_block=4, prefill_bucket=8)
    world = InProcessTransport.create_world(2)
    log = None
    hub = world[0]
    if plan is not None:
        log = ChaosLog()
        hub = FaultyTransport(world[0], plan, log=log)
    frontend = ServingFrontend(engine, hub, **frontend_kw)
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    return engine, world, frontend, thread, log


def _teardown_serve(world, frontend, thread):
    frontend.stop()
    thread.join(timeout=10)
    for t in world.values():
        t.close()


def test_serving_stream_token_identical_under_frame_loss(lm_and_params):
    """Acceptance (serving half): with seeded loss injected on StreamTokens
    frames, the client-driven resume protocol recovers every gap and the
    collected stream is token-identical to a standalone generate()."""
    from distributed_ml_pytorch_tpu.models.generate import generate
    from distributed_ml_pytorch_tpu.serving.frontend import ServingClient

    model, params = lm_and_params
    # seed 29 drops stream frames #0, #1, #3, #4 on this channel — the
    # resume path must recover the very first frame and mid-stream gaps
    plan = ChaosPlan(
        [FaultRule(code=int(MessageCode.StreamTokens), drop=0.3)], seed=29)
    engine, world, frontend, thread, log = _serve_world(lm_and_params,
                                                        plan=plan)
    try:
        client = ServingClient(world[1], resume_after=0.25)
        prompt = np.random.default_rng(0).integers(0, SERVE_VOCAB, size=5)
        tokens = client.generate(prompt, 14, timeout=120.0)
        want = np.asarray(
            generate(model, params, jnp.asarray(prompt, jnp.int32)[None], 14)
        )[0, 5:].tolist()
        assert tokens == want
        # a sampled stream survives loss identically (per-request rng rides
        # the submit frame, not the stream)
        tokens_s = client.generate(prompt, 10, temperature=0.8, top_k=8,
                                   seed=3, timeout=120.0)
        want_s = np.asarray(generate(
            model, params, jnp.asarray(prompt, jnp.int32)[None], 10,
            temperature=0.8, top_k=8, rng=jax.random.key(3)))[0, 5:].tolist()
        assert tokens_s == want_s
        assert log.counts().get("drop", 0) > 0, "no frame loss ever injected"
    finally:
        _teardown_serve(world, frontend, thread)


def test_serving_silent_client_is_reaped_and_state_freed(lm_and_params):
    """Satellite (stream-state leak): a client that submits and then goes
    silent past the deadline gets its request cancelled, slot evicted, and
    route/history freed — nothing leaks engine-side."""
    from distributed_ml_pytorch_tpu.serving.frontend import ServingFrontend
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode as MC,
    )
    from distributed_ml_pytorch_tpu.serving.frontend import encode_submit

    model, params = lm_and_params
    engine = ServingEngine(model, params, slots=2, cache_size=64,
                           decode_block=4, prefill_bucket=8)
    world = InProcessTransport.create_world(2)
    frontend = ServingFrontend(engine, world[0], client_deadline=0.2,
                               done_ttl=0.2)
    try:
        # no serve loop: drive scheduling by hand so the timeline is exact
        world[1].send(MC.SubmitRequest,
                      encode_submit(1, np.arange(4), 40), dst=0)
        deadline = time.monotonic() + 5
        while not frontend._routes and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(frontend._routes) == 1
        time.sleep(0.3)  # client goes silent past the deadline
        frontend._sweep(time.monotonic())
        assert frontend.reaped == 1
        engine.run_until_idle()  # cancellation drains queue + slots
        assert all(r is None for r in engine._slot_req)
        with engine._lock:
            assert not engine._queue
        # the done route ages out after done_ttl — history freed too
        time.sleep(0.3)
        frontend._sweep(time.monotonic())
        assert not frontend._routes and not frontend._by_client
    finally:
        frontend.stop()
        for t in world.values():
            t.close()


def test_serving_reconnect_and_resume_by_request_id(lm_and_params):
    """A client that consumed part of a stream and went away (reconnect)
    reattaches by request id and receives exactly the remainder."""
    from distributed_ml_pytorch_tpu.models.generate import generate
    from distributed_ml_pytorch_tpu.serving.frontend import ServingClient

    model, params = lm_and_params
    engine, world, frontend, thread, _ = _serve_world(lm_and_params)
    try:
        prompt = np.random.default_rng(1).integers(0, SERVE_VOCAB, size=6)
        want = np.asarray(
            generate(model, params, jnp.asarray(prompt, jnp.int32)[None], 12)
        )[0, 6:].tolist()

        first_client = ServingClient(world[1], resume_after=0.25)
        rid = first_client.submit(prompt, 12)
        it = first_client.stream(rid, timeout=60.0)
        head = [next(it) for _ in range(3)]
        it.close()  # the client vanishes mid-stream

        # ...and reconnects (same transport rank) later, resuming by id
        second_client = ServingClient(world[1], resume_after=0.25)
        second_client.resume_from(rid, n_have=len(head))
        tail = list(second_client.stream(rid, timeout=60.0, n_have=len(head)))
        assert head + tail == want
    finally:
        _teardown_serve(world, frontend, thread)


def test_serving_resume_unknown_request_rejected(lm_and_params):
    from distributed_ml_pytorch_tpu.serving.frontend import (
        RequestRejected,
        ServingClient,
    )

    engine, world, frontend, thread, _ = _serve_world(lm_and_params)
    try:
        client = ServingClient(world[1], resume_after=0.25)
        client.resume_from(12345, n_have=0)
        with pytest.raises(RequestRejected):
            list(client.stream(12345, timeout=20.0))
    finally:
        _teardown_serve(world, frontend, thread)


@pytest.mark.slow
def test_soak_serving_heavy_loss_many_requests(lm_and_params):
    """Soak: heavier loss (incl. dup + reorder on stream frames), several
    interleaved greedy/sampled requests — all streams exact."""
    from distributed_ml_pytorch_tpu.models.generate import generate
    from distributed_ml_pytorch_tpu.serving.frontend import ServingClient

    model, params = lm_and_params
    plan = ChaosPlan(
        [FaultRule(code=int(MessageCode.StreamTokens), drop=0.4, dup=0.2,
                   reorder=0.2)],
        seed=99)
    engine, world, frontend, thread, log = _serve_world(lm_and_params,
                                                        plan=plan)
    try:
        client = ServingClient(world[1], resume_after=0.25)
        rng = np.random.default_rng(4)
        jobs = []
        for i in range(5):
            prompt = rng.integers(0, SERVE_VOCAB, size=int(rng.integers(2, 8)))
            sampled = bool(i % 2)
            kw = (dict(temperature=0.7, top_k=8, seed=i) if sampled else {})
            rid = client.submit(prompt, 10, **kw)
            jobs.append((rid, prompt, kw))
        for rid, prompt, kw in jobs:
            got = list(client.stream(rid, timeout=180.0))
            gen_kw = dict(kw)
            if gen_kw:
                gen_kw["rng"] = jax.random.key(gen_kw.pop("seed"))
            want = np.asarray(generate(
                model, params, jnp.asarray(prompt, jnp.int32)[None], 10,
                **gen_kw))[0, len(prompt):].tolist()
            assert got == want, (rid, got, want)
        assert log.counts().get("drop", 0) > 0
    finally:
        _teardown_serve(world, frontend, thread)
