"""bf16 smoke across every parallelism path: one train step in bfloat16
activations must produce a finite loss and finite params on the virtual
mesh. Guards the class of dtype bug where integer-like bookkeeping (slot
counts, positions, masks) silently degrades in half precision — found once
in MoE routing (cumsum slot collisions past 256) and now fenced for every
mode."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.models import TransformerLM
from distributed_ml_pytorch_tpu.models.moe import MoETransformerLM
from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
    create_lm_train_state,
    next_token_targets,
    shard_lm_batch,
)
from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh


def _tokens(b=4, s=512, vocab=64, seed=0):
    tokens = np.random.default_rng(seed).integers(0, vocab, size=(b, s)).astype(np.int32)
    return tokens, next_token_targets(tokens)


def _finite(tree):
    return all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(tree))


def _lm(**kw):
    cfg = dict(vocab_size=64, d_model=32, n_heads=8, n_layers=2, d_ff=64,
               max_len=1024, dtype=jnp.bfloat16)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.mark.parametrize("mode", ["sp", "ulysses"])
def test_bf16_sequence_parallel_long_seq(mode):
    """512-token sequences: long enough that bf16 bookkeeping bugs past the
    256-integer boundary would surface."""
    from distributed_ml_pytorch_tpu.parallel.seq_parallel import make_sp_train_step
    from distributed_ml_pytorch_tpu.parallel.ulysses import make_ulysses_train_step

    mesh = make_mesh({"data": 2, "seq": 4})
    lm = _lm()
    tx = optax.sgd(0.01)
    state = create_lm_train_state(lm, jax.random.key(0), tx)
    tokens, targets = _tokens()
    tok, tgt = shard_lm_batch(mesh, tokens, targets)
    make = make_sp_train_step if mode == "sp" else make_ulysses_train_step
    state, loss = make(lm, tx, mesh)(state, tok, tgt)
    assert np.isfinite(float(loss)) and _finite(state.params)


def test_bf16_tensor_parallel():
    from distributed_ml_pytorch_tpu.parallel.tensor_parallel import (
        create_tp_train_state,
        make_tp_train_step,
        shard_tp_batch,
    )

    mesh = make_mesh({"data": 2, "model": 4})
    lm = _lm()
    tx = optax.sgd(0.01)
    state = create_tp_train_state(lm, jax.random.key(1), tx, mesh)
    tokens, targets = _tokens(s=64)
    tok, tgt = shard_tp_batch(mesh, tokens, targets)
    state, loss = make_tp_train_step(lm, tx, mesh)(state, tok, tgt)
    assert np.isfinite(float(loss)) and _finite(state.params)


def test_bf16_fsdp_and_composite():
    from distributed_ml_pytorch_tpu.parallel.composite import (
        create_composite_train_state,
        make_composite_train_step,
        shard_composite_batch,
    )
    from distributed_ml_pytorch_tpu.parallel.fsdp import (
        create_fsdp_train_state,
        make_fsdp_lm_train_step,
        shard_fsdp_batch,
    )
    from distributed_ml_pytorch_tpu.training.trainer import TrainState

    lm = _lm()
    tx = optax.sgd(0.01)
    tokens, targets = _tokens(b=8, s=64)

    mesh = make_mesh({"data": 8})

    def init_fn(key):
        params = lm.init(key, jnp.zeros((1, 8), jnp.int32))["params"]
        return TrainState.create(params, tx)

    state, shardings = create_fsdp_train_state(init_fn, jax.random.key(2), mesh)
    tok, tgt = shard_fsdp_batch(mesh, tokens, targets)
    state, loss = make_fsdp_lm_train_step(lm, tx, mesh, shardings)(state, tok, tgt)
    assert np.isfinite(float(loss)) and _finite(state.params)

    cmesh = make_mesh({"data": 2, "fsdp": 2, "model": 2})
    cstate, cshard = create_composite_train_state(lm, jax.random.key(3), tx, cmesh)
    ctok, ctgt = shard_composite_batch(cmesh, tokens, targets)
    cstate, closs = make_composite_train_step(lm, tx, cmesh, cshard)(cstate, ctok, ctgt)
    assert np.isfinite(float(closs)) and _finite(cstate.params)


def test_bf16_moe_long_seq_no_slot_collisions():
    """The regression that motivated this file: bf16 MoE at seq 512 with
    top-2 routing — >256 assignments per expert queue. Every kept assignment
    must land in a distinct slot (dispatch is one-hot per (expert, slot))."""
    from distributed_ml_pytorch_tpu.models.moe import topk_route

    b, s, e = 1, 512, 2
    probs = jax.nn.softmax(
        jnp.asarray(
            np.random.default_rng(4).normal(size=(b, s, e)).astype(np.float32)
        ),
        axis=-1,
    ).astype(jnp.bfloat16)
    capacity = 2 * 2 * s // e  # cf=2, k=2 provisioning: ample
    dispatch, _ = topk_route(probs, capacity=capacity, k=2)
    d = np.asarray(dispatch, np.float32)  # [B,S,E,C]
    # every slot holds at most one token
    per_slot = d.sum(axis=1)  # [B,E,C]
    assert per_slot.max() <= 1.0 + 1e-6, f"slot collision: {per_slot.max()}"
    # and nothing dropped at this capacity: all 2*s assignments dispatched
    assert d.sum() == pytest.approx(2 * s, abs=1e-3)
