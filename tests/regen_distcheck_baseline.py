"""Regenerate tests/distcheck_baseline.txt — the known-findings cut for
``make lint`` (mirrors the slow_tests.txt workflow: the cut is data).

Runs the distcheck analyzer over the installed package, collects the
baseline keys of every ACTIVE finding (suppressed ones never enter the
baseline — they are already explained in-line), and rewrites the file:

    python tests/regen_distcheck_baseline.py

The intended steady state is an EMPTY baseline: every finding either
fixed or suppressed with a reason at the site. The baseline exists so an
emergency landing with a known finding does not wedge CI — regenerate,
land, then burn the entry down. tests/test_distcheck.py asserts the real
package produces no findings beyond this file.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "distcheck_baseline.txt")

HEADER = """# Known distcheck findings carried by `make lint` (GENERATED — do not
# hand-edit; regenerate with `python tests/regen_distcheck_baseline.py`).
#
# Keys are line-number-free: `path | CODE | message`. The healthy state of
# this file is EMPTY below this header — fix findings or suppress them at
# the site with `# distcheck: ignore[DCnnn] <reason>`; park one here only
# to unwedge CI, then burn it down.
"""


def main() -> int:
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_ml_pytorch_tpu.analysis",
         "--keys"],
        cwd=os.path.dirname(HERE), capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if proc.returncode not in (0, 1):
        sys.stderr.write(proc.stderr)
        return proc.returncode
    keys = [line for line in proc.stdout.splitlines() if line.strip()]
    with open(OUT, "w") as fh:
        fh.write(HEADER)
        for key in keys:
            fh.write(key + "\n")
    print(f"wrote {OUT} ({len(keys)} baselined finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
