"""Multi-controller rendezvous: ``runtime.initialize_distributed`` must form
a real multi-process world on localhost — the parity test for the reference's
``dist.init_process_group('gloo', rank, world_size)`` TCP rendezvous
(``example/main.py:163-165``).

What can and cannot be validated on this hardware, explicitly: the
coordination service (rendezvous, barriers, key-value exchange — the DCN
control plane) is fully exercised across real processes below. Cross-process
*device* collectives are the TPU runtime's job (ICI/DCN under XLA) and this
CPU build does not federate devices across processes — those paths are
covered by the in-process 8-device virtual mesh tests and by
``dryrun_multichip``.
"""

import subprocess
import sys
import textwrap

from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env

WORKER = textwrap.dedent(
    """
    import sys
    proc, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from distributed_ml_pytorch_tpu.runtime.mesh import initialize_distributed
    initialize_distributed(f"localhost:{port}", num_processes=n, process_id=proc)

    import jax
    from jax._src import distributed

    client = distributed.global_state.client
    assert jax.process_index() == proc, (jax.process_index(), proc)
    assert jax.process_count() == n, (jax.process_count(), n)

    # cross-process key-value exchange through the coordinator (the control
    # plane the PS transports' rendezvous maps onto at pod scale)
    client.key_value_set(f"hello/{proc}", f"from-{proc}")
    client.wait_at_barrier("bootstrap-test", 20_000)
    for peer in range(n):
        got = client.key_value_try_get(f"hello/{peer}")
        assert got == f"from-{peer}", (peer, got)
    print(f"OK proc={proc}", flush=True)
    """
)


def test_two_process_rendezvous_barrier_and_kv():
    port = _free_port()
    env = cpu_platform_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(rank), "2", port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for rank in range(2)
    ]
    outs = [p.communicate(timeout=110)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"OK proc={rank}" in out, out
