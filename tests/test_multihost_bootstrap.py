"""Multi-controller rendezvous AND cross-process device collectives: the
parity tests for the reference's ``dist.init_process_group('gloo', rank,
world_size)`` TCP rendezvous and its inter-process tensor traffic
(``example/main.py:163-165``).

Two layers, both across REAL processes on localhost:

- the coordination service (rendezvous, barriers, key-value exchange — the
  DCN control plane), and
- the data plane: ``test_two_process_sync_dp_matches_in_process`` runs the
  framework's actual sync-DP train step over a 2-process mesh with JAX's
  cross-process CPU collectives (``jax_cpu_collectives_implementation =
  'gloo'`` — literally the same transport family the reference's
  ``init_process_group('gloo')`` used), each process feeding half the
  global batch, and checks the psum'd result against the identical step on
  an in-process 2-device mesh.
"""

import subprocess
import sys
import textwrap

import numpy as np

from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env

WORKER = textwrap.dedent(
    """
    import sys
    proc, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from distributed_ml_pytorch_tpu.runtime.mesh import initialize_distributed
    initialize_distributed(f"localhost:{port}", num_processes=n, process_id=proc)

    import jax
    from jax._src import distributed

    client = distributed.global_state.client
    assert jax.process_index() == proc, (jax.process_index(), proc)
    assert jax.process_count() == n, (jax.process_count(), n)

    # cross-process key-value exchange through the coordinator (the control
    # plane the PS transports' rendezvous maps onto at pod scale)
    client.key_value_set(f"hello/{proc}", f"from-{proc}")
    client.wait_at_barrier("bootstrap-test", 20_000)
    for peer in range(n):
        got = client.key_value_try_get(f"hello/{peer}")
        assert got == f"from-{peer}", (peer, got)
    print(f"OK proc={proc}", flush=True)
    """
)


def test_two_process_rendezvous_barrier_and_kv():
    port = _free_port()
    env = cpu_platform_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(rank), "2", port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for rank in range(2)
    ]
    outs = [p.communicate(timeout=110)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"OK proc={rank}" in out, out

SYNC_DP_WORKER = textwrap.dedent(
    """
    import sys
    proc, n, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                               sys.argv[3], sys.argv[4])
    import jax
    # the reference's gloo process group, recast as JAX's cross-process CPU
    # collectives: XLA psum/ppermute now move real tensors BETWEEN processes
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from distributed_ml_pytorch_tpu.runtime.mesh import initialize_distributed
    initialize_distributed(f"localhost:{port}", num_processes=n, process_id=proc)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.parallel.sync import make_sync_train_step
    from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh
    from distributed_ml_pytorch_tpu.training.trainer import create_train_state

    assert jax.process_count() == n and len(jax.devices()) == n
    mesh = make_mesh({"data": n})

    model = LeNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    # identical on every process (same seed) -> replicated placement is legal
    rep = NamedSharding(mesh, P())
    state = jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(rep, np.asarray(a)),
        state,
    )
    rng = jax.make_array_from_process_local_data(
        rep, np.asarray(jax.random.PRNGKey(1))
    )

    data = np.random.default_rng(7)
    xb = data.normal(size=(16, 32, 32, 3)).astype(np.float32)
    yb = data.integers(0, 10, 16).astype(np.int32)
    # THIS process holds only its 1/n share of the global batch
    share = slice(proc * (16 // n), (proc + 1) * (16 // n))
    gx = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), xb[share])
    gy = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), yb[share])

    step = make_sync_train_step(model, tx, mesh)
    state, loss = step(state, gx, gy, rng)
    state, loss = step(state, gx, gy, rng)  # 2 steps: grads flowed both ways
    loss = float(loss)  # replicated output: addressable on every process
    leaves = [np.asarray(l) for l in jax.tree.leaves(state.params)]
    if proc == 0:
        np.savez(out_path, loss=loss, *leaves)
    print(f"SYNC-DP-OK proc={proc} loss={loss:.6f}", flush=True)
    """
)


def _run_sync_dp_world(n, tmp_path, timeout):
    """Launch n real processes running the sync-DP worker (1/n of the global
    batch each, psum over gloo) and compare rank 0's result against the
    identical compiled step on an in-process n-device mesh."""
    port = _free_port()
    out_path = str(tmp_path / "rank0.npz")
    env = cpu_platform_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", SYNC_DP_WORKER, str(rank), str(n), port,
             out_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for rank in range(n)
    ]
    outs = [p.communicate(timeout=timeout)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"SYNC-DP-OK proc={rank}" in out, out
    # every process computed the same replicated loss
    losses = {o.split("loss=")[1].split()[0] for o in outs}
    assert len(losses) == 1, losses

    # in-process reference: the identical step on n virtual devices
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.parallel.sync import (
        make_sync_train_step,
        put_sharded,
        replicate,
    )
    from distributed_ml_pytorch_tpu.training.trainer import create_train_state

    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    model = LeNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    state = replicate(mesh, state)
    rng = replicate(mesh, jax.random.PRNGKey(1))
    data = np.random.default_rng(7)
    xb = data.normal(size=(16, 32, 32, 3)).astype(np.float32)
    yb = data.integers(0, 10, 16).astype(np.int32)
    gx = put_sharded(mesh, xb, P("data"))
    gy = put_sharded(mesh, yb, P("data"))
    step = make_sync_train_step(model, tx, mesh)
    state, loss = step(state, gx, gy, rng)
    state, loss = step(state, gx, gy, rng)

    got = np.load(out_path)
    assert abs(float(got["loss"]) - float(loss)) < 1e-6
    ref_leaves = [np.asarray(l) for l in jax.tree.leaves(state.params)]
    cross_leaves = [got[f"arr_{i}"] for i in range(len(ref_leaves))]
    for a, b in zip(ref_leaves, cross_leaves):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_two_process_sync_dp_matches_in_process(tmp_path):
    """The reference's 3-process gloo world moved real tensors between
    processes; this runs the framework's sync-DP data plane across 2 real
    processes (half the global batch each, psum over gloo) and requires the
    result to match the same compiled step on an in-process 2-device mesh."""
    _run_sync_dp_world(2, tmp_path, timeout=240)


def test_four_process_sync_dp_matches_in_process(tmp_path):
    """VERDICT r4 #7: past the reference's 3-process world — 4 real
    processes, quarter-batches each, one gloo psum data plane; result must
    match the in-process 4-device step exactly."""
    _run_sync_dp_world(4, tmp_path, timeout=360)
