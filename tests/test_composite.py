"""3-D composite sharding (data × fsdp × tensor) on the 8-device virtual mesh.

The whole strategy is one spec tree; correctness means the 2×2×2-sharded
training trajectory is numerically the unsharded one, while parameters are
genuinely distributed over fsdp×model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_ml_pytorch_tpu.models import TransformerLM
from distributed_ml_pytorch_tpu.parallel.composite import (
    composite_specs,
    create_composite_train_state,
    make_composite_train_step,
    shard_composite_batch,
)
from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets
from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh
from distributed_ml_pytorch_tpu.training.trainer import TrainState


@pytest.fixture(scope="module")
def mesh222():
    return make_mesh({"data": 2, "fsdp": 2, "model": 2})


def _lm():
    return TransformerLM(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=64)


def test_composite_specs_merge_tp_and_fsdp():
    tree = {
        "attn_q": jnp.zeros((32, 32)),   # tp: P(None, model) → fsdp takes dim 0
        "ln_scale": jnp.zeros((32,)),    # tp: P() → fsdp takes dim 0
        "tiny": jnp.zeros((3,)),         # nothing divisible → untouched
    }
    # fake tp specs by path rules won't trigger on these names → all P();
    # the merge rule alone is what's under test for ln_scale/tiny
    specs = composite_specs(tree, fsdp_size=2)
    assert specs["ln_scale"] == P("fsdp")
    assert specs["tiny"] == P()
    # attn_q has no 'attn' path component here, so tp leaves it replicated
    # and fsdp shards its largest dim (ties → trailing dim)
    assert specs["attn_q"] == P(None, "fsdp")


def test_composite_step_matches_single_device(mesh222):
    lm = _lm()
    tx = optax.sgd(0.05, momentum=0.9)
    state_c, shardings = create_composite_train_state(
        lm, jax.random.key(0), tx, mesh222
    )

    def init_fn(rng):
        params = lm.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]
        return TrainState.create(params, tx)

    state_s = init_fn(jax.random.key(0))

    tokens = np.random.default_rng(0).integers(0, 64, size=(8, 32)).astype(np.int32)
    targets = next_token_targets(tokens)

    comp_step = make_composite_train_step(lm, tx, mesh222, shardings)

    @jax.jit
    def single_step(state, tokens, targets):
        def loss_fn(params):
            logits = lm.apply({"params": params}, tokens)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
            mask = jnp.ones_like(ce).at[:, -1].set(0.0)
            return jnp.sum(ce * mask) / jnp.sum(mask)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(params=params, opt_state=opt_state,
                             step=state.step + 1), loss

    tok_c, tgt_c = shard_composite_batch(mesh222, tokens, targets)
    for _ in range(3):
        state_s, loss_s = single_step(state_s, tokens, targets)
        state_c, loss_c = comp_step(state_c, tok_c, tgt_c)
        np.testing.assert_allclose(float(loss_s), float(loss_c), rtol=2e-5)

    for a, b in zip(jax.tree.leaves(state_s.params), jax.tree.leaves(state_c.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=2e-6)


def test_composite_params_are_distributed(mesh222):
    """Every tp-ruled 2-D kernel (attn projections, MLP denses, lm_head) must
    be sharded on BOTH the fsdp and model axes (4× memory reduction); every
    other large leaf must at least be fsdp-sharded."""
    lm = _lm()
    state, shardings = create_composite_train_state(
        lm, jax.random.key(1), optax.sgd(0.1), mesh222
    )
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    checked_both = 0
    for path, leaf in flat:
        if getattr(leaf, "ndim", 0) != 2 or leaf.shape[0] < 32:
            continue
        joined = "/".join(getattr(k, "key", str(k)) for k in path)
        spec = leaf.sharding.spec
        names = {s for s in spec if s is not None}
        assert "fsdp" in names, f"{joined} not fsdp-sharded: {spec}"
        if any(t in joined for t in ("attn", "Dense_", "lm_head")):
            assert "model" in names, f"{joined} lost its tp sharding: {spec}"
            checked_both += 1
    assert checked_both >= 4  # q/k/v/o + MLP pairs + lm_head across blocks
