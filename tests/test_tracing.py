"""Tracing/profiling subsystem: step timing stats + bounded trace windows."""

import os
import time

import jax
import jax.numpy as jnp

from distributed_ml_pytorch_tpu.utils.tracing import StepTimer, TraceWindow


def test_step_timer_excludes_warmup_and_reports_stats():
    t = StepTimer(skip=2, items_per_step=64)
    for i in range(6):
        t.start()
        time.sleep(0.01)
        t.tick()
    s = t.summary()
    # 6 intervals seen, first 2 skipped as warmup
    assert s["steps"] == 4
    assert 5.0 <= s["mean_ms"] <= 100.0
    assert s["p50_ms"] <= s["p99_ms"] * 1.0001
    assert s["items_per_sec"] > 0
    assert "items/s" in t.report()


def test_step_timer_empty_reports_none():
    assert StepTimer().summary() is None
    assert StepTimer().report() is None


def test_step_timer_tick_without_start_records_nothing():
    t = StepTimer(skip=0)
    t.tick()  # no start(): must not record an interval
    assert t.summary() is None


def test_step_timer_excludes_between_step_work():
    t = StepTimer(skip=0)
    t.start()
    time.sleep(0.005)
    t.tick()
    time.sleep(0.05)  # between-steps host work: must not be timed
    t.start()
    time.sleep(0.005)
    t.tick()
    s = t.summary()
    assert s["steps"] == 2
    assert s["p99_ms"] < 40.0, "between-step gap leaked into step timing"


def test_trace_window_captures_bounded_steps(tmp_path):
    profile_dir = str(tmp_path / "trace")
    tw = TraceWindow(profile_dir, start=2, n_steps=2)
    x = jnp.ones((64, 64))
    f = jax.jit(lambda a: a @ a)
    for step in range(6):
        tw.on_step(step)
        f(x).block_until_ready()
    tw.close()
    # xprof writes under <dir>/plugins/profile/<run>/
    found = []
    for root, _dirs, files in os.walk(profile_dir):
        found.extend(files)
    assert found, f"no trace files written under {profile_dir}"


def test_trace_window_closes_when_run_ends_inside_window(tmp_path):
    profile_dir = str(tmp_path / "trace2")
    tw = TraceWindow(profile_dir, start=1, n_steps=10)
    x = jnp.ones((8, 8))
    f = jax.jit(lambda a: a @ a)
    for step in range(3):  # run ends well before start+n_steps
        tw.on_step(step)
        f(x).block_until_ready()
        tw.after_step(step + 1)
    # after_step must NOT have closed early (window still open at step 3)...
    assert tw._active
    tw.close()  # ...but close() bounds it at end of run
    assert not tw._active and tw._done


def test_trace_window_after_step_bounds_exactly(tmp_path):
    tw = TraceWindow(str(tmp_path / "trace3"), start=0, n_steps=2)
    x = jnp.ones((8, 8))
    f = jax.jit(lambda a: a @ a)
    tw.on_step(0)
    f(x).block_until_ready()
    tw.after_step(1)
    assert tw._active  # window covers steps [0, 2)
    tw.on_step(1)
    f(x).block_until_ready()
    tw.after_step(2)
    assert not tw._active and tw._done  # closed the moment step 1 completed


def test_trace_window_disabled_is_noop(tmp_path):
    tw = TraceWindow(None)
    for step in range(5):
        tw.on_step(step)
    tw.close()  # must not raise or write
    tw.warn_if_never_opened()  # disabled: stays silent


def test_trace_window_warns_when_never_reached(capsys):
    tw = TraceWindow("/tmp/unused-trace-dir", start=100, n_steps=10)
    for step in range(3):
        tw.on_step(step)
    tw.close()
    tw.warn_if_never_opened()
    err = capsys.readouterr().err
    assert "never reached" in err


def test_trace_window_opens_when_chunk_strides_over_it(tmp_path):
    profile_dir = str(tmp_path / "trace4")
    tw = TraceWindow(profile_dir, start=10, n_steps=10)
    x = jnp.ones((8, 8))
    f = jax.jit(lambda a: a @ a)
    tw.on_step(0, n_steps=32)  # chunk [0, 32) strides over [10, 20)
    assert tw._active
    f(x).block_until_ready()
    tw.after_step(32)
    assert tw._done


def test_step_timer_tick_n_drops_warmup_chunks():
    t = StepTimer(skip=2)
    t.start()
    time.sleep(0.05)  # "compile" chunk: includes warmup steps → dropped whole
    t.tick_n(8)
    assert t.summary() is None
    t.start()
    time.sleep(0.008)
    t.tick_n(4)  # steady chunk: all 4 recorded at dt/4 each
    s = t.summary()
    assert s["steps"] == 4
    assert s["mean_ms"] < 10.0, "compile time leaked into steady-state stats"
