"""Ring attention on the 8-device virtual CPU mesh vs. full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_ml_pytorch_tpu.ops import attention_reference
from distributed_ml_pytorch_tpu.parallel.ring import make_ring_attention
from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh({"seq": 8})


def _qkv(b=2, h=2, s=256, d=32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(seq_mesh, causal):
    q, k, v = _qkv()
    fn = make_ring_attention(seq_mesh, "seq", causal=causal, block_k=16)
    got = fn(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_ring_attention_output_stays_sharded(seq_mesh):
    q, k, v = _qkv()
    spec = P(None, None, "seq", None)
    q = jax.device_put(q, NamedSharding(seq_mesh, spec))
    out = make_ring_attention(seq_mesh, "seq")(q, k, v)
    assert out.sharding.spec == spec


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_is_differentiable(seq_mesh, causal):
    q, k, v = _qkv(b=1, h=1, s=64, d=16)
    fn = make_ring_attention(seq_mesh, "seq", causal=causal, block_k=8)

    def ring_loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-5, rtol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_full(seq_mesh, causal):
    """The flash-kernel ring (chunk-level logsumexp combine) must agree with
    dense attention — interpret-mode flash on the CPU mesh, the analog of
    the TPU path where local chunks fit the kernel blocking."""
    q, k, v = _qkv(s=1024, d=32)  # s_local = 128 = min flash block
    fn = make_ring_attention(seq_mesh, "seq", causal=causal, impl="flash")
    got = fn(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_is_differentiable(seq_mesh, causal):
    """Gradients through the flash ring: the per-chunk lse outputs carry
    cotangents (the combine weights depend on them), exercising the
    dlse→delta folding in the kernel backward."""
    q, k, v = _qkv(b=1, h=1, s=1024, d=16, seed=3)
    fn = make_ring_attention(seq_mesh, "seq", causal=causal, impl="flash")

    def ring_loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=5e-4, rtol=5e-3)


def test_ring_attention_long_context_smoke(seq_mesh):
    """8k tokens over 8 devices — each device only ever holds 1k."""
    q, k, v = _qkv(b=1, h=1, s=8192, d=32)
    out = make_ring_attention(seq_mesh, "seq", causal=True, block_k=256)(q, k, v)
    assert out.shape == (1, 1, 8192, 32)
    assert bool(jnp.isfinite(out).all())
