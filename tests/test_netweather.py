"""ISSUE 7 — the adaptive wire under network weather.

Three layers of evidence, all seeded and deterministic:

- units for the weather model itself (latency/jitter draws byte-identical
  across runs, bandwidth caps serialize a link, one-way degradation);
- units for the adaptive reliability machinery (RTT-estimated RTO climbing
  out of a spurious-retransmit storm, window/credit backpressure bounding
  pending, circuit breaker open -> half-open probe -> close, the flapping
  peer regression, cumulative-ack drain after a one-way partition heals);
- THE acceptance scenario: the 2-worker DownPour training run over a
  10x-latency + jitter + 1%-loss + bandwidth-capped wire converges in the
  fault-free corridor with a bounded resend ratio and bounded pending
  depth, and its chaos log is byte-identical across 3 runs.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models import LeNet
from distributed_ml_pytorch_tpu.parallel.async_ps import (
    Asynchronous,
    ParameterServer,
)
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosPlan,
    FaultRule,
    FaultyTransport,
    WeatherRule,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
    make_world,
)
from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

pytestmark = pytest.mark.netweather


# ---------------------------------------------------------------------------
# unit: the weather model
# ---------------------------------------------------------------------------

def _drain(t, n, timeout=10.0):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < n and time.monotonic() < deadline:
        m = t.recv(timeout=0.2)
        if m is not None:
            got.append(m)
    return got


def test_weather_latency_delays_but_delivers_and_log_is_byte_identical():
    def run():
        plan = ChaosPlan(seed=21, weather=[
            WeatherRule(latency=0.05, jitter=0.02)])
        world, log = make_world(2, plan=plan)
        t0 = time.monotonic()
        for i in range(6):
            world[1].send(MessageCode.GradientUpdate,
                          np.full(4, i, np.float32))
        got = _drain(world[0], 6)
        dt = time.monotonic() - t0
        for t in world.values():
            t.close()
        return got, dt, log.lines()

    got1, dt1, lines1 = run()
    got2, _dt2, lines2 = run()
    _got3, _dt3, lines3 = run()
    assert len(got1) == len(got2) == 6  # delayed, never lost
    assert dt1 >= 0.03  # the latency actually happened
    # the drawn per-frame latencies replay exactly: byte-identical logs
    assert lines1 and lines1 == lines2 == lines3
    assert "weather+" in lines1


def test_weather_bandwidth_cap_serializes_the_link():
    payload = np.zeros(25_000, np.float32)  # 100 KB
    plan = ChaosPlan(seed=3, weather=[
        WeatherRule(bandwidth=1_000_000)])  # 1 MB/s -> 0.1 s per frame
    world, _log = make_world(2, plan=plan)
    t0 = time.monotonic()
    for _ in range(4):
        world[1].send(MessageCode.GradientUpdate, payload)
    got = _drain(world[0], 4)
    dt = time.monotonic() - t0
    for t in world.values():
        t.close()
    assert len(got) == 4
    # 4 x 100 KB through 1 MB/s is >= ~0.4 s of transmission time
    assert dt >= 0.3, dt


def test_weather_one_way_degradation_is_directional():
    plan = ChaosPlan(seed=9, weather=[
        WeatherRule(src=1, dst=0, latency=0.15)])
    world, _log = make_world(2, plan=plan)
    # degraded direction: 1 -> 0
    t0 = time.monotonic()
    world[1].send(MessageCode.GradientUpdate, np.ones(2, np.float32))
    assert world[0].recv(timeout=5) is not None
    slow = time.monotonic() - t0
    # clean direction: 0 -> 1
    t0 = time.monotonic()
    world[0].send(MessageCode.ParameterUpdate, np.ones(2, np.float32), dst=1)
    assert world[1].recv(timeout=5) is not None
    fast = time.monotonic() - t0
    for t in world.values():
        t.close()
    assert slow >= 0.1 and fast < 0.1, (slow, fast)


def test_weather_never_perturbs_existing_fault_decisions():
    """Adding weather must not shift a plan's seeded fault stream — the
    weather draws ride a separate RNG namespace."""
    def fault_log(weather):
        plan = ChaosPlan([FaultRule(drop=0.3, dup=0.2)], seed=11,
                         weather=weather)
        world, log = make_world(2, plan=plan)
        for i in range(30):
            world[1].send(MessageCode.GradientUpdate,
                          np.full(2, i, np.float32))
        _drain(world[0], 1, timeout=1.0)
        for t in world.values():
            t.close()
        return [e for e in log.events() if e[4] in ("drop", "dup")]

    assert fault_log(()) == fault_log(
        (WeatherRule(latency=0.001, jitter=0.0005),))


# ---------------------------------------------------------------------------
# unit: adaptive RTO
# ---------------------------------------------------------------------------

def test_rto_adapts_above_weather_latency_and_retransmits_stop():
    """The RTO floor sits BELOW the link's real RTT: early frames storm
    (spurious retransmits), Karn part 2 backs the RTO off, a clean sample
    re-estimates it above the RTT, and the storm ends."""
    plan = ChaosPlan(seed=5, weather=[WeatherRule(latency=0.05)])
    world, _log = make_world(2, plan=plan, reliable=True, reliable_opts={
        "ack_timeout": 0.02, "max_backoff": 2.0})
    a, b = world[0], world[1]
    stop = threading.Event()

    def rx():
        while not stop.is_set():
            a.recv(timeout=0.2)

    t = threading.Thread(target=rx)
    t.start()
    try:
        for i in range(12):
            b.send(MessageCode.GradientUpdate, np.full(2, i, np.float32))
            time.sleep(0.05)
        assert b.flush(timeout=20), b.stats
        warm_retries = b.stats["retries"]
        assert b.rto(0) > 0.09, (
            "RTO did not adapt above the ~100 ms weather RTT: "
            f"{b.rto(0)}")
        # steady state: the adapted RTO stops the storm
        for i in range(8):
            b.send(MessageCode.GradientUpdate, np.full(2, i, np.float32))
            time.sleep(0.05)
        assert b.flush(timeout=20), b.stats
        assert b.stats["retries"] - warm_retries <= 1, b.stats
    finally:
        stop.set()
        t.join(timeout=5)
        for tr in world.values():
            tr.close()


# ---------------------------------------------------------------------------
# unit: window + credit backpressure
# ---------------------------------------------------------------------------

def test_send_window_bounds_pending_against_a_silent_receiver():
    """A receiver that consumes nothing exerts backpressure: the sender
    blocks at its window instead of queueing without bound, and drains the
    moment the receiver starts serving."""
    world = InProcessTransport.create_world(2)
    a = ReliableTransport(world[0], ack_timeout=0.2)
    b = ReliableTransport(world[1], ack_timeout=0.2, send_window=4)
    n, sent, peak = 20, [], [0]

    def tx():
        for i in range(n):
            b.send(MessageCode.GradientUpdate, np.full(2, i, np.float32))
            peak[0] = max(peak[0], b.pending_depth(0))
            sent.append(i)

    t = threading.Thread(target=tx)
    t.start()
    time.sleep(0.5)
    # the sender must be stuck at the window, not done
    assert len(sent) < n
    assert b.pending_depth(0) <= 4
    assert b.pressure() == 1.0
    assert b.stats["window_blocked"] >= 1
    got = _drain(a, n, timeout=20)  # receiver comes alive: all delivered
    t.join(timeout=20)
    assert not t.is_alive() and len(sent) == n
    assert len(got) == n
    assert peak[0] <= 4, "window failed to bound pending"
    a.close()
    b.close()


def test_advertised_credit_narrows_the_senders_window():
    world = InProcessTransport.create_world(2)
    a = ReliableTransport(world[0], ack_timeout=0.2)
    b = ReliableTransport(world[1], ack_timeout=0.2, send_window=16)
    # one exchange teaches b the credit a advertises
    b.send(MessageCode.GradientUpdate, np.ones(2, np.float32))
    assert a.recv(timeout=5) is not None
    assert b.flush(timeout=5)
    a.advertise_credit(2)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        b.recv(timeout=0.05)  # pump: the credit rides a CumAck frame
        with b._lock:
            st = b._peers.get(0)
            if st is not None and st.credit == 2:
                break
    with b._lock:
        assert b._peers[0].credit == 2
    peak = [0]

    def tx():
        for i in range(12):
            b.send(MessageCode.GradientUpdate, np.full(2, i, np.float32))
            peak[0] = max(peak[0], b.pending_depth(0))

    t = threading.Thread(target=tx)
    t.start()
    _drain(a, 12, timeout=20)
    t.join(timeout=20)
    assert not t.is_alive()
    assert peak[0] <= 2, f"credit=2 ignored: peak pending {peak[0]}"
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# unit: circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_opens_fails_fast_probes_and_recovers():
    world = InProcessTransport.create_world(2)
    b = ReliableTransport(world[1], ack_timeout=0.02, max_backoff=0.1,
                          max_retries=200, breaker_fails=3,
                          breaker_grace=0.05, breaker_cooldown=0.1)
    # no receiver wrapper on rank 0 yet: frames land in the raw mailbox,
    # nothing ever acks -> RTO blowups -> the breaker opens
    b.send(MessageCode.GradientUpdate, np.ones(2, np.float32))
    deadline = time.monotonic() + 10
    while b.breaker_state(0) != "open" and time.monotonic() < deadline:
        time.sleep(0.02)
    assert b.breaker_state(0) == "open"
    assert b.stats["breaker_opens"] >= 1
    with pytest.raises(ConnectionError):
        b.send(MessageCode.GradientUpdate, np.ones(2, np.float32))
    # the peer was truly gone: everything sent so far vanished unacked
    # (drain the raw mailbox) — only a half-open PROBE can now deliver
    # the pending frame; its ack closes the breaker and service resumes
    while world[0].recv(timeout=0.3) is not None:
        pass
    a = ReliableTransport(world[0], ack_timeout=0.05)
    got = _drain(a, 1, timeout=10)
    assert got and got[0][1] == MessageCode.GradientUpdate
    deadline = time.monotonic() + 10
    while b.breaker_state(0) != "closed" and time.monotonic() < deadline:
        b.flush(timeout=0.2)
    assert b.breaker_state(0) == "closed"
    assert b.stats["probes"] >= 1
    assert b.open_breakers() == 0
    b.send(MessageCode.GradientUpdate, np.full(2, 7.0, np.float32))
    got = _drain(a, 1, timeout=10)
    assert got and int(got[0][2][0]) == 7
    a.close()
    b.close()


def test_flapping_peer_cannot_grow_pending_without_bound():
    """ISSUE 7 satellite regression: a peer that keeps dying and reviving
    must never let the sender's pending set grow past its window — the
    flap used to be an OOM vector when pending was unbounded."""
    world = InProcessTransport.create_world(2)
    fw, _log = FaultyTransport.wrap_world(world, ChaosPlan())
    b = ReliableTransport(fw[1], ack_timeout=0.02, max_backoff=0.05,
                          max_retries=3, send_window=6)
    a = ReliableTransport(fw[0], ack_timeout=0.05)
    stop = threading.Event()
    peak = [0]

    def flapper():
        while not stop.is_set():
            fw[0].crash()
            time.sleep(0.05)
            fw[0].restart()
            time.sleep(0.05)

    def rx():
        while not stop.is_set():
            a.recv(timeout=0.1)

    threads = [threading.Thread(target=flapper), threading.Thread(target=rx)]
    for t in threads:
        t.start()
    sent = dropped = 0
    t_end = time.monotonic() + 3.0
    while time.monotonic() < t_end:
        try:
            b.send(MessageCode.GradientUpdate, np.ones(2, np.float32))
            sent += 1
        except ConnectionError:
            dropped += 1  # breaker/death fail-fast IS the bound surfacing
            time.sleep(0.01)
        peak[0] = max(peak[0], b.pending_depth(0))
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert sent > 0
    assert peak[0] <= 6, (
        f"pending grew to {peak[0]} under flap (window 6) — the OOM "
        "regression is back")
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# unit: cumulative ack after a one-way partition heals (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

def test_partition_heal_drains_pending_via_one_cumulative_ack():
    """A one-way partition (scripted deterministically as an index-windowed
    drop of the first N data frames) heals: the retransmissions deliver,
    and ONE cumulative ack drains the sender's whole pending set — no
    per-frame re-ack storm, bounded resend ratio, byte-identical logs."""
    n = 10

    def run():
        plan = ChaosPlan(
            [FaultRule(src=1, dst=0, code=int(MessageCode.ReliableFrame),
                       drop=1.0, until=n)],
            seed=17)
        world, log = make_world(2, plan=plan, reliable=True, reliable_opts={
            "ack_timeout": 0.3, "max_backoff": 2.0})
        a, b = world[0], world[1]
        stop = threading.Event()

        def rx():
            while not stop.is_set():
                a.recv(timeout=0.1)

        t = threading.Thread(target=rx)
        t.start()
        for i in range(n):
            b.send(MessageCode.GradientUpdate, np.full(2, i, np.float32))
        ok = b.flush(timeout=20)
        stop.set()
        t.join(timeout=5)
        stats = dict(b.stats), dict(a.stats)
        lines = log.lines()
        for tr in world.values():
            tr.close()
        return ok, stats, lines

    runs = [run() for _ in range(3)]
    for ok, (b_stats, a_stats), _lines in runs:
        assert ok, b_stats
        assert b_stats["acked"] == n
        # resend ratio: every original was deterministically dropped, so
        # exactly one retransmission each (the heal) — and no more
        assert b_stats["retries"] <= n + 1, b_stats
        # the drain was CUMULATIVE: no per-frame ack storm on the heal
        assert a_stats["acks_tx"] == 0, a_stats
        assert a_stats["cum_acks_tx"] <= 3, a_stats
    lines = [r[2] for r in runs]
    assert lines[0] and lines[0] == lines[1] == lines[2]
    assert lines[0].count("drop") == n


# ---------------------------------------------------------------------------
# THE acceptance: 2-worker training under 10x-latency + loss + bandwidth cap
# ---------------------------------------------------------------------------

_MODEL = LeNet()
_STEPS = 16
_BATCH = 16

#: graceful-degradation weather: ~40 ms one-way latency with +/-10 ms
#: jitter (10x a LAN-ish few-ms hop) and a 25 MB/s bandwidth cap on every
#: DATA channel (the reliability envelope, code 9), plus 1% loss there.
#: Ack channels stay weatherless — a deliberately ASYMMETRIC (one-way
#: degraded) wire, and the determinism contract holds because ack-flush
#: counts are timing-dependent while data-frame counts are not.
_WEATHER_PLAN = ChaosPlan(
    [FaultRule(code=int(MessageCode.ReliableFrame), drop=0.01)],
    seed=1052,  # chosen so the 1% loss FIRES on both directions
    weather=[WeatherRule(code=int(MessageCode.ReliableFrame),
                         latency=0.04, jitter=0.01, bandwidth=25e6)])

#: RTO floor FAR above the weather RTT (2x45 ms + queueing + one
#: ack-batch tick) so retransmissions are LOSS-driven, hence seeded and
#: deterministic — the chaos layer's determinism contract. The margin is
#: sized for this 2-core rig's worst observed stall: a per-run jit
#: re-trace or a loaded scheduler can starve the ack path for SECONDS
#: (>2 s observed under a concurrent full-suite run), and any stall past
#: the floor fires a spurious retransmit that shifts the per-channel
#: send counts the byte-identical log rides on.
_RELIABLE_OPTS = {"ack_timeout": 4.0, "max_backoff": 8.0, "send_window": 8}


@pytest.fixture(scope="module")
def ps_fixture():
    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = _MODEL.apply({"params": q}, bx, train=True,
                                  rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = _MODEL.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


def _run_weather_world(ps_fixture, plan=None, n_workers=2):
    """One in-process 1-server/N-worker DownPour run over the adaptive
    wire; returns (losses, chaos log, server, worker transports, peak
    pending depth observed by a sampler thread)."""
    x, y, grad_fn, params0 = ps_fixture
    world, log = make_world(
        n_workers + 1, plan=plan, reliable=True,
        reliable_opts=dict(_RELIABLE_OPTS))
    server = ParameterServer(
        params=np.asarray(ravel_model_params(params0)),
        transport=world[0], n_workers=n_workers)
    server_thread = threading.Thread(target=server.run,
                                     kwargs={"timeout": 300})
    server_thread.start()
    results = {}
    peak = [0]
    stop_sampler = threading.Event()

    def sampler():
        while not stop_sampler.is_set():
            for r in range(1, n_workers + 1):
                peak[0] = max(peak[0], world[r].pending_depth())
            time.sleep(0.005)

    sam = threading.Thread(target=sampler)
    sam.start()

    def worker(rank):
        params = jax.tree.map(jnp.asarray, params0)
        opt = Asynchronous(params, lr=0.05, n_push=4, n_pull=4,
                           transport=world[rank])
        rng = jax.random.key(rank)
        losses = []
        for step in range(_STEPS):
            sel = np.random.default_rng(rank * 100 + step).integers(
                0, len(x), _BATCH)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            losses.append(float(loss))
        opt.finish()
        results[rank] = losses

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(1, n_workers + 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    server_thread.join(timeout=120)
    assert not server_thread.is_alive(), "server did not shut down"
    stop_sampler.set()
    sam.join(timeout=5)
    workers = {r: world[r] for r in range(1, n_workers + 1)}
    stats = {r: dict(t.stats) for r, t in workers.items()}
    for t in world.values():
        t.close()
    return results, log, server, stats, peak[0]


def test_training_degrades_gracefully_under_network_weather(ps_fixture,
                                                            lock_witness):
    """THE ISSUE 7 acceptance: under a seeded 10x-latency + 1%-loss +
    bandwidth-capped wire the 2-worker scenario still converges in the
    fault-free corridor, the resend ratio stays bounded (<= 1.5x total
    transmissions), pending depth stays bounded by the send window, and
    the chaos log is byte-identical across 3 runs."""
    clean, _, _, _, _ = _run_weather_world(ps_fixture, plan=None)
    clean_final = np.mean([np.mean(l[-6:]) for l in clean.values()])

    logs, finals = [], []
    for _run in range(3):
        results, log, server, stats, peak = _run_weather_world(
            ps_fixture, plan=_WEATHER_PLAN)
        assert np.isfinite(server.central).all()
        logs.append(log.lines())
        finals.append(np.mean([np.mean(l[-6:]) for l in results.values()]))
        for losses in results.values():
            assert np.mean(losses[-6:]) < np.mean(losses[:6]), losses
        for rank, s in stats.items():
            assert s["sent"] > 0
            # resend ratio: total transmissions <= 1.5x originals
            assert s["retries"] <= 0.5 * s["sent"], (rank, s)
            assert s["gave_up"] == 0 and s["breaker_opens"] == 0, (rank, s)
        # bounded pending: the window held under weather
        assert peak <= _RELIABLE_OPTS["send_window"], peak
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "weather chaos log not byte-identical across runs")
    assert "weather+" in logs[0]
    assert " drop" in logs[0]  # the 1% loss genuinely fired
    for final in finals:
        assert abs(final - clean_final) < 0.45, (final, clean_final)
