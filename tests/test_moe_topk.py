"""GShard top-k routing: reduction to Switch at k=1, exactness vs a naive
per-token reference at k=2, rank-major capacity priority, and expert-parallel
equality (the dispatch/combine contract is unchanged, so the ep sharding
must work for any k)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.models.moe import (
    MoEMLP,
    MoETransformerLM,
    switch_route,
    topk_route,
)


def _probs(b=2, s=8, e=4, seed=0):
    rng = np.random.default_rng(seed)
    logits = rng.normal(size=(b, s, e)).astype(np.float32)
    return jnp.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))


def test_top1_unnormalized_reduces_to_switch():
    probs = _probs()
    d1, c1 = switch_route(probs, capacity=4)
    d2, c2 = topk_route(probs, capacity=4, k=1, normalize=False)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=1e-7)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-7)


def test_top2_matches_naive_per_token_mixture():
    """With ample capacity nothing drops: the layer output must equal the
    per-token normalized two-expert mixture computed naively."""
    b, s, d, e = 1, 6, 8, 4
    model = MoEMLP(d_model=d, d_ff=16, n_experts=e, capacity_factor=8.0,
                   router_top_k=2)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(b, s, d)).astype(np.float32))
    variables = model.init(jax.random.key(0), x)
    params = variables["params"]
    out = model.apply({"params": params}, x)

    # naive reference: per token, run its top-2 experts' FFNs directly
    logits = x @ params["router"]["kernel"]
    probs = np.asarray(jax.nn.softmax(logits.astype(jnp.float32), axis=-1))
    w_up, b_up = np.asarray(params["w_up"]), np.asarray(params["b_up"])
    w_dn, b_dn = np.asarray(params["w_down"]), np.asarray(params["b_down"])
    want = np.zeros((b, s, d), np.float32)
    for bi in range(b):
        for si in range(s):
            top2 = np.argsort(-probs[bi, si])[:2]
            gates = probs[bi, si, top2]
            gates = gates / gates.sum()
            for g, ei in zip(gates, top2):
                h = np.asarray(jax.nn.gelu(
                    jnp.asarray(x[bi, si] @ w_up[ei] + b_up[ei])
                ))
                want[bi, si] += g * (h @ w_dn[ei] + b_dn[ei])
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4, atol=2e-5)


def test_rank_major_capacity_priority():
    """Capacity 1: every token's FIRST choice outranks any second choice —
    the only token dispatched to each expert's single slot via rank 1 is one
    whose rank-0 peers left room."""
    # 3 tokens all prefer expert 0 first, expert 1 second
    probs = jnp.asarray(
        [[[0.6, 0.3, 0.1], [0.5, 0.4, 0.1], [0.55, 0.35, 0.1]]], jnp.float32
    )
    dispatch, _ = topk_route(probs, capacity=1, k=2)
    d = np.asarray(dispatch)  # [1, 3, 3, 1]
    assert d[0, 0, 0, 0] == 1.0  # token 0 won expert 0's slot (rank 0)
    assert d[0, 1, 0, 0] == 0.0 and d[0, 2, 0, 0] == 0.0  # others dropped there
    # expert 1's single slot goes to a rank-1 choice — exactly one of them
    assert np.asarray(dispatch)[0, :, 1, 0].sum() == 1.0


def test_top2_lm_trains_and_matches_ep_sharding():
    """The ep-sharded top-2 MoE step must equal the unsharded (1×1 mesh)
    step exactly — the same contract as the existing top-1 ep test, now for
    k=2's doubled dispatch traffic."""
    from distributed_ml_pytorch_tpu.parallel.expert_parallel import (
        create_ep_train_state,
        make_ep_train_step,
        shard_ep_batch,
    )
    from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets
    from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

    moe = MoETransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, max_len=64, router_top_k=2,
    )
    tx = optax.sgd(0.05)
    tokens = np.random.default_rng(2).integers(0, 64, size=(4, 32)).astype(np.int32)
    targets = next_token_targets(tokens)

    mesh_s = make_mesh({"data": 1, "expert": 1}, devices=jax.devices()[:1])
    mesh_p = make_mesh({"data": 2, "expert": 4})
    states, losses = {}, {}
    for name, mesh in (("unsharded", mesh_s), ("sharded", mesh_p)):
        state = create_ep_train_state(moe, jax.random.key(0), tx, mesh)
        tok, tgt = shard_ep_batch(mesh, tokens, targets)
        step = make_ep_train_step(moe, tx, mesh)
        ls = []
        for _ in range(3):
            state, (loss, _aux) = step(state, tok, tgt)
            ls.append(float(loss))
        states[name], losses[name] = state, ls

    assert losses["sharded"][-1] < losses["sharded"][0]
    np.testing.assert_allclose(losses["unsharded"], losses["sharded"], rtol=1e-5)
    for a, b in zip(
        jax.tree.leaves(states["unsharded"].params),
        jax.tree.leaves(states["sharded"].params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_moe_incremental_decode_matches_full_forward():
    """Prefill + cached single-token MoE decode must reproduce the full
    causal forward's logits (the MoE FFN itself is stateless across steps —
    only attention caches). capacity_factor=4 makes capacity == seq so the
    teacher-forced forward cannot DROP tokens: a single-token decode step
    has effectively unbounded capacity and never drops, so exact agreement
    only holds when the full forward didn't drop either (inherent Switch
    semantics — see MoETransformerLM's decode note)."""
    import numpy as np
    from distributed_ml_pytorch_tpu.models.generate import init_cache

    model = MoETransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, max_len=64, capacity_factor=4.0,
    )
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 10)), jnp.int32
    )
    full = model.apply({"params": params}, tokens)

    dec = model.clone(decode=True, cache_size=10, attn_fn=None)
    cache = init_cache(model, 2, 10)
    got = []
    for t in range(10):
        logits, mutated = dec.apply(
            {"params": params, "cache": cache},
            tokens[:, t: t + 1],
            jnp.full((2, 1), t, jnp.int32),
            mutable=["cache"],
        )
        cache = mutated["cache"]
        got.append(logits[:, 0])
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), rtol=2e-4, atol=2e-5
    )


def test_moe_generate_blocked_and_sampled():
    """generate() serves the MoE LM end to end: the >= DECODE_BLOCK run
    takes the blocked (ring + fused-qkv) path and must match the plain
    one-token scan; sampling is reproducible."""
    import numpy as np
    from distributed_ml_pytorch_tpu.models.generate import (
        _decode_model,
        _generate_jit,
        generate,
        init_cache,
    )

    model = MoETransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, max_len=128,
    )
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(2, 6)), jnp.int32
    )
    n = 24  # >= DECODE_BLOCK -> blocked path
    blocked = generate(model, params, prompt, n)
    ref = _generate_jit(
        _decode_model(model, 6 + n), n, 0.0, 0, 1.0, params,
        init_cache(model, 2, 6 + n), prompt, jax.random.key(0)
    )
    np.testing.assert_array_equal(np.asarray(blocked), np.asarray(ref))

    s1 = generate(model, params, prompt, 20, temperature=0.9,
                  rng=jax.random.key(2), top_k=8)
    s2 = generate(model, params, prompt, 20, temperature=0.9,
                  rng=jax.random.key(2), top_k=8)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert int(s1.max()) < 64 and int(s1.min()) >= 0
