"""The restructured lm_head train step (ops/fused_head.py) must compute the
SAME function as the AD step over fsdp.lm_loss_builder + plain SGD — loss
and every updated parameter — with either dW+update path (the default XLA
formulation and the Pallas kernel in interpret mode)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.models import TransformerLM
from distributed_ml_pytorch_tpu.ops.fused_head import (
    BLOCK_N,
    head_update_sgd,
    make_fused_head_sgd_step,
)
from distributed_ml_pytorch_tpu.ops.fused_update import force_pallas_interpret
from distributed_ml_pytorch_tpu.parallel.fsdp import lm_loss_builder
from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
    create_lm_train_state,
    next_token_targets,
)


def _ref_step(lm, tx):
    loss_builder = lm_loss_builder(lm)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, tokens, targets):
        loss, grads = jax.value_and_grad(
            loss_builder(state, tokens, targets))(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(params=params, opt_state=opt_state,
                             step=state.step + 1), loss

    return step


@pytest.mark.slow  # two compiled LM train worlds
@pytest.mark.parametrize("use_kernel", [False, True])
def test_fused_head_step_matches_ad_step(use_kernel):
    lm = TransformerLM(vocab_size=640, d_model=64, n_heads=4, n_layers=2,
                       d_ff=128, max_len=4096)
    lr = 0.05
    tx = optax.sgd(lr)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 640, (2, 1024)), jnp.int32)
    targets = next_token_targets(tokens)
    assert tokens.size % BLOCK_N == 0  # the kernel path must actually run

    state = create_lm_train_state(lm, jax.random.key(0), tx)
    ref_state, ref_loss = _ref_step(lm, tx)(state, tokens, targets)

    state2 = create_lm_train_state(lm, jax.random.key(0), tx)
    with force_pallas_interpret():
        fused = make_fused_head_sgd_step(lm, lr, use_kernel=use_kernel)
        new_state, loss = fused(state2, tokens, targets)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    for (p, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(ref_state.params)),
        jax.tree_util.tree_leaves_with_path(jax.device_get(new_state.params)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7,
            err_msg=jax.tree_util.keystr(p))
    assert int(new_state.step) == 1


def test_head_update_kernel_matches_xla_formulation():
    """head_update_sgd's two paths agree on the same inputs (kernel in
    interpret mode), including a vocab that leaves a ragged final block."""
    rng = np.random.default_rng(1)
    n, d, v = 2048, 32, 640  # 640 = 512 + 128: ragged final BLOCK_V tile
    W = jnp.asarray(rng.normal(size=(d, v)) * 0.02, jnp.float32)
    h2 = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    logits = h2 @ W
    lse = jax.nn.logsumexp(logits, axis=-1)
    labels = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)
    gscale = jnp.asarray(rng.uniform(0, 1e-3, (n,)), jnp.float32)
    gscale = gscale.at[::7].set(0.0)  # masked rows (log2(0) path)

    ref = head_update_sgd(W, h2, logits, lse, labels, gscale, 0.05,
                          use_kernel=False)
    with force_pallas_interpret():
        got = head_update_sgd(W, h2, logits, lse, labels, gscale, 0.05,
                              use_kernel=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=1e-6)
