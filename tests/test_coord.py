"""ISSUE 3 coord suite: the elastic control plane.

Layers:

- unit: ShardMap encode/decode + rebalance fresh-range accounting;
  Coordinator membership under a fake clock (join / leave / lease expiry /
  incarnation ordering); staleness-damped apply; HeartbeatSender self-heal.
- race (satellite): a finished worker's parting CoordLeave racing a
  replacement's join on the same rank — the incarnation bump must win; and
  at the ReliableTransport level, an old life's retried GradientUpdate
  arriving after the new life's frames is acked-but-never-applied (no
  double-apply).
- revive (satellite): ``ShardedAsynchronous._mark_down`` is no longer
  forever — a reply from the downed shard restores its push/pull service.
- system: THE elastic acceptance scenario — 2 workers + 2 shard servers
  under ``FaultyTransport``, a 3rd worker joins at step N, a shard server
  is silently crashed at step M, the coordinator rebalances, training
  continues, and the final loss lands in the fault-free corridor; run 3x
  with identical seeds. Plus Sandblaster speculation: a scripted 10x-slow
  straggler no longer gates epoch completion, and its late duplicate
  result is dedup-dropped at the PS.
- serving: the frontend holds submits while the coordinator reports the
  engine fleet down, and re-admits them on recovery.

Fast seeded cases carry the ``coord`` marker and run in tier-1
(``make coord`` selects all of them); the wall-clock-heavy scenario tests
are additionally measured into tests/slow_tests.txt.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.coord.coordinator import (
    KIND_SHARD,
    KIND_WORKER,
    Coordinator,
    encode_join,
    encode_leave,
    encode_renew,
)
from distributed_ml_pytorch_tpu.coord.demo import elastic_scenario
from distributed_ml_pytorch_tpu.coord.elastic import ElasticShardServer
from distributed_ml_pytorch_tpu.coord.member import CoordClient, FleetView
from distributed_ml_pytorch_tpu.coord.shardmap import (
    ShardEntry,
    ShardMap,
    rebalance,
)
from distributed_ml_pytorch_tpu.models import LeNet
from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer
from distributed_ml_pytorch_tpu.parallel.sharded_ps import ShardedAsynchronous
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
)
from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

pytestmark = pytest.mark.coord

# the shared lock_witness fixture (tests/conftest.py) arms the acceptance
# scenario below as a concurrency validator under DISTCHECK_WITNESS=1

# ---------------------------------------------------------------------------
# unit: shard maps
# ---------------------------------------------------------------------------

def test_shardmap_roundtrips_and_orders_entries():
    m = ShardMap(7, 62006, [ShardEntry(1, 0, 31003), ShardEntry(4, 31003, 62006, 40000, 62006)])
    m2 = ShardMap.decode(m.encode())
    assert m2 == m
    assert m2.ranges == [(0, 31003), (31003, 62006)]
    assert m2.entries[1].needs_install and not m2.entries[0].needs_install
    with pytest.raises(ValueError):
        ShardMap.decode(np.asarray([2.0, 0, 0, 0, 0], np.float32))  # short


def test_rebalance_fresh_ranges_cover_exactly_the_moved_params():
    m1 = rebalance(ShardMap(0, 100, ()), [1])
    assert m1.version == 1 and m1.entries == (ShardEntry(1, 0, 100, 0, 100),)
    # join of server 3: it gets [50,100) all-fresh; server 1 keeps [0,50)
    m2 = rebalance(m1, [1, 3])
    assert m2.entries == (ShardEntry(1, 0, 50, 0, 0),
                          ShardEntry(3, 50, 100, 50, 100))
    # death of server 3: server 1 grows a fresh right flank
    m3 = rebalance(m2, [1])
    assert m3.entries == (ShardEntry(1, 0, 100, 50, 100),)
    # death of server 1 instead: server 3's range grows left — the
    # overlap [50,100) keeps its authoritative values, only [0,50) is fresh
    m3b = rebalance(m2, [3])
    assert m3b.entries == (ShardEntry(3, 0, 100, 0, 50),)


# ---------------------------------------------------------------------------
# unit: coordinator membership (fake clock — no sleeping)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_membership_lease_expiry_rebalances_and_logs():
    clock = _Clock()
    c = Coordinator(None, 100, lease=2.0, clock=clock, speculation=False)
    c.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 10))
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_SHARD, 11))
    c.handle(5, MessageCode.CoordJoin, encode_join(KIND_WORKER, 12))
    assert c.shard_map.version == 2 and len(c.shard_map.entries) == 2
    clock.t = 1.9
    c.handle(1, MessageCode.LeaseRenew, encode_renew(10, 3, 7, 12.5))
    c.handle(5, MessageCode.LeaseRenew, encode_renew(12))
    assert not c.tick()  # shard 2 is 1.9s silent: inside the lease
    clock.t = 2.1
    assert c.tick()  # now shard 2 (and nobody else) expires
    assert c.shard_map.version == 3
    assert c.shard_map.entries == (ShardEntry(1, 0, 100, 50, 100),)
    assert 2 not in c.members and 1 in c.members and 5 in c.members
    assert c.members[1].push_count == 3 and c.members[1].ewma_ms == 12.5
    fs = c.fleet_state()
    assert fs["n_shards"] == 1 and fs["n_workers"] == 1
    assert not fs["workers_done"]
    c.handle(5, MessageCode.CoordLeave, encode_leave(12))
    assert c.fleet_state()["workers_done"]


def test_five_field_pre_issue7_renew_still_renews_and_rejects_stale():
    """WIRE_SCHEMAS tolerance contract: a 5-field pre-ISSUE-7 LeaseRenew
    (no wire_open) is a FULL renew — lease refreshed, progress adopted,
    stale incarnations still rejected — and leaves the last wire-health
    report standing rather than reading absence as healthy."""
    clock = _Clock()
    c = Coordinator(None, 100, lease=2.0, clock=clock, speculation=False)
    c.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 10))
    # a 6-field renew reports a degraded wire
    c.handle(1, MessageCode.LeaseRenew, encode_renew(10, 1, 1, 5.0,
                                                     wire_open=2))
    assert c.members[1].wire_open == 2
    clock.t = 1.9
    legacy = encode_renew(10, 7, 9, 33.0)[:5]  # the pre-ISSUE-7 frame
    assert legacy.size == 5
    c.handle(1, MessageCode.LeaseRenew, legacy)
    m = c.members[1]
    assert m.last_seen == 1.9 and m.push_count == 7 and m.step == 9
    assert m.ewma_ms == 33.0
    assert m.wire_open == 2  # absent field != healthy
    clock.t = 2.5
    assert not c.tick()  # the 5-field renew refreshed the lease
    # a stale life's 5-field renew is still rejected
    before = m.last_seen
    c.handle(1, MessageCode.LeaseRenew, encode_renew(9, 99, 99, 1.0)[:5])
    assert c.members[1].last_seen == before
    assert c.members[1].push_count != 99


def test_workerdone_racing_join_same_rank_incarnation_bump_wins():
    """Satellite: rank 5's old life finishes (its CoordLeave is still in
    flight) while a replacement with a HIGHER incarnation joins the same
    rank. Whatever order the frames land in, the new life survives."""
    clock = _Clock()
    # order 1: join(new) then stale leave(old)
    c = Coordinator(None, 100, lease=5.0, clock=clock, speculation=False)
    c.handle(5, MessageCode.CoordJoin, encode_join(KIND_WORKER, 10))
    c.handle(5, MessageCode.CoordJoin, encode_join(KIND_WORKER, 20))  # rebirth
    c.handle(5, MessageCode.CoordLeave, encode_leave(10))  # old life's parting
    assert 5 in c.members and c.members[5].incarnation == 20
    assert any("stale leave" in e for e in c.events)
    # a stale renew can't refresh either
    before = c.members[5].last_seen
    clock.t = 3.0
    c.handle(5, MessageCode.LeaseRenew, encode_renew(10, 99, 99, 1.0))
    assert c.members[5].last_seen == before and c.members[5].push_count != 99
    # order 2: old leave lands first, then the new join — the leave removes
    # the old life, the join (re)creates the new one
    c2 = Coordinator(None, 100, lease=5.0, clock=clock, speculation=False)
    c2.handle(5, MessageCode.CoordJoin, encode_join(KIND_WORKER, 10))
    c2.handle(5, MessageCode.CoordLeave, encode_leave(10))
    c2.handle(5, MessageCode.CoordJoin, encode_join(KIND_WORKER, 20))
    assert 5 in c2.members and c2.members[5].incarnation == 20
    # and a delayed OLD join can never demote the new life
    c2.handle(5, MessageCode.CoordJoin, encode_join(KIND_WORKER, 10))
    assert c2.members[5].incarnation == 20


def test_reliable_transport_no_double_apply_across_lives():
    """Satellite (wire level): the old life's retried GradientUpdate
    arriving AFTER the new life's frames on the same rank is acked (so the
    dead process stops retrying) but never delivered — the PS cannot
    double-apply across a finish()/join race."""
    boxes = InProcessTransport.create_world(2)
    server = ReliableTransport(boxes[0], ack_timeout=0.05)
    old_life = ReliableTransport(boxes[1], ack_timeout=0.05)
    new_life = ReliableTransport(boxes[1].attach_rank(1), ack_timeout=0.05)
    assert new_life.incarnation > old_life.incarnation
    old_life.send(MessageCode.GradientUpdate, np.full(4, 1.0, np.float32))
    got = [server.recv(timeout=2)]
    new_life.send(MessageCode.GradientUpdate, np.full(4, 2.0, np.float32))
    got.append(server.recv(timeout=2))
    # the old life's retry of its frame #0 lands after the new life was
    # seen — rebuild that exact wire frame and inject it
    import numpy as _np

    from distributed_ml_pytorch_tpu.utils.messaging import (
        _frame_crc,
        _split16,
    )

    arr = _np.full(4, 1.0, _np.float32)
    crc = _frame_crc(old_life.incarnation, 0, int(MessageCode.GradientUpdate),
                     arr.tobytes())
    stale = _np.concatenate([
        _np.asarray([*_split16(old_life.incarnation), *_split16(0),
                     *_split16(crc), float(int(MessageCode.GradientUpdate)),
                     *_split16(0)],  # corr id (ISSUE 12): none
                    _np.float32), arr])
    boxes[1].send(MessageCode.ReliableFrame, stale, dst=0)
    assert server.recv(timeout=0.5) is None  # acked-dropped, NOT delivered
    assert server.stats["delivered"] == 2
    vals = sorted(float(m[2][0]) for m in got)
    assert vals == [1.0, 2.0]
    for t in (server, old_life, new_life):
        t.close()


def test_staleness_damping_scales_stale_pushes_only():
    flat = np.zeros(8, np.float32)
    ps = ParameterServer(params=flat, staleness_damping=1.0)
    one = np.ones(8, np.float32)
    ps.handle(1, MessageCode.GradientUpdate, one)  # staleness 0: raw apply
    np.testing.assert_allclose(ps.central, 1.0)
    # worker 1 never re-pulled: staleness is now 1 → scale 1/(1+1)
    ps.handle(1, MessageCode.GradientUpdate, one)
    np.testing.assert_allclose(ps.central, 1.5)
    # staleness 2 → 1/3
    ps.handle(1, MessageCode.GradientUpdate, one)
    np.testing.assert_allclose(ps.central, 1.5 + 1.0 / 3.0, rtol=1e-6)
    # damping off (default): raw adds regardless of staleness
    ps2 = ParameterServer(params=np.zeros(8, np.float32))
    ps2.handle(1, MessageCode.GradientUpdate, one)
    ps2.handle(1, MessageCode.GradientUpdate, one)
    np.testing.assert_allclose(ps2.central, 2.0)


def test_expired_member_readmitted_by_join_retry():
    """A member whose lease expires during a transient stall (renewals
    dropped) must be RE-ADMITTED once connectivity returns: the client's
    periodic re-join closes the loop the coordinator's ignore-unknown-ranks
    rule would otherwise leave open forever."""
    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan, FaultyTransport

    world = InProcessTransport.create_world(2)
    fw, _ = FaultyTransport.wrap_world(world, ChaosPlan())
    coord = Coordinator(fw[0], 100, lease=0.4, speculation=False)
    t = threading.Thread(target=coord.run, kwargs={"timeout": 60},
                         daemon=True)
    t.start()
    client = CoordClient(fw[1], "shard", renew_interval=0.1)
    try:
        m = client.join(timeout=10)
        assert m is not None and m.entries
        fw[1].partition(0)  # the stall: renewals (and joins) vanish
        deadline = time.monotonic() + 10
        while 1 in coord.members and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 not in coord.members, "lease never expired"
        assert not coord.shard_map.entries  # rebalanced out
        fw[1].heal(0)
        deadline = time.monotonic() + 10
        while 1 not in coord.members and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in coord.members, "expired member never re-admitted"
        assert coord.shard_map.entry_for(1) is not None  # range restored
        # the fleet never read as done: expiry is an outage, not a finish
        assert not coord.fleet_state()["workers_done"]
    finally:
        client.close()
        coord.stop()
        t.join(timeout=10)
        for tr in fw.values():
            tr.close()


@pytest.mark.coordfail
def test_member_fails_open_through_long_coordinator_outage():
    """The ``coord_down`` contract (ISSUE 17): a coordinator outage far
    longer than the lease must not take the member down with it. The
    client flags ``coord_down`` on the dead socket, keeps the LAST shard
    map (training steps on), keeps its renew/rejoin loop alive, and the
    serving plane keeps admitting — a rollback hold whose completion
    broadcast died with the coordinator
    expires via its TTL instead of wedging the frontend. On revival the
    join retry re-attaches cleanly: ``coord_down`` clears, the member is
    re-admitted, its range restored."""
    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan, FaultyTransport

    world = InProcessTransport.create_world(2)
    fw, _ = FaultyTransport.wrap_world(world, ChaosPlan())
    coord = Coordinator(fw[0], 100, lease=0.3, speculation=False)
    t = threading.Thread(target=coord.run, kwargs={"timeout": 60},
                         daemon=True)
    t.start()
    client = CoordClient(fw[1], "shard", renew_interval=0.075,
                         rollback_hold_ttl=0.5)
    try:
        m = client.join(timeout=10)
        assert m is not None and m.entries
        v0 = client.current_map().version
        assert client.coord_down is False
        # a rollback barrier opens... and its completion broadcast will
        # die with the coordinator — only the TTL can release the hold
        client.fleet.note_rollback(True, ttl=0.5)

        fw[0].crash()  # the arbiter dies mid-flight: a dead socket now
        deadline = time.monotonic() + 10
        while not client.coord_down and time.monotonic() < deadline:
            time.sleep(0.02)
        assert client.coord_down, "dead socket never flagged"
        time.sleep(1.2)  # outage = 4x lease: a LONG control-plane blip
        # fail-open, in every plane: the member still holds the last map
        # (the data plane keeps stepping on it), still flags the outage,
        # its renew/rejoin loop is still breathing, and the orphaned
        # rollback hold has TTL-expired instead of wedging admission
        assert client.coord_down
        assert client.current_map() is not None
        assert client.current_map().version == v0
        assert not client.fleet.rollback_active()

        fw[0].restart()  # revival: the join retry closes the loop
        deadline = time.monotonic() + 10
        while (client.coord_down or 1 not in coord.members) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not client.coord_down, "revival never cleared coord_down"
        assert 1 in coord.members, "member never re-admitted"
        deadline = time.monotonic() + 10
        while coord.shard_map.entry_for(1) is None \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert coord.shard_map.entry_for(1) is not None  # range restored
    finally:
        client.close()
        coord.stop()
        t.join(timeout=10)
        for tr in fw.values():
            tr.close()


def test_heartbeat_sender_self_heals_peer_down():
    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan, FaultyTransport
    from distributed_ml_pytorch_tpu.utils.failure import HeartbeatSender

    world = InProcessTransport.create_world(2)
    fw, _ = FaultyTransport.wrap_world(world, ChaosPlan())
    hb = HeartbeatSender(fw[1], interval=0.05)
    hb.start()
    try:
        fw[1].crash()
        deadline = time.monotonic() + 5
        while not hb.peer_down and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hb.peer_down
        fw[1].restart()
        deadline = time.monotonic() + 5
        while hb.peer_down and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not hb.peer_down  # the probe loop cleared it on success
    finally:
        hb.stop()
        for t in fw.values():
            t.close()


# ---------------------------------------------------------------------------
# revive-on-contact (satellite)
# ---------------------------------------------------------------------------

def _lenet_params(seed=0):
    return LeNet().init(jax.random.key(seed), jnp.zeros((1, 32, 32, 3)))["params"]


def test_shard_down_revives_on_contact(capsys):
    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan, FaultyTransport

    params = _lenet_params()
    world = InProcessTransport.create_world(2)
    fw, _ = FaultyTransport.wrap_world(world, ChaosPlan())
    opt = ShardedAsynchronous(params, lr=0.0, n_push=100, n_pull=100,
                              transports=[fw[1]])
    try:
        while fw[0].recv(timeout=0.2) is not None:
            pass  # drain the construction install
        fw[0].crash()  # the shard server dies
        opt._send(0, MessageCode.ParameterRequest, np.zeros(0, np.float32))
        assert opt.shard_down == [True]
        # down-marked shards still get pull PROBES (the revival path) —
        # while crashed they just fail quietly
        opt._send(0, MessageCode.ParameterRequest, np.zeros(0, np.float32))
        assert opt.shard_down == [True]
        # ...the server restarts: the next probe reaches it over the REAL
        # wire, its reply lands via the listener thread, and the next
        # step-boundary install revives the slot
        fw[0].restart()
        opt._send(0, MessageCode.ParameterRequest, np.zeros(0, np.float32))
        probe = fw[0].recv(timeout=2)
        assert probe is not None and probe[1] == MessageCode.ParameterRequest
        flat = np.asarray(ravel_model_params(params), np.float32)
        fw[0].send(MessageCode.ParameterUpdate, flat, dst=1)  # the reply
        assert opt.listeners[0].wait_for_update(5), "reply never arrived"
        opt._install_arrived(params)
        assert opt.shard_down == [False]
        err = capsys.readouterr().err
        assert "state up->down" in err and "state down->up" in err
    finally:
        opt.finish()
        for t in fw.values():
            t.close()


# ---------------------------------------------------------------------------
# system: the elastic acceptance scenario + speculation
# ---------------------------------------------------------------------------

_MODEL = LeNet()
_BATCH = 16


@pytest.fixture(scope="module")
def elastic_fixture():
    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = _MODEL.apply({"params": q}, bx, train=True,
                                  rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = _MODEL.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


def test_elastic_acceptance_join_crash_rebalance_corridor(
        elastic_fixture, lock_witness):
    """THE acceptance test (ISSUE 3): 2 workers + 2 PS shards under
    FaultyTransport; a 3rd worker joins at step N; a shard server is
    silently crashed at step M; the coordinator detects the death by lease
    expiry and rebalances; training continues and the final loss lands in
    the fault-free corridor. Runs 3x with identical seeds."""
    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan, FaultRule

    clean = elastic_scenario(
        seed=0, steps=20, n_workers=2, n_shards=2, fixture=elastic_fixture,
        lease=0.5, step_sleep=0.06)
    assert clean["ok"], clean["errors"]
    clean_final = np.mean([np.mean(l[-6:]) for l in clean["losses"].values()])

    plan = ChaosPlan(
        [FaultRule(code=int(c), drop=0.05, dup=0.05)
         for c in (MessageCode.GradientUpdate, MessageCode.ParameterRequest,
                   MessageCode.ParameterUpdate)],
        seed=42)
    for _run in range(3):
        out = elastic_scenario(
            seed=0, steps=20, n_workers=2, n_shards=2,
            join_worker_at=5, join_worker_steps=10, crash_shard_at=8,
            plan=plan, lease=0.5, step_sleep=0.06, fixture=elastic_fixture)
        assert out["ok"], (out["errors"], out["events"])
        # the coordinator rebalanced at least twice beyond bootstrap:
        # v1 (shard 1), v2 (shard 2), v3 (crash-detected rebalance), ...
        assert out["map_version"] >= 3, out["events"]
        assert any("lease expired" in e for e in out["events"]), out["events"]
        # all three workers trained to completion
        assert sorted(out["losses"]) == [1, 2, 3]
        assert len(out["losses"][3]) == 10  # the joiner did its steps
        # training CONTINUED past the rebalance: the original workers
        # adopted the crash-detected map (v3+) before finishing
        assert out["worker_map_versions"][1] >= 3, out["worker_map_versions"]
        assert out["worker_map_versions"][2] >= 3, out["worker_map_versions"]
        # the surviving shard server resized and absorbed the moved range
        surv = out["stats"][1]
        assert surv["resizes"] >= 1
        # every worker's loss trended down and the fleet landed in the
        # fault-free corridor
        for losses in out["losses"].values():
            assert np.mean(losses[-6:]) < np.mean(losses[:6]) + 0.05, losses
        final = np.mean([np.mean(l[-6:]) for l in out["losses"].values()])
        assert abs(final - clean_final) < 0.45, (final, clean_final)


def test_speculation_straggler_no_longer_gates_epoch(elastic_fixture):
    """Sandblaster backup tasks: a scripted 10x-slow straggler's remaining
    work is replicated to the fastest worker; the epoch's full gradient
    contribution lands in ~fast-worker time, and the straggler's late
    duplicate is dedup-dropped (no double-apply)."""
    x, y, grad_fn, params0 = elastic_fixture
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n = flat0.shape[0]
    from distributed_ml_pytorch_tpu.coord.demo import ElasticWorld, _worker_rank

    steps = 10
    slow_sleep = 0.4  # the scripted 10x slowdown (fast step ~0.04s here)
    world = ElasticWorld(n_shards=1, max_workers=2)
    coord = Coordinator(world.coord_world[0], n, lease=2.0,
                        straggler_factor=3.0, straggler_after_steps=2,
                        speculation=True)
    coord_thread = threading.Thread(target=coord.run, kwargs={"timeout": 120},
                                    daemon=True)
    coord_thread.start()
    sclient = CoordClient(world.coord_world[1], "shard", renew_interval=0.2)
    srv = ElasticShardServer(server_id=1, n_params=n,
                             transport=world.shard_worlds[0][0],
                             coord=sclient, init_params=flat0)
    srv_thread = threading.Thread(target=srv.run, kwargs={"timeout": 120},
                                  daemon=True)
    srv_thread.start()

    done_at = {}
    spec_by_worker = {}

    def worker(j, slow):
        tasks = []
        spec_by_worker[j] = tasks
        client = CoordClient(world.coord_world[_worker_rank(1, j)], "worker",
                             renew_interval=0.2,
                             on_speculate=lambda *a: tasks.append(a))
        m = client.join(timeout=30)
        factory = world.worker_factory(j)
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=0.05, n_push=2, n_pull=2,
            transports=[factory(e) for e in m.entries], coord=client,
            transport_factory=factory, shard_map=m)
        for step in range(steps):
            sel = np.random.default_rng(j * 100 + step).integers(0, len(x), _BATCH)
            _loss, grads = grad_fn(params, x[sel], y[sel],
                                   jax.random.fold_in(jax.random.key(j), step))
            params = opt.step(params, grads)
            if slow:
                time.sleep(slow_sleep)
        if not slow:
            # the BACKUP: wait (bounded) for the coordinator to notice the
            # straggler, then race its tail — one summed update for the
            # speculated steps, tagged with the task id
            deadline = time.monotonic() + 30
            while not tasks and time.monotonic() < deadline:
                time.sleep(0.05)
        if tasks:
            tid, _victim, _frm = tasks[0]
            upd = np.zeros(n, np.float32)
            upd[:8] = 0.001  # stand-in tail contribution
            # the backup pushes it NOW; the victim pushes the SAME task
            # when it finally finishes — the PS must apply exactly one
            opt.push_speculative(tid, upd)
        opt.finish()
        client.close()
        done_at[j] = time.monotonic()

    t0 = time.monotonic()
    fast = threading.Thread(target=worker, args=(1, False), daemon=True)
    slow = threading.Thread(target=worker, args=(2, True), daemon=True)
    fast.start()
    slow.start()
    fast.join(timeout=120)
    slow.join(timeout=120)
    srv.stop()
    srv_thread.join(timeout=30)
    coord.stop()
    coord_thread.join(timeout=10)

    # the detector FIRED (coord.speculated is cleaned up when the victim
    # leaves, so the decision log is the durable evidence)
    assert any("straggler:" in e for e in coord.events), coord.events
    # both parties were told (victim tags its tail, backup races it)
    assert spec_by_worker[1] and spec_by_worker[2]
    assert spec_by_worker[1][0] == spec_by_worker[2][0]
    # the tail's contribution applied exactly once
    assert srv.stats["spec_applied"] == 1
    assert srv.stats["spec_dropped"] == 1
    # epoch semantics: the fleet's full contribution (incl. the victim's
    # tail, via the backup) was at the PS by the FAST worker's finish —
    # long before the straggler's own finish
    fast_done = done_at[1] - t0
    slow_done = done_at[2] - t0
    assert slow_done > fast_done + 0.5 * slow_sleep * steps / 2, (
        fast_done, slow_done)  # the script really did straggle
    world.close()


# ---------------------------------------------------------------------------
# serving: fleet-state reject-or-queue (the serving hook)
# ---------------------------------------------------------------------------

SERVE_VOCAB = 64


@pytest.fixture(scope="module")
def lm_and_params():
    from distributed_ml_pytorch_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=SERVE_VOCAB, d_model=32, n_heads=4,
                          n_layers=2, d_ff=64, max_len=128)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def test_frontend_holds_submits_on_engine_loss_and_readmits(lm_and_params):
    from distributed_ml_pytorch_tpu.models.generate import generate
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine
    from distributed_ml_pytorch_tpu.serving.frontend import (
        ServingClient,
        ServingFrontend,
    )

    model, params = lm_and_params
    engine = ServingEngine(model, params, slots=2, cache_size=64,
                           decode_block=4, prefill_bucket=8)
    world = InProcessTransport.create_world(2)
    fleet = FleetView()
    fleet.update({"version": 1, "n_workers": 0, "n_shards": 0,
                  "n_engines": 0, "workers_done": False})  # engine DOWN
    frontend = ServingFrontend(engine, world[0], fleet=fleet)
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    try:
        client = ServingClient(world[1], resume_after=0.25)
        prompt = np.random.default_rng(0).integers(0, SERVE_VOCAB, size=5)
        rid = client.submit(prompt, 8)
        deadline = time.monotonic() + 5
        while not frontend._held and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(frontend._held) == 1  # queued, not submitted, not rejected
        assert not frontend._routes
        # recovery: the coordinator reports an engine again; the sweep
        # re-admits the held submit and the stream completes normally
        fleet.update({"version": 2, "n_workers": 0, "n_shards": 0,
                      "n_engines": 1, "workers_done": False})
        tokens = list(client.stream(rid, timeout=60.0))
        want = np.asarray(
            generate(model, params, jnp.asarray(prompt, jnp.int32)[None], 8)
        )[0, 5:].tolist()
        assert tokens == want
        assert not frontend._held and frontend.held_peak == 1
    finally:
        frontend.stop()
        thread.join(timeout=10)
        for t in world.values():
            t.close()


def test_fleet_view_fails_open_without_reports():
    fleet = FleetView()
    assert fleet.engine_up()  # no control plane / no report yet: admit
    assert fleet.live_engine_ranks() is None  # per-engine view fails open too
    fleet.update({"version": 1, "n_workers": 1, "n_shards": 1,
                  "n_engines": 0, "workers_done": False})
    assert not fleet.engine_up()
    fleet.update({"version": 2, "n_workers": 1, "n_shards": 1,
                  "n_engines": 2, "workers_done": False})
    assert fleet.engine_up()


def test_fleet_state_carries_live_engine_ranks_on_the_wire():
    """ISSUE 6: the FleetState broadcast's tail lists the live engine
    coord-ranks, so a router can tell WHICH engine's lease expired."""
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        KIND_ENGINE,
        decode_fleet,
        encode_fleet,
    )

    frame = encode_fleet(3, 2, 2, 2, False, engine_ranks=[51, 57])
    decoded = decode_fleet(frame)
    assert decoded["engine_ranks"] == [51, 57]
    # a legacy counts-only frame still decodes (empty rank list)
    legacy = encode_fleet(3, 2, 2, 2, False)
    assert decode_fleet(legacy)["engine_ranks"] == []
    # and the coordinator's own state produces the same view
    clock = _Clock()
    c = Coordinator(None, 100, lease=2.0, clock=clock, speculation=False)
    c.handle(51, MessageCode.CoordJoin, encode_join(KIND_ENGINE, 10))
    c.handle(57, MessageCode.CoordJoin, encode_join(KIND_ENGINE, 11))
    assert c.live_engine_ranks() == {51, 57}
    assert c.fleet_state()["engine_ranks"] == [51, 57]


# ---------------------------------------------------------------------------
# satellite (ISSUE 6): the equal-size stale-map blind spot is CLOSED —
# elastic push/pull frames are version-tagged on the wire
# ---------------------------------------------------------------------------

class _StubCoord:
    """Just enough CoordClient surface for ElasticShardServer.handle."""

    on_snapshot = None

    def __init__(self):
        self.reports = []

    def report(self, *a):
        self.reports.append(a)


def _same_count_rebalance_maps(n=100):
    """THE blind-spot construction: a join and a death landing in one
    rebalance — server 2 keeps a 50-param range but at a MOVED offset."""
    m1 = rebalance(ShardMap(0, n, ()), [1, 2])   # v1: s1=[0,50) s2=[50,100)
    m2 = rebalance(m1, [2, 3])                   # v2: s2=[0,50) s3=[50,100)
    e1, e2 = m1.entry_for(2), m2.entry_for(2)
    assert (e1.size == e2.size == 50) and (e1.lo, e1.hi) != (e2.lo, e2.hi)
    return m1, m2


def _push_frame(version, lo, hi, values):
    from distributed_ml_pytorch_tpu.utils.messaging import _split16

    return np.concatenate(
        [np.asarray([*_split16(version), *_split16(lo), *_split16(hi)],
                    np.float32), values])


def test_stamped_push_drops_same_size_cross_version_traffic():
    from distributed_ml_pytorch_tpu.utils.messaging import _split16

    n = 100
    flat0 = np.arange(n, dtype=np.float32)
    m1, m2 = _same_count_rebalance_maps(n)
    world = InProcessTransport.create_world(2)
    srv = ElasticShardServer(server_id=2, n_params=n, transport=world[0],
                             coord=_StubCoord(), init_params=flat0)
    srv._apply_map(m1)
    assert (srv.lo, srv.hi) == (50, 100)
    srv._apply_map(m2)
    assert (srv.lo, srv.hi) == (0, 50)  # equal size, moved offsets
    before = srv.central
    delta = np.full(50, 0.5, np.float32)
    # a worker still on v1 pushes a 50-param slice it cut for [50,100):
    # the length check alone could NEVER catch this — the range stamp does
    srv.handle(9, MessageCode.ShardPush,
               _push_frame(m1.version, 50, 100, delta))
    assert srv.stats["stale_dropped"] == 1
    np.testing.assert_array_equal(srv.central, before)
    # an UNSTAMPED equal-size push (the pre-upgrade wire) is refused too
    srv.handle(9, MessageCode.GradientUpdate, delta)
    assert srv.stats["stale_dropped"] == 2
    np.testing.assert_array_equal(srv.central, before)
    # the same slice cut for the agreed range applies
    srv.handle(9, MessageCode.ShardPush,
               _push_frame(m2.version, 0, 50, delta))
    np.testing.assert_array_equal(srv.central, before + delta)
    # speculative updates carry the stamp as well
    spec_stale = np.concatenate(
        [np.asarray([*_split16(7), *_split16(m1.version), *_split16(50),
                     *_split16(100)], np.float32), delta])
    srv.handle(9, MessageCode.SpeculativeUpdate, spec_stale)
    assert srv.stats["stale_dropped"] == 3 and srv.stats["spec_applied"] == 0
    # and the benign flip side (the drill's restore-rejoin): a version
    # bump whose range stayed put keeps in-flight pushes COMPATIBLE — an
    # acked gradient is never dropped for a stamp that moved nothing
    m3 = rebalance(m2, [2, 3])
    assert m3.entry_for(2).lo == 0 and m3.entry_for(2).hi == 50
    srv._apply_map(m3)
    srv.handle(9, MessageCode.ShardPush,
               _push_frame(m2.version, 0, 50, delta))
    np.testing.assert_array_equal(srv.central, before + 2 * delta)
    assert srv.stats["stale_dropped"] == 3  # unchanged
    for t in world.values():
        t.close()


def test_stamped_pull_reply_dropped_by_cross_version_worker():
    """The pull direction of the same blind spot: the server's reply is
    stamped (ShardParams) and a worker whose slot expects other offsets
    drops it instead of installing 50 params at the wrong place."""
    from distributed_ml_pytorch_tpu.parallel.async_ps import Listener
    from distributed_ml_pytorch_tpu.utils.messaging import _split16

    n = 100
    flat0 = np.arange(n, dtype=np.float32)
    m1, m2 = _same_count_rebalance_maps(n)
    world = InProcessTransport.create_world(2)
    srv = ElasticShardServer(server_id=2, n_params=n, transport=world[0],
                             coord=_StubCoord(), init_params=flat0)
    srv._apply_map(m1)
    srv._apply_map(m2)
    # the moved range is entirely fresh: hand over its values (first
    # install wins) so pulls are no longer parked
    srv.handle(1, MessageCode.RangeInstall, np.concatenate(
        [np.asarray([*_split16(0), *_split16(50)], np.float32),
         flat0[0:50]]))
    assert srv.pending_install is None
    # worker pulls: the reply must be a stamped ShardParams frame carrying
    # (version, lo, hi)
    srv.handle(1, MessageCode.ParameterRequest, np.zeros(0, np.float32))
    listener = Listener(transport=world[1])
    msg = world[1].recv(timeout=5)
    assert msg is not None and msg[1] == MessageCode.ShardParams
    listener.receive(*msg)
    stamp, values = listener.take_latest_versioned()
    assert stamp == (m2.version, 0, 50) and values.shape == (50,)
    # a worker slot still expecting m1's [50,100) sees the range mismatch
    # and drops the reply (ShardedAsynchronous._install_arrived's gate)
    e1 = m1.entry_for(2)
    assert stamp[1:] != (e1.lo, e1.hi)
    # legacy ParameterUpdate replies still flow stamp-less (None)
    listener.receive(1, MessageCode.ParameterUpdate, np.zeros(50, np.float32))
    stamp2, values2 = listener.take_latest_versioned()
    assert stamp2 is None and values2.shape == (50,)
    for t in world.values():
        t.close()


# ---------------------------------------------------------------------------
# ISSUE 6: the coordinator's engine-scaling advisory (per-engine metrics)
# ---------------------------------------------------------------------------

def test_engine_scaling_advisory_from_reported_metrics():
    from distributed_ml_pytorch_tpu.coord.coordinator import KIND_ENGINE

    clock = _Clock()
    advice = []
    c = Coordinator(None, 100, lease=10.0, clock=clock, speculation=False,
                    engine_occ_high=0.85, engine_occ_low=0.2,
                    engine_slo_ttft_ms=500.0, scale_cooldown=5.0,
                    on_scale=lambda d, detail: advice.append((d, detail)))
    c.handle(51, MessageCode.CoordJoin, encode_join(KIND_ENGINE, 10))
    c.handle(52, MessageCode.CoordJoin, encode_join(KIND_ENGINE, 11))
    # no reports yet: no advice (a just-joined fleet must not be scaled)
    assert c.check_engine_scaling() is None
    # engines renew with (occupancy%, queue depth, TTFT ms) in the renewal
    # slots — 95% mean occupancy breaches occ_high
    c.handle(51, MessageCode.LeaseRenew, encode_renew(10, 95, 3, 80.0))
    c.handle(52, MessageCode.LeaseRenew, encode_renew(11, 95, 2, 90.0))
    assert c.check_engine_scaling() == "up"
    assert advice and advice[-1][0] == "up"
    assert advice[-1][1]["per_engine"][51]["occupancy"] == 0.95
    # cooldown: immediately asking again stays quiet
    assert c.check_engine_scaling() is None
    clock.t += 6.0
    # healthy occupancy but TTFT SLO breached: still scale-up
    c.handle(51, MessageCode.LeaseRenew, encode_renew(10, 50, 0, 900.0))
    c.handle(52, MessageCode.LeaseRenew, encode_renew(11, 50, 0, 800.0))
    assert c.check_engine_scaling() == "up"
    clock.t += 6.0
    # near-idle fleet with >1 replicas: scale-down advised
    c.handle(51, MessageCode.LeaseRenew, encode_renew(10, 5, 0, 10.0))
    c.handle(52, MessageCode.LeaseRenew, encode_renew(11, 5, 0, 12.0))
    assert c.check_engine_scaling() == "down"
    clock.t += 6.0
    # a FULLY idle fleet (all-zero renewals) still earns scale-down —
    # idle renewals count as reports, only never-renewed members don't
    c.handle(51, MessageCode.LeaseRenew, encode_renew(10, 0, 0, 0.0))
    c.handle(52, MessageCode.LeaseRenew, encode_renew(11, 0, 0, 0.0))
    assert c.check_engine_scaling() == "down"
    # the decision log carries the evidence
    assert any("scale-up advised" in e for e in c.events)
    assert any("scale-down advised" in e for e in c.events)
