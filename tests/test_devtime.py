"""Tests for utils/devtime — the device-true timing instrument.

The TPU path (profiler trace → device spans) can't run on the CPU test
mesh, so the parser is exercised on a canned Chrome-trace dict shaped like
a real capture (process_name metadata + nested device spans), and the
public entry is exercised through its wall-clock fallback.
"""

import jax.numpy as jnp

from distributed_ml_pytorch_tpu.utils.devtime import (
    DeviceTiming,
    _top_level_total,
    device_time,
    parse_device_spans,
)


def _canned_trace():
    return {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 3,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "name": "process_name", "pid": 9,
             "args": {"name": "/host:CPU"}},
            # two calls of one program, 2.5 ms each (dur is microseconds)
            {"ph": "X", "pid": 3, "name": "jit_step(123)", "dur": 2500},
            {"ph": "X", "pid": 3, "name": "jit_step(123)", "dur": 2500},
            # nested fusion spans — counted under their own names only
            {"ph": "X", "pid": 3, "name": "fusion.1", "dur": 2000},
            {"ph": "X", "pid": 3, "name": "copy.2", "dur": 100},
            # host spans must be ignored even with a 'dur'
            {"ph": "X", "pid": 9, "name": "jit_step(123)", "dur": 99999},
        ]
    }


def test_parse_device_spans_filters_host_and_groups_by_name():
    spans = parse_device_spans(_canned_trace())
    assert spans["jit_step(123)"] == (2, 0.005)
    assert spans["fusion.1"] == (1, 0.002)
    assert "copy.2" in spans
    # the host's 99999 span did not leak into the device total
    n, total = _top_level_total(spans)
    assert n == 2
    assert abs(total - 0.005) < 1e-12


def test_top_level_total_sums_distinct_programs():
    spans = {
        "jit_fwd(1)": (4, 0.004),
        "jit_bwd(2)": (4, 0.012),
        "fusion.3": (4, 0.003),  # nested — excluded
    }
    n, total = _top_level_total(spans)
    assert n == 4
    assert abs(total - 0.016) < 1e-12


def test_device_time_wallclock_fallback_off_tpu():
    # on the CPU test mesh the fallback path must produce a sane timing
    f = lambda x: x * 2.0
    t = device_time(f, jnp.ones((4,)), calls=3, warmup=1)
    assert isinstance(t, DeviceTiming)
    assert t.source == "wallclock"
    assert t.per_call_s > 0
    assert t.calls == 3
