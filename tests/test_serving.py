"""Continuous-batching serving engine: parity with generate(), scheduling,
admission control, and the slot pool's exactness contract.

The load-bearing property is ARRIVAL-ORDER-INDEPENDENT EXACTNESS: whatever
mix of requests shares the slot pool, each request's output must be
token-identical (CPU) to a standalone ``generate()`` with the same
``(params, prompt, rng)`` — slots are independent vmap lanes over the same
attention module, so sharing a batch must never leak between requests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models.generate import (
    generate,
    sample_tokens,
    sample_tokens_dynamic,
)
from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
from distributed_ml_pytorch_tpu.serving.engine import (
    QueueFullError,
    ServingEngine,
)

VOCAB = 64


def tiny_lm():
    return TransformerLM(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_len=128
    )


@pytest.fixture(scope="module")
def lm_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def make_engine(lm_and_params, **kw):
    model, params = lm_and_params
    kw.setdefault("slots", 3)
    kw.setdefault("cache_size", 96)
    kw.setdefault("decode_block", 4)
    kw.setdefault("prefill_bucket", 8)
    return ServingEngine(model, params, **kw)


def ref_tokens(model, params, prompt, max_new, **kw):
    """Standalone generate() continuation for one request (the oracle)."""
    out = generate(model, params, jnp.asarray(prompt, jnp.int32)[None],
                   max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def prompts_rng(seed=0):
    return np.random.default_rng(seed)


def test_single_request_greedy_matches_generate(lm_and_params):
    model, params = lm_and_params
    eng = make_engine(lm_and_params)
    prompt = prompts_rng(1).integers(0, VOCAB, size=5)
    req = eng.submit(prompt, 20)
    eng.run_until_idle()
    assert req.done and len(req.tokens) == 20
    assert req.tokens == ref_tokens(model, params, prompt, 20)


def test_mixed_arrival_parity_and_midflight_admission(lm_and_params):
    """The acceptance-criterion test: a late request is admitted while an
    earlier one is mid-decode, and EVERY request still matches its
    standalone generate() output exactly."""
    model, params = lm_and_params
    eng = make_engine(lm_and_params)
    rng = prompts_rng(2)
    pa = rng.integers(0, VOCAB, size=6)
    pb = rng.integers(0, VOCAB, size=3)
    pc = rng.integers(0, VOCAB, size=9)

    ra = eng.submit(pa, 30)
    eng.step()  # admits A, decodes one block
    eng.step()
    assert not ra.done and len(ra.tokens) > 1  # A is mid-decode
    rb = eng.submit(pb, 9)
    rc = eng.submit(pc, 17)
    eng.run_until_idle()

    assert rb.active_at_admit >= 1  # B joined while A held a slot
    for req, prompt, n in ((ra, pa, 30), (rb, pb, 9), (rc, pc, 17)):
        assert req.done and len(req.tokens) == n
        assert req.tokens == ref_tokens(model, params, prompt, n), (
            f"request {req.request_id} diverged from standalone generate()")


def test_sampled_request_matches_generate_rng(lm_and_params):
    """Temperature/top-k/top-p requests must reproduce generate()'s exact
    token stream for the same seed — the per-slot fold_in key schedule is
    part of the engine's contract, not just greedy argmax."""
    model, params = lm_and_params
    eng = make_engine(lm_and_params)
    prompt = prompts_rng(3).integers(0, VOCAB, size=4)
    req = eng.submit(prompt, 18, temperature=0.8, top_k=7, top_p=0.9, seed=11)
    other = eng.submit(prompts_rng(4).integers(0, VOCAB, size=7), 12)
    eng.run_until_idle()
    want = ref_tokens(model, params, prompt, 18, temperature=0.8,
                      top_k=7, top_p=0.9, rng=jax.random.key(11))
    assert req.tokens == want
    assert other.done and len(other.tokens) == 12


def test_parity_independent_of_arrival_order(lm_and_params):
    """Same request set, two arrival orders -> identical per-request
    outputs (and equal to running each alone)."""
    model, params = lm_and_params
    rng = prompts_rng(5)
    reqs = [(rng.integers(0, VOCAB, size=int(rng.integers(2, 10))),
             int(rng.integers(5, 22))) for _ in range(4)]
    outs = []
    for order in (range(4), reversed(range(4))):
        eng = make_engine(lm_and_params)
        handles = {}
        for i in order:
            prompt, n = reqs[i]
            handles[i] = eng.submit(prompt, n)
            eng.step()  # interleave admission with decode
        eng.run_until_idle()
        outs.append({i: handles[i].tokens for i in range(4)})
    assert outs[0] == outs[1]
    for i, (prompt, n) in enumerate(reqs):
        assert outs[0][i] == ref_tokens(model, params, prompt, n)


def test_prefill_bucketing_is_exact(lm_and_params):
    """Right-padding prompts to the prefill bucket must not change a single
    token (padded K/V is causally invisible and cursor-rewound)."""
    model, params = lm_and_params
    prompt = prompts_rng(6).integers(0, VOCAB, size=5)
    outs = []
    for bucket in (1, 8):
        eng = make_engine(lm_and_params, prefill_bucket=bucket)
        req = eng.submit(prompt, 13)
        eng.run_until_idle()
        outs.append(req.tokens)
    assert outs[0] == outs[1] == ref_tokens(model, params, prompt, 13)


def test_single_token_prompt_pads_past_decode_discriminator(lm_and_params):
    """A 1-token prompt must still prefill correctly: inside the blocked
    module ``s == 1`` means a DECODE step, so admission pads the prompt to
    at least 2 even at prefill_bucket=1 — and stays exact vs generate()."""
    model, params = lm_and_params
    eng = make_engine(lm_and_params, prefill_bucket=1)
    prompt = np.asarray([7], np.int32)
    req = eng.submit(prompt, 14)
    eng.run_until_idle()
    assert req.tokens == ref_tokens(model, params, prompt, 14)


def test_kv_quant_pool_deterministic_and_in_vocab(lm_and_params):
    """int8 slot caches: deterministic, shape-correct, in-vocab; and the
    first generated token matches the exact-cache engine (prefill logits
    carry no quantization noise — the single-prefill contract holds for
    every fresh slot admission)."""
    outs = []
    prompt = prompts_rng(7).integers(0, VOCAB, size=6)
    for quant in (True, True, False):
        eng = make_engine(lm_and_params, kv_quant=quant)
        req = eng.submit(prompt, 15)
        eng.run_until_idle()
        outs.append(req.tokens)
    assert outs[0] == outs[1]
    assert len(outs[0]) == 15
    assert all(0 <= t < VOCAB for t in outs[0])
    assert outs[0][0] == outs[2][0]


def test_queue_backpressure_raises(lm_and_params):
    eng = make_engine(lm_and_params, slots=1, max_queue=2)
    prompt = np.arange(4)
    eng.submit(prompt, 6)
    eng.submit(prompt, 6)
    with pytest.raises(QueueFullError):
        eng.submit(prompt, 6)
    eng.run_until_idle()
    summary = eng.slo_summary()
    assert summary["rejected"] == 1 and summary["completed"] == 2


def test_submit_rejects_oversized_request(lm_and_params):
    eng = make_engine(lm_and_params, cache_size=32)
    with pytest.raises(ValueError, match="cache rows"):
        eng.submit(np.arange(4), 40)
    with pytest.raises(ValueError):
        eng.submit(np.arange(4), 0)


def test_cancel_queued_and_active(lm_and_params):
    eng = make_engine(lm_and_params, slots=1)
    ra = eng.submit(np.arange(5), 25)
    rb = eng.submit(np.arange(3), 10)
    eng.step()  # A active, B queued
    assert eng.cancel(rb.request_id)
    eng.step()
    assert eng.cancel(ra.request_id)
    eng.run_until_idle()
    assert ra.done and ra.cancelled and len(ra.tokens) < 25
    assert rb.done and rb.cancelled and rb.tokens == []
    assert not eng.cancel(12345)


def test_eos_token_truncates_stream(lm_and_params):
    model, params = lm_and_params
    prompt = prompts_rng(8).integers(0, VOCAB, size=5)
    full = ref_tokens(model, params, prompt, 20)
    eos = full[4]  # force an early stop at a token greedy decode emits
    eng = make_engine(lm_and_params)
    req = eng.submit(prompt, 20, eos_token=eos)
    eng.run_until_idle()
    stop = full.index(eos)
    assert req.tokens == full[: stop + 1]


def test_max_new_tokens_one_completes_at_admission(lm_and_params):
    model, params = lm_and_params
    prompt = prompts_rng(9).integers(0, VOCAB, size=6)
    eng = make_engine(lm_and_params)
    req = eng.submit(prompt, 1)
    eng.run_until_idle()
    assert req.done and req.tokens == ref_tokens(model, params, prompt, 1)
    # the slot freed at admission must be swept like any evicted slot
    assert eng.pool.live_lengths().max() == 0


def test_slot_reuse_after_completion_is_clean(lm_and_params):
    """A recycled slot must give the same output as a fresh engine — no
    leakage from the previous occupant's cache."""
    model, params = lm_and_params
    eng = make_engine(lm_and_params, slots=1)
    p1 = prompts_rng(10).integers(0, VOCAB, size=7)
    p2 = prompts_rng(11).integers(0, VOCAB, size=4)
    eng.submit(p1, 12)
    eng.run_until_idle()
    req = eng.submit(p2, 16)  # reuses the single slot
    eng.run_until_idle()
    assert req.tokens == ref_tokens(model, params, p2, 16)


def test_slo_summary_reports_percentiles(lm_and_params):
    eng = make_engine(lm_and_params)
    for seed in range(3):
        eng.submit(prompts_rng(seed).integers(0, VOCAB, size=4), 9)
    eng.run_until_idle()
    s = eng.slo_summary()
    assert s["completed"] == 3
    assert s["ttft_ms"] is not None and s["ttft_ms"]["count"] == 3
    assert set(s["ttft_ms"]) >= {"count", "mean", "p50", "p90", "p99", "max"}
    assert s["tpot_ms"]["count"] == 3 and s["tpot_ms"]["p50"] > 0
    assert 0 < s["slot_occupancy"] <= 1
    assert s["queue_depth"]["max"] >= 0


def test_live_lengths_track_slot_progress(lm_and_params):
    eng = make_engine(lm_and_params)
    eng.submit(np.arange(1, 6), 20)
    eng.step()
    lens = eng.pool.live_lengths()
    assert lens.shape == (3,)
    assert lens.max() == 5 + eng.pool.decode_block  # prompt + one block
    eng.run_until_idle()
    assert eng.pool.live_lengths().max() == 0  # everything evicted + reset


def test_sample_tokens_dynamic_matches_scalar_rowwise():
    """The traced-params sampler must agree bit-for-bit with sample_tokens
    for every configuration a request can carry (greedy, temp-only, top-k,
    top-p, combined) — this equivalence is what lets one compiled block
    program serve heterogeneous sampling params."""
    logits = jnp.asarray(
        np.random.default_rng(0).normal(size=(5, VOCAB)) * 2.0, jnp.float32)
    configs = [
        (0.0, 0, 1.0), (1.0, 0, 1.0), (0.7, 5, 1.0), (0.7, 0, 0.9),
        (1.3, 8, 0.85), (0.5, 1, 1.0), (0.9, VOCAB + 10, 0.5),
    ]
    for i, (t, k, p) in enumerate(configs):
        key = jax.random.key(100 + i)
        for row in range(logits.shape[0]):
            want = sample_tokens(
                logits[row][None], key, temperature=t, top_k=k, top_p=p)[0]
            got = sample_tokens_dynamic(
                logits[row][None], key[None],
                jnp.asarray([t]), jnp.asarray([k]), jnp.asarray([p]))[0]
            assert int(got) == int(want), (t, k, p, row)


def test_sample_tokens_dynamic_heterogeneous_rows():
    """A batch mixing greedy and differently-truncated sampled rows equals
    running each row separately."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, VOCAB)), jnp.float32)
    keys = jax.vmap(jax.random.key)(jnp.arange(4, dtype=jnp.uint32))
    temps = jnp.asarray([0.0, 0.8, 1.2, 0.6])
    ks = jnp.asarray([0, 5, 0, 3])
    ps = jnp.asarray([1.0, 1.0, 0.8, 0.7])
    batched = sample_tokens_dynamic(logits, keys, temps, ks, ps)
    for row in range(4):
        alone = sample_tokens_dynamic(
            logits[row][None], keys[row][None], temps[row][None],
            ks[row][None], ps[row][None])[0]
        assert int(batched[row]) == int(alone)
