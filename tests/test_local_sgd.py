"""Local-SGD (compiled periodic averaging) correctness tests."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_ml_pytorch_tpu.data import load_cifar10
from distributed_ml_pytorch_tpu.models import AlexNet
from distributed_ml_pytorch_tpu.parallel.local_sgd import make_local_sgd_round
from distributed_ml_pytorch_tpu.parallel.sync import (
    make_sync_train_step,
    replicate,
    shard_batch,
)
from distributed_ml_pytorch_tpu.training.trainer import create_train_state


def _put_round(mesh, rx, ry):
    rx = jax.device_put(rx, NamedSharding(mesh, P(None, "data", None, None, None)))
    ry = jax.device_put(ry, NamedSharding(mesh, P(None, "data")))
    return rx, ry


def test_k1_local_sgd_equals_sync_dp(mesh8):
    """With plain SGD, averaging params after 1 local step from a common start
    is algebraically identical to per-step gradient allreduce."""
    x, y, *_ = load_cifar10(n_train=64, n_test=16, synthetic=True)
    model = AlexNet()
    state0, tx = create_train_state(model, jax.random.key(0), lr=0.05)

    sync_state = replicate(mesh8, state0)
    local_state = replicate(mesh8, state0)
    sync_step = make_sync_train_step(model, tx, mesh8)
    round_fn = make_local_sgd_round(model, tx, mesh8)
    rng = replicate(mesh8, jax.random.key(1))

    bx, by = shard_batch(mesh8, x[:64], y[:64])
    sync_state, sync_loss = sync_step(sync_state, bx, by, rng)

    rx, ry = _put_round(mesh8, x[:64][None], y[:64][None])  # k=1 round
    local_state, local_losses = round_fn(local_state, rx, ry, rng)

    for a, b in zip(
        jax.tree.leaves(sync_state.params), jax.tree.leaves(local_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_local_sgd_round_shapes_and_progress(mesh8):
    x, y, *_ = load_cifar10(n_train=512, n_test=16, synthetic=True)
    from distributed_ml_pytorch_tpu.models import LeNet

    model = LeNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    state = replicate(mesh8, state)
    round_fn = make_local_sgd_round(model, tx, mesh8)
    rng = replicate(mesh8, jax.random.key(1))
    k, gb = 4, 64
    round_means = []
    for r in range(6):
        sel = slice((r % 2) * k * gb, (r % 2 + 1) * k * gb)
        rx, ry = _put_round(
            mesh8, x[sel].reshape(k, gb, 32, 32, 3), y[sel].reshape(k, gb)
        )
        state, losses = round_fn(state, rx, ry, rng)
        assert losses.shape == (k,)
        round_means.append(float(np.mean(np.asarray(losses))))
    assert round_means[-1] < round_means[0], round_means
    # params remain replicated (identical) across devices after averaging
    leaf = jax.tree.leaves(state.params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_k2_local_sgd_differs_from_sync(mesh8):
    """With k>1 the per-device trajectories diverge between averages, so the
    result must NOT equal two sync-DP steps — proving the local steps really
    use local gradients (no hidden cross-device psum)."""
    x, y, *_ = load_cifar10(n_train=128, n_test=16, synthetic=True)
    model = AlexNet()
    state0, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    sync_state = replicate(mesh8, state0)
    local_state = replicate(mesh8, state0)
    sync_step = make_sync_train_step(model, tx, mesh8)
    round_fn = make_local_sgd_round(model, tx, mesh8)
    rng = replicate(mesh8, jax.random.key(1))

    for s in range(2):
        bx, by = shard_batch(mesh8, x[s * 64 : (s + 1) * 64], y[s * 64 : (s + 1) * 64])
        sync_state, _ = sync_step(sync_state, bx, by, rng)
    rx, ry = _put_round(mesh8, x[:128].reshape(2, 64, 32, 32, 3), y[:128].reshape(2, 64))
    local_state, _ = round_fn(local_state, rx, ry, rng)

    diffs = [
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(
            jax.tree.leaves(sync_state.params), jax.tree.leaves(local_state.params)
        )
    ]
    assert max(diffs) > 1e-6, "local-SGD trajectory identical to sync DP — grads are not local"


def test_local_sgd_honors_optimizer_knobs(mesh8, tmp_path):
    """--optimizer adam + --lr-schedule cosine through the local-sgd loop:
    the knobs must actually train (adam momentum state exists, loss falls)
    now that the CLI no longer rejects them for this mode."""
    from distributed_ml_pytorch_tpu.parallel.local_sgd import train_local_sgd
    from distributed_ml_pytorch_tpu.training.cli import build_parser

    args = build_parser().parse_args([
        "--mode", "local-sgd", "--epochs", "2", "--synthetic-data",
        "--synthetic-train-size", "512", "--synthetic-test-size", "32",
        "--batch-size", "2", "--model", "lenet", "--lr", "0.003",
        "--optimizer", "adam", "--lr-schedule", "cosine", "--grad-clip", "1.0",
        "--log-interval", "1000", "--log-dir", str(tmp_path), "--sync-every", "2",
    ])
    state, logger = train_local_sgd(args, mesh8)
    losses = [r["training_loss"] for r in logger.records]
    q = max(1, len(losses) // 4)
    assert float(np.mean(losses[-q:])) < float(np.mean(losses[:q]))
    # adam leaves second-moment state behind — proof the knob took effect
    flat = jax.tree_util.tree_leaves(state.opt_state)
    assert len(flat) > 1
    # the rounds' averaging must not launder adam's int32 count into f32
    # (pmean(int32) returns float32; integer leaves are pmax'd instead)
    import jax.numpy as jnp

    assert any(jnp.issubdtype(l.dtype, jnp.integer) for l in flat), (
        "adam's count leaf lost its integer dtype across rounds"
    )


def test_local_sgd_step_counter_advances(mesh8):
    x, y, *_ = load_cifar10(n_train=128, n_test=16, synthetic=True)
    model = AlexNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=0.01)
    state = replicate(mesh8, state)
    round_fn = make_local_sgd_round(model, tx, mesh8)
    rng = replicate(mesh8, jax.random.key(1))
    rx, ry = _put_round(mesh8, x[:128].reshape(2, 64, 32, 32, 3), y[:128].reshape(2, 64))
    state, _ = round_fn(state, rx, ry, rng)
    assert int(np.asarray(state.step)) == 2
