"""Sync data-parallel correctness: the psum-allreduce step over 8 virtual
devices must match single-device large-batch SGD exactly (the DDP invariant),
and the p2p ppermute demo must reproduce the reference's observable behavior
(``pytorch_p2p_ex.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.data import load_cifar10
from distributed_ml_pytorch_tpu.models import LeNet, AlexNet
from distributed_ml_pytorch_tpu.parallel.p2p import p2p_send_recv, p2p_shift, run_demo
from distributed_ml_pytorch_tpu.parallel.sync import (
    make_sync_scan_step,
    make_sync_train_step,
    put_sharded,
    replicate,
    shard_batch,
)
from distributed_ml_pytorch_tpu.training.trainer import (
    create_train_state,
    make_train_step,
)


def test_sync_step_matches_single_device(mesh8):
    """8-way DDP with per-device batch 8 == single-device batch 64."""
    x, y, *_ = load_cifar10(n_train=64, n_test=16, synthetic=True)
    model = AlexNet()  # no dropout → deterministic comparison
    state_s, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    state_p = replicate(mesh8, state_s)

    single_step = make_train_step(model, tx)
    sync_step = make_sync_train_step(model, tx, mesh8)

    rng = jax.random.key(7)
    prng = replicate(mesh8, rng)
    bx, by = shard_batch(mesh8, x[:64], y[:64])

    for _ in range(3):
        state_s, loss_s = single_step(state_s, x[:64], y[:64], rng)
        state_p, loss_p = sync_step(state_p, bx, by, prng)
        np.testing.assert_allclose(float(loss_s), float(loss_p), rtol=1e-5)

    for a, b in zip(jax.tree.leaves(state_s.params), jax.tree.leaves(state_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_sync_scan_matches_per_step_exactly(mesh8):
    """K scanned DDP steps in one dispatch == K per-step dispatches (same
    body, same rng stream) — the --steps-per-dispatch contract for sync."""
    from jax.sharding import PartitionSpec as P

    x, y, *_ = load_cifar10(n_train=192, n_test=16, synthetic=True)
    model = AlexNet()
    state_a, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    state_a = replicate(mesh8, state_a)
    state_b = replicate(mesh8, state_a)
    rng = replicate(mesh8, jax.random.key(5))

    step = make_sync_train_step(model, tx, mesh8)
    scan = make_sync_scan_step(model, tx, mesh8)

    K, B = 3, 64
    per_losses = []
    for i in range(K):
        bx, by = shard_batch(mesh8, x[i * B:(i + 1) * B], y[i * B:(i + 1) * B])
        state_a, loss = step(state_a, bx, by, rng)
        per_losses.append(float(loss))

    bxs = put_sharded(mesh8, x[: K * B].reshape(K, B, 32, 32, 3), P(None, "data"))
    bys = put_sharded(mesh8, y[: K * B].reshape(K, B), P(None, "data"))
    state_b, losses = scan(state_b, bxs, bys, rng)

    np.testing.assert_allclose(per_losses, np.asarray(losses), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_sync_step_loss_decreases(mesh8):
    x, y, *_ = load_cifar10(n_train=128, n_test=16, synthetic=True)
    model = LeNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=0.05)
    state = replicate(mesh8, state)
    step = make_sync_train_step(model, tx, mesh8)
    rng = replicate(mesh8, jax.random.key(3))
    bx, by = shard_batch(mesh8, x, y)
    losses = []
    for _ in range(20):
        state, loss = step(state, bx, by, rng)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_p2p_send_recv(mesh8):
    x = shard_batch(mesh8, jnp.arange(8.0))
    out = np.asarray(p2p_send_recv(x, mesh8, [(3, 1)]))
    # dst gets src's shard; everyone else zeros (ppermute semantics)
    expected = np.zeros(8)
    expected[1] = 3.0
    np.testing.assert_array_equal(out, expected)


def test_p2p_send_recv_keep_fill(mesh8):
    x = shard_batch(mesh8, jnp.arange(8.0))
    out = np.asarray(p2p_send_recv(x, mesh8, [(3, 1)], fill="keep"))
    # dst overwritten, every other device keeps its shard (torch send/recv semantics)
    expected = np.arange(8.0)
    expected[1] = 3.0
    np.testing.assert_array_equal(out, expected)


def test_p2p_ring_shift(mesh8):
    x = shard_batch(mesh8, jnp.arange(8.0))
    out = np.asarray(p2p_shift(x, mesh8, shift=1))
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


def test_p2p_demo_parity(capsys):
    """rank 0 sends 1.0 to rank 1 — both end up printing their values."""
    vals = run_demo(2)
    assert vals[1] == 1.0
    out = capsys.readouterr().out
    assert "Rank  0" in out and "Rank  1" in out
