"""ISSUE 17 — control-plane durability and fencing (coord/coordinator.py).

The coordinator becomes a crash-restartable member of its own fleet:

1. **Durable restart** — every control-plane transition is WAL'd
   (log-then-mutate, distcheck DC406) and periodically checkpointed;
   a new life replays ckpt+WAL and reconstructs the member table, the
   version clocks, and — critically — the durable parked-rank table.
2. **Epoch fencing** — a persisted monotonic epoch stamps every
   outbound control frame; members reject stale-epoch commands, so a
   zombie pre-crash coordinator cannot rebalance, preempt, or roll
   back the fleet its successor owns.
3. **Restart grace window** — lease expiry and speculation stay
   suspended until the join-retry traffic re-populates liveness; a
   control-plane blip must not cascade into mass eviction.

The drill/model acceptance (kill the coordinator mid-snapshot and
mid-preemption, bounded-exhaustive `coordfail` plane) lives in
test_distmodel.py and the slow drill test at the bottom of this file.
"""

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.coord.coordinator import (
    KIND_SHARD,
    Coordinator,
    encode_join,
    encode_leave,
    encode_preempt_done,
    encode_preempt_request,
    encode_renew,
    encode_rollback_request,
)
from distributed_ml_pytorch_tpu.coord.member import CoordClient
from distributed_ml_pytorch_tpu.coord.sched import (
    PARKED,
    FleetScheduler,
)
from distributed_ml_pytorch_tpu.coord.shardmap import ShardEntry, ShardMap
from distributed_ml_pytorch_tpu.coord.tenants import (
    TENANT_SERVING,
    Tenant,
    TenantRegistry,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    stamp_epoch,
    strip_epoch,
)

pytestmark = pytest.mark.coordfail

TRAIN, SERVE = 1, 2


def _close(world):
    for t in world.values():
        t.close()


def _registry():
    reg = TenantRegistry()
    reg.register(Tenant(tenant_id=TRAIN, name="train", priority=1,
                        demand=2, min_slots=1))
    reg.register(Tenant(tenant_id=SERVE, name="serve",
                        kind=TENANT_SERVING, priority=5, demand=0))
    return reg


def _durable_coord(world, tmp_path, now, *, lease=2.0, **kw):
    return Coordinator(world[0], 8, lease=lease, speculation=False,
                       clock=lambda: now[0], durable_dir=str(tmp_path),
                       **kw)


def _attach_sched(coord, *, with_members=True):
    sched = FleetScheduler(coord, registry=_registry(),
                           require_manifest=False)
    if with_members:
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))
            sched.register_member_slot(rank, TRAIN)
    return sched


def _park_victim(coord, sched, now):
    """Demand spike -> PreemptRequest -> PreemptDone; the victim parks."""
    sched.registry.set_demand(SERVE, 1)
    sched.tick(now[0])
    p = sched._pending
    assert p is not None
    victim, gid = p["slot"].rank, p["grant_id"]
    coord.handle(victim, MessageCode.PreemptDone,
                 encode_preempt_done(gid, 0, 4, 8, 17))
    return victim, gid


# ------------------------------------- satellite: durable park exemption

@pytest.mark.sched
def test_restart_preserves_parked_rank_lease_exemption(tmp_path):
    """THE strand-forever regression (ISSUE 17 satellite 1): a member
    parked mid-preemption must survive a coordinator crash-restart —
    before the durable park table, the successor's lease sweep silently
    evicted it (its exemption lived only in the dead scheduler's RAM)."""
    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now, lease=2.0)
        sched = _attach_sched(coord)
        victim, _ = _park_victim(coord, sched, now)
        assert victim in coord.members

        # the coordinator crashes; its successor restores from disk
        coord2 = _durable_coord(world, tmp_path, now, lease=2.0)
        assert coord2.epoch == coord.epoch + 1
        assert coord2.parked_ranks() == {victim}

        now[0] += 50.0  # way past every lease AND the grace window
        coord2.tick()
        assert victim in coord2.members  # a park, not a death
        assert 1 not in coord2.members   # the unparked silent rank expired
    finally:
        _close(world)


@pytest.mark.sched
def test_restart_reconciles_sched_slot_to_parked(tmp_path):
    """A successor's freshly attached scheduler re-learns the park from
    the durable table: the slot comes back PARKED with the restore
    ticket intact, so the resume path still works and the slot can
    never be double-granted."""
    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now)
        sched = _attach_sched(coord)
        victim, _ = _park_victim(coord, sched, now)
        coord.tick()  # checkpoint covers the ledger state too

        coord2 = _durable_coord(world, tmp_path, now)
        sched2 = FleetScheduler(coord2, registry=_registry(),
                                require_manifest=False)
        parked_slots = [s for s in sched2.ledger.slots.values()
                        if s.state == PARKED]
        assert len(parked_slots) == 1
        slot = parked_slots[0]
        assert slot.rank == victim
        assert slot.parked["rank"] == victim
        assert slot.parked["apply_seq"] == 17
        assert sched2.ledger.audit() == []
    finally:
        _close(world)


# --------------------------------------------------- durable restart core

def test_restart_restores_members_map_and_epoch(tmp_path):
    """ckpt+WAL replay reconstructs the member table and version clocks;
    the persisted epoch is strictly monotonic across lives."""
    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now)
        assert coord.epoch == 1  # first life over an empty dir
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))
        v1 = coord.shard_map.version
        assert v1 > 0

        coord2 = _durable_coord(world, tmp_path, now)
        assert coord2.epoch == 2
        assert set(coord2.members) == {1, 2}
        assert coord2.members[1].incarnation == 1
        assert coord2.shard_map.version == v1
        assert [(e.server_id, e.lo, e.hi) for e in coord2.shard_map.entries] \
            == [(e.server_id, e.lo, e.hi) for e in coord.shard_map.entries]
        assert coord2.restored_members == 2
    finally:
        _close(world)


def test_wal_records_after_checkpoint_replay_on_top_of_it(tmp_path):
    """A checkpoint covers its prefix; ops journaled AFTER it replay on
    top — the seq gate makes restore idempotent, never double-applied."""
    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now)
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))
        coord.checkpoint()
        # post-ckpt: rank 2 leaves (WAL-only — no newer checkpoint)
        coord.handle(2, MessageCode.CoordLeave, encode_leave(2))
        assert 2 not in coord.members

        coord2 = _durable_coord(world, tmp_path, now)
        assert set(coord2.members) == {1}
    finally:
        _close(world)


def test_first_life_over_empty_dir_has_no_grace_window(tmp_path):
    now = [0.0]
    world = InProcessTransport.create_world(2)
    try:
        coord = _durable_coord(world, tmp_path, now, lease=2.0)
        assert coord.restored_members == 0 and coord._grace_until == 0.0
        coord.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 1))
        now[0] += 50.0
        coord.tick()
        assert 1 not in coord.members  # normal lease expiry, no grace
    finally:
        _close(world)


# ------------------------------------------------------ restart grace window

def test_grace_window_suspends_lease_expiry_until_reattach(tmp_path):
    """A control-plane blip must not cascade into mass eviction: after a
    restart, restored members are exempt from lease expiry until the
    grace window ends — members that re-attach inside it survive."""
    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now, lease=2.0)
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))

        coord2 = _durable_coord(world, tmp_path, now, lease=2.0,
                                grace=10.0)
        now[0] = 5.0  # past every lease, inside the grace window
        coord2.tick()
        assert set(coord2.members) == {1, 2}  # nobody evicted blind
        # rank 1 re-attaches (the join-retry traffic); rank 2 stays silent
        coord2.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 1))
        now[0] = 11.0
        coord2.handle(1, MessageCode.LeaseRenew, encode_renew(1))
        now[0] = 12.0  # grace over: expiry re-armed
        coord2.tick()
        assert 1 in coord2.members
        assert 2 not in coord2.members  # truly-dead member finally expires
    finally:
        _close(world)


def test_grace_window_closes_early_when_every_member_reattaches(tmp_path):
    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now, lease=2.0)
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))

        coord2 = _durable_coord(world, tmp_path, now, lease=2.0,
                                grace=100.0)
        for rank in (1, 2):
            coord2.handle(rank, MessageCode.CoordJoin,
                          encode_join(KIND_SHARD, rank))
        coord2.tick()
        assert coord2._grace_until == 0.0  # closed early, not at +100 s
        now[0] = 5.0  # silence past the lease is fatal again
        coord2.tick()
        assert coord2.members == {}
    finally:
        _close(world)


def test_expire_on_restart_knob_disables_the_grace_window(tmp_path):
    """``grace=0`` is the distmodel ``expire_on_restart`` mutation: the
    successor evicts every restored member the instant its (unrenewable)
    lease reads stale — the mass-eviction cascade the window prevents."""
    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now, lease=2.0)
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))

        coord2 = _durable_coord(world, tmp_path, now, lease=2.0, grace=0.0)
        now[0] = 3.0  # one lease past the restore
        coord2.tick()
        assert coord2.members == {}  # everyone evicted before rejoining
    finally:
        _close(world)


# ---------------------------------------------------------- epoch fencing

def _client(world, rank=1, **kw):
    return CoordClient(world[rank], "shard", renew_interval=30.0, **kw)


def _map_frame(version, epoch):
    m = ShardMap(version, 8, [ShardEntry(1, 0, 8)])
    return stamp_epoch(m.encode(), epoch)


def test_stale_epoch_rebalance_rejected_on_the_wire():
    """Command path 1/3 (rebalance): a zombie pre-crash coordinator's
    ShardMapUpdate — stamped with its old epoch — must not move the
    member, whatever map version it claims."""
    world = InProcessTransport.create_world(2)
    client = _client(world)
    try:
        client._handle(MessageCode.ShardMapUpdate, _map_frame(3, epoch=2))
        assert client.current_map().version == 3
        assert client.coord_epoch == 2
        client._handle(MessageCode.ShardMapUpdate, _map_frame(9, epoch=1))
        assert client.current_map().version == 3  # zombie map refused
        assert client.stale_epoch_dropped == 1
        # the live coordinator still advances the member normally
        client._handle(MessageCode.ShardMapUpdate, _map_frame(4, epoch=2))
        assert client.current_map().version == 4
    finally:
        client.stop()
        _close(world)


def test_stale_epoch_preempt_rejected_on_the_wire():
    """Command path 2/3 (preempt): a zombie's PreemptRequest must never
    park a member of the successor's fleet."""
    world = InProcessTransport.create_world(2)
    client = _client(world)
    preempts = []
    client.on_preempt = lambda gid, snap: preempts.append((gid, snap))
    try:
        client._handle(MessageCode.PreemptRequest,
                       stamp_epoch(encode_preempt_request(7, 3), 2))
        assert preempts == [(7, 3)]
        client._handle(MessageCode.PreemptRequest,
                       stamp_epoch(encode_preempt_request(8, 4), 1))
        assert preempts == [(7, 3)]  # zombie preempt dropped
        assert client.stale_epoch_dropped == 1
    finally:
        client.stop()
        _close(world)


def test_stale_epoch_rollback_rejected_on_the_wire():
    """Command path 3/3 (rollback): a zombie's RollbackRequest must not
    hold admission or roll the data plane back."""
    world = InProcessTransport.create_world(2)
    client = _client(world)
    rollbacks = []
    client.on_rollback = lambda rid, phase: rollbacks.append((rid, phase))
    try:
        client._handle(MessageCode.ShardMapUpdate, _map_frame(1, epoch=2))
        client._handle(
            MessageCode.RollbackRequest,
            stamp_epoch(encode_rollback_request(5, 1, 2, 0), 1))
        assert rollbacks == []  # zombie barrier dropped
        assert not client.fleet.rollback_active()
        assert client.stale_epoch_dropped == 1
        client._handle(
            MessageCode.RollbackRequest,
            stamp_epoch(encode_rollback_request(5, 1, 2, 0), 2))
        assert rollbacks == [(5, 0)]
        assert client.fleet.rollback_active()
    finally:
        client.stop()
        _close(world)


def test_unstamped_frames_accepted_for_compatibility():
    """A pre-fencing coordinator's frames carry no epoch trailer and are
    accepted unchanged — mixed-version fleets keep working."""
    world = InProcessTransport.create_world(2)
    client = _client(world)
    try:
        m = ShardMap(3, 8, [ShardEntry(1, 0, 8)])
        client._handle(MessageCode.ShardMapUpdate, m.encode())
        assert client.current_map().version == 3
        assert client.coord_epoch == -1  # no epoch ever witnessed
        assert client.stale_epoch_dropped == 0
    finally:
        client.stop()
        _close(world)


def test_no_epoch_fence_knob_lets_the_zombie_wedge_the_member():
    """The distmodel ``no_epoch_fence`` mutation, on the real client: with
    the fence off, a zombie's high-version map is adopted — and the live
    coordinator's NEXT map is then refused by the version gate, wedging
    the member on a dead coordinator's topology."""
    world = InProcessTransport.create_world(2)
    client = _client(world, epoch_fence=False)
    try:
        client._handle(MessageCode.ShardMapUpdate, _map_frame(3, epoch=2))
        client._handle(MessageCode.ShardMapUpdate, _map_frame(9, epoch=1))
        assert client.current_map().version == 9  # the zombie won
        client._handle(MessageCode.ShardMapUpdate, _map_frame(4, epoch=2))
        assert client.current_map().version == 9  # successor locked out
        assert client.stale_epoch_dropped == 0
    finally:
        client.stop()
        _close(world)


def test_coordinator_stamps_every_outbound_frame_with_its_epoch(tmp_path):
    """The one stamp point: whatever a durable coordinator sends arrives
    wearing its persisted epoch."""
    now = [0.0]
    world = InProcessTransport.create_world(2)
    try:
        coord = _durable_coord(world, tmp_path, now)
        coord.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 1))
        seen = []
        while True:
            msg = world[1].recv(timeout=0.05)
            if msg is None:
                break
            _sender, code, payload = msg
            _body, epoch = strip_epoch(payload)
            seen.append((code, epoch))
        assert seen, "the join must be answered"
        assert all(epoch == coord.epoch for _code, epoch in seen), seen
    finally:
        _close(world)


# ------------------------------ crash mid-preemption, BEFORE a checkpoint

@pytest.mark.sched
def test_sched_slot_resynthesized_from_wal_park_ticket(tmp_path):
    """A crash between the WAL'd park and the next checkpoint leaves the
    successor's scheduler with NO ledger snapshot at all — the slot must
    be resynthesized from the park ticket alone: PARKED, still owned by
    the borrowing tenant under its original grant (no double-grant), and
    releasing it still drives the resume (no stranded member)."""
    from distributed_ml_pytorch_tpu.coord.sched import RESUMING

    now = [0.0]
    world = InProcessTransport.create_world(4)
    try:
        coord = _durable_coord(world, tmp_path, now, ckpt_every=10_000)
        sched = _attach_sched(coord)
        victim, gid = _park_victim(coord, sched, now)
        # NO coord.tick(): the checkpoint never covers this preemption

        coord2 = _durable_coord(world, tmp_path, now, ckpt_every=10_000)
        reg2 = _registry()
        reg2.set_demand(SERVE, 1)  # peak persists across the restart
        sched2 = FleetScheduler(coord2, registry=reg2,
                                require_manifest=False)
        slots = [s for s in sched2.ledger.slots.values()
                 if s.rank == victim]
        assert len(slots) == 1, sched2.ledger.slots
        slot = slots[0]
        assert slot.state == PARKED
        assert slot.owners == [SERVE]       # the borrower kept its grant
        assert slot.grant_id == gid
        assert slot.parked["rank"] == victim
        assert sched2.ledger.audit() == []

        # serve demand already satisfied by the resynthesized slot: a
        # tick must NOT hand the victim's capacity out a second time
        sched2.tick(now[0])
        assert [s for s in sched2.ledger.owned(SERVE)] == [slot]
        assert sched2._pending is None

        # demand drop: the release drives the resume — never a strand
        reg2.set_demand(SERVE, 0)
        sched2.tick(now[0])
        assert slot.state == RESUMING
        assert sched2._resuming is not None
        assert sched2._resuming["slot"] is slot
    finally:
        _close(world)


# ------------------------------------ system: kill-the-coordinator drill

_DRILL_STEPS = 20


@pytest.fixture(scope="module")
def coordfail_fixture():
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        cross_entropy_loss,
    )

    model = LeNet()
    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = model.apply({"params": q}, bx, train=True,
                                 rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = model.init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


@pytest.mark.drill
def test_coordfail_drill_snapshot_kill_three_runs_byte_identical(
        coordfail_fixture, tmp_path, lock_witness):
    """THE tentpole acceptance, mid-barrier flavor, 3x with identical
    seeds: the coordinator broadcasts a snapshot barrier and is crashed
    before the dones land; the fleet trains fail-open through the
    outage, re-attaches to the restarted epoch with NO member evicted
    (grace window), the new life drives a barrier of its own to a
    manifest, every run converges into the fault-free corridor, and the
    chaos log renders byte-identically run after run."""
    from distributed_ml_pytorch_tpu.coord.drill import coordfail_drill

    clean = coordfail_drill(
        base_dir=str(tmp_path / "clean"), seed=7, steps=_DRILL_STEPS,
        kill_at=None, fixture=coordfail_fixture)
    assert clean["ok"], (clean["errors"], clean["events"])
    assert clean["evictions"] == []
    clean_final = np.mean(
        [np.mean(l[-4:]) for l in clean["losses"].values()])

    logs, finals = [], []
    for run in range(3):
        out = coordfail_drill(
            base_dir=str(tmp_path / f"run{run}"), seed=7,
            steps=_DRILL_STEPS, kill_during="snapshot",
            fixture=coordfail_fixture)
        assert out["ok"], (out["errors"], out["violations"],
                           out["events"], out["events2"])
        assert out["accounting_ok"], (out["acked"], out["applied"])
        # the restart contract, in one line each:
        assert out["epochs"] == (1, 2)              # fencing is armed
        assert out["evictions"] == []               # grace held everyone
        assert out["restored_members"] >= 2         # ckpt+WAL replayed
        assert out["map_versions"][1] >= out["map_versions"][0]
        assert out["manifests_written"][1] > 0      # life 2 barriers work
        assert out["mttr_s"] is not None and out["mttr_s"] < 60
        # every live member learned the successor's epoch
        assert set(out["member_epochs"].values()) == {2}
        logs.append(out["chaos_lines"])
        finals.append(np.mean(
            [np.mean(l[-4:]) for l in out["losses"].values()]))
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "chaos log not byte-identical across coordinator-kill runs")
    for final in finals:
        assert abs(final - clean_final) < 0.5, (final, clean_final)


@pytest.mark.drill
@pytest.mark.sched
def test_coordfail_drill_preempt_kill_never_strands_parked(
        coordfail_fixture, tmp_path, lock_witness):
    """THE tentpole acceptance, mid-preemption flavor: the coordinator is
    crashed with the victim parked and the serving grant outstanding.
    The restarted life restores the park from the WAL, never re-grants
    the slot, and when demand drops it resumes the victim bit-identically
    — the parked member outlives its arbiter."""
    from distributed_ml_pytorch_tpu.coord.drill import coordfail_drill

    out = coordfail_drill(
        base_dir=str(tmp_path / "preempt"), seed=7, steps=24,
        kill_at=10, verify_at=16, kill_during="preempt",
        fixture=coordfail_fixture)
    assert out["ok"], (out["errors"], out["violations"],
                       out["events"], out["events2"])
    assert out["accounting_ok"], (out["acked"], out["applied"])
    assert out["violations"] == []
    assert out["epochs"] == (1, 2)
    assert out["evictions"] == []
    assert out["resumes_done"] == 1
    assert out["bit_identical"] is True
    assert out["replayed_updates"] > 0
    # exactly ONE serving grant ever issued (then its revoke) — the
    # restart did not hand the parked slot out a second time
    grant_actions = [(g[1], g[2]) for g in out["grants"]]
    assert grant_actions == [(SERVE, 1), (SERVE, 0)], out["grants"]
    assert out["mttr_s"] is not None and out["mttr_s"] < 60
