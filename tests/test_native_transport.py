"""Native C++ transport tests: build/load, round-trip, wire-format interop
with the pure-Python TCPTransport, and an end-to-end async-PS world running
over the native control plane (native analog of the reference's gloo C++
backend, SURVEY.md §2.2)."""

import socket
import threading
import time

import numpy as np
import pytest

from distributed_ml_pytorch_tpu import native
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    TCPTransport,
    make_transport,
)

pytestmark = pytest.mark.skipif(
    not native.native_available(),
    reason=f"native transport unavailable: {native.native_load_error()}",
)


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _world(port, server_cls, worker_cls, n_workers=1):
    """Start a server transport in a thread plus worker transports; returns
    (server_transport_future, workers)."""
    out = {}

    def serve():
        out["server"] = server_cls(0, n_workers + 1, "localhost", port)

    st = threading.Thread(target=serve)
    st.start()
    workers = [worker_cls(r, n_workers + 1, "localhost", port) for r in range(1, n_workers + 1)]
    st.join(timeout=30)
    assert "server" in out, "server rendezvous did not complete"
    return out["server"], workers


@pytest.mark.parametrize(
    "server_cls,worker_cls",
    [
        (native.NativeTCPTransport, native.NativeTCPTransport),
        (TCPTransport, native.NativeTCPTransport),  # python server, native worker
        (native.NativeTCPTransport, TCPTransport),  # native server, python worker
    ],
    ids=["native-native", "py-server", "native-server"],
)
def test_round_trip_and_interop(server_cls, worker_cls):
    port = _free_port()
    server, (worker,) = _world(port, server_cls, worker_cls)
    try:
        payload = np.arange(5, dtype=np.float32) * 1.5
        worker.send(MessageCode.GradientUpdate, payload)
        msg = server.recv(timeout=10)
        assert msg is not None
        sender, code, got = msg
        assert sender == 1 and code == MessageCode.GradientUpdate
        np.testing.assert_array_equal(got, payload)

        server.send(MessageCode.ParameterUpdate, np.full(7, 3.0, np.float32), dst=1)
        reply = worker.recv(timeout=10)
        assert reply is not None
        assert reply[0] == 0 and reply[1] == MessageCode.ParameterUpdate
        np.testing.assert_array_equal(reply[2], np.full(7, 3.0, np.float32))

        # empty payloads (ParameterRequest carries no data)
        worker.send(MessageCode.ParameterRequest, np.zeros(0, np.float32))
        req = server.recv(timeout=10)
        assert req is not None and req[1] == MessageCode.ParameterRequest
        assert req[2].size == 0
    finally:
        server.close()
        worker.close()


def test_recv_timeout_and_close_unblocks():
    port = _free_port()
    server, (worker,) = _world(port, native.NativeTCPTransport, native.NativeTCPTransport)
    try:
        t0 = time.monotonic()
        assert server.recv(timeout=0.2) is None
        assert time.monotonic() - t0 < 5.0

        # a blocking recv must return None once the transport is closed
        got = {}

        def blocked():
            got["msg"] = worker.recv(timeout=None)

        th = threading.Thread(target=blocked)
        th.start()
        time.sleep(0.2)
        worker.close()
        th.join(timeout=10)
        assert not th.is_alive()
        assert got["msg"] is None
    finally:
        server.close()
        worker.close()


def test_large_payload():
    # a ResNet-50-sized flat vector (~25M floats = 100MB) in one frame
    port = _free_port()
    server, (worker,) = _world(port, native.NativeTCPTransport, native.NativeTCPTransport)
    try:
        n = 5_000_000  # 20MB — large enough to exercise multi-chunk send/recv
        payload = np.random.default_rng(0).normal(size=n).astype(np.float32)
        worker.send(MessageCode.GradientUpdate, payload)
        msg = server.recv(timeout=30)
        assert msg is not None
        np.testing.assert_array_equal(msg[2], payload)
    finally:
        server.close()
        worker.close()


def test_rendezvous_tolerates_malformed_handshake():
    """A malformed or invalid-rank hello must NOT poison the rendezvous (the
    elastic server tolerates garbage connections — port scans, half-dead
    workers — and keeps waiting); a good worker arriving afterwards still
    completes the world."""
    import struct

    port = _free_port()
    out = {}

    def serve():
        out["server"] = native.NativeTCPTransport(0, 3, "localhost", port, connect_timeout=10)

    st = threading.Thread(target=serve)
    st.start()
    time.sleep(0.2)
    # first worker: valid hello (rank 1, code 1, empty payload) → admitted
    s1 = socket.create_connection(("localhost", port), timeout=5)
    s1.sendall(struct.pack("<iiq", 1, 1, 0))
    time.sleep(0.2)
    # garbage: malformed hello (nonzero payload length) → dropped, not fatal
    bad = socket.create_connection(("localhost", port), timeout=5)
    bad.sendall(struct.pack("<iiq", 2, 1, 4))
    time.sleep(0.2)
    # garbage: out-of-range rank → dropped, not fatal
    bad2 = socket.create_connection(("localhost", port), timeout=5)
    bad2.sendall(struct.pack("<iiq", 99, 1, 0))
    time.sleep(0.2)
    assert st.is_alive(), "server gave up on rendezvous instead of tolerating garbage"
    # a real rank-2 worker completes the rendezvous
    s2 = socket.create_connection(("localhost", port), timeout=5)
    s2.sendall(struct.pack("<iiq", 2, 1, 0))
    st.join(timeout=20)
    assert not st.is_alive() and "server" in out
    # the admitted workers are live: a frame from each reaches the inbox
    s1.sendall(struct.pack("<iiq", 1, 2, 4) + np.float32(7).tobytes())
    msg = out["server"].recv(timeout=5.0)
    assert msg is not None and msg[0] == 1
    for s in (s1, s2, bad, bad2):
        s.close()
    out["server"].close()


def test_make_transport_factory():
    port = _free_port()
    server, (worker,) = _world(
        port,
        lambda *a: make_transport(*a, kind="native"),
        lambda *a: make_transport(*a, kind="auto"),
    )
    try:
        assert isinstance(server, native.NativeTCPTransport)
        worker.send(MessageCode.GradientUpdate, np.ones(3, np.float32))
        msg = server.recv(timeout=10)
        assert msg is not None and msg[1] == MessageCode.GradientUpdate
    finally:
        server.close()
        worker.close()
    with pytest.raises(ValueError):
        make_transport(0, 1, kind="bogus")


def test_async_ps_world_over_native_transport():
    """Full DownPour world (1 server + 2 workers) on the native control plane."""
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.parallel.async_ps import Asynchronous, ParameterServer
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss
    from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

    port = _free_port()
    model = LeNet()
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]

    server_out = {}

    def serve():
        t = native.NativeTCPTransport(0, 3, "localhost", port)
        srv = ParameterServer(params, transport=t, n_workers=2)
        srv.run(timeout=60)
        server_out["srv"] = srv
        t.close()

    st = threading.Thread(target=serve)
    st.start()

    def work(rank, seed):
        t = native.NativeTCPTransport(rank, 3, "localhost", port)
        opt = Asynchronous(params, lr=0.01, n_push=2, n_pull=2, transport=t)
        rng = jax.random.key(seed)
        p = params
        x = jax.random.normal(jax.random.fold_in(rng, 1), (4, 32, 32, 3))
        y = jnp.zeros(4, jnp.int32)

        def loss_fn(q):
            return cross_entropy_loss(model.apply({"params": q}, x, train=False), y)

        for _ in range(6):
            _, grads = jax.value_and_grad(loss_fn)(p)
            p = opt.step(p, grads)
        opt.finish()
        time.sleep(0.2)
        t.close()

    ws = [threading.Thread(target=work, args=(r, r)) for r in (1, 2)]
    for w in ws:
        w.start()
    for w in ws:
        w.join(timeout=120)
    st.join(timeout=120)
    assert not st.is_alive(), "server did not terminate after WorkerDone x2"

    srv = server_out["srv"]
    assert srv.message_counts[MessageCode.GradientUpdate] >= 2
    assert srv.message_counts[MessageCode.ParameterRequest] >= 2
    # central params must have moved away from init (gradient pushes applied)
    init_flat = np.asarray(ravel_model_params(params))
    assert not np.allclose(srv.central, init_flat)
