"""Test bootstrap: run everything on an 8-device virtual CPU mesh.

The framework's distributed paths (psum allreduce, ppermute p2p, sharded
train steps) are unit-tested on virtual CPU devices — the single-host
cluster simulation recommended in SURVEY.md §4, replacing the reference's
localhost multi-process smoke topology (``Makefile:13-20``).

This environment's sitecustomize registers and initializes a TPU PJRT
plugin at interpreter boot, so by the time conftest runs the backend is
already locked to one TPU device. We clear JAX's backend caches and
re-initialize on the CPU platform with 8 virtual devices.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TPU_DISTBELIEF_TEST_ENV"] = "1"

import jax  # noqa: E402

from distributed_ml_pytorch_tpu.runtime.mesh import force_cpu_devices  # noqa: E402

N_DEVICES = 8

force_cpu_devices(N_DEVICES)

assert len(jax.devices()) == N_DEVICES and jax.devices()[0].platform == "cpu", (
    f"expected {N_DEVICES} virtual CPU devices, got {jax.devices()}"
)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from distributed_ml_pytorch_tpu.runtime import data_mesh

    return data_mesh(8)


@pytest.fixture
def lock_witness():
    """Runtime lock-order witness (analysis/witness.py, ISSUE 4): under
    DISTCHECK_WITNESS=1 the chaos/coord acceptance scenarios double as
    concurrency validators — every lock acquisition order observed during
    the run must be acyclic. Without the env flag this is a no-op, so the
    default suite pays nothing."""
    from distributed_ml_pytorch_tpu.analysis.witness import maybe_install

    w = maybe_install()
    yield w
    if w is not None:
        w.uninstall()
        assert not w.cycles(), w.report()
        # bounded-state witness (ISSUE 19): at teardown, every container
        # the static DC503 pass cleared via a fallible exemption must
        # actually be within budget — read-only len() sampling, so the
        # chaos suites' byte-identical log guarantees are untouched
        from distributed_ml_pytorch_tpu.analysis.witness import (
            check_exempt_budget,
        )

        over = check_exempt_budget()
        assert not over, (
            "DC503-exempt containers over budget at scenario teardown "
            f"(cls, attr, len): {over}")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "network: needs internet egress (real CIFAR-10 download); "
        "deselect with -m 'not network' — these skip themselves when "
        "the download fails",
    )
    config.addinivalue_line(
        "markers",
        "slow: spawns real processes or trains end-to-end (minutes on a "
        "1-core host); `make test` deselects these for a fast core signal, "
        "`make test-all` runs everything",
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection tests (utils/chaos.py + the "
        "reliability layer); `make chaos` selects exactly these — fast "
        "seeded cases run in tier-1, soak variants are additionally slow",
    )
    config.addinivalue_line(
        "markers",
        "coord: elastic control-plane tests (coord/ — membership, leases, "
        "shard rebalancing, speculation); `make coord` selects exactly "
        "these — fast cases run in tier-1, the wall-clock scenario tests "
        "are additionally listed in slow_tests.txt",
    )
    config.addinivalue_line(
        "markers",
        "drill: disaster-recovery drill tests (coord/drill.py + utils/"
        "wal.py — snapshot barrier, kill-and-restore, sequence "
        "accounting); `make drill` selects exactly these — fast cases run "
        "in tier-1, the full kill-all scenarios are additionally measured "
        "into slow_tests.txt",
    )
    config.addinivalue_line(
        "markers",
        "fleet: fleet-serving tests (serving/fleet.py — occupancy routing, "
        "stream migration across engine death, overload shed/brownout); "
        "`make fleet` selects exactly these — fast cases run in tier-1, "
        "the acceptance scenarios are additionally in slow_tests.txt",
    )
    config.addinivalue_line(
        "markers",
        "soak: sustained-load scenarios (the 2x-overload goodput soak); "
        "`make soak` selects exactly these — all also slow, so tier-1 "
        "never pays for them",
    )
    config.addinivalue_line(
        "markers",
        "health: numerical-health tests (utils/health.py admission gate, "
        "SDC chaos, UpdateNack quarantine, worker reputation, coordinator "
        "auto-rollback — ISSUE 8); `make health` selects exactly these — "
        "fast units run in tier-1, the 3x acceptance scenario is "
        "additionally measured into slow_tests.txt",
    )
    config.addinivalue_line(
        "markers",
        "mpmd: MPMD pipeline-plane tests (parallel/mpmd.py + "
        "coord/stages.py — per-stage compiled programs, StagePlacement, "
        "stage death/restart with watermark replay, stage speculation — "
        "ISSUE 10); `make mpmd` selects exactly these — fast units run in "
        "tier-1, the fleet scenarios are additionally measured into "
        "slow_tests.txt; the manifest drill variant also carries the "
        "drill marker",
    )
    config.addinivalue_line(
        "markers",
        "codec: codec-plane tests (utils/codecs.py — the WIRE_PLANES "
        "registry's totality over codec-id-bearing WIRE_SCHEMAS, loss "
        "contracts, the int8 bound, delta-reply identity, tok16 "
        "exactness — ISSUE 18); `make codec` selects exactly these — "
        "all fast, all in tier-1",
    )
    config.addinivalue_line(
        "markers",
        "distmodel: bounded protocol model checking (analysis/"
        "distmodel.py — exactly-once / lease / watermark-replay "
        "invariants, the seeded-mutation soundness corpus, and the "
        "counterexample-to-chaos replays against the real transport "
        "stack — ISSUE 13); `make distmodel` runs the checker itself, "
        "these tests run in tier-1",
    )
    config.addinivalue_line(
        "markers",
        "sched: multi-tenant scheduler tests (coord/sched.py + "
        "coord/tenants.py — capacity ledger, admit/pack/preempt/resume "
        "decisions, the park-and-restore drill, autoscale actuation — "
        "ISSUE 16); `make sched` selects exactly these — fast units run "
        "in tier-1, the full drill scenarios are additionally measured "
        "into slow_tests.txt",
    )
    config.addinivalue_line(
        "markers",
        "coordfail: control-plane durability tests (coord/coordinator.py "
        "WAL+checkpoint restart, epoch fencing, the restart grace window, "
        "the coordfail distmodel plane and the kill-the-coordinator drill "
        "— ISSUE 17); `make coordfail` selects exactly these — fast units "
        "run in tier-1, the 3x drill acceptance is additionally in "
        "slow_tests.txt",
    )
    config.addinivalue_line(
        "markers",
        "distflow: interprocedural dataflow lint tests (analysis/"
        "distflow.py — DC501 receive ordering, DC502 fenced-mutation "
        "gating, DC503 bounded state + the runtime bounded-state "
        "witness, DC504 blocking-under-lock — ISSUE 19); `make "
        "distflow` selects exactly these — all fast, all in tier-1",
    )
    config.addinivalue_line(
        "markers",
        "netweather: adaptive-wire tests under network weather "
        "(utils/chaos.WeatherRule + the RTO/window/breaker machinery in "
        "utils/messaging.ReliableTransport); `make netweather` selects "
        "exactly these — fast units run in tier-1, the training "
        "acceptance is additionally measured into slow_tests.txt",
    )
    config.addinivalue_line(
        "markers",
        "gray: gray-failure plane tests (coord/grayhealth.py adaptive "
        "suspicion + containment ladder, utils/chaos.GrayRule scheduled "
        "one-way partitions / lossy links / stalls, the renew-tail wire "
        "compatibility, the gray distmodel plane — ISSUE 20); `make "
        "gray` selects exactly these — fast units run in tier-1, the "
        "mid-training gray drill acceptance is additionally measured "
        "into slow_tests.txt",
    )


# Modules whose tests launch real subprocess worlds (interpreter start + jit
# compile per process) or run whole example trainings — the wall-clock tail
# of the suite. Marked wholesale here so a new test in these files cannot be
# forgotten; in-process tests that also take minutes opt in with an explicit
# @pytest.mark.slow at the test site.
SLOW_MODULES = {
    "test_examples",
    "test_launchers",
    "test_multihost_bootstrap",
    "test_multihost_branches",
    "test_ps_fault_injection",
    "test_ps_multiprocess",
    "test_real_data",
    "test_sharded_ps",
}


def _listed_slow_tests():
    """Node IDs marked slow by measurement (>= 4 s call time on this host —
    see tests/slow_tests.txt for the regeneration command). Kept as a
    generated file so the cut is data, not opinion; a renamed test drops
    out of the list and simply runs fast-set until the next regeneration."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "slow_tests.txt")
    if not os.path.exists(path):
        return frozenset()
    with open(path) as fh:
        return frozenset(
            line.strip() for line in fh
            if line.strip() and not line.startswith("#")
        )


SLOW_TESTS = _listed_slow_tests()


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (
            item.module.__name__.rsplit(".", 1)[-1] in SLOW_MODULES
            or item.nodeid in SLOW_TESTS
        ):
            item.add_marker(pytest.mark.slow)
