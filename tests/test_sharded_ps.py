"""Sharded parameter server (VERDICT r1 #10): k shard servers each owning a
contiguous slice of the central vector, workers pushing/pulling per shard.
Unit tests drive the in-process transports; the k=2 integration test runs
real server processes over TCP."""

import os
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env
from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
    ShardedAsynchronous,
    make_shard_server,
    shard_ranges,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    make_transport,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_shard_ranges_cover_disjointly():
    for n, k in [(8, 2), (10, 3), (5, 5), (7, 1)]:
        ranges = shard_ranges(n, k)
        assert ranges[0][0] == 0 and ranges[-1][1] == n
        for (a, b), (c, d) in zip(ranges, ranges[1:]):
            assert b == c and b > a and d > c
    with pytest.raises(ValueError):
        shard_ranges(4, 5)


def _params():
    return {
        "w": jnp.arange(5, dtype=jnp.float32),
        "b": jnp.arange(3, dtype=jnp.float32) + 10.0,
    }  # ravels to 8 elements → shards [0,4) and [4,8)


def test_sharded_downpour_updates_each_shard_server():
    """2 shards, 1 worker, n_push=1: after 2 steps of all-ones grads each
    server's central slice must be install − 2·lr (worker pre-scales by
    −lr, server-side application is addition)."""
    params = _params()
    worlds = [InProcessTransport.create_world(2) for _ in range(2)]
    servers = [
        make_shard_server(model=params, shard=s, n_shards=2,
                          transport=worlds[s][0], n_workers=1)
        for s in range(2)
    ]
    threads = [threading.Thread(target=s.run) for s in servers]
    for t in threads:
        t.start()
    opt = ShardedAsynchronous(params, lr=0.1, n_push=1, n_pull=100,
                              transports=[w[1] for w in worlds])
    try:
        grads = {"w": jnp.ones(5), "b": jnp.ones(3)}
        p = params
        for _ in range(2):
            p = opt.step(p, grads)
    finally:
        opt.finish()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)
    from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

    init = np.asarray(ravel_model_params(_params()))
    want = init - 0.2  # two pushes of −lr·1
    got = np.concatenate([servers[0].central, servers[1].central])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_partial_shard_install_patches_only_arrived_range():
    """A reply from one shard installs alone — per-shard staleness."""
    params = _params()
    worlds = [InProcessTransport.create_world(2) for _ in range(2)]
    opt = ShardedAsynchronous(params, lr=0.0, n_push=100, n_pull=100,
                              transports=[w[1] for w in worlds])
    try:
        from distributed_ml_pytorch_tpu.utils.serialization import (
            ravel_model_params,
        )

        init = np.asarray(ravel_model_params(params))
        fresh = np.full(4, 99.0, np.float32)
        opt.listeners[1]._latest = fresh  # shard [4,8) reply arrived
        patched = opt._install_arrived(params)
        flat = np.asarray(ravel_model_params(patched))
        np.testing.assert_allclose(flat[:4], init[:4])
        np.testing.assert_allclose(flat[4:], fresh)
        # wrong-size reply fails loudly, never silently corrupts
        opt.listeners[0]._latest = np.zeros(3, np.float32)
        with pytest.raises(ValueError, match="ranges disagree"):
            opt._install_arrived(params)
    finally:
        opt.finish()


_SERVER_SRC = """
import sys
import numpy as np
from distributed_ml_pytorch_tpu.parallel.sharded_ps import make_shard_server
from distributed_ml_pytorch_tpu.utils.messaging import make_transport

shard, port = int(sys.argv[1]), sys.argv[2]
t = make_transport(0, 2, port=int(port), kind="python")
srv = make_shard_server(params=np.zeros(8, np.float32), shard=shard,
                        n_shards=2, transport=t, n_workers=1)
srv.run()
print("shard", shard, "central", ",".join(f"{x:.4f}" for x in srv.central),
      flush=True)
t.close()
"""


def test_sharded_ps_two_server_processes_over_tcp(tmp_path):
    """The k=2 DistBelief layout with real processes: two shard servers on
    their own TCP stars, one worker pushing/pulling slices of a LeNet-free
    toy model; each server must end at install − Σ lr·grads for its slice."""
    ports = [_free_port(), _free_port()]
    env = cpu_platform_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, "-c", _SERVER_SRC, str(s), ports[s]],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for s in range(2)
    ]
    params = _params()
    transports = [
        make_transport(1, 2, port=int(p), kind="python", connect_timeout=120)
        for p in ports
    ]
    try:
        opt = ShardedAsynchronous(params, lr=0.5, n_push=1, n_pull=100,
                                  transports=transports)
        grads = {"w": jnp.ones(5), "b": jnp.ones(3)}
        p = params
        for _ in range(3):
            p = opt.step(p, grads)
        opt.finish()
    finally:
        outs = []
        for proc in procs:
            try:
                out, _ = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                out = proc.communicate()[0]
            outs.append(out)
        for t in transports:
            t.close()
    assert all(p.returncode == 0 for p in procs), "\n\n".join(outs)
    from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

    init = np.asarray(ravel_model_params(_params()))
    want = init - 3 * 0.5  # three pushes of −lr·1
    for s, out in enumerate(outs):
        line = [l for l in out.splitlines() if l.startswith(f"shard {s} central")]
        assert line, out
        got = np.array([float(x) for x in line[0].split("central ")[1].split(",")])
        lo, hi = (0, 4) if s == 0 else (4, 8)
        np.testing.assert_allclose(got, want[lo:hi], rtol=1e-5)


def test_dead_shard_degrades_that_slice_only():
    """A dead shard server must not crash the worker: sends to it stop, the
    other shard keeps its push/pull service (per-shard degradation)."""
    params = _params()
    worlds = [InProcessTransport.create_world(2) for _ in range(2)]

    class Dying:
        def __init__(self, inner):
            self.inner, self.dead = inner, False

        def send(self, code, payload, dst=0):
            if self.dead:
                raise ConnectionError("shard down")
            self.inner.send(code, payload, dst)

        def recv(self, timeout=None):
            return self.inner.recv(timeout)

        def close(self):
            self.inner.close()

        @property
        def rank(self):
            return self.inner.rank

    dying = Dying(worlds[0][1])
    opt = ShardedAsynchronous(params, lr=0.1, n_push=1, n_pull=1,
                              transports=[dying, worlds[1][1]])
    try:
        grads = {"w": jnp.ones(5), "b": jnp.ones(3)}
        p = opt.step(params, grads)
        dying.dead = True
        for _ in range(2):  # must not raise
            p = opt.step(p, grads)
        assert opt.shard_down == [True, False]
        # the healthy shard kept receiving pushes: drain its server box
        seen = []
        while True:
            msg = worlds[1][0].recv(timeout=0.2)
            if msg is None:
                break
            seen.append(msg[1])
        assert seen.count(MessageCode.GradientUpdate) == 3
    finally:
        opt.finish()  # also must not raise


def test_sharded_ps_cli_world_end_to_end(tmp_path):
    """`launch --n-servers 2` runs the full CLI topology: 2 shard servers +
    2 workers train LeNet and everyone exits cleanly."""
    from distributed_ml_pytorch_tpu.launch import launch_world

    code = launch_world(
        4,
        ["--model", "lenet", "--epochs", "1", "--batch-size", "16",
         "--test-batch-size", "32", "--num-push", "4", "--num-pull", "4",
         "--synthetic-data", "--synthetic-train-size", "96",
         "--synthetic-test-size", "32", "--log-interval", "1000",
         "--log-dir", str(tmp_path)],
        n_servers=2,
    )
    assert code == 0
    # worker CSVs keep the unsharded node1..N convention (first worker =
    # node1.csv) regardless of the k server ranks before them (ADVICE r2)
    for w in (1, 2):
        assert os.path.exists(tmp_path / f"node{w}.csv")
    assert not os.path.exists(tmp_path / "node3.csv")


def test_sharded_rejoin_adopts_central_without_install():
    """rejoin=True must PULL each shard's central params (never install the
    fresh init) and the first step starts from the adopted values."""
    params = _params()
    worlds = [InProcessTransport.create_world(2) for _ in range(2)]
    central = np.arange(8, dtype=np.float32) * 10.0
    servers = [
        make_shard_server(params=central, shard=s, n_shards=2,
                          transport=worlds[s][0], n_workers=1)
        for s in range(2)
    ]
    threads = [threading.Thread(target=s.run) for s in servers]
    for t in threads:
        t.start()
    opt = ShardedAsynchronous(params, lr=0.0, n_push=100, n_pull=100,
                              transports=[w[1] for w in worlds], rejoin=True)
    try:
        # install codes must never have been applied: centrals unchanged
        np.testing.assert_allclose(
            np.concatenate([servers[0].central, servers[1].central]), central)
        grads = {"w": jnp.zeros(5), "b": jnp.zeros(3)}
        p = opt.step(params, grads)  # installs the pulled replies
        from distributed_ml_pytorch_tpu.utils.serialization import (
            ravel_model_params,
        )

        np.testing.assert_allclose(
            np.asarray(ravel_model_params(p)), central)
        for srv in servers:
            assert srv.message_counts[MessageCode.ParameterUpdate] == 0
    finally:
        opt.finish()
    for t in threads:
        t.join(timeout=30)


def test_peer_down_heartbeat_degrades_shard():
    """A shard whose heartbeat sender reports peer_down degrades without
    ever attempting the (possibly blocking) TCP send."""
    params = _params()
    worlds = [InProcessTransport.create_world(2) for _ in range(2)]

    class FakeHeartbeat:
        peer_down = False

    hbs = [FakeHeartbeat(), FakeHeartbeat()]
    opt = ShardedAsynchronous(params, lr=0.1, n_push=1, n_pull=1,
                              transports=[w[1] for w in worlds],
                              heartbeats=hbs)
    try:
        grads = {"w": jnp.ones(5), "b": jnp.ones(3)}
        p = opt.step(params, grads)
        hbs[0].peer_down = True
        p = opt.step(p, grads)
        assert opt.shard_down == [True, False]
    finally:
        opt.finish()


def test_dead_shard_slice_follows_pure_local_sgd_quantitatively():
    """The per-slice degradation contract, MEASURED (VERDICT r3): after a
    shard dies, its slice of the worker's params must evolve EXACTLY as
    pure local SGD (no installs ever land there), while the healthy
    shard's slice still receives server installs — asserted numerically,
    not just by absence of crashes."""
    from distributed_ml_pytorch_tpu.parallel.sharded_ps import shard_ranges
    from distributed_ml_pytorch_tpu.utils.serialization import (
        make_unraveler,
        ravel_model_params,
    )

    params = _params()
    worlds = [InProcessTransport.create_world(2) for _ in range(2)]

    class Dying:
        def __init__(self, inner):
            self.inner, self.dead = inner, False

        def send(self, code, payload, dst=0):
            if self.dead:
                raise ConnectionError("shard down")
            self.inner.send(code, payload, dst)

        def recv(self, timeout=None):
            return self.inner.recv(timeout)

        def close(self):
            self.inner.close()

        @property
        def rank(self):
            return self.inner.rank

    dying = Dying(worlds[0][1])
    # healthy shard 1 gets a real server thread so pulls are answered
    server1 = make_shard_server(model=params, shard=1, n_shards=2,
                                transport=worlds[1][0], n_workers=1)
    t1 = threading.Thread(target=server1.run)
    t1.start()

    lr = 0.1
    opt = ShardedAsynchronous(params, lr=lr, n_push=1, n_pull=1,
                              transports=[dying, worlds[1][1]])
    n = ravel_model_params(params).shape[0]
    (lo0, hi0), (lo1, hi1) = shard_ranges(n, 2)
    grads = {"w": jnp.ones(5), "b": jnp.ones(3)}
    try:
        p = opt.step(params, grads)
        dying.dead = True
        # expected pure-local-SGD trajectory for the dead slice from the
        # moment of death (whatever p holds after step 0)
        expect_dead = np.asarray(ravel_model_params(p))[lo0:hi0].copy()
        m = 4
        for _ in range(m):
            p = opt.step(p, grads)
            expect_dead -= lr * 1.0  # all-ones grads, plain SGD
            time.sleep(0.05)  # let healthy-shard installs arrive
        flat = np.asarray(ravel_model_params(p))
        # dead slice: EXACTLY the local-SGD prediction — no install touched it
        np.testing.assert_allclose(flat[lo0:hi0], expect_dead, rtol=1e-6)
        assert opt.shard_down == [True, False]
        # healthy slice: the server answered pulls, so at least one install
        # replaced local values with central ones — the server's central
        # slice is a stale snapshot of the worker trajectory, which local
        # SGD alone could never reproduce once further steps ran
        assert server1.message_counts[MessageCode.ParameterRequest] >= 1
    finally:
        opt.finish()
        t1.join(timeout=30)
    assert not t1.is_alive()
