"""Serving transport frontend: submit/stream/reject/cancel over the L1
messaging layer (in-process world and real TCP sockets), plus the serve
CLI's demo path — the test_examples-style face of the serving stack."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models.generate import generate
from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
from distributed_ml_pytorch_tpu.serving.engine import ServingEngine
from distributed_ml_pytorch_tpu.serving.frontend import (
    RequestRejected,
    ServingClient,
    ServingFrontend,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    TCPTransport,
)

VOCAB = 64


@pytest.fixture(scope="module")
def lm_and_params():
    model = TransformerLM(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_len=128
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def make_engine(lm_and_params, **kw):
    model, params = lm_and_params
    kw.setdefault("slots", 2)
    kw.setdefault("cache_size", 64)
    kw.setdefault("decode_block", 4)
    kw.setdefault("prefill_bucket", 8)
    return ServingEngine(model, params, **kw)


def serve_world(engine):
    """In-process 2-rank world: rank 0 engine hub, rank 1 client."""
    world = InProcessTransport.create_world(2)
    frontend = ServingFrontend(engine, world[0])
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    return world, frontend, thread


def test_inprocess_roundtrip_matches_generate(lm_and_params):
    model, params = lm_and_params
    engine = make_engine(lm_and_params)
    world, frontend, thread = serve_world(engine)
    try:
        client = ServingClient(world[1])
        prompt = np.random.default_rng(0).integers(0, VOCAB, size=5)
        tokens = client.generate(prompt, 14)
        want = np.asarray(
            generate(model, params, jnp.asarray(prompt, jnp.int32)[None], 14)
        )[0, 5:].tolist()
        assert tokens == want
    finally:
        frontend.stop()
        thread.join(timeout=5)
        for t in world.values():
            t.close()


def test_inprocess_concurrent_streams_and_cancel(lm_and_params):
    engine = make_engine(lm_and_params)
    world, frontend, thread = serve_world(engine)
    try:
        client = ServingClient(world[1])
        ra = client.submit(np.arange(4), 20)
        rb = client.submit(np.arange(6), 8)
        rc = client.submit(np.arange(2), 30)
        client.cancel(rc)
        toks_a = list(client.stream(ra))
        toks_b = list(client.stream(rb))
        assert len(toks_a) == 20 and len(toks_b) == 8
        toks_c = list(client.stream(rc, timeout=30.0))
        assert len(toks_c) < 30  # cancelled mid-flight (done frame closes it)
    finally:
        frontend.stop()
        thread.join(timeout=5)
        for t in world.values():
            t.close()


def test_backpressure_rejects_over_transport(lm_and_params):
    engine = make_engine(lm_and_params, slots=1, max_queue=1)
    world, frontend, thread = serve_world(engine)
    try:
        client = ServingClient(world[1])
        rids = [client.submit(np.arange(4), 12) for _ in range(4)]
        outcomes = []
        for rid in rids:
            try:
                outcomes.append(len(list(client.stream(rid, timeout=60.0))))
            except RequestRejected:
                outcomes.append("rejected")
        assert "rejected" in outcomes  # backpressure reached the client
        assert any(o == 12 for o in outcomes)  # and service continued
    finally:
        frontend.stop()
        thread.join(timeout=5)
        for t in world.values():
            t.close()


def test_tcp_roundtrip(lm_and_params):
    """The same frontend over real sockets (port-offset from the PS tests'
    29500 range to avoid collisions)."""
    engine = make_engine(lm_and_params)
    port = 29617
    server_tp = {}

    def serve():
        server_tp["t"] = TCPTransport(0, 2, port=port)

    boot = threading.Thread(target=serve)
    boot.start()
    client_tp = TCPTransport(1, 2, port=port)
    boot.join(timeout=30)
    frontend = ServingFrontend(engine, server_tp["t"])
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    try:
        client = ServingClient(client_tp)
        prompt = np.random.default_rng(1).integers(0, VOCAB, size=6)
        toks = client.generate(prompt, 10)
        assert len(toks) == 10 and all(0 <= t < VOCAB for t in toks)
    finally:
        frontend.stop()
        thread.join(timeout=5)
        client_tp.close()
        server_tp["t"].close()


def test_malformed_frames_do_not_kill_the_hub(lm_and_params):
    """Client garbage must never wedge the server: a truncated submit gets
    an explicit reject (not a silent drop), an empty cancel is ignored,
    and the pump thread survives to serve the next well-formed request."""
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    engine = make_engine(lm_and_params)
    world, frontend, thread = serve_world(engine)
    try:
        client = ServingClient(world[1])
        # truncated submit (header only, no prompt) under a client-chosen id
        rid = next(client._ids)
        client._buffers[rid] = __import__("queue").Queue()
        world[1].send(MessageCode.SubmitRequest,
                      np.asarray([rid, 5, 0, 0, 1, 0, -1], np.float32), dst=0)
        with pytest.raises(RequestRejected):
            list(client.stream(rid, timeout=10.0))
        world[1].send(MessageCode.CancelRequest,
                      np.zeros(0, np.float32), dst=0)  # empty cancel: ignored
        toks = client.generate(np.arange(4), 6)  # hub still alive
        assert len(toks) == 6
    finally:
        frontend.stop()
        thread.join(timeout=5)
        for t in world.values():
            t.close()


def test_encode_submit_rejects_wire_inexact_ints():
    from distributed_ml_pytorch_tpu.serving.frontend import encode_submit

    with pytest.raises(ValueError, match="2\\^24"):
        encode_submit(1, [1, 2], 8, seed=1 << 24)
    assert encode_submit(1, [1, 2], 8, seed=(1 << 24) - 1).shape == (9,)


def test_serve_cli_demo(capsys):
    from distributed_ml_pytorch_tpu.serving.cli import main

    rc = main([
        "--demo", "4", "--vocab", "64", "--d-model", "32", "--n-heads", "4",
        "--n-layers", "1", "--d-ff", "64", "--slots", "2",
        "--cache-size", "64", "--decode-block", "4", "--prefill-bucket", "8",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serving demo complete" in out
    assert "SLO summary" in out and "ttft_ms" in out


def test_serve_cli_rejects_bad_model(capsys):
    from distributed_ml_pytorch_tpu.serving.cli import main

    with pytest.raises(SystemExit):
        main(["--demo", "1", "--d-model", "30", "--n-heads", "4"])
