"""Flight recorder / metrics registry / bounded decision log (ISSUE 12).

Covers the observability plane's contracts:

- SpanRecorder ring bounds + drop accounting, exporters (JSONL + Chrome
  trace), correlation-id thread-local plumbing;
- the correlation id RIDING the reliability envelope (sender stamps,
  receiver's handler thread inherits it) and surviving the CRC;
- StateClock exclusive-state attribution summing to the wall clock;
- BoundedEvents: the coordinator's decision log as a capped ring whose
  ``[-20:]`` rendering and iteration are unchanged;
- Registry: owned metrics + attached providers in one snapshot;
- EWMA migration safety: the shared Ewma/EwmaMeanVar are BIT-identical to
  the hand-rolled idioms they replaced (LeaseRenew float layout pinned);
- the chaos-determinism guard: enabling a recorder cannot perturb a
  chaos log by one byte.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils import obs
from distributed_ml_pytorch_tpu.utils.metrics import (
    Counter,
    Ewma,
    EwmaMeanVar,
    Registry,
)


# ------------------------------------------------------------ SpanRecorder

def test_recorder_ring_bounds_and_drop_accounting():
    rec = obs.SpanRecorder("m", "mpmd", capacity=8)
    for i in range(20):
        rec.event(f"e{i}")
    assert rec.total == 20
    assert len(rec.snapshot()) == 8
    assert rec.dropped == 12
    # the ring keeps the NEWEST window (the one that explains a crash)
    assert rec.snapshot()[-1]["name"] == "e19"
    rec.clear()
    assert rec.total == 0 and rec.snapshot() == []


def test_disabled_recorder_records_nothing():
    rec = obs.SpanRecorder("m", "mpmd", enabled=False)
    rec.event("e")
    with rec.span("s"):
        pass
    assert rec.total == 0 and rec.snapshot() == []


def test_span_context_times_and_survives_raise():
    rec = obs.SpanRecorder("m", "mpmd")
    with pytest.raises(RuntimeError):
        with rec.span("boom", state="compute"):
            raise RuntimeError("x")
    (s,) = rec.snapshot()
    assert s["name"] == "boom" and s["state"] == "compute"
    assert s["t1_ns"] >= s["t0_ns"]


def test_corr_thread_local_and_scope_nesting():
    obs.set_corr(0)
    assert obs.current_corr() == 0
    with obs.corr_scope(111):
        assert obs.current_corr() == 111
        with obs.corr_scope(222):
            assert obs.current_corr() == 222
        assert obs.current_corr() == 111
    assert obs.current_corr() == 0
    # ids are per-thread: another thread sees its own (empty) slot
    seen = {}

    def other():
        seen["corr"] = obs.current_corr()

    with obs.corr_scope(333):
        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert seen["corr"] == 0


def test_recorder_adopts_thread_corr_and_explicit_overrides():
    rec = obs.SpanRecorder("m", "mpmd")
    with obs.corr_scope(42):
        rec.event("implicit")
        rec.event("explicit", corr=7)
    rows = rec.snapshot()
    assert rows[0]["corr"] == 42 and rows[1]["corr"] == 7


def test_exports_jsonl_and_chrome_trace(tmp_path):
    rec = obs.SpanRecorder("stage1", "mpmd")
    with rec.span("fwd", state="compute", corr=5):
        pass
    rec.event("mark", corr=5, step=3)
    p = rec.dump_jsonl(str(tmp_path / "d.jsonl"))
    lines = [json.loads(x) for x in open(p).read().splitlines()]
    assert lines[0]["kind"] == "meta" and lines[0]["member"] == "stage1"
    assert lines[1]["name"] == "fwd" and lines[1]["corr"] == 5
    ct = rec.chrome_trace(str(tmp_path / "t.json"))
    trace = json.load(open(ct))
    phases = {e["name"]: e["ph"] for e in trace["traceEvents"]}
    assert phases["fwd"] == "X" and phases["mark"] == "i"


def test_flight_dump_sanitizes_and_writes(tmp_path):
    rec = obs.SpanRecorder("stage/1 bad", "mpmd")
    rec.event("e")
    paths = obs.flight_dump(rec, str(tmp_path), "why: because!")
    assert len(paths) == 1
    import os

    name = os.path.basename(paths[0])
    assert name.startswith("flight_") and "/" not in name and " " not in name
    assert json.loads(open(paths[0]).readline())["reason"] == "why: because!"
    # None recorders are skipped, not an error
    assert obs.flight_dump(None, str(tmp_path), "x") == []


# -------------------------------------------------------------- StateClock

def test_state_clock_attribution_sums_to_wall():
    rec = obs.SpanRecorder("m", "mpmd")
    clk = obs.StateClock(rec, "idle", min_span_us=0)
    t0 = time.monotonic_ns()
    clk.set("compute")
    time.sleep(0.02)
    clk.set("wait-act")
    time.sleep(0.01)
    seconds = clk.flush()
    wall = (time.monotonic_ns() - t0) / 1e9
    assert set(seconds) <= {"idle", "compute", "wait-act"}
    assert seconds["compute"] >= 0.015
    # exclusive states: the total equals the wall clock (within timer slop)
    assert abs(sum(seconds.values())
               - (wall + seconds.get("idle", 0.0))) < 0.05
    attr = [e for e in rec.snapshot() if e["name"] == "attribution"]
    assert attr and attr[-1]["meta"]["wall_s"] > 0


def test_state_clock_carve_moves_seconds():
    clk = obs.StateClock(None, "compute", min_span_us=0)
    time.sleep(0.01)
    clk.carve("wire-blocked", 0.004)
    seconds = clk.flush()
    assert seconds["wire-blocked"] == pytest.approx(0.004)
    # the carved time came OUT of the open stretch: no double counting
    assert seconds["compute"] >= 0.005
    assert sum(seconds.values()) < 0.2


# ----------------------------------------------------------- BoundedEvents

def test_bounded_events_caps_and_keeps_rendering():
    ev = obs.BoundedEvents(maxlen=16)
    for i in range(100):
        ev.append(f"decision {i}")
    assert ev.total == 100 and len(ev) == 16 and ev.dropped == 84
    # the CLI's last-20 rendering works unchanged on the retained window
    tail = ev[-20:]
    assert tail[-1] == "decision 99" and len(tail) == 16
    assert any("decision 99" in e for e in ev)
    assert ev[0] == "decision 84"
    assert bool(ev)
    assert "total=100" in repr(ev)


def test_coordinator_decision_log_is_bounded():
    from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator

    c = Coordinator(None, 16, lease=10.0)
    for i in range(5000):
        c._log(f"event {i}")
    assert c.events.total == 5000
    assert len(c.events) == c.events.maxlen
    assert list(c.events)[-1] == "event 4999"


def test_coordinator_log_promotes_to_recorder():
    from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator

    c = Coordinator(None, 16, lease=10.0)
    c.recorder = obs.SpanRecorder("coord", "coord")
    c._log("hello plane")
    rows = [e for e in c.recorder.snapshot() if e["name"] == "coord"]
    assert rows and rows[-1]["meta"]["msg"] == "hello plane"


# ---------------------------------------------------------------- Registry

def test_registry_owned_metrics_and_kind_clash():
    r = Registry("t")
    r.counter("pushes").inc(3)
    r.gauge("occupancy").set(0.5)
    r.ewma("lat_ms").update(10.0)
    with pytest.raises(ValueError):
        r.gauge("pushes")
    snap = r.snapshot()
    assert snap["pushes"] == 3 and snap["occupancy"] == 0.5
    assert snap["lat_ms"] == 10.0


def test_registry_attach_providers_and_failure_isolation(tmp_path):
    r = Registry("t")
    stats = {"sent": 7, "acked": 6}
    r.attach("wire", lambda: stats)
    r.attach("bad", lambda: 1 / 0)
    snap = r.snapshot()
    assert snap["wire.sent"] == 7 and snap["wire.acked"] == 6
    assert "division" in snap["bad.error"]
    path = tmp_path / "m.json"
    text = r.dump_json(str(path))
    assert json.loads(path.read_text()) == json.loads(text)
    r.detach("bad")
    assert "bad.error" not in r.snapshot()


def test_counter_and_gauge_primitives():
    c = Counter()
    assert c.inc() == 1 and c.inc(4) == 5
    e = Ewma(alpha=0.5)
    assert e.update(2.0) == 2.0  # first sample seeds
    assert e.update(4.0) == pytest.approx(3.0)
    e.reset()
    assert e.value == 0.0


# --------------------------------------------- EWMA migration bit-identity

def test_ewma_bit_identical_to_hand_rolled_idiom():
    """The migrated sites computed ``x if e == 0.0 else 0.7*e + 0.3*x``.
    The shared Ewma must reproduce those floats EXACTLY (1.0 - 0.3 == 0.7
    in IEEE double), or telemetry wire frames would drift."""
    rng = np.random.default_rng(0)
    xs = rng.uniform(0.1, 50.0, size=200)
    hand = 0.0
    e = Ewma()  # TELEMETRY_ALPHA
    for x in xs:
        x = float(x)
        hand = x if hand == 0.0 else 0.7 * hand + 0.3 * x
        e.update(x)
        assert e.value == hand  # exact, not approx


def test_lease_renew_floats_byte_unchanged_after_migration():
    """Regression for the ISSUE 12 telemetry-drift satellite: a LeaseRenew
    frame built from the shared-Ewma values is byte-identical to one built
    from the legacy hand-rolled chain."""
    from distributed_ml_pytorch_tpu.coord.coordinator import encode_renew

    rng = np.random.default_rng(7)
    steps = rng.uniform(1.0, 30.0, size=64)
    losses = rng.uniform(0.01, 4.0, size=64)
    hand_ms, hand_loss = 0.0, 0.0
    ew_ms, ew_loss = Ewma(), Ewma()
    for dt, loss in zip(steps, losses):
        dt, loss = float(dt), float(loss)
        hand_ms = dt if hand_ms == 0.0 else 0.7 * hand_ms + 0.3 * dt
        hand_loss = loss if hand_loss == 0.0 else 0.7 * hand_loss + 0.3 * loss
        ew_ms.update(dt)
        ew_loss.update(loss)
    old = encode_renew(123, 4, 5, hand_ms, 1, 2, 0, hand_loss, 6.0)
    new = encode_renew(123, 4, 5, ew_ms.value, 1, 2, 0, ew_loss.value, 6.0)
    assert old.tobytes() == new.tobytes()


def test_ewma_mean_var_matches_legacy_admission_stats():
    """utils/health's _SenderStats math, now in EwmaMeanVar: mean/var
    updates with the 2-sigma winsor clamp are bit-identical."""
    rng = np.random.default_rng(3)
    xs = [float(x) for x in rng.uniform(0.0, 10.0, size=100)]
    mean, var, count = 0.0, 0.0, 0
    st = EwmaMeanVar(alpha=0.2)
    for x in xs:
        clamp = None
        if count >= 8:
            import math

            sigma = max(math.sqrt(max(var, 0.0)), 0.5)
            clamp = 2.0 * sigma
            assert st.sigma(0.5) == sigma
        # legacy inline update
        if count == 0:
            mean, var = x, 0.0
        else:
            d = x - mean
            if clamp is not None:
                d = max(-clamp, min(clamp, d))
            mean += 0.2 * d
            var = (1.0 - 0.2) * (var + 0.2 * d * d)
        count += 1
        st.update(x, winsor=clamp)
        assert (st.mean, st.var, st.count) == (mean, var, count)


def test_admission_gate_snapshot_shape_survived_migration():
    from distributed_ml_pytorch_tpu.utils.health import GradientAdmission

    gate = GradientAdmission(warmup=2)
    for _ in range(4):
        assert gate.evaluate(1, np.ones(8, np.float32)) is None
    snap = gate.snapshot()
    mean, var, count = snap[1]
    assert count == 4 and var == pytest.approx(0.0) and mean > 0


# ------------------------------------------- corr id rides the envelope

def _pair(reliable_opts=None):
    from distributed_ml_pytorch_tpu.utils.messaging import make_world

    world, _ = make_world(2, reliable=True,
                          reliable_opts=reliable_opts or {})
    return world[0], world[1]


def test_corr_id_rides_reliability_envelope():
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    a, b = _pair()
    try:
        with obs.corr_scope(31337):
            b.send(MessageCode.GradientUpdate,
                   np.arange(4, dtype=np.float32))
        obs.set_corr(0)
        msg = a.recv(timeout=5)
        assert msg is not None and msg[1] == MessageCode.GradientUpdate
        # delivery restored the sender's correlation id on THIS thread
        assert obs.current_corr() == 31337
    finally:
        obs.set_corr(0)
        a.close()
        b.close()


def test_corr_id_survives_crc_and_is_covered_by_it():
    """The CRC covers the corr halves: flipping one drops the frame."""
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode,
        _frame_crc,
        _split16,
    )

    a, b = _pair()
    try:
        body = np.arange(4, dtype=np.float32)
        crc = _frame_crc(b.incarnation, 0, int(MessageCode.GradientUpdate),
                         body.tobytes(), 99)
        frame = np.concatenate([
            np.asarray([*_split16(b.incarnation), *_split16(0),
                        *_split16(crc),
                        float(int(MessageCode.GradientUpdate)),
                        *_split16(98)],  # corr flipped vs the CRC
                       np.float32), body])
        b.inner.send(MessageCode.ReliableFrame, frame, dst=0)
        assert a.recv(timeout=0.3) is None
        assert a.stats["crc_dropped"] == 1
        # the honest frame (corr matching its crc) delivers
        frame[7:9] = np.asarray(_split16(99), np.float32)
        b.inner.send(MessageCode.ReliableFrame, frame, dst=0)
        msg = a.recv(timeout=5)
        assert msg is not None and obs.current_corr() == 99
    finally:
        obs.set_corr(0)
        a.close()
        b.close()


def test_requeued_frames_keep_their_corr_id():
    """Review regression: frames surfaced while flush()/a blocked send
    pumped the transport are parked for the next recv — popping one must
    restore ITS delivery's correlation id, not whatever a later delivery
    left on the thread-local."""
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    a, b = _pair()
    try:
        for corr in (101, 202):
            with obs.corr_scope(corr):
                b.send(MessageCode.GradientUpdate,
                       np.full(4, float(corr), np.float32))
        # a's flush() pumps its inner transport: both inbound frames get
        # delivered during the pump and parked in the requeue, each
        # delivery overwriting the thread-local corr
        assert a.flush(timeout=5)
        obs.set_corr(0)
        first = a.recv(timeout=1)
        assert first is not None and obs.current_corr() == int(first[2][0])
        second = a.recv(timeout=1)
        assert second is not None and obs.current_corr() == int(second[2][0])
        assert {int(first[2][0]), int(second[2][0])} == {101, 202}
    finally:
        obs.set_corr(0)
        a.close()
        b.close()


def test_registry_ewma_alpha_is_honored_and_clash_raises():
    from distributed_ml_pytorch_tpu.utils.metrics import Registry

    r = Registry("t")
    e = r.ewma("x", alpha=0.5)
    assert e.alpha == 0.5
    assert r.ewma("x", alpha=0.5) is e
    with pytest.raises(ValueError, match="alpha"):
        r.ewma("x", alpha=0.25)


def test_transport_emits_wire_stats_event_on_close():
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    a, b = _pair()
    rec = obs.SpanRecorder("w", "wire")
    b.recorder = rec
    b.send(MessageCode.GradientUpdate, np.ones(4, np.float32))
    assert a.recv(timeout=5) is not None
    assert b.flush(timeout=5)
    a.close()
    b.close()
    stats = [e for e in rec.snapshot() if e["name"] == "wire-stats"]
    assert stats and stats[-1]["meta"]["sent"] == 1


# ------------------------------------------------- chaos-determinism guard

def _chaos_log_lines(with_recorder: bool) -> str:
    """One fixed send script through a faulty world; returns the chaos
    log rendering. The recorder must not move a single byte of it."""
    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan, FaultRule
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode,
        make_world,
    )

    plan = ChaosPlan(
        [FaultRule(code=int(MessageCode.ReliableFrame), drop=0.2, dup=0.2)],
        seed=11)
    # RTO far above the pump window: no retransmit ever fires, so the
    # faulted channel's send sequence is EXACTLY the 30 scripted sends —
    # the log is a pure function of the seed by construction
    world, log = make_world(
        2, reliable=True, plan=plan,
        reliable_opts=dict(ack_timeout=30.0, max_backoff=60.0))
    a, b = world[0], world[1]
    recs = []
    if with_recorder:
        for i, t in enumerate((a, b)):
            rec = obs.SpanRecorder(f"r{i}", "wire")
            t.recorder = rec
            recs.append(rec)
    got = 0
    try:
        for i in range(30):
            with obs.corr_scope():
                b.send(MessageCode.GradientUpdate,
                       np.full(8, float(i), np.float32))
        idle_since = time.monotonic()
        while time.monotonic() - idle_since < 0.3:
            if a.recv(timeout=0.1) is not None:
                got += 1
                idle_since = time.monotonic()
    finally:
        obs.set_corr(0)
        # detach, don't close: close()'s flush would wait on the frames
        # the chaos layer deliberately dropped
        a.detach()
        b.detach()
    assert got > 0
    if with_recorder:
        assert sum(r.total for r in recs) > 0  # it DID observe something
    return log.lines()


def test_recorder_never_perturbs_chaos_log():
    """THE determinism guard (ISSUE 12): fault decisions are drawn from
    seeded per-channel streams keyed by send indices; the recorder reads
    clocks only. Same script, recorder on vs off -> byte-identical log."""
    without = _chaos_log_lines(with_recorder=False)
    with_rec = _chaos_log_lines(with_recorder=True)
    assert without == with_rec
    assert "drop" in without or "dup" in without  # chaos actually fired


# ---------------------------------------------------- FleetState metrics

def test_fleet_state_metrics_tail_roundtrip():
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        decode_fleet,
        encode_fleet,
    )

    frame = encode_fleet(3, 2, 2, 2, False, engine_ranks=[4, 5],
                         fleet_metrics=[120.0, 33.5, 1.0, 2.0])
    got = decode_fleet(frame)
    assert got["engine_ranks"] == [4, 5]
    assert got["fleet_metrics"] == {
        "events_total": 120.0, "mean_ewma_ms": 33.5,
        "wire_open": 1.0, "nacks": 2.0}
    # the pre-ISSUE-12 form (no separator) still decodes, metrics empty
    legacy = encode_fleet(3, 2, 2, 2, False, engine_ranks=[4, 5])
    got = decode_fleet(legacy)
    assert got["engine_ranks"] == [4, 5]
    assert got["fleet_metrics"] == {}


def test_rollback_completion_writes_flight_dump(tmp_path):
    """ISSUE 12 acceptance slice: a completed rollback barrier persists
    the coordinator's timeline automatically — the MTTR number ships with
    its black box."""
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        KIND_SHARD,
        KIND_WORKER,
        Coordinator,
        encode_join,
        encode_renew,
        encode_rollback_done,
        encode_snapshot_done,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    clock = [0.0]
    c = Coordinator(None, 100, lease=100.0, speculation=False,
                    clock=lambda: clock[0], manifest_dir=str(tmp_path),
                    auto_rollback=True, rollback_loss_factor=1.5,
                    rollback_cooldown=50.0, rollback_timeout=20.0)
    c.recorder = obs.SpanRecorder("coord", "coord")
    c.obs_dir = str(tmp_path / "obs")
    c.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 3))
    c.handle(4, MessageCode.CoordJoin, encode_join(KIND_WORKER, 5))
    mv = c.shard_map.version
    c.trigger_snapshot()
    c.tick()
    c.handle(1, MessageCode.SnapshotDone,
             encode_snapshot_done(1, mv, 0, 100, 12, 12))
    c.handle(4, MessageCode.LeaseRenew, encode_renew(5, 1, 1, 1.0,
                                                     loss_ewma=2.0))
    clock[0] = 1.0
    c.tick()
    c.handle(4, MessageCode.LeaseRenew, encode_renew(5, 2, 2, 1.0,
                                                     loss_ewma=3.5))
    clock[0] = 2.0
    c.tick()
    assert c._roll is not None
    c.handle(1, MessageCode.RollbackDone,
             encode_rollback_done(1, mv, 0, 100, 12))
    assert c.rollbacks_done == 1
    dumps = os.listdir(c.obs_dir)
    assert any("rollback1" in d for d in dumps), dumps
    # the dump covers the fault window: the ROLLBACK decision is in it
    path = os.path.join(c.obs_dir, [d for d in dumps if "rollback1" in d][0])
    text = open(path).read()
    assert "ROLLBACK 1 started" in text and "complete" in text


def test_coordinator_broadcasts_fleet_metrics():
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        Coordinator,
        encode_join,
        encode_renew,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    world = InProcessTransport.create_world(2)
    c = Coordinator(world[0], 16, lease=10.0)
    c.handle(1, MessageCode.CoordJoin, encode_join(0, 100))
    c.handle(1, MessageCode.LeaseRenew,
             encode_renew(100, 3, 4, 25.0, 1, 2, 0, 1.5, 0.5))
    fs = c.fleet_state()
    assert fs["fleet_metrics"][0] == float(c.events.total)
    assert fs["fleet_metrics"][1] == 25.0  # the one reporter's ewma
    assert fs["fleet_metrics"][2] == 1.0 and fs["fleet_metrics"][3] == 2.0
