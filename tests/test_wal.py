"""ISSUE 5 durability-plane units: atomic_write, the write-ahead log, and
the ParameterServer's checkpoint/WAL handshake.

The corrupt-state recovery matrix (ISSUE 5 satellite):

- torn WAL tail (partial final record) → tolerated, counted, earlier
  records intact;
- CRC-corrupt record MID-log (valid records after it) → fails LOUDLY;
- stale-incarnation records (a dead life's tail flushed after the new
  life's records) → skipped and counted, never applied;
- replay idempotence when a checkpoint raced the log truncation → records
  the checkpoint covers are skipped by apply-seq;
- the checkpoint tear window (crash between the meta and vector renames)
  → detected by CRC, resolved to the consistent previous generation, and
  with the WAL on, replayed back to the exact pre-crash state.

All fast and in-process; they carry the ``drill`` marker so ``make drill``
runs the whole durability surface.
"""

import io
import os

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer
from distributed_ml_pytorch_tpu.utils.durability import atomic_write
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)
from distributed_ml_pytorch_tpu.utils.wal import (
    WALCorruptionError,
    WriteAheadLog,
    replay_wal,
)

pytestmark = pytest.mark.drill


# ------------------------------------------------------------ atomic_write

def test_atomic_write_replaces_durably(tmp_path, monkeypatch):
    """Content lands atomically, the temp file is gone, and BOTH the data
    and the containing directory were fsync'd (power-loss durability —
    plain write+rename syncs neither)."""
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(fd),
                                                 real_fsync(fd))[1])
    path = str(tmp_path / "state.bin")
    atomic_write(path, b"generation-1")
    atomic_write(path, b"generation-2")
    with open(path, "rb") as f:
        assert f.read() == b"generation-2"
    assert not os.path.exists(path + ".tmp")
    # per write: one data fsync + one directory fsync
    assert len(synced) >= 4


# ---------------------------------------------------------------- WAL core

def _fill(path, n=3, inc=20, start_seq=1):
    w = WriteAheadLog(path, incarnation=inc)
    for i in range(n):
        w.append(start_seq + i, np.full(4, start_seq + i, np.float32),
                 sender=1, env_inc=9, env_seq=i)
    w.sync()
    return w


def test_wal_roundtrip_and_truncate(tmp_path):
    path = str(tmp_path / "w.log")
    w = _fill(path)
    records, stats = replay_wal(path)
    assert [r.seq for r in records] == [1, 2, 3]
    assert stats == {"records": 3, "torn_tail": 0, "stale_skipped": 0}
    assert records[1].sender == 1 and records[1].env_seq == 1
    np.testing.assert_array_equal(records[2].payload,
                                  np.full(4, 3, np.float32))
    w.truncate(2)  # a checkpoint at apply seq 2 covers records 1-2
    records, _ = replay_wal(path)
    assert [r.seq for r in records] == [3]
    # the log keeps appending after a truncation
    w.append(4, np.zeros(4, np.float32))
    w.sync()
    assert [r.seq for r in replay_wal(path)[0]] == [3, 4]
    w.close()


def test_wal_torn_tail_is_tolerated(tmp_path):
    """A partial final record is the expected crash artifact: dropped and
    counted, with every earlier record intact."""
    path = str(tmp_path / "w.log")
    _fill(path).close()
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[:-5])  # the crash tore the last write
    records, stats = replay_wal(path)
    assert [r.seq for r in records] == [1, 2]
    assert stats["torn_tail"] == 1


def test_wal_v1_log_refused_loudly_not_torn_tail(tmp_path):
    """A pre-ISSUE-14 WAL1 log holds acked state this parser cannot
    decode: replay must REFUSE (the old magic is recognized for exactly
    this error), never classify the whole log as a torn tail and silently
    resume without its records."""
    import struct as _struct

    import numpy as _np

    from distributed_ml_pytorch_tpu.utils.wal import _MAGIC_V1

    # one record in the old WAL1 layout: magic inc seq sender env_inc
    # env_seq nbytes crc + payload (no codec field)
    body = _np.ones(4, _np.float32).tobytes()
    head = _struct.pack("<IIQiIIQ", _MAGIC_V1, 7, 1, 2, 0, 0, len(body))
    import zlib as _zlib

    crc = _zlib.crc32(body, _zlib.crc32(head)) & 0xFFFFFFFF
    path = str(tmp_path / "w.log")
    with open(path, "wb") as f:
        f.write(head + _struct.pack("<I", crc) + body)
    with pytest.raises(WALCorruptionError, match="WAL1"):
        replay_wal(path)


def test_wal_midlog_corruption_fails_loudly(tmp_path):
    """A CRC-corrupt record with valid records AFTER it is damage, not a
    torn tail — replay must refuse, never skip-and-continue past silently
    lost acked state."""
    path = str(tmp_path / "w.log")
    _fill(path).close()
    with open(path, "rb") as f:
        data = f.read()
    record_len = len(data) // 3
    flipped = bytearray(data)
    flipped[record_len - 3] ^= 0x5A  # inside record #1's payload
    with open(path, "wb") as f:
        f.write(bytes(flipped))
    with pytest.raises(WALCorruptionError):
        replay_wal(path)


def test_wal_stale_incarnation_records_skipped(tmp_path):
    """A record whose incarnation goes BACKWARD mid-log is a dead life's
    late-flushed tail: applying it over the newer life's state would
    corrupt it — skipped and counted."""
    path = str(tmp_path / "w.log")
    _fill(path, n=2, inc=20, start_seq=1).close()
    stale = WriteAheadLog(path, incarnation=10)  # an OLDER life appends
    stale.append(99, np.full(4, 99, np.float32))
    stale.sync()
    stale.close()
    _fill(path, n=1, inc=21, start_seq=3).close()
    records, stats = replay_wal(path)
    assert [r.seq for r in records] == [1, 2, 3]
    assert stats["stale_skipped"] == 1


# ------------------------------------------- ParameterServer + WAL handshake

def _server(tmp_path, wal=True, **kw):
    return ParameterServer(params=np.zeros(8, np.float32),
                           ckpt_dir=str(tmp_path), ckpt_every=0, wal=wal,
                           **kw)


def test_ps_wal_replays_acked_updates_without_checkpoint(tmp_path):
    """The tentpole guarantee, minimal form: updates applied + committed
    but NEVER checkpointed survive a crash via WAL replay alone, with the
    sequence accounting (apply seq, per-sender counts, staleness clock)
    restored alongside the vector."""
    ps = _server(tmp_path)
    delta = np.arange(8, dtype=np.float32)
    for _ in range(3):
        ps.handle(1, MessageCode.GradientUpdate, delta)
    ps.commit()  # the group fsync that releases the acks
    del ps  # the crash: no save_checkpoint

    ps2 = _server(tmp_path)
    assert ps2.maybe_restore()
    np.testing.assert_allclose(ps2.central, 3 * delta)
    assert ps2._apply_seq == 3 and ps2._push_count == 3
    assert ps2.applied_by_sender == {1: 3}
    assert ps2.staleness.version == 3
    assert ps2.replayed_updates == 3


def test_ps_wal_replay_is_idempotent_when_checkpoint_raced_truncation(
        tmp_path, monkeypatch):
    """A crash between save_checkpoint() and the WAL truncation leaves
    records the checkpoint already covers — replay must skip them by apply
    seq, not add them twice."""
    ps = _server(tmp_path)
    delta = np.arange(8, dtype=np.float32)
    for _ in range(3):
        ps.handle(1, MessageCode.GradientUpdate, delta)
    monkeypatch.setattr(WriteAheadLog, "truncate",
                        lambda self, upto_seq: None)  # the crash window
    ps.save_checkpoint()
    records, _ = replay_wal(ps.wal.path)
    assert len(records) == 3  # the covered records really are still there
    del ps

    ps2 = _server(tmp_path)
    assert ps2.maybe_restore()
    np.testing.assert_allclose(ps2.central, 3 * delta)  # NOT 6x
    assert ps2._apply_seq == 3 and ps2.replayed_updates == 0


def test_ckpt_tear_window_restores_consistent_previous_generation(tmp_path):
    """THE regression (ISSUE 5 satellite): a crash between the meta rename
    and the vector rename used to pair a v+1 vector with a v staleness
    clock silently. Now the meta carries the vector CRC + the previous
    generation: the tear restores the consistent OLD (vector, clock) pair,
    and the WAL replays the difference back to the exact pre-crash state."""
    import distributed_ml_pytorch_tpu.parallel.async_ps as async_ps

    ps = _server(tmp_path)
    delta = np.arange(8, dtype=np.float32)
    ps.handle(1, MessageCode.GradientUpdate, delta)
    ps.save_checkpoint()  # generation 1: vector == 1*delta, version 1
    for _ in range(2):
        ps.handle(1, MessageCode.GradientUpdate, delta)
    ps.commit()

    real = async_ps.atomic_write
    calls = []

    def crash_on_vector(path, data):
        if path.endswith("ps_central.npy"):
            calls.append(path)
            raise OSError("simulated crash between the two renames")
        return real(path, data)

    async_ps.atomic_write = crash_on_vector
    try:
        with pytest.raises(OSError):
            ps.save_checkpoint()  # meta (gen 2) lands, vector does not
    finally:
        async_ps.atomic_write = real
    assert calls  # the tear really happened after the meta rename
    del ps

    ps2 = _server(tmp_path)
    assert ps2.maybe_restore()
    # gen-1 vector adopted with gen-1 clock (not gen-2's), then the WAL
    # replayed updates 2..3 on top — the full pre-crash state, loss-free
    np.testing.assert_allclose(ps2.central, 3 * delta)
    assert ps2._apply_seq == 3 and ps2._push_count == 3
    assert ps2.replayed_updates == 2


def test_ckpt_vector_matching_neither_generation_fails_loudly(tmp_path):
    ps = _server(tmp_path, wal=False)
    ps.handle(1, MessageCode.GradientUpdate, np.ones(8, np.float32))
    ps.save_checkpoint()
    # real corruption: a vector that matches neither meta nor prev CRC
    buf = io.BytesIO()
    np.save(buf, np.full(8, 7.5, np.float32))
    with open(ps._ckpt_path(), "wb") as f:
        f.write(buf.getvalue())
    ps2 = _server(tmp_path, wal=False)
    with pytest.raises(ValueError, match="neither its meta"):
        ps2.maybe_restore()


def test_ps_wal_requires_ckpt_dir():
    with pytest.raises(ValueError, match="ckpt_dir"):
        ParameterServer(params=np.zeros(4, np.float32), wal=True)


def test_wrong_size_update_dropped_before_wal_or_accounting(tmp_path):
    """A wrong-size GradientUpdate must be dropped BEFORE the apply clock,
    per-sender counts, or the WAL see it — a logged record replay can
    never fit would refuse every future restore, and a size-1 payload
    would otherwise numpy-broadcast into the vector silently."""
    ps = _server(tmp_path)
    ps.handle(1, MessageCode.GradientUpdate, np.ones(3, np.float32))
    ps.handle(1, MessageCode.GradientUpdate, np.ones(1, np.float32))
    assert ps.dropped_bad_updates == 2
    assert ps._apply_seq == 0 and ps.applied_by_sender == {}
    assert ps.wal.appended == 0
    np.testing.assert_array_equal(ps.central, np.zeros(8, np.float32))


def test_ckpt_meta_keeps_envelope_tail_across_truncation(tmp_path):
    """save_checkpoint truncates the WAL (and its per-record envelopes),
    but an ack can be lost in flight — the meta's recent_envelopes tail
    must keep re-seeding dedup for retries of updates the checkpoint
    already covers."""
    seeded = []

    class FakeReliable(InProcessTransport):
        def seed_dedup(self, entries):
            seeded.extend(entries)

        def ack_delivered(self):
            pass

    world = InProcessTransport.create_world(2)
    t = FakeReliable(0, world[0]._boxes)
    ps = ParameterServer(params=np.zeros(8, np.float32), transport=t,
                         ckpt_dir=str(tmp_path), ckpt_every=0, wal=True)
    ps._envelope = (777, 3)
    ps.handle(2, MessageCode.GradientUpdate, np.ones(8, np.float32))
    ps.save_checkpoint()  # truncates the record away
    assert replay_wal(ps.wal.path)[0] == []
    del ps

    ps2 = ParameterServer(params=np.zeros(8, np.float32), transport=t,
                          ckpt_dir=str(tmp_path), ckpt_every=0, wal=True)
    assert ps2.maybe_restore()
    assert seeded == [(2, 777, 3)]  # the tail survived the truncation


def test_ps_wal_records_delivery_envelope_and_reseeds_dedup(tmp_path):
    """handle() stamps each WAL record with the reliability envelope that
    delivered it; maybe_restore() hands those identities back to the
    transport so a retry of an applied-but-unacked frame is deduped."""
    seeded = []

    class FakeReliable(InProcessTransport):
        def seed_dedup(self, entries):
            seeded.extend(entries)

    world = InProcessTransport.create_world(2)
    t = FakeReliable(0, world[0]._boxes)
    ps = ParameterServer(params=np.zeros(8, np.float32), transport=t,
                         ckpt_dir=str(tmp_path), ckpt_every=0, wal=True)
    ps._envelope = (1234, 7)  # what run() stashes from last_delivery
    ps.handle(2, MessageCode.GradientUpdate, np.ones(8, np.float32))
    ps.commit()
    del ps

    ps2 = ParameterServer(params=np.zeros(8, np.float32), transport=t,
                          ckpt_dir=str(tmp_path), ckpt_every=0, wal=True)
    assert ps2.maybe_restore()
    assert seeded == [(2, 1234, 7)]
