"""M3 contract tests: ravel/unravel round-trip (SURVEY.md §4 gap-closing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils.serialization import (
    flat_size,
    make_unraveler,
    ravel_model_params,
    unravel_model_params,
    zeros_like_flat,
)


def _params():
    from distributed_ml_pytorch_tpu.models import LeNet

    model = LeNet()
    return model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]


def test_ravel_is_flat_and_sized():
    params = _params()
    flat = ravel_model_params(params)
    assert flat.ndim == 1
    assert flat.shape[0] == flat_size(params)
    assert zeros_like_flat(params).shape == flat.shape


def test_round_trip_exact():
    params = _params()
    flat = ravel_model_params(params)
    rebuilt = unravel_model_params(params, flat)
    assert jax.tree.structure(rebuilt) == jax.tree.structure(params)
    for a, b in zip(jax.tree.leaves(rebuilt), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grads_ravel_aligns_with_params():
    """A flat gradient vector must line up element-for-element with the flat
    parameter vector (server applies flat grads to flat params)."""
    params = _params()
    grads = jax.tree.map(jnp.ones_like, params)
    flat_p = ravel_model_params(params)
    flat_g = ravel_model_params(params, grads=grads)
    assert flat_g.shape == flat_p.shape
    stepped = unravel_model_params(params, flat_p - 0.1 * flat_g)
    for s, p in zip(jax.tree.leaves(stepped), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(s), np.asarray(p) - 0.1, rtol=1e-6)


def test_unraveler_cache_matches():
    params = _params()
    unravel = make_unraveler(params)
    flat = ravel_model_params(params)
    a = unravel(flat)
    b = unravel_model_params(params, flat)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_jit_compatible():
    params = _params()
    unravel = make_unraveler(params)

    @jax.jit
    def step(p):
        flat = ravel_model_params(p)
        return unravel(flat * 2.0)

    doubled = step(params)
    for d, p in zip(jax.tree.leaves(doubled), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(d), 2 * np.asarray(p), rtol=1e-6)
