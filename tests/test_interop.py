"""Torch→JAX weight migration: converted reference-architecture checkpoints
must reproduce the torch model's logits."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from distributed_ml_pytorch_tpu.models import AlexNet, LeNet  # noqa: E402
from distributed_ml_pytorch_tpu.utils.interop import load_torch_state_dict  # noqa: E402


def torch_alexnet():
    # the reference's CIFAR AlexNet architecture (SURVEY.md C7)
    return tnn.Sequential(
        tnn.Conv2d(3, 64, 11, stride=4, padding=5), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Flatten(),
        tnn.Linear(256, 10),
    )


def torch_lenet():
    class TL(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(3, 6, 5)
            self.conv2 = tnn.Conv2d(6, 16, 5)
            self.fc1 = tnn.Linear(400, 120)
            self.fc2 = tnn.Linear(120, 84)
            self.fc3 = tnn.Linear(84, 10)

        def forward(self, x):
            import torch.nn.functional as F

            x = F.relu(F.max_pool2d(self.conv1(x), 2))
            x = F.relu(F.max_pool2d(self.conv2(x), 2))
            x = x.flatten(1)
            x = F.relu(self.fc1(x))
            x = F.relu(self.fc2(x))
            return self.fc3(x)

    return TL()


def _compare(torch_model, flax_model, flatten_shape=None, rtol=2e-4, atol=2e-5):
    torch.manual_seed(0)
    x = np.random.default_rng(0).normal(size=(4, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        want = torch_model(torch.from_numpy(x.transpose(0, 3, 1, 2).copy())).numpy()

    template = flax_model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    params = load_torch_state_dict(
        template, torch_model.state_dict(), flatten_shape=flatten_shape
    )
    got = np.asarray(flax_model.apply({"params": params}, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)


def test_alexnet_torch_weights_reproduce_logits():
    _compare(torch_alexnet(), AlexNet(num_classes=10))  # 1x1 flatten: no permute


def test_lenet_torch_weights_reproduce_logits():
    # fc1 consumes a 16x5x5 flatten: CHW→HWC column permutation required
    _compare(torch_lenet(), LeNet(num_classes=10), flatten_shape=(16, 5, 5))


def test_converter_rejects_wrong_architecture():
    template = AlexNet(num_classes=10).init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3))
    )["params"]
    with pytest.raises(ValueError, match="architectures differ"):
        load_torch_state_dict(template, torch_lenet().state_dict())


def test_converter_rejects_shape_mismatch():
    template = LeNet(num_classes=10).init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3))
    )["params"]
    bad = torch_lenet()
    sd = dict(bad.state_dict())
    sd["fc3.weight"] = torch.zeros(11, 84)  # wrong num_classes
    with pytest.raises(ValueError, match="no state_dict tensor matches"):
        load_torch_state_dict(template, sd)


def test_converter_rejects_batchnorm_checkpoints():
    model = tnn.Sequential(tnn.Conv2d(3, 8, 3), tnn.BatchNorm2d(8))
    template = AlexNet(num_classes=10).init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3))
    )["params"]
    with pytest.raises(ValueError, match="BatchNorm"):
        load_torch_state_dict(template, model.state_dict())


def test_converter_rejects_unmatched_flatten_shape():
    template = LeNet(num_classes=10).init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3))
    )["params"]
    with pytest.raises(ValueError, match="flatten_shape"):
        load_torch_state_dict(
            template, torch_lenet().state_dict(), flatten_shape=(16, 5, 4)
        )
