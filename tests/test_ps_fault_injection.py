"""Fault injection on the real PS topology (VERDICT r1 #9): kill the server
mid-push and a worker mid-epoch — real processes over TCP, asserting the
documented degradation / failure-detection / checkpoint-resume states. The
reference hangs forever in every one of these scenarios (SURVEY.md §5.3)."""

import os
import signal
import subprocess
import sys
import time

from distributed_ml_pytorch_tpu.launch import _free_port, cpu_platform_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args, tmp_path):
    env = cpu_platform_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "distributed_ml_pytorch_tpu.training.cli"] + args,
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _common(port, tmp_path, world=3, **over):
    flags = {
        "--mode": "ps", "--model": "lenet", "--epochs": "3",
        "--batch-size": "16", "--test-batch-size": "32", "--lr": "0.05",
        "--num-push": "2", "--num-pull": "2", "--log-interval": "1000",
        "--synthetic-data": None, "--synthetic-train-size": "256",
        "--synthetic-test-size": "32", "--world-size": str(world),
        "--port": port, "--log-dir": str(tmp_path),
    }
    flags.update(over)
    out = []
    for k, v in flags.items():
        out.append(k)
        if v is not None:
            out.append(str(v))
    return out


def _wait_for(path, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.2)
    return False


def _drain(procs):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                outs.append(p.communicate()[0])
    return outs


def test_server_killed_mid_push_workers_degrade_and_resume(tmp_path):
    """SIGKILL the server once pushes are flowing: both workers must finish
    locally (exit 0, CSVs on disk, degradation message), and a restarted
    server must resume the checkpointed central vector."""
    port = _free_port()
    ckpt = tmp_path / "ckpt"
    common = _common(port, tmp_path)
    server = _spawn(common + ["--rank", "0", "--server", "--ckpt-dir",
                              str(ckpt), "--ckpt-every", "1"], tmp_path)
    workers = [_spawn(common + ["--rank", str(r)], tmp_path) for r in (1, 2)]

    assert _wait_for(ckpt / "ps_central.npy"), "no push ever checkpointed"
    time.sleep(0.5)  # let a few more pushes land: the kill is mid-stream
    server.send_signal(signal.SIGKILL)
    outs = _drain(workers)
    assert all(w.returncode == 0 for w in workers), "\n\n".join(outs)
    for out, rank in zip(outs, (1, 2)):
        assert "parameter server unreachable" in out, out
        assert "Finished Training" in out, out
        assert os.path.exists(tmp_path / f"node{rank}.csv")
    server.communicate()

    # restart the world against the same checkpoint (the transport
    # rendezvous needs its workers, so the restart brings one): the server
    # must adopt the saved central params and a --rejoin worker must pull
    # them and train to completion — the documented recovery flow
    port2 = _free_port()
    common2 = _common(port2, tmp_path, world=2, **{"--epochs": "1"})
    restarted = _spawn(common2 + ["--rank", "0", "--server", "--ckpt-dir",
                                  str(ckpt), "--resume"], tmp_path)
    rejoiner = _spawn(common2 + ["--rank", "1", "--rejoin"], tmp_path)
    routs = _drain([restarted, rejoiner])
    assert restarted.returncode == 0, routs[0]
    assert "resumed central params from" in routs[0], routs[0]
    assert rejoiner.returncode == 0, routs[1]
    assert "Finished Training" in routs[1], routs[1]


def test_worker_killed_mid_epoch_server_completes(tmp_path):
    """SIGKILL one worker mid-epoch: the server must declare it failed after
    --worker-timeout and still exit cleanly once the surviving worker is
    done; the survivor is unaffected."""
    port = _free_port()
    ckpt = tmp_path / "ckpt"
    common = _common(port, tmp_path)
    server = _spawn(common + ["--rank", "0", "--server", "--worker-timeout",
                              "3", "--ckpt-dir", str(ckpt),
                              "--ckpt-every", "1"], tmp_path)
    survivor = _spawn(common + ["--rank", "1"], tmp_path)
    victim = _spawn(common + ["--rank", "2"], tmp_path)

    assert _wait_for(ckpt / "ps_central.npy"), "no push ever checkpointed"
    victim.send_signal(signal.SIGKILL)
    victim.communicate()

    outs = _drain([server, survivor])
    assert server.returncode == 0, outs[0]
    assert "worker 2 silent" in outs[0] and "declaring it failed" in outs[0], outs[0]
    assert "all workers done" not in outs[0]  # one died; server must say so
    assert survivor.returncode == 0, outs[1]
    assert "Finished Training" in outs[1], outs[1]
    assert os.path.exists(tmp_path / "node1.csv")
