"""Network-marked real-data tests (VERDICT r2 #6).

These run ONLY where egress exists: ``pytest -m network tests/test_real_data.py``.
In this sandbox (zero egress) they skip cleanly — the point is that the
moment the suite runs somewhere with network, the real-CIFAR-10 claims
close themselves with no code changes.
"""

import subprocess
import sys

import pytest


@pytest.fixture(scope="module")
def real_cifar(tmp_path_factory):
    from distributed_ml_pytorch_tpu.data import load_cifar10

    root = str(tmp_path_factory.mktemp("cifar_real"))
    try:
        data = load_cifar10(root=root, synthetic=False, download=True)
    except Exception as e:
        pytest.skip(f"real CIFAR-10 unavailable (no egress?): {e}")
    return data


@pytest.mark.network
def test_real_cifar10_downloads_and_has_canonical_shapes(real_cifar):
    x, y, xt, yt, is_synth = real_cifar
    assert not is_synth
    assert x.shape == (50000, 32, 32, 3) and xt.shape == (10000, 32, 32, 3)
    assert set(y.tolist()) == set(range(10))


@pytest.mark.network
def test_real_data_short_training_learns(real_cifar):
    """A few hundred reference-recipe steps on the genuine data must beat
    chance decisively — the sanity gate before the full parity run."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        create_train_state,
        make_eval_fn,
        make_scan_train_step,
    )

    x, y, xt, yt, _ = real_cifar
    model = AlexNet()
    state, tx = create_train_state(model, jax.random.key(0), lr=0.008)
    scan = make_scan_train_step(model, tx)
    ev = make_eval_fn(model)
    idx = np.random.default_rng(0).integers(0, len(x), size=(8, 50, 64))
    for sel in idx:
        state, _ = scan(state, jnp.asarray(x[sel]), jnp.asarray(y[sel]),
                        jax.random.key(1))
    _, preds = ev(state.params, jnp.asarray(xt[:2000]), jnp.asarray(yt[:2000]))
    acc = float((np.asarray(preds) == yt[:2000]).mean())
    assert acc > 0.25, f"400 real-data steps only reached {acc:.3f}"


def test_verify_real_data_script_skips_cleanly_without_egress(tmp_path):
    """The one-command closer must exit 0 with an explicit SKIP record when
    the download cannot happen — runnable unconditionally in CI."""
    import os
    import shutil

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # run from a scratch cwd so ./data stays empty and BASELINE.md untouched;
    # a dead proxy makes the download fail FAST even on networked hosts, so
    # this test deterministically exercises the skip path everywhere
    shutil.copy(os.path.join(repo, "verify_real_data.py"), tmp_path)
    out = subprocess.run(
        [sys.executable, str(tmp_path / "verify_real_data.py")],
        capture_output=True, text=True, cwd=tmp_path,
        env={**os.environ,
             "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
             "http_proxy": "http://127.0.0.1:9",
             "https_proxy": "http://127.0.0.1:9"},
        timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "skipped_no_egress" in out.stdout
