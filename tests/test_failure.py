"""Failure detection + staleness audit — subsystems the reference lacks
(SURVEY.md §5.2-5.3): crashed-worker detection at the server, dead-server
degradation at the worker, and measured gradient staleness."""

import threading
import time

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.parallel.async_ps import Asynchronous, ParameterServer
from distributed_ml_pytorch_tpu.utils.failure import (
    FailureDetector,
    HeartbeatSender,
    StalenessAuditor,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_detector_reports_expired_rank_once():
    clk = FakeClock()
    d = FailureDetector(timeout=5.0, ranks=[1, 2], clock=clk)
    clk.t = 3.0
    d.note(1)
    clk.t = 6.0  # rank 2 silent for 6 > 5; rank 1 seen 3s ago
    assert d.expired() == {2}
    assert d.expired() == set()  # reported exactly once
    assert d.failed == {2}
    assert d.alive() == {1}


def test_detector_rejoin_and_forget():
    clk = FakeClock()
    d = FailureDetector(timeout=1.0, ranks=[1], clock=clk)
    clk.t = 2.0
    assert d.expired() == {1}
    d.note(1)  # failed rank speaks again → rejoins
    assert d.failed == set()
    d.forget(1)  # clean finish → not tracked, never expires
    clk.t = 10.0
    assert d.expired() == set()


def test_detector_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        FailureDetector(timeout=0.0)


def test_staleness_auditor_measures_versions_between_pull_and_push():
    a = StalenessAuditor()
    a.on_pull(1)          # worker 1 pulls at version 0
    a.on_push(2)          # version 0→1 while worker 1 trains
    a.on_push(2)          # version 1→2
    s = a.on_push(1)      # worker 1's push is 2 versions stale
    assert s == 2
    summary = a.summary()
    assert summary["pushes"] == 3 and summary["max"] == 2
    assert "staleness" in a.report()


def test_staleness_auditor_empty_is_silent():
    assert StalenessAuditor().summary() is None
    assert StalenessAuditor().report() is None


def test_heartbeat_sender_emits_frames():
    world = InProcessTransport.create_world(2)
    hb = HeartbeatSender(world[1], interval=0.02)
    hb.start()
    msg = world[0].recv(timeout=2.0)
    hb.stop()
    assert msg is not None
    sender, code, payload = msg
    assert sender == 1 and code == MessageCode.Heartbeat and payload.size == 0


def test_server_declares_silent_worker_failed_instead_of_hanging():
    world = InProcessTransport.create_world(3)
    server = ParameterServer(
        params=np.zeros(4, np.float32),
        transport=world[0],
        n_workers=2,
        worker_timeout=0.3,
    )
    # worker 1 finishes cleanly; worker 2 "crashes" (never sends anything)
    world[1].send(MessageCode.WorkerDone, np.zeros(0, np.float32))
    t0 = time.monotonic()
    server.run(timeout=10.0)  # guard: must exit via detection, not timeout
    assert time.monotonic() - t0 < 5.0
    assert server.failed_workers == {2}


def test_heartbeats_keep_long_cadence_worker_alive():
    world = InProcessTransport.create_world(2)
    server = ParameterServer(
        params=np.zeros(4, np.float32),
        transport=world[0],
        n_workers=1,
        worker_timeout=0.4,
    )
    hb = HeartbeatSender(world[1], interval=0.05)
    hb.start()
    result = {}

    def serve():
        server.run(timeout=10.0)
        result["failed"] = set(server.failed_workers)

    t = threading.Thread(target=serve)
    t.start()
    time.sleep(1.0)  # well past worker_timeout: heartbeats must keep rank 1 alive
    assert t.is_alive(), "server exited while its only worker was heartbeating"
    world[1].send(MessageCode.WorkerDone, np.zeros(0, np.float32))
    t.join(timeout=5.0)
    hb.stop()
    assert result["failed"] == set()


def test_concurrent_sends_do_not_interleave_frames():
    """Heartbeat thread + training thread share one socket; frames must not
    tear (TCPTransport serializes writers per peer socket)."""
    from distributed_ml_pytorch_tpu.launch import _free_port
    from distributed_ml_pytorch_tpu.utils.messaging import TCPTransport

    port = _free_port()
    server_box = {}

    def serve():
        server_box["t"] = TCPTransport(0, 2, port=port)

    st = threading.Thread(target=serve)
    st.start()
    worker = TCPTransport(1, 2, port=port)
    st.join()
    server = server_box["t"]

    n_big, n_beats = 12, 300
    big = np.arange(500_000, dtype=np.float32)  # 2 MB: sendall spans syscalls

    def push():
        for _ in range(n_big):
            worker.send(MessageCode.GradientUpdate, big)

    def beat():
        for _ in range(n_beats):
            worker.send(MessageCode.Heartbeat, np.zeros(0, np.float32))

    threads = [threading.Thread(target=push), threading.Thread(target=beat)]
    for t in threads:
        t.start()
    got_big = got_beat = 0
    # (the worker's hello frame is consumed by the server's accept loop)
    for _ in range(n_big + n_beats):
        msg = server.recv(timeout=10.0)
        assert msg is not None, "stream corrupted or stalled"
        _, code, payload = msg
        if code == MessageCode.GradientUpdate:
            got_big += 1
            np.testing.assert_array_equal(payload, big)
        elif code == MessageCode.Heartbeat:
            got_beat += 1
    for t in threads:
        t.join()
    assert got_big == n_big and got_beat == n_beats
    worker.close()
    server.close()


def test_failed_worker_that_finishes_is_not_double_counted():
    world = InProcessTransport.create_world(3)
    server = ParameterServer(
        params=np.zeros(4, np.float32),
        transport=world[0],
        n_workers=2,
        worker_timeout=0.3,
    )
    result = {}

    def serve():
        server.run(timeout=10.0)
        result["failed"] = set(server.failed_workers)

    hb2 = HeartbeatSender(world[2], interval=0.05)  # worker 2 stays healthy
    hb2.start()
    t = threading.Thread(target=serve)
    t.start()
    time.sleep(0.6)  # worker 1 silent past the timeout → declared failed
    # worker 1 was only slow (long jit compile): it finishes cleanly. It must
    # rejoin and count as done only — NOT as done AND failed, which would end
    # the run while worker 2 is still training.
    world[1].send(MessageCode.WorkerDone, np.zeros(0, np.float32))
    time.sleep(0.3)
    assert t.is_alive(), (
        "server exited counting a finished worker as both done and failed"
    )
    world[2].send(MessageCode.WorkerDone, np.zeros(0, np.float32))
    t.join(timeout=5.0)
    hb2.stop()
    assert not t.is_alive()
    assert result["failed"] == set()


class DyingTransport(InProcessTransport):
    """Starts delivering, then raises on send — a mid-run server death."""

    def __init__(self, rank, mailboxes):
        super().__init__(rank, mailboxes)
        self.dead = False

    def send(self, code, payload, dst=0):
        if self.dead:
            raise ConnectionError("server is gone")
        super().send(code, payload, dst=dst)


def test_worker_degrades_to_local_sgd_when_server_dies():
    import jax.numpy as jnp

    boxes = InProcessTransport.create_world(2)
    dying = DyingTransport(1, boxes[1]._boxes)
    params = {"w": jnp.ones((3,)), "b": jnp.zeros((2,))}
    grads = {"w": jnp.ones((3,)), "b": jnp.ones((2,))}
    opt = Asynchronous(params, lr=0.1, n_push=1, n_pull=1, transport=dying)
    params = opt.step(params, grads)  # healthy step
    dying.dead = True
    for _ in range(3):  # must not raise; training continues locally
        params = opt.step(params, grads)
    assert opt.server_down
    opt.finish()  # also must not raise
    np.testing.assert_allclose(np.asarray(params["w"]), np.full(3, 1.0 - 0.4), rtol=1e-6)


def _rejoin_scenario(make_worker, server):
    """Shared elastic-rejoin drill for both transports: worker 2 dies, a
    replacement process (new transport, same rank) reconnects and talks."""
    w1 = make_worker(1)
    w2 = make_worker(2)
    w2.send(MessageCode.GradientUpdate, np.arange(4, dtype=np.float32))
    msg = server.recv(timeout=5.0)
    assert msg is not None and msg[0] == 2
    w2.close()  # worker 2 "crashes"
    time.sleep(0.2)
    w2b = make_worker(2)  # restarted process rejoins with the same rank
    w2b.send(MessageCode.ParameterRequest, np.zeros(0, np.float32))
    msg = server.recv(timeout=5.0)
    assert msg is not None and msg[0] == 2 and msg[1] == MessageCode.ParameterRequest
    # server can reply to the REJOINED socket
    server.send(MessageCode.ParameterUpdate, np.ones(3, np.float32), dst=2)
    got = w2b.recv(timeout=5.0)
    assert got is not None and got[1] == MessageCode.ParameterUpdate
    np.testing.assert_array_equal(got[2], np.ones(3, np.float32))
    # the surviving worker is unaffected
    w1.send(MessageCode.WorkerDone, np.zeros(0, np.float32))
    msg = server.recv(timeout=5.0)
    assert msg is not None and msg[0] == 1 and msg[1] == MessageCode.WorkerDone
    for t in (w1, w2b):
        t.close()
    server.close()


def test_python_tcp_transport_supports_worker_rejoin():
    from distributed_ml_pytorch_tpu.launch import _free_port
    from distributed_ml_pytorch_tpu.utils.messaging import TCPTransport

    port = _free_port()
    box = {}
    st = threading.Thread(target=lambda: box.update(s=TCPTransport(0, 3, port=port)))
    st.start()
    workers = {}

    def make_worker(rank):
        t = TCPTransport(rank, 3, port=port)
        workers[rank] = t
        return t

    make_worker(1), make_worker(2)
    st.join(timeout=10.0)
    server = box["s"]
    for t in workers.values():
        t.close()
    _rejoin_scenario(make_worker, server)


def test_native_transport_supports_worker_rejoin():
    from distributed_ml_pytorch_tpu import native
    from distributed_ml_pytorch_tpu.launch import _free_port

    if not native.native_available():
        pytest.skip(f"native transport unavailable: {native.native_load_error()}")
    port = _free_port()
    box = {}
    st = threading.Thread(
        target=lambda: box.update(s=native.NativeTCPTransport(0, 3, port=port))
    )
    st.start()
    workers = {}

    def make_worker(rank):
        t = native.NativeTCPTransport(rank, 3, port=port)
        workers[rank] = t
        return t

    make_worker(1), make_worker(2)
    st.join(timeout=30.0)
    server = box["s"]
    for t in workers.values():
        t.close()
    _rejoin_scenario(make_worker, server)


def test_rejoining_worker_adopts_central_params_instead_of_stomping():
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params

    world = InProcessTransport.create_world(2)
    central = np.arange(5, dtype=np.float32)  # the run's learned state
    server = ParameterServer(params=central, transport=world[0], n_workers=1)

    params = {"w": jnp.zeros((3,)), "b": jnp.zeros((2,))}  # fresh init
    opt = Asynchronous(params, lr=0.1, n_push=10, n_pull=10,
                       transport=world[1], rejoin=True)
    # serve the pending ParameterRequest (and nothing else)
    msg = world[0].recv(timeout=2.0)
    assert msg is not None and msg[1] == MessageCode.ParameterRequest
    server.handle(*msg)
    np.testing.assert_array_equal(server.central, central)  # NOT stomped
    time.sleep(0.3)  # listener deposits the reply
    grads = {"w": jnp.zeros((3,)), "b": jnp.zeros((2,))}
    params = opt.step(params, grads)  # first boundary installs central
    np.testing.assert_allclose(
        np.asarray(ravel_model_params(params)), central, rtol=1e-6
    )
    opt.finish()


def test_half_open_connection_does_not_wedge_elastic_accept():
    """A connection that never sends its hello (port scan / instant death)
    must not block later rejoins: the handshake is timeout-bounded."""
    import socket as socket_mod

    from distributed_ml_pytorch_tpu.launch import _free_port
    from distributed_ml_pytorch_tpu.utils.messaging import TCPTransport

    port = _free_port()
    box = {}
    st = threading.Thread(target=lambda: box.update(s=TCPTransport(0, 2, port=port)))
    st.start()
    w1 = TCPTransport(1, 2, port=port)
    st.join(timeout=10.0)
    server = box["s"]
    # half-open garbage: connects, sends nothing
    zombie = socket_mod.create_connection(("localhost", port), timeout=5)
    w1.close()
    time.sleep(0.2)
    # the rejoin must get through even while the zombie handshake is pending
    # (bounded at 5s, so allow for it to be processed first)
    w1b = TCPTransport(1, 2, port=port, connect_timeout=20)
    w1b.send(MessageCode.Heartbeat, np.zeros(0, np.float32))
    deadline = time.monotonic() + 15.0
    got = None
    while time.monotonic() < deadline:
        msg = server.recv(timeout=1.0)
        if msg is not None and msg[1] == MessageCode.Heartbeat:
            got = msg
            break
    assert got is not None and got[0] == 1, "rejoin blocked by half-open connection"
    zombie.close()
    w1b.close()
    server.close()
