"""ISSUE 16 — the multi-tenant fleet scheduler (coord/sched.py).

Four layers:

1. **Ledger + registry units** — exclusive grants, the double-owner
   audit, priority ordering and wire-exact tenant ids.
2. **Scheduler protocol, synchronously** — a real ``Coordinator`` with a
   fake clock, driven entirely by ``handle()``/``tick()`` calls: admit
   over free capacity, the preempt → park → hand-over → resume round
   trip, lease exemption for parked ranks, resume timeout, and the
   capped decision ring.
3. **Autoscale actuation** — ``FleetAutoscaler`` closes the
   ``check_engine_scaling`` advisory loop (spawn on up, retire the
   emptiest on down, capacity bounds refuse, MTTR sampling).
4. **The drill** — ``sched_drill`` preempts a live training member
   mid-run under wire chaos, parks it via the FleetManifest, resumes it
   bit-for-bit, and proves acked <= applied per (worker, shard), zero
   double-applied deltas, and 3x byte-identical chaos logs.
"""

import pytest

from distributed_ml_pytorch_tpu.coord import drill
from distributed_ml_pytorch_tpu.coord.coordinator import (
    KIND_ENGINE,
    KIND_SHARD,
    Coordinator,
    encode_join,
    encode_preempt_done,
    encode_renew,
)
from distributed_ml_pytorch_tpu.coord.sched import (
    HELD,
    PARKED,
    PARKING,
    RESUMING,
    CapacityLedger,
    FleetScheduler,
)
from distributed_ml_pytorch_tpu.coord.tenants import (
    TENANT_SERVING,
    Tenant,
    TenantRegistry,
)
from distributed_ml_pytorch_tpu.serving.fleet import FleetAutoscaler
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)

pytestmark = pytest.mark.sched

TRAIN, SERVE = 1, 2


# ------------------------------------------------------- ledger + registry

def test_ledger_exclusive_grant_refused_until_released():
    ledger = CapacityLedger()
    slot = ledger.add_slot(rank=3, tenant_id=TRAIN)
    assert slot.state == HELD and ledger.owned(TRAIN) == [slot]
    assert not ledger.grant(slot, SERVE, grant_id=1)  # exclusivity refuses
    assert slot.owners == [TRAIN] and ledger.audit() == []
    ledger.release(slot, TRAIN)
    assert ledger.grant(slot, SERVE, grant_id=2)
    assert slot.owners == [SERVE] and slot.grant_id == 2


def test_ledger_audit_flags_double_owner_when_gate_dropped():
    ledger = CapacityLedger(enforce_exclusive=False)
    slot = ledger.add_slot(rank=3, tenant_id=TRAIN)
    assert ledger.grant(slot, SERVE, grant_id=1)  # the bug surface
    (line,) = ledger.audit()
    assert "double-granted" in line and f"[{TRAIN}, {SERVE}]" in line


def test_registry_priority_order_and_wire_exact_ids():
    reg = TenantRegistry()
    reg.register(Tenant(tenant_id=TRAIN, name="train", priority=1))
    reg.register(Tenant(tenant_id=SERVE, name="serve",
                        kind=TENANT_SERVING, priority=5))
    assert [t.tenant_id for t in reg.all()] == [SERVE, TRAIN]
    assert [t.tenant_id for t in reg.by_priority_asc()] == [TRAIN, SERVE]
    with pytest.raises(ValueError):
        reg.register(Tenant(tenant_id=1 << 16, name="too-wide"))
    reg.set_demand(SERVE, 3)
    assert reg.get(SERVE).demand == 3


# ------------------------------------- scheduler protocol, synchronously

def _harness(*, require_manifest=False, enforce_exclusive=True,
             lease=60.0, resume_timeout=30.0):
    """A real Coordinator + scheduler on a fake clock; ranks 1..2 are
    shard members registered as the training tenant's slots."""
    now = [0.0]
    world = InProcessTransport.create_world(4)
    coord = Coordinator(world[0], 8, lease=lease, speculation=False,
                        clock=lambda: now[0])
    reg = TenantRegistry()
    reg.register(Tenant(tenant_id=TRAIN, name="train", priority=1,
                        demand=2, min_slots=1))
    reg.register(Tenant(tenant_id=SERVE, name="serve",
                        kind=TENANT_SERVING, priority=5, demand=0))
    sched = FleetScheduler(coord, registry=reg,
                           require_manifest=require_manifest,
                           enforce_exclusive=enforce_exclusive,
                           resume_timeout=resume_timeout)
    for rank in (1, 2):
        coord.handle(rank, MessageCode.CoordJoin,
                     encode_join(KIND_SHARD, rank))
        sched.register_member_slot(rank, TRAIN)
    grants = []
    sched.on_grant = lambda gid, tid, action, slot: grants.append(
        (gid, tid, action, slot.slot_id))
    return coord, sched, now, grants, world


def _close(world):
    for t in world.values():
        t.close()


def test_free_slot_admitted_without_preempting_anyone():
    coord, sched, now, grants, world = _harness()
    try:
        sched.ledger.add_slot(rank=None)  # spare capacity
        sched.registry.set_demand(SERVE, 1)
        sched.tick(now[0])
        assert [g[1:3] for g in grants] == [(SERVE, 1)]
        assert len(sched.ledger.owned(SERVE)) == 1
        assert sched.preempts_done == 0 and sched.ledger.audit() == []
        assert any(f"tenant {SERVE}: admit" in d for d in sched.decisions)
    finally:
        _close(world)


def _park_victim(coord, sched, now):
    """Drive demand spike -> PreemptRequest -> PreemptDone; returns the
    victim rank and the serving grant id."""
    sched.registry.set_demand(SERVE, 1)
    sched.tick(now[0])
    p = sched._pending
    assert p is not None and p["slot"].state == PARKING
    victim = p["slot"].rank
    gid = p["grant_id"]
    coord.handle(victim, MessageCode.PreemptDone,
                 encode_preempt_done(gid, 0, 4, 8, 17))
    return victim, gid


def test_preempt_parks_victim_then_hands_slot_over_exclusively():
    coord, sched, now, grants, world = _harness()
    try:
        victim, gid = _park_victim(coord, sched, now)
        assert victim == 2  # last-owned slot of the lowest-priority tenant
        (slot,) = sched.ledger.owned(SERVE)
        assert slot.state == PARKED and slot.owners == [SERVE]
        assert slot.parked["rank"] == victim
        assert slot.parked == dict(rank=victim, tenant=TRAIN,
                                   incarnation=victim, snapshot_id=0,
                                   lo=4, hi=8, apply_seq=17,
                                   # the borrowing side rides the ticket
                                   # so a coordinator crash between the
                                   # WAL'd park and the next checkpoint
                                   # can resynthesize the slot (ISSUE 17)
                                   slot_id=slot.slot_id, borrower=SERVE,
                                   grant_id=gid)
        # the grant fired only AFTER PreemptDone freed the slot
        assert grants == [(gid, SERVE, 1, slot.slot_id)]
        assert sched.preempts_done == 1 and len(sched.preempt_mttrs) == 1
        assert sched.ledger.audit() == []
        assert sched.parked_ranks() == {victim}
        # min_slots floor: more demand finds no second victim
        sched.registry.set_demand(SERVE, 2)
        sched.tick(now[0])
        assert sched._pending is None and sched.preempts_done == 1
    finally:
        _close(world)


def test_parked_rank_is_exempt_from_lease_expiry():
    coord, sched, now, grants, world = _harness(lease=2.0)
    try:
        victim, _ = _park_victim(coord, sched, now)
        now[0] += 50.0  # way past every lease
        coord.tick()
        assert victim in coord.members   # a park, not a death
        assert 1 not in coord.members    # the unparked silent rank expired
    finally:
        _close(world)


def test_resume_completes_when_the_rank_rejoins_newer():
    coord, sched, now, grants, world = _harness()
    try:
        victim, _ = _park_victim(coord, sched, now)
        resumes = []
        sched.on_resume = lambda gid, parked: resumes.append(parked)
        sched.registry.set_demand(SERVE, 0)  # off-peak
        now[0] += 1.0
        sched.tick(now[0])
        (slot,) = [s for s in sched.ledger.slots.values()
                   if s.state == RESUMING]
        assert resumes and resumes[0]["rank"] == victim
        # revoke actuated before the restore started
        assert grants[-1][1:3] == (SERVE, 0)
        # the rank's new life joins with a newer incarnation
        coord.handle(victim, MessageCode.CoordJoin,
                     encode_join(KIND_SHARD, victim + 1))
        now[0] += 0.5
        sched.tick(now[0])
        assert slot.state == HELD and slot.owners == [TRAIN]
        assert slot.parked is None
        assert sched.resumes_done == 1 and len(sched.resume_mttrs) == 1
        assert sched.ledger.audit() == []
    finally:
        _close(world)


def test_resume_timeout_falls_back_to_parked_not_lost():
    coord, sched, now, grants, world = _harness(resume_timeout=5.0)
    try:
        victim, _ = _park_victim(coord, sched, now)
        sched.registry.set_demand(SERVE, 0)
        sched.tick(now[0])
        now[0] += 6.0  # no rejoin arrives
        sched.tick(now[0])
        (slot,) = [s for s in sched.ledger.slots.values()
                   if s.state == PARKED]
        assert slot.parked["rank"] == victim  # restore ticket survives
        assert sched.resumes_done == 0
        assert any("ABANDONED" in d for d in sched.decisions)
    finally:
        _close(world)


def test_decisions_ride_a_capped_ring_with_tenant_ids():
    coord, sched, now, grants, world = _harness()
    try:
        for i in range(600):
            sched._log(SERVE, f"decision {i}")
        assert sched.decisions.total == 600
        assert len(sched.decisions) == 512
        assert sched.decisions.dropped == 88
        assert all(d.startswith(f"tenant {SERVE}:")
                   for d in sched.decisions)
        summary = sched.summary()
        assert summary["decisions_total"] == 600
        assert summary["decisions_dropped"] == 88
    finally:
        _close(world)


def test_require_manifest_gates_the_preempt_behind_the_barrier():
    coord, sched, now, grants, world = _harness(require_manifest=True)
    try:
        sched.registry.set_demand(SERVE, 1)
        sched.tick(now[0])
        p = sched._pending
        assert p is not None and p["snap_requested"] and not p["sent"]
        sched.tick(now[0])
        assert not sched._pending["sent"]  # barrier still in flight
        # the barrier lands: a manifest is durable now
        coord.manifests_written += 1
        coord.last_manifest = type("M", (), {"snapshot_id": 7})()
        sched.tick(now[0])
        assert sched._pending["sent"] and sched._pending["snap_id"] == 7
        assert any("snapshot 7" in d for d in sched.decisions)
    finally:
        _close(world)


# ------------------------------------------------------ autoscale actuation

class _FakeMember:
    def __init__(self, engine_id):
        self.engine_id = engine_id
        self.last_beat = 0.0
        self.busy = 0
        self.queued = 0
        self.stopped = False

    def start(self):
        pass

    def stop(self):
        self.stopped = True

    def pressure(self):
        return self.busy, 1, self.queued


class _FakeRouter:
    def __init__(self, members=()):
        self.members = {m.engine_id: m for m in members}

    def add_member(self, member):
        self.members[member.engine_id] = member

    def remove_member(self, engine_id):
        return self.members.pop(engine_id, None)


def test_autoscaler_spawns_retires_and_refuses_at_bounds():
    now = [10.0]
    m0 = _FakeMember(0)
    router = _FakeRouter([m0])
    next_eid = [1]

    def factory():
        m = _FakeMember(next_eid[0])
        next_eid[0] += 1
        return m

    auto = FleetAutoscaler(router, factory, min_engines=1, max_engines=2,
                           clock=lambda: now[0])
    auto.on_scale("up", {})
    assert auto.quiesce() and auto.scaled_up == 1
    assert set(router.members) == {0, 1}
    auto.on_scale("up", {})  # at max_engines
    assert auto.quiesce() and auto.refused == 1 and len(router.members) == 2
    # MTTR closes at the first poll after the replica beats
    auto.poll()
    assert list(auto.scale_up_mttr_s) == []
    router.members[1].last_beat = 12.5
    auto.poll()
    assert list(auto.scale_up_mttr_s) == [pytest.approx(2.5)]
    # down retires the EMPTIEST replica
    m0.busy = 3
    auto.on_scale("down", {})
    assert auto.quiesce() and auto.scaled_down == 1
    assert set(router.members) == {0}
    auto.on_scale("down", {})  # at min_engines
    assert auto.quiesce() and auto.refused == 2 and set(router.members) == {0}
    s = auto.summary()
    assert s["scaled_up"] == 1 and s["scaled_down"] == 1 and s["refused"] == 2


def test_engine_scaling_advice_actually_spawns_a_replica():
    """The closed loop: an overloaded engine's renewal -> the
    coordinator's advisory -> FleetAutoscaler spawns a new member."""
    now = [0.0]
    world = InProcessTransport.create_world(2)
    coord = Coordinator(world[0], 8, lease=60.0, speculation=False,
                        engine_occ_high=0.85, scale_cooldown=1.0,
                        clock=lambda: now[0])
    try:
        router = _FakeRouter([_FakeMember(0)])
        auto = FleetAutoscaler(router, lambda: _FakeMember(1),
                               min_engines=1, max_engines=4,
                               clock=lambda: now[0])
        coord.on_scale = auto.on_scale
        coord.handle(1, MessageCode.CoordJoin, encode_join(KIND_ENGINE, 1))
        coord.handle(1, MessageCode.LeaseRenew,
                     encode_renew(1, push_count=95, step=4, ewma_ms=40.0))
        assert coord.check_engine_scaling(now[0]) == "up"
        assert auto.quiesce() and auto.scaled_up == 1
        assert set(router.members) == {0, 1}
        # cooldown rate-limits the next advisory
        assert coord.check_engine_scaling(now[0]) is None
    finally:
        for t in world.values():
            t.close()


# -------------------------------------------------------------- the drill

@pytest.mark.drill
@pytest.mark.chaos
def test_sched_drill_preempt_resume_bit_identical_3x(tmp_path):
    """The acceptance drill, three times over: peak demand preempts a
    LIVE training shard mid-run under seeded wire chaos, parks it via
    the FleetManifest, resumes it off-peak with exactly-once WAL
    replay — and the three runs' chaos logs are byte-identical, so the
    whole preempt/resume protocol is deterministic under the plan."""
    chaos_logs = []
    for rep in range(3):
        out = drill.sched_drill(base_dir=str(tmp_path / f"rep{rep}"),
                                seed=0, plan=drill.default_drill_plan(0))
        assert out["ok"], (out["violations"], out["errors"],
                           out["stuck_workers"])
        assert out["violations"] == [] and out["errors"] == []
        s = out["sched"]
        assert s["preempts_done"] == 1 and s["resumes_done"] == 1
        assert s["audit"] == []
        # the park window produced WAL-only deltas and the restore
        # replayed them exactly once, bit-for-bit
        assert out["replayed_updates"] > 0
        assert out["bit_identical"] is True
        # acked <= applied per (worker, shard): nothing acked was lost,
        # nothing was double-applied
        for worker, per_shard in out["acked"].items():
            for shard, acked in per_shard.items():
                assert acked <= out["applied"][worker][shard], (
                    f"worker {worker} shard {shard}: acked {acked} > "
                    f"applied {out['applied'][worker][shard]}")
        assert out["chaos_counts"].get("drop", 0) > 0  # chaos really ran
        chaos_logs.append(out["chaos_lines"])
    assert chaos_logs[0] == chaos_logs[1] == chaos_logs[2]


def test_autoscaler_summary_reads_under_the_counter_lock():
    """DC204 closure (ISSUE 19 satellite): ``summary()`` must read the
    scale counters under ``_mu`` — the actuator thread mutates them in
    ``quiesce``. Pin the behavior: a held ``_mu`` blocks ``summary()``
    until release, so the read really is inside the critical section."""
    import threading

    router = _FakeRouter([_FakeMember(0)])
    auto = FleetAutoscaler(router, lambda: _FakeMember(1),
                           min_engines=1, max_engines=2,
                           clock=lambda: 0.0)
    done = threading.Event()
    out = {}

    def read():
        out["summary"] = auto.summary()
        done.set()

    auto._mu.acquire()
    try:
        t = threading.Thread(target=read, daemon=True)
        t.start()
        assert not done.wait(0.25), \
            "summary() returned while the counter lock was held"
    finally:
        auto._mu.release()
    assert done.wait(2.0)
    t.join(2.0)
    assert out["summary"]["scaled_up"] == 0
