"""C7 parity tests: LeNet/AlexNet shapes and parameter counts vs the
reference architectures (``example/models.py:5-49``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models import AlexNet, LeNet, get_model


def test_lenet_output_shape():
    model = LeNet()
    x = jnp.zeros((4, 32, 32, 3))
    params = model.init(jax.random.key(0), x)["params"]
    out = model.apply({"params": params}, x)
    assert out.shape == (4, 10)


def test_lenet_param_count_matches_reference():
    # torch LeNet (example/models.py:5-23):
    # conv1 3*6*25+6=456; conv2 6*16*25+16=2416; fc1 400*120+120=48120;
    # fc2 120*84+84=10164; fc3 84*10+10=850  → 61,006? compute: 456+2416+48120+10164+850
    expected = 456 + 2416 + 48120 + 10164 + 850
    model = LeNet()
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert total == expected


def test_alexnet_param_count_matches_reference():
    # torch AlexNet (example/models.py:25-49):
    # conv1 3*64*121+64; conv2 64*192*25+192; conv3 192*384*9+384;
    # conv4 384*256*9+256; conv5 256*256*9+256; fc 256*10+10
    expected = (
        (3 * 64 * 121 + 64)
        + (64 * 192 * 25 + 192)
        + (192 * 384 * 9 + 384)
        + (384 * 256 * 9 + 256)
        + (256 * 256 * 9 + 256)
        + (256 * 10 + 10)
    )
    model = AlexNet()
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert total == expected


def test_alexnet_feature_map_is_256():
    """The classifier sees exactly 256 features at 32×32 input (the reference's
    single Linear(256, num_classes), example/models.py:43)."""
    model = AlexNet()
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    kernel = variables["params"]["classifier"]["kernel"]
    assert kernel.shape == (256, 10)
    assert model.apply(variables, x).shape == (2, 10)


def test_lenet_dropout_train_vs_eval():
    model = LeNet()
    x = jnp.ones((8, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    eval1 = model.apply(variables, x, train=False)
    eval2 = model.apply(variables, x, train=False)
    np.testing.assert_array_equal(np.asarray(eval1), np.asarray(eval2))
    train_out = model.apply(
        variables, x, train=True, rngs={"dropout": jax.random.key(1)}
    )
    assert not np.allclose(np.asarray(train_out), np.asarray(eval1))


def test_get_model_registry():
    assert isinstance(get_model("lenet"), LeNet)
    assert isinstance(get_model("alexnet"), AlexNet)
    with pytest.raises(ValueError):
        get_model("nope")


def test_bfloat16_compute_dtype():
    model = AlexNet(dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.key(0), x)["params"]
    out = model.apply({"params": params}, x)
    assert out.dtype == jnp.float32  # logits promoted back for a stable loss


def test_max_pool_2x2_matches_nn_max_pool_values_and_tie_gradients():
    """The reshape-max pooling must equal nn.max_pool forward AND backward
    bit-for-bit — including tied windows (post-relu zeros), where its
    first-max rule must reproduce select_and_scatter's winner — so the
    swap is a pure speed change (measured +7.2% on the batch-64 AlexNet
    step) with training trajectories untouched."""
    from flax import linen as nn

    from distributed_ml_pytorch_tpu.models.cnn import max_pool_2x2

    rng = np.random.default_rng(0)
    # quantized, relu-clipped values: many exact ties inside windows
    x = jnp.asarray(np.maximum(rng.integers(-2, 3, (4, 8, 8, 16)), 0),
                    jnp.float32)
    g = jnp.asarray(rng.normal(size=(4, 4, 4, 16)), jnp.float32)

    old = lambda x: nn.max_pool(x, (2, 2), strides=(2, 2))
    assert bool(jnp.all(old(x) == max_pool_2x2(x)))
    g_old = jax.vjp(old, x)[1](g)[0]
    g_new = jax.vjp(max_pool_2x2, x)[1](g)[0]
    np.testing.assert_array_equal(np.asarray(g_old), np.asarray(g_new))
