"""VERDICT r3 #1: every advertised CLI knob works in --mode ps and local-sgd.

The DownPour generalization under test: the worker's local optimizer is an
arbitrary optax transform; pushes carry the accumulated local param DELTAS
(for the default SGD recipe these are exactly −lr·grads, the reference's
lr-pre-scaled accumulator), and the server contract — add the payload —
is unchanged. The invariant that makes this checkable without a server:
between installs, the accumulator always equals the worker's local param
drift since the last push.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.models import LeNet, get_model
from distributed_ml_pytorch_tpu.parallel.async_ps import (
    Asynchronous,
    default_downpour_tx,
    init_downpour_accumulator,
    make_downpour_chunk_step,
    make_downpour_device_step,
)
from distributed_ml_pytorch_tpu.training.trainer import (
    make_lr_schedule,
    make_optimizer,
    tx_from_args,
)
from distributed_ml_pytorch_tpu.utils.messaging import InProcessTransport, MessageCode
from distributed_ml_pytorch_tpu.utils.serialization import ravel_model_params


def _lenet_params(seed=0):
    model = LeNet()
    return model, model.init(jax.random.key(seed), jnp.zeros((1, 32, 32, 3)))["params"]


def _client(params, **kw):
    world = InProcessTransport.create_world(2)
    opt = Asynchronous(params, transport=world[1], **kw)
    opt._send = lambda code, payload: None  # no server: pure local math
    return opt


def _rand_grads(params, seed):
    leaves, treedef = jax.tree.flatten(params)
    rng = np.random.default_rng(seed)
    return jax.tree.unflatten(
        treedef,
        [jnp.asarray(rng.normal(size=l.shape) * 0.01, l.dtype) for l in leaves],
    )


def test_push_payload_equals_local_param_drift_momentum():
    """With momentum the per-step update is no longer −lr·grad, but the
    accumulator must still equal (params_now − params_at_last_push): that is
    exactly what the server needs to add to track this worker."""
    _, params = _lenet_params()
    tx = make_optimizer("sgd", 0.05, momentum=0.9)
    opt = _client(params, lr=0.05, n_push=100, n_pull=100, tx=tx)
    # step 0 fires a push (the reference's idx%n==0-at-0 quirk) and zeroes
    # the accumulator, so the drift baseline is the post-step-0 params
    p = opt.step(params, _rand_grads(params, 99))
    flat0 = np.asarray(ravel_model_params(p))
    for s in range(4):
        p = opt.step(p, _rand_grads(params, s))
    drift = np.asarray(ravel_model_params(p)) - flat0
    accum = np.asarray(opt.accum[: opt._flat_n])
    np.testing.assert_allclose(accum, drift, rtol=1e-5, atol=1e-7)
    # momentum really engaged: repeated equal grads accelerate the move
    g = _rand_grads(params, 99)
    plain = _client(params, lr=0.05, n_push=100, n_pull=100)
    q = params
    for _ in range(3):
        q = plain.step(q, g)
    mom = _client(params, lr=0.05, n_push=100, n_pull=100, tx=tx)
    m = params
    for _ in range(3):
        m = mom.step(m, g)
    drift_plain = np.abs(np.asarray(ravel_model_params(q)) - flat0).sum()
    drift_mom = np.abs(np.asarray(ravel_model_params(m)) - flat0).sum()
    assert drift_mom > 1.5 * drift_plain


def test_default_tx_is_reference_math():
    """Default client (no tx): accumulator == −lr·Σgrads exactly — the
    reference's lr-pre-scaled accumulation (Asynchronous.py:55)."""
    _, params = _lenet_params()
    opt = _client(params, lr=0.1, n_push=100, n_pull=100)
    g = _rand_grads(params, 0)
    p = opt.step(params, g)  # step 0: pushes + zeroes accum (reference quirk)
    p = opt.step(p, g)
    p = opt.step(p, g)
    flat_g = np.asarray(ravel_model_params(params, grads=g))
    np.testing.assert_allclose(
        np.asarray(opt.accum[: opt._flat_n]), -0.1 * flat_g * 2, rtol=1e-6, atol=1e-8
    )


def test_lr_schedule_decays_ps_updates():
    """inverse-epoch schedule through the PS client: the same gradient
    produces visibly smaller updates in later 'epochs' (steps//spe + 1)."""
    _, params = _lenet_params()
    lr = make_lr_schedule("inverse-epoch", 0.1, steps_per_epoch=2)
    opt = _client(params, lr=0.1, n_push=100, n_pull=100, tx=optax.sgd(lr))
    g = _rand_grads(params, 3)
    flat_prev = np.asarray(ravel_model_params(params))
    step_norms = []
    p = params
    for s in range(6):
        p = opt.step(p, g)
        flat = np.asarray(ravel_model_params(p))
        step_norms.append(float(np.abs(flat - flat_prev).sum()))
        flat_prev = flat
    # epochs of 2 steps at lr, lr/2, lr/3
    np.testing.assert_allclose(step_norms[2] / step_norms[0], 0.5, rtol=1e-4)
    np.testing.assert_allclose(step_norms[4] / step_norms[0], 1 / 3, rtol=1e-4)


def test_grad_accum_in_ps_updates_every_k():
    """MultiSteps(k=2) through the PS client: params move only on every
    second step, and the push accumulator tracks exactly the applied moves."""
    _, params = _lenet_params()
    tx = optax.MultiSteps(optax.sgd(0.05), every_k_schedule=2)
    opt = _client(params, lr=0.05, n_push=100, n_pull=100, tx=tx)
    flat0 = np.asarray(ravel_model_params(params))
    p = opt.step(params, _rand_grads(params, 0))
    f1 = np.asarray(ravel_model_params(p))
    np.testing.assert_array_equal(f1, flat0)  # mid-accumulation: no move
    p = opt.step(p, _rand_grads(params, 1))
    f2 = np.asarray(ravel_model_params(p))
    assert np.abs(f2 - flat0).sum() > 0  # emission step moves
    np.testing.assert_allclose(
        np.asarray(opt.accum[: opt._flat_n]), f2 - flat0, rtol=1e-5, atol=1e-7
    )


def test_chunked_matches_per_step_with_adam():
    """The fused chunk dispatch must reproduce the per-step device math for a
    stateful optimizer too (adam: moments thread through the scan carry)."""
    model = get_model("lenet")
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    tx = optax.adam(1e-3)
    _, n, pad, accum = init_downpour_accumulator(params)
    rng = np.random.default_rng(0)
    L = 4
    bxs = jnp.asarray(rng.normal(size=(L, 8, 32, 32, 3)), jnp.float32)
    bys = jnp.asarray(rng.integers(0, 10, (L, 8)))
    key = jax.random.key(7)

    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    device_step = make_downpour_device_step(tx, pad)

    def grad_fn(p, bx, by, idx):
        def loss_fn(q):
            logits = model.apply(
                {"params": q}, bx, train=True,
                rngs={"dropout": jax.random.fold_in(key, idx)},
            )
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    p_ref, s_ref, a_ref = params, tx.init(params), accum
    for i in range(L):
        _, grads = grad_fn(p_ref, bxs[i], bys[i], i)
        p_ref, s_ref, a_ref = device_step(p_ref, s_ref, grads, a_ref)

    chunk_step = make_downpour_chunk_step(model, tx, pad)
    _, _, _, accum2 = init_downpour_accumulator(params)
    p_chk, s_chk, a_chk, _ = chunk_step(
        params, tx.init(params), accum2, bxs, bys, key, 0
    )
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_chk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(a_ref), np.asarray(a_chk), rtol=1e-5, atol=1e-7)


def test_tx_from_args_full_surface():
    """tx_from_args is the single knob-reading point: grad-accum wraps
    MultiSteps, schedule + clip + momentum compose."""

    class A:
        lr = 0.01
        epochs = 2
        lr_schedule = "cosine"
        optimizer = "sgd"
        momentum = 0.9
        weight_decay = 1e-4
        grad_clip = 1.0
        grad_accum = 3
        seed = 0

    tx = tx_from_args(A(), steps_per_epoch=10)
    _, params = _lenet_params()
    state = tx.init(params)
    g = _rand_grads(params, 0)
    updates, state = tx.update(g, state, params)
    # MultiSteps: first micro-batch emits zero update
    assert all(float(jnp.abs(u).max()) == 0.0 for u in jax.tree.leaves(updates))


def test_local_sgd_rounds_fusion_matches_per_round(mesh8):
    """make_local_sgd_rounds (R fused rounds, one dispatch) must equal R
    make_local_sgd_round dispatches exactly — same params, same losses."""
    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.parallel.local_sgd import (
        make_local_sgd_round,
        make_local_sgd_rounds,
    )
    from distributed_ml_pytorch_tpu.parallel.sync import put_sharded, replicate
    from distributed_ml_pytorch_tpu.training.trainer import create_train_state
    from jax.sharding import PartitionSpec as P

    x, y, *_ = load_cifar10(n_train=256, n_test=16, synthetic=True)
    model = LeNet()
    state0, tx = create_train_state(model, jax.random.key(0), lr=0.05, momentum=0.9)
    R, k, gb = 2, 2, 64
    data_x = x[: R * k * gb].reshape(R, k, gb, 32, 32, 3)
    data_y = y[: R * k * gb].reshape(R, k, gb)
    rng = replicate(mesh8, jax.random.key(1))

    st_a = replicate(mesh8, state0)
    round_fn = make_local_sgd_round(model, tx, mesh8)
    losses_a = []
    for r in range(R):
        rx = put_sharded(mesh8, data_x[r], P(None, "data", None, None, None))
        ry = put_sharded(mesh8, data_y[r], P(None, "data"))
        st_a, losses = round_fn(st_a, rx, ry, rng)
        losses_a.append(np.asarray(losses))

    st_b = replicate(mesh8, state0)
    rounds_fn = make_local_sgd_rounds(model, tx, mesh8)
    rx = put_sharded(mesh8, data_x, P(None, None, "data", None, None, None))
    ry = put_sharded(mesh8, data_y, P(None, None, "data"))
    st_b, losses_b = rounds_fn(st_b, rx, ry, rng)

    np.testing.assert_allclose(
        np.stack(losses_a), np.asarray(losses_b), rtol=1e-5, atol=1e-7
    )
    for a, b in zip(jax.tree.leaves(st_a.params), jax.tree.leaves(st_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)
    assert int(st_b.step) == R * k


def test_local_sgd_ckpt_resume_matches_uninterrupted(mesh8, tmp_path):
    """--ckpt-dir + --resume in local-sgd: a run killed after epoch 0 and
    resumed must land on the same params as an uninterrupted run (the data
    order is a pure function of (seed, epoch))."""
    from distributed_ml_pytorch_tpu.parallel.local_sgd import train_local_sgd
    from distributed_ml_pytorch_tpu.training.cli import build_parser

    def make_args(epochs, extra=()):
        return build_parser().parse_args([
            "--mode", "local-sgd", "--epochs", str(epochs), "--synthetic-data",
            "--synthetic-train-size", "256", "--synthetic-test-size", "16",
            "--batch-size", "2", "--model", "lenet", "--lr", "0.01",
            "--log-interval", "1000", "--sync-every", "2",
            "--log-dir", str(tmp_path / "log"), *extra,
        ])

    ref_state, _ = train_local_sgd(make_args(2), mesh8)

    ck = str(tmp_path / "ck")
    st1, _ = train_local_sgd(make_args(1, ("--ckpt-dir", ck)), mesh8)
    st2, _ = train_local_sgd(
        make_args(2, ("--ckpt-dir", ck, "--resume")), mesh8
    )
    assert int(st2.step) == int(ref_state.step)
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(st2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_local_sgd_steps_per_dispatch_same_trajectory(mesh8, tmp_path):
    """--steps-per-dispatch through the local-sgd CLI loop: fused-round
    training must reproduce the per-round trajectory exactly (same final
    params, same CSV losses)."""
    from distributed_ml_pytorch_tpu.parallel.local_sgd import train_local_sgd
    from distributed_ml_pytorch_tpu.training.cli import build_parser

    def run(extra, sub):
        args = build_parser().parse_args([
            "--mode", "local-sgd", "--epochs", "1", "--synthetic-data",
            "--synthetic-train-size", "256", "--synthetic-test-size", "16",
            "--batch-size", "2", "--model", "lenet", "--lr", "0.01",
            "--log-interval", "6", "--sync-every", "2",
            "--log-dir", str(tmp_path / sub), *extra,
        ])
        return train_local_sgd(args, mesh8)

    st_a, log_a = run((), "a")
    st_b, log_b = run(("--steps-per-dispatch", "6"), "b")
    la = [r["training_loss"] for r in log_a.records]
    lb = [r["training_loss"] for r in log_b.records]
    np.testing.assert_allclose(la, lb, rtol=1e-5)
    # eval rows land at the same steps with the same params
    ea = {r["iteration"]: r["test_accuracy"] for r in log_a.records if "test_accuracy" in r}
    eb = {r["iteration"]: r["test_accuracy"] for r in log_b.records if "test_accuracy" in r}
    assert set(ea) == set(eb) and len(ea) > 0
    for i in ea:
        np.testing.assert_allclose(ea[i], eb[i], rtol=1e-5)
    for a, b in zip(jax.tree.leaves(st_a.params), jax.tree.leaves(st_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def _trace_files(profile_dir):
    import os

    found = []
    for root, _dirs, files in os.walk(profile_dir):
        found += [f for f in files if not f.startswith(".")]
    return found


def _ps_args(tmp_path, extra=()):
    from distributed_ml_pytorch_tpu.training.cli import build_parser

    return build_parser().parse_args([
        "--mode", "ps", "--epochs", "1", "--synthetic-data",
        "--synthetic-train-size", "64", "--synthetic-test-size", "16",
        "--batch-size", "4", "--model", "lenet", "--lr", "0.05",
        "--log-interval", "1000", "--log-dir", str(tmp_path / "log"),
        "--heartbeat-interval", "0", *extra,
    ])


def _run_ps_world(args):
    """1 in-process server + 1 train_worker over the given args."""
    import threading

    from distributed_ml_pytorch_tpu.parallel.async_ps import (
        ParameterServer,
        train_worker,
    )

    model, params0 = _lenet_params(seed=args.seed)
    world = InProcessTransport.create_world(2)
    server = ParameterServer(
        params=np.asarray(ravel_model_params(params0)),
        transport=world[0], n_workers=1,
    )
    th = threading.Thread(target=server.run, kwargs={"timeout": 300})
    th.start()
    try:
        params, logger = train_worker(args, world[1])
    finally:
        th.join(timeout=60)
    assert not th.is_alive()
    return server, params, logger


def test_ps_profile_dir_per_step(tmp_path):
    """--profile-dir in --mode ps (per-step dispatch): a trace is written."""
    trace = tmp_path / "trace"
    args = _ps_args(tmp_path, (
        "--profile-dir", str(trace), "--profile-start", "2",
        "--profile-steps", "2", "--chunked-dispatch", "off",
    ))
    _server, _params, logger = _run_ps_world(args)
    assert _trace_files(trace), "no trace files written in ps per-step mode"
    assert len(logger.records) == 16


def test_ps_profile_dir_and_steps_per_dispatch_chunked(tmp_path):
    """--steps-per-dispatch K in --mode ps caps the fused chunk length (and
    forces chunking on), and --profile-dir traces the chunked window."""
    trace = tmp_path / "trace"
    args = _ps_args(tmp_path, (
        "--profile-dir", str(trace), "--profile-start", "4",
        "--profile-steps", "4", "--steps-per-dispatch", "3",
    ))
    server, _params, logger = _run_ps_world(args)
    assert _trace_files(trace), "no trace files written in ps chunked mode"
    # 16 steps, cadence 10/10: every step still logs a CSV row
    assert len(logger.records) == 16
    assert server.message_counts[MessageCode.GradientUpdate] >= 2


def test_ps_cli_knobs_full_worker(tmp_path):
    """The previously-gated knobs through the REAL worker loop: momentum +
    inverse-epoch schedule + grad clipping in --mode ps trains and pushes."""
    args = _ps_args(tmp_path, (
        "--momentum", "0.9", "--lr-schedule", "inverse-epoch",
        "--grad-clip", "1.0", "--optimizer", "sgd", "--epochs", "2",
    ))
    server, _params, logger = _run_ps_world(args)
    losses = [r["training_loss"] for r in logger.records]
    assert len(losses) == 32
    assert float(np.mean(losses[-8:])) < float(np.mean(losses[:8]))
    assert np.isfinite(server.central).all()


def test_local_sgd_profile_dir(tmp_path, mesh8):
    """--profile-dir in --mode local-sgd writes a trace."""
    from distributed_ml_pytorch_tpu.parallel.local_sgd import train_local_sgd
    from distributed_ml_pytorch_tpu.training.cli import build_parser

    trace = tmp_path / "trace"
    args = build_parser().parse_args([
        "--mode", "local-sgd", "--epochs", "1", "--synthetic-data",
        "--synthetic-train-size", "128", "--synthetic-test-size", "16",
        "--batch-size", "2", "--model", "lenet", "--lr", "0.01",
        "--log-interval", "1000", "--sync-every", "2",
        "--log-dir", str(tmp_path / "log"),
        "--profile-dir", str(trace), "--profile-start", "2",
        "--profile-steps", "2",
    ])
    train_local_sgd(args, mesh8)
    assert _trace_files(trace), "no trace files written in local-sgd mode"
