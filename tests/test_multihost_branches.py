"""Multi-host code paths, tested without multi-host (VERDICT r1 #6).

This jax build cannot federate CPU processes into one global device set
(see tests/test_multihost_bootstrap.py for what IS runnable), so the
``process_count > 1`` branches are covered at their seams: monkeypatch
``jax.process_count`` / ``jax.make_array_from_process_local_data`` and
assert the routing, shardings, and per-process slices that a pod run
would produce."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_ml_pytorch_tpu.data import shard_for_process
from distributed_ml_pytorch_tpu.parallel.sync import put_sharded, shard_batch
from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh


@pytest.fixture
def mesh():
    return make_mesh({"data": 8})


def test_put_sharded_single_process_is_device_put(mesh):
    x = np.arange(16, dtype=np.float32).reshape(16, 1)
    out = put_sharded(mesh, x, P("data", None))
    assert out.sharding == NamedSharding(mesh, P("data", None))
    np.testing.assert_array_equal(np.asarray(out), x)


def test_put_sharded_multiprocess_branch_routes_local_data(mesh, monkeypatch):
    """With process_count > 1, the array must go through
    make_array_from_process_local_data with the exact sharding and the
    process-LOCAL slice — never through plain device_put."""
    calls = {}
    sentinel = object()

    monkeypatch.setattr(jax, "process_count", lambda: 2)

    def fake_assemble(sharding, array):
        calls["sharding"] = sharding
        calls["array"] = array
        return sentinel

    monkeypatch.setattr(jax, "make_array_from_process_local_data", fake_assemble)

    def forbidden_device_put(*a, **k):  # the single-process path must not run
        raise AssertionError("device_put used on the multi-process branch")

    monkeypatch.setattr(jax, "device_put", forbidden_device_put)

    local = np.arange(8, dtype=np.float32).reshape(8, 1)  # this host's slice
    out = put_sharded(mesh, local, P("data", None))
    assert out is sentinel
    assert calls["sharding"] == NamedSharding(mesh, P("data", None))
    assert calls["array"] is local


def test_shard_batch_multiprocess_specs_per_array(mesh, monkeypatch):
    """shard_batch must lift each array's leading axis to the data axis —
    images (b,h,w,c) → P(data,None,None,None), labels (b,) → P(data)."""
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    seen = []
    monkeypatch.setattr(
        jax, "make_array_from_process_local_data",
        lambda sharding, array: seen.append((sharding.spec, array.shape)) or array,
    )
    images = np.zeros((8, 32, 32, 3), np.float32)
    labels = np.zeros((8,), np.int32)
    shard_batch(mesh, images, labels)
    assert seen == [
        (P("data", None, None, None), (8, 32, 32, 3)),
        (P("data"), (8,)),
    ]


def test_shard_for_process_feeds_put_sharded_consistently(mesh, monkeypatch):
    """Integration of the per-host loader with the assembly seam: each
    simulated process passes its strided shard, and the union of what
    reaches make_array_from_process_local_data is exactly the global batch,
    each share under the same global sharding."""
    global_x = np.arange(16, dtype=np.float32).reshape(16, 1)
    global_y = np.arange(16, dtype=np.int32)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    received = []
    monkeypatch.setattr(
        jax, "make_array_from_process_local_data",
        lambda sharding, array: received.append((sharding, array)) or array,
    )
    for rank in (0, 1):
        lx, ly = shard_for_process(global_x, global_y, rank, 2)
        assert len(lx) == 8  # half the global batch per host
        shard_batch(mesh, lx, ly)
    shardings = {s for s, _ in received}
    assert shardings == {
        NamedSharding(mesh, P("data", None)),
        NamedSharding(mesh, P("data")),
    }
    label_payloads = [a for _, a in received if a.ndim == 1]
    union = np.sort(np.concatenate(label_payloads))
    np.testing.assert_array_equal(union, global_y)  # disjoint, complete


def test_assembly_seam_matches_device_put_single_process(mesh):
    """The real make_array_from_process_local_data (1 process: local = global)
    must agree with device_put — validating that the branch the stubs cover
    produces the same array contents where both paths are runnable."""
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    sharding = NamedSharding(mesh, P("data", None))
    a = jax.device_put(x, sharding)
    b = jax.make_array_from_process_local_data(sharding, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.sharding == b.sharding
