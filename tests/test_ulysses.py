"""Ulysses all-to-all sequence parallelism vs full attention and the ring path.

Both SP implementations compute *exact* full-sequence causal attention over a
sequence-sharded batch; they must agree with the dense reference and with
each other step-for-step — the communication pattern (all-to-all head
re-sharding vs ring K/V rotation) is the only difference.
"""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_ml_pytorch_tpu.models import TransformerLM
from distributed_ml_pytorch_tpu.ops import attention_reference
from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
    create_lm_train_state,
    make_sp_train_step,
    next_token_targets,
    shard_lm_batch,
)
from distributed_ml_pytorch_tpu.parallel.ulysses import (
    make_ulysses_eval_fn,
    make_ulysses_train_step,
    ulysses_attention,
)
from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh({"seq": 8})


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"data": 2, "seq": 4})


def test_ulysses_attention_matches_full(seq_mesh):
    rng = np.random.default_rng(0)
    q, k, v = (rng.normal(size=(2, 8, 128, 16)).astype(np.float32) for _ in range(3))
    spec = P(None, None, "seq", None)
    fn = jax.jit(
        jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis="seq", axis_size=8),
            mesh=seq_mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
    )
    got = fn(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_ulysses_train_matches_ring_step_for_step(sp_mesh):
    """From identical init, Ulysses and ring SP must produce the same losses
    and the same parameters — they are the same math, different collectives."""
    lm = TransformerLM(vocab_size=64, d_model=32, n_heads=8, n_layers=2,
                       d_ff=64, max_len=128)
    tx = optax.sgd(0.05)
    state_r = create_lm_train_state(lm, jax.random.key(0), tx)
    state_u = create_lm_train_state(lm, jax.random.key(0), tx)

    tokens = np.random.default_rng(1).integers(0, 64, size=(4, 64)).astype(np.int32)
    targets = next_token_targets(tokens)
    tok, tgt = shard_lm_batch(sp_mesh, tokens, targets)

    ring_step = make_sp_train_step(lm, tx, sp_mesh)
    uly_step = make_ulysses_train_step(lm, tx, sp_mesh)

    for _ in range(3):
        state_r, loss_r = ring_step(state_r, tok, tgt)
        state_u, loss_u = uly_step(state_u, tok, tgt)
        np.testing.assert_allclose(float(loss_r), float(loss_u), rtol=1e-5)

    for a, b in zip(jax.tree.leaves(state_r.params), jax.tree.leaves(state_u.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_ulysses_eval_matches_train_loss_definition(sp_mesh):
    lm = TransformerLM(vocab_size=64, d_model=32, n_heads=8, n_layers=2,
                       d_ff=64, max_len=128)
    tx = optax.sgd(0.0)  # lr 0: the train step's loss is the pre-update loss
    state = create_lm_train_state(lm, jax.random.key(2), tx)
    tokens = np.random.default_rng(2).integers(0, 64, size=(4, 64)).astype(np.int32)
    targets = next_token_targets(tokens)
    tok, tgt = shard_lm_batch(sp_mesh, tokens, targets)

    eval_loss = make_ulysses_eval_fn(lm, sp_mesh)(state.params, tok, tgt)
    _, train_loss = make_ulysses_train_step(lm, tx, sp_mesh)(state, tok, tgt)
    np.testing.assert_allclose(float(eval_loss), float(train_loss), rtol=1e-6)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    lm = TransformerLM(vocab_size=64, d_model=30, n_heads=6, n_layers=1,
                       d_ff=64, max_len=128)  # 6 heads, seq axis 4
    with pytest.raises(ValueError, match="divisible"):
        make_ulysses_train_step(lm, optax.sgd(0.1), sp_mesh)
