"""GPipe pipeline parallelism: the S-stage microbatch schedule must be
numerically identical to the single-stage (plain sequential) forward."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_ml_pytorch_tpu.parallel.pipeline import (
    PipelineLMConfig,
    create_pp_train_state,
    make_pp_train_step,
    microbatch,
)
from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets
from distributed_ml_pytorch_tpu import LEGACY_SHARD_MAP

#: ISSUE 3 satellite tracking note: on runtimes with the OLD
#: experimental shard_map (jax <= 0.4.x), the model-axis pipeline
#: composites trace only under the compat shim's check_rep=False fallback,
#: which skips transpose-time psum insertion INSIDE the tp block's
#: collective chain — the forward (loss) is exact (sharded_init made the
#: multi-axis-mesh inits value-identical, and __graft_entry__'s
#: dryrun_multichip asserts dp×pp×tp loss == pure-pp to 1e-4), but
#: param-level gradient parity deviates per layer. The dp-only composite
#: is FIXED by the explicit reductions in pipeline._wrap_pp_step; the
#: model-axis fix needs the graduated shard_map's vma transpose rules,
#: i.e. a jax upgrade. strict=True: this is a deterministic deviation —
#: if it starts passing, the runtime changed and the mark must go.
#: (ISSUE 10 status: these tp-block xfails are the ONLY legacy pipeline
#: xfails left — the pp-gradient ones were burned down by the MPMD
#: per-stage-compiled step, see the note below — and they stay because
#: the MPMD plane does not yet run a tensor-parallel stage forward.)
legacy_tp_grads_xfail = pytest.mark.xfail(
    LEGACY_SHARD_MAP, strict=True,
    reason="legacy shard_map check_rep=False fallback skips transpose-time "
           "psums inside the model-axis (Megatron) block — gradient parity "
           "needs the graduated shard_map (see comment above)")

#: ISSUE 10 burn-down note: the former ``legacy_pp_grads_xfail`` entries
#: (pipeline-vs-single-stage and 1f1b-vs-gpipe GRADIENT parity, both
#: pre-existing at the growth seed) are GONE: the MPMD pipeline plane
#: (``parallel/mpmd.py``) compiles every stage STANDALONE — plain jit +
#: per-stage vjp, no shard_map — so those capabilities now hold exactly on
#: every runtime and are asserted un-xfailed below via the MPMD step.
#: The SHARD_MAP versions of the same comparisons keep running where their
#: gradient semantics are defined (the graduated shard_map); on legacy
#: runtimes they are skipped with this tracking note — the deviation is
#: the old runtime's transpose machinery, not this repo's math, and the
#: exact path there is the MPMD plane. Only the tp-block xfail above
#: remains genuinely pre-existing.
legacy_shard_map_grads_skip = pytest.mark.skipif(
    LEGACY_SHARD_MAP,
    reason="legacy shard_map pipeline-gradient deviation vs the unsharded "
           "reference (pre-existing at the seed; loss parity holds) — the "
           "exact multi-stage path on this runtime is the MPMD plane, "
           "asserted by the un-skipped tests below and tests/test_mpmd.py")


def cfg4():
    return PipelineLMConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_len=128
    )


def stage_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("stage",))


def make_batch(batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, 64, size=(batch, seq)).astype(np.int32)
    return tokens, next_token_targets(tokens)


def run_steps(n_stages, n_micro, n_steps=2):
    cfg = cfg4()
    mesh = stage_mesh(n_stages)
    tx = optax.sgd(0.1)
    state = create_pp_train_state(cfg, jax.random.key(0), tx, mesh)
    step = make_pp_train_step(cfg, tx, mesh, n_microbatches=n_micro)
    tokens, targets = make_batch()
    tok_mb, tgt_mb = microbatch(tokens, targets, n_micro)
    losses = []
    for _ in range(n_steps):
        state, loss = step(state, tok_mb, tgt_mb)
        losses.append(float(loss))
    return losses, jax.device_get(state.params)


def test_pipeline_matches_single_stage():
    """The 4-stage pipeline equals the single-stage reference — loss AND
    updated params — via the MPMD per-stage-compiled step, which holds
    exactly on every runtime (ISSUE 10 burned down the legacy xfail; see
    the tracking note above)."""
    from distributed_ml_pytorch_tpu.parallel.mpmd import MpmdLocal

    ref_losses, ref_params = run_steps(n_stages=1, n_micro=1)
    tokens, targets = make_batch()
    pp = MpmdLocal(cfg4(), 4, 4, 0.1, jax.random.key(0))
    tok_mb, tgt_mb = tokens.reshape(4, 2, 16), targets.reshape(4, 2, 16)
    pp_losses = [pp.step(tok_mb, tgt_mb) for _ in range(2)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_params),
                    jax.tree.leaves(pp.full_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-5,
                                   atol=1e-6)


@legacy_shard_map_grads_skip
def test_shard_map_pipeline_matches_single_stage():
    """The shard_map schedule's version of the same parity, where its
    gradient semantics are defined (graduated shard_map runtimes)."""
    ref_losses, ref_params = run_steps(n_stages=1, n_micro=1)
    pp_losses, pp_params = run_steps(n_stages=4, n_micro=4)
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(pp_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-5, atol=1e-6)


def test_pipeline_microbatch_count_does_not_change_loss():
    l1, _ = run_steps(n_stages=2, n_micro=2, n_steps=1)
    l2, _ = run_steps(n_stages=2, n_micro=8, n_steps=1)
    np.testing.assert_allclose(l1, l2, rtol=2e-5)


def test_pp_state_blocks_sharded_over_stages():
    cfg = cfg4()
    mesh = stage_mesh(4)
    state = create_pp_train_state(cfg, jax.random.key(0), optax.sgd(0.1, momentum=0.9), mesh)
    leaf = jax.tree.leaves(state.params["blocks"])[0]
    assert leaf.sharding.spec[0] == "stage"
    mom = jax.tree.leaves(state.opt_state[0].trace["blocks"])[0]
    assert mom.sharding.spec[0] == "stage"
    # replicated pieces stay replicated
    assert state.params["head"]["kernel"].sharding.spec == P()


def test_pp_rejects_indivisible_layers():
    cfg = PipelineLMConfig(n_layers=3)
    with pytest.raises(ValueError, match="divide evenly"):
        create_pp_train_state(cfg, jax.random.key(0), optax.sgd(0.1), stage_mesh(2))


def test_microbatch_rejects_indivisible_batch():
    tokens, targets = make_batch(batch=6)
    with pytest.raises(ValueError, match="microbatches"):
        microbatch(tokens, targets, 4)


def test_interleaved_schedule_matches_gpipe_loss_and_grads():
    """The interleaved (virtual-stage) schedule computes the SAME function
    as GPipe — identical loss and identical parameter updates (modulo the
    documented layer-storage permutation) — only the execution order and
    bubble differ."""
    from distributed_ml_pytorch_tpu.parallel.pipeline import (
        interleave_layer_order,
    )

    cfg = PipelineLMConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=8, d_ff=64, max_len=128
    )
    S, v, M = 4, 2, 4
    mesh = stage_mesh(S)
    tx = optax.sgd(0.1)
    tokens, targets = make_batch(batch=M * 2, seq=16)
    tmb, gmb = microbatch(tokens, targets, M)

    state_g = create_pp_train_state(cfg, jax.random.key(0), tx, mesh)
    step_g = make_pp_train_step(cfg, tx, mesh, n_microbatches=M)
    _, loss_g = step_g(state_g, tmb, gmb)

    order = interleave_layer_order(cfg.n_layers, S, v)
    state_i = create_pp_train_state(cfg, jax.random.key(0), tx, mesh)
    state_i = state_i.replace(
        params={**state_i.params,
                "blocks": jax.tree.map(lambda x: x[order],
                                       state_i.params["blocks"])})
    step_i = make_pp_train_step(cfg, tx, mesh, n_microbatches=M,
                                schedule="interleaved", virtual_stages=v)
    new_i, loss_i = step_i(state_i, tmb, gmb)

    np.testing.assert_allclose(float(loss_i), float(loss_g), rtol=1e-5)

    # one more GPipe step to get its updated blocks; the interleaved update
    # must equal it under the same permutation
    new_g, _ = step_g(create_pp_train_state(cfg, jax.random.key(0), tx, mesh),
                      tmb, gmb)
    for a, b in zip(jax.tree.leaves(
            jax.tree.map(lambda x: x[order], new_g.params["blocks"])),
            jax.tree.leaves(new_i.params["blocks"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_interleaved_schedule_wrap_fifo_depths():
    """M > S exercises the wrap FIFO (D = M − S > 0); M == S the direct
    hand-off — both must agree with GPipe."""
    from distributed_ml_pytorch_tpu.parallel.pipeline import (
        interleave_layer_order,
    )

    cfg = PipelineLMConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_len=128
    )
    S, v = 2, 2
    mesh = stage_mesh(S)
    tx = optax.sgd(0.1)
    order = interleave_layer_order(cfg.n_layers, S, v)
    for M in (2, 6):  # D = 0 and D = 4
        tokens, targets = make_batch(batch=M * 2, seq=16, seed=M)
        tmb, gmb = microbatch(tokens, targets, M)
        state = create_pp_train_state(cfg, jax.random.key(1), tx, mesh)
        _, loss_g = make_pp_train_step(cfg, tx, mesh, n_microbatches=M)(
            state, tmb, gmb)
        state_i = create_pp_train_state(cfg, jax.random.key(1), tx, mesh)
        state_i = state_i.replace(
            params={**state_i.params,
                    "blocks": jax.tree.map(lambda x: x[order],
                                           state_i.params["blocks"])})
        _, loss_i = make_pp_train_step(
            cfg, tx, mesh, n_microbatches=M, schedule="interleaved",
            virtual_stages=v)(state_i, tmb, gmb)
        np.testing.assert_allclose(float(loss_i), float(loss_g), rtol=1e-5)


def test_interleaved_rejects_too_few_microbatches():
    cfg = PipelineLMConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=8, d_ff=64, max_len=128
    )
    mesh = stage_mesh(4)
    with pytest.raises(ValueError, match="n_microbatches >= n_stages"):
        make_pp_train_step(cfg, optax.sgd(0.1), mesh, n_microbatches=2,
                           schedule="interleaved", virtual_stages=2)


def test_1f1b_schedule_timetable_properties():
    """Structural proof of the 1F1B memory claim: simulate oneF1B_tick_roles
    over every (tick, stage) — the EXACT function the compiled step traces —
    and check (a) every microbatch runs F then B exactly once per stage,
    (b) a stage never does two units in one tick, (c) backward hand-offs
    arrive exactly one tick after their producer, and (d) the S-slot
    arrivals ring (the schedule's ONLY activation storage, vs GPipe's
    all-M-live profile) serves every forward and backward read correctly:
    a slot is written at arrival (F(s−1,m)+1), reread at F(s,m) and at
    B(s,m), and never overwritten while live."""
    from distributed_ml_pytorch_tpu.parallel.pipeline import oneF1B_tick_roles

    for S, M in [(2, 4), (4, 8), (4, 4), (3, 7), (4, 2), (1, 3)]:
        T = 2 * (M + S - 1)
        F = {}
        B = {}
        for s in range(S):
            ring = {}  # slot -> parked microbatch (live = not yet backward'd)
            peak = 0
            for t in range(T):
                m_f, m_b = oneF1B_tick_roles(t, s, S, M)
                assert not (m_f >= 0 and m_b >= 0), (S, M, s, t)
                if s > 0:
                    # the compiled step's arrival-detection call, verbatim
                    m_a, _ = oneF1B_tick_roles(t - 1, s - 1, S, M)
                    if m_a >= 0:
                        assert ring.get(m_a % S) is None, "overwrote live slot"
                        ring[m_a % S] = m_a
                        peak = max(peak, sum(v is not None for v in ring.values()))
                if m_f >= 0:
                    assert (s, m_f) not in F, "double forward"
                    F[(s, m_f)] = t
                    if s > 0:  # stage 0 recomputes its embedding input
                        assert ring.get(m_f % S) == m_f, "fwd read wrong slot"
                if m_b >= 0:
                    assert (s, m_b) not in B, "double backward"
                    B[(s, m_b)] = t
                    if s > 0:
                        assert ring.get(m_b % S) == m_b, "bwd read wrong slot"
                        ring[m_b % S] = None  # freed: backward consumed it
            if s > 0:
                # ≤ S parked activations ever (the ring IS the memory bound)
                assert peak <= min(S, M) and peak >= 1, (S, M, s, peak)
        for s in range(S):
            for m in range(M):
                assert (s, m) in F and (s, m) in B
                assert B[(s, m)] > F[(s, m)]
                if s > 0:
                    # fwd hand-off arrives one tick after the producer but
                    # may rest in the arrivals ring before consumption
                    # (warmup→steady boundary); never consumed before sent
                    assert F[(s, m)] >= F[(s - 1, m)] + 1
                if s < S - 1:
                    assert B[(s, m)] == B[(s + 1, m)] + 1  # bwd hand-off: exact
        assert max(B.values()) == T - 1  # schedule is tight


def test_1f1b_matches_gpipe_loss_and_grads():
    """The 1F1B and GPipe execution orders compute the same function:
    identical loss and identical parameter updates. Asserted via the MPMD
    per-stage-compiled step — exact on every runtime (ISSUE 10 burned
    down the legacy xfail; the shard_map comparison keeps its own test
    below) — with the per-microbatch work depth-first (bounded
    activations) vs all-forwards-then-backwards."""
    from distributed_ml_pytorch_tpu.parallel.mpmd import MpmdLocal

    tokens, targets = make_batch()
    tok_mb, tgt_mb = tokens.reshape(4, 2, 16), targets.reshape(4, 2, 16)
    g = MpmdLocal(cfg4(), 4, 4, 0.1, jax.random.key(0))
    f = MpmdLocal(cfg4(), 4, 4, 0.1, jax.random.key(0), schedule="1f1b")
    np.testing.assert_allclose(f.step(tok_mb, tgt_mb),
                               g.step(tok_mb, tgt_mb), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g.full_params()),
                    jax.tree.leaves(f.full_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-8)


@legacy_shard_map_grads_skip
def test_shard_map_1f1b_matches_gpipe_loss_and_grads():
    """schedule='1f1b' computes the same function as GPipe on the
    shard_map plane: identical loss and identical parameter updates (the
    hand-built backward against AD) — where the legacy transpose
    semantics don't interfere."""
    cfg = PipelineLMConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=8, d_ff=64, max_len=128
    )
    S, M = 4, 8
    mesh = stage_mesh(S)
    tx = optax.sgd(0.1)
    tokens, targets = make_batch(batch=M * 2, seq=16)
    tmb, gmb = microbatch(tokens, targets, M)

    step_g = make_pp_train_step(cfg, tx, mesh, n_microbatches=M)
    new_g, loss_g = step_g(create_pp_train_state(cfg, jax.random.key(0), tx, mesh),
                           tmb, gmb)
    step_f = make_pp_train_step(cfg, tx, mesh, n_microbatches=M, schedule="1f1b")
    new_f, loss_f = step_f(create_pp_train_state(cfg, jax.random.key(0), tx, mesh),
                           tmb, gmb)

    np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_g.params), jax.tree.leaves(new_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_1f1b_m_equals_s_and_m_less_than_s():
    """Edge cadences: M == S and M < S (all-warmup, no steady state) must
    still match GPipe."""
    cfg = PipelineLMConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=4, d_ff=32, max_len=64
    )
    S = 4
    mesh = stage_mesh(S)
    tx = optax.sgd(0.05)
    for M in (4, 2):
        tokens, targets = make_batch(batch=M * 2, seq=8)
        tmb, gmb = microbatch(tokens, targets, M)
        _, loss_g = make_pp_train_step(cfg, tx, mesh, n_microbatches=M)(
            create_pp_train_state(cfg, jax.random.key(1), tx, mesh), tmb, gmb)
        _, loss_f = make_pp_train_step(
            cfg, tx, mesh, n_microbatches=M, schedule="1f1b")(
            create_pp_train_state(cfg, jax.random.key(1), tx, mesh), tmb, gmb)
        np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-5)


@pytest.mark.slow  # ~20 s/case on this host (two compiled worlds per case)
@pytest.mark.parametrize("sched,kw", [
    ("gpipe", {}), ("interleaved", {"virtual_stages": 2}), ("1f1b", {}),
])
def test_dp_pp_composite_matches_pure_pp(sched, kw):
    """dp x pp on a (data=2, stage=2) mesh must produce the same loss and
    post-update params as pure pp on the identical global batch — for every
    schedule. Catches both the batch-sharding spec and the grad
    normalization (AD auto-psums param cotangents over the data axis; a
    naive pmean left grads exactly 2x at dp=2 during development)."""
    cfg = PipelineLMConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=4,
                           d_ff=64, max_len=64)
    tx = optax.sgd(0.1)
    M, mb, seq = 4, 8, 16
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(M, mb, seq)).astype(np.int32)
    targets = rng.integers(0, 64, size=(M, mb, seq)).astype(np.int32)

    mesh_pp = Mesh(np.array(jax.devices()[:2]), ("stage",))
    st = create_pp_train_state(cfg, jax.random.key(0), tx, mesh_pp)
    st1, loss_ref = make_pp_train_step(
        cfg, tx, mesh_pp, n_microbatches=M, schedule=sched, **kw
    )(st, tokens, targets)

    mesh_dp = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                   ("data", "stage"))
    st_dp = create_pp_train_state(cfg, jax.random.key(0), tx, mesh_dp)
    st2, loss_dp = make_pp_train_step(
        cfg, tx, mesh_dp, n_microbatches=M, schedule=sched,
        data_axis="data", **kw
    )(st_dp, tokens, targets)

    assert abs(float(loss_ref) - float(loss_dp)) < 1e-5
    for a, b in zip(jax.tree.leaves(st1.params), jax.tree.leaves(st2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # two compiled worlds per case
@legacy_tp_grads_xfail
@pytest.mark.parametrize("sched,kw", [
    ("gpipe", {}), ("interleaved", {"virtual_stages": 2}), ("1f1b", {}),
])
def test_pp_tp_composite_matches_pure_pp(sched, kw):
    """pp x tp on a (stage=2, model=2) mesh — Megatron sharding inside each
    stage — must produce the same loss and post-update params as pure pp
    running the SAME schedule on the identical batch. Float tolerance, not
    bitwise: the tp block's psums reassociate the o/down contraction."""
    cfg = PipelineLMConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=4,
                           d_ff=64, max_len=64)
    tx = optax.sgd(0.1)
    M, mb, seq = 4, 8, 16
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(M, mb, seq)).astype(np.int32)
    targets = rng.integers(0, 64, size=(M, mb, seq)).astype(np.int32)

    mesh_pp = Mesh(np.array(jax.devices()[:2]), ("stage",))
    st = create_pp_train_state(cfg, jax.random.key(0), tx, mesh_pp)
    st1, loss_ref = make_pp_train_step(
        cfg, tx, mesh_pp, n_microbatches=M, schedule=sched, **kw
    )(st, tokens, targets)

    mesh_tp = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                   ("stage", "model"))
    st_tp = create_pp_train_state(cfg, jax.random.key(0), tx, mesh_tp,
                                  model_axis="model")
    st2, loss_tp = make_pp_train_step(
        cfg, tx, mesh_tp, n_microbatches=M, schedule=sched,
        model_axis="model", **kw
    )(st_tp, tokens, targets)

    assert abs(float(loss_ref) - float(loss_tp)) < 1e-5
    for a, b in zip(jax.tree.leaves(st1.params), jax.tree.leaves(st2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-5, atol=1e-6)


@pytest.mark.slow
@legacy_tp_grads_xfail
def test_dp_pp_tp_2x2x2_matches_pure_pp():
    """The full composite: dp x pp x tp on a (data=2, stage=2, model=2)
    mesh — the canonical deep-LM 3-D layout — must match pure pp on the
    identical global batch (loss and updated params)."""
    cfg = PipelineLMConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=4,
                           d_ff=64, max_len=64)
    tx = optax.sgd(0.1)
    M, mb, seq = 4, 8, 16
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(M, mb, seq)).astype(np.int32)
    targets = rng.integers(0, 64, size=(M, mb, seq)).astype(np.int32)

    mesh_pp = Mesh(np.array(jax.devices()[:2]), ("stage",))
    st = create_pp_train_state(cfg, jax.random.key(0), tx, mesh_pp)
    st1, loss_ref = make_pp_train_step(cfg, tx, mesh_pp, n_microbatches=M)(
        st, tokens, targets)

    mesh3 = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                 ("data", "stage", "model"))
    st3 = create_pp_train_state(cfg, jax.random.key(0), tx, mesh3,
                                model_axis="model")
    st2, loss3 = make_pp_train_step(
        cfg, tx, mesh3, n_microbatches=M, data_axis="data",
        model_axis="model")(st3, tokens, targets)

    assert abs(float(loss_ref) - float(loss3)) < 1e-5
    for a, b in zip(jax.tree.leaves(st1.params), jax.tree.leaves(st2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-5, atol=1e-6)


def test_pp_tp_state_megatron_sharded():
    """pp x tp state: q/k/v column-, o row-, MLP up column-/down row-sharded
    over model WITHIN the stage shard; down bias and LNs model-replicated."""
    from distributed_ml_pytorch_tpu.parallel.pipeline import pp_param_specs

    cfg = PipelineLMConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=4,
                           d_ff=64, max_len=64)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("stage", "model"))
    state = create_pp_train_state(cfg, jax.random.key(0),
                                  optax.sgd(0.1, momentum=0.9), mesh,
                                  model_axis="model")
    blocks = state.params["blocks"]
    assert blocks["attn"]["q"]["kernel"].sharding.spec == P(
        "stage", None, "model")
    assert blocks["attn"]["o"]["kernel"].sharding.spec == P(
        "stage", "model", None)
    assert blocks["Dense_0"]["kernel"].sharding.spec == P(
        "stage", None, "model")
    assert blocks["Dense_0"]["bias"].sharding.spec == P("stage", "model")
    assert blocks["Dense_1"]["kernel"].sharding.spec == P(
        "stage", "model", None)
    assert blocks["Dense_1"]["bias"].sharding.spec == P("stage", None)
    assert blocks["LayerNorm_0"]["scale"].sharding.spec == P("stage", None)
    # optimizer momentum mirrors the params (path-based specs)
    mom = state.opt_state[0].trace["blocks"]["attn"]["q"]["kernel"]
    assert mom.sharding.spec == P("stage", None, "model")
    # replicated pieces stay replicated
    assert state.params["head"]["kernel"].sharding.spec == P()
    # and the spec function exposes the same rules standalone
    specs = pp_param_specs(state.params, "stage", "model")
    assert specs["blocks"]["attn"]["v"]["kernel"] == P("stage", None, "model")


def test_pp_tp_rejects_indivisible_dims():
    cfg = PipelineLMConfig(vocab_size=64, d_model=30, n_heads=3, n_layers=4,
                           d_ff=64, max_len=64)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("stage", "model"))
    with pytest.raises(ValueError, match="n_heads"):
        create_pp_train_state(cfg, jax.random.key(0), optax.sgd(0.1), mesh,
                              model_axis="model")
    with pytest.raises(ValueError, match="n_heads"):
        make_pp_train_step(cfg, optax.sgd(0.1), mesh, n_microbatches=2,
                           model_axis="model")
    with pytest.raises(ValueError, match="model_axis"):
        make_pp_train_step(
            PipelineLMConfig(n_layers=4), optax.sgd(0.1),
            Mesh(np.array(jax.devices()[:2]), ("stage",)),
            n_microbatches=2, model_axis="model")


def test_dp_pp_rejects_unknown_data_axis():
    cfg = PipelineLMConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                           d_ff=64, max_len=64)
    mesh = Mesh(np.array(jax.devices()[:2]), ("stage",))
    with pytest.raises(ValueError, match="data_axis"):
        make_pp_train_step(cfg, optax.sgd(0.1), mesh, n_microbatches=2,
                           data_axis="data")
