"""GPipe pipeline parallelism: the S-stage microbatch schedule must be
numerically identical to the single-stage (plain sequential) forward."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_ml_pytorch_tpu.parallel.pipeline import (
    PipelineLMConfig,
    create_pp_train_state,
    make_pp_train_step,
    microbatch,
)
from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets


def cfg4():
    return PipelineLMConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_len=128
    )


def stage_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("stage",))


def make_batch(batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, 64, size=(batch, seq)).astype(np.int32)
    return tokens, next_token_targets(tokens)


def run_steps(n_stages, n_micro, n_steps=2):
    cfg = cfg4()
    mesh = stage_mesh(n_stages)
    tx = optax.sgd(0.1)
    state = create_pp_train_state(cfg, jax.random.key(0), tx, mesh)
    step = make_pp_train_step(cfg, tx, mesh, n_microbatches=n_micro)
    tokens, targets = make_batch()
    tok_mb, tgt_mb = microbatch(tokens, targets, n_micro)
    losses = []
    for _ in range(n_steps):
        state, loss = step(state, tok_mb, tgt_mb)
        losses.append(float(loss))
    return losses, jax.device_get(state.params)


def test_pipeline_matches_single_stage():
    ref_losses, ref_params = run_steps(n_stages=1, n_micro=1)
    pp_losses, pp_params = run_steps(n_stages=4, n_micro=4)
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(pp_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-5, atol=1e-6)


def test_pipeline_microbatch_count_does_not_change_loss():
    l1, _ = run_steps(n_stages=2, n_micro=2, n_steps=1)
    l2, _ = run_steps(n_stages=2, n_micro=8, n_steps=1)
    np.testing.assert_allclose(l1, l2, rtol=2e-5)


def test_pp_state_blocks_sharded_over_stages():
    cfg = cfg4()
    mesh = stage_mesh(4)
    state = create_pp_train_state(cfg, jax.random.key(0), optax.sgd(0.1, momentum=0.9), mesh)
    leaf = jax.tree.leaves(state.params["blocks"])[0]
    assert leaf.sharding.spec[0] == "stage"
    mom = jax.tree.leaves(state.opt_state[0].trace["blocks"])[0]
    assert mom.sharding.spec[0] == "stage"
    # replicated pieces stay replicated
    assert state.params["head"]["kernel"].sharding.spec == P()


def test_pp_rejects_indivisible_layers():
    cfg = PipelineLMConfig(n_layers=3)
    with pytest.raises(ValueError, match="divide evenly"):
        create_pp_train_state(cfg, jax.random.key(0), optax.sgd(0.1), stage_mesh(2))


def test_microbatch_rejects_indivisible_batch():
    tokens, targets = make_batch(batch=6)
    with pytest.raises(ValueError, match="microbatches"):
        microbatch(tokens, targets, 4)
