"""C11/C12/M5 launcher-layer tests: graph plotting and cloud submission spec."""

import os

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.cloud import TPUJobSpec, submit
from distributed_ml_pytorch_tpu.graph import make_graphs
from distributed_ml_pytorch_tpu.utils.metrics import MetricsLogger


def test_make_graphs_from_csv(tmp_path):
    logger = MetricsLogger(str(tmp_path / "log"))
    for i in range(10):
        extra = {"test_loss": 2.0 - i * 0.1, "test_accuracy": 0.1 * i} if i % 4 == 0 else {}
        logger.log_step(i, 2.3 - 0.05 * i, **extra)
    logger.to_csv("node1.csv")
    written = make_graphs(str(tmp_path / "log"), str(tmp_path))
    assert sorted(os.path.basename(w) for w in written) == ["test_time.png", "train_time.png"]
    for w in written:
        assert os.path.getsize(w) > 1000


def test_make_graphs_skips_schemaless_csv(tmp_path):
    """A zero-epoch run writes a CSV with no schema columns — must be skipped,
    not crash the plotter."""
    log_dir = tmp_path / "log"
    logger = MetricsLogger(str(log_dir))
    logger.to_csv("empty.csv")  # no records → headerless frame
    logger2 = MetricsLogger(str(log_dir))
    logger2.log_step(0, 2.0)
    logger2.to_csv("real.csv")
    written = make_graphs(str(log_dir), str(tmp_path))
    assert len(written) == 2


def test_make_graphs_no_logs(tmp_path):
    with pytest.raises(FileNotFoundError):
        make_graphs(str(tmp_path), str(tmp_path))


def test_cloud_dry_run_prints_commands(capsys):
    spec = TPUJobSpec(script_args=["--no-distributed", "--epochs", "1"])
    url = submit(spec, dry_run=True)
    out = capsys.readouterr().out
    assert "gcloud compute tpus tpu-vm create distbelief-single" in out
    assert "--no-distributed --epochs 1" in out
    assert url.startswith("https://console.cloud.google.com/")
    assert url in out
