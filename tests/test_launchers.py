"""C11/C12/M5 launcher-layer tests: graph plotting and cloud submission spec."""

import os

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.cloud import TPUJobSpec, submit
from distributed_ml_pytorch_tpu.graph import make_graphs
from distributed_ml_pytorch_tpu.utils.metrics import MetricsLogger


def test_make_graphs_from_csv(tmp_path):
    logger = MetricsLogger(str(tmp_path / "log"))
    for i in range(10):
        extra = {"test_loss": 2.0 - i * 0.1, "test_accuracy": 0.1 * i} if i % 4 == 0 else {}
        logger.log_step(i, 2.3 - 0.05 * i, **extra)
    logger.to_csv("node1.csv")
    written = make_graphs(str(tmp_path / "log"), str(tmp_path))
    assert sorted(os.path.basename(w) for w in written) == ["test_time.png", "train_time.png"]
    for w in written:
        assert os.path.getsize(w) > 1000


def test_make_graphs_skips_schemaless_csv(tmp_path):
    """A zero-epoch run writes a CSV with no schema columns — must be skipped,
    not crash the plotter."""
    log_dir = tmp_path / "log"
    logger = MetricsLogger(str(log_dir))
    logger.to_csv("empty.csv")  # no records → headerless frame
    logger2 = MetricsLogger(str(log_dir))
    logger2.log_step(0, 2.0)
    logger2.to_csv("real.csv")
    written = make_graphs(str(log_dir), str(tmp_path))
    assert len(written) == 2


def test_make_graphs_no_logs(tmp_path):
    with pytest.raises(FileNotFoundError):
        make_graphs(str(tmp_path), str(tmp_path))


def test_cloud_dry_run_prints_commands(capsys):
    spec = TPUJobSpec(script_args=["--no-distributed", "--epochs", "1"])
    url = submit(spec, dry_run=True)
    out = capsys.readouterr().out
    assert "gcloud compute tpus tpu-vm create distbelief-single" in out
    assert "--no-distributed --epochs 1" in out
    assert url.startswith("https://console.cloud.google.com/")
    assert url in out


def test_rank_env_pins_exactly_one_rank_to_tpu():
    """VERDICT r1 #2: the PS topology can give one worker the real chip.
    rank_env must hand the pinned rank the default platform env and keep
    every other rank on the CPU platform."""
    from distributed_ml_pytorch_tpu.launch import cpu_platform_env, rank_env

    envs = {r: rank_env(r, tpu_worker_rank=1) for r in range(3)}
    # pinned rank: no CPU-platform override, TPU plugin not disabled
    assert envs[1].get("JAX_PLATFORMS") == os.environ.get("JAX_PLATFORMS")
    assert "--xla_force_host_platform_device_count" not in envs[1].get("XLA_FLAGS", "")
    # all other ranks: the standard CPU-platform sandbox
    for r in (0, 2):
        assert envs[r]["JAX_PLATFORMS"] == "cpu"
        assert "--xla_force_host_platform_device_count" in envs[r]["XLA_FLAGS"]
        assert envs[r]["PALLAS_AXON_POOL_IPS"] == ""
    # default behavior unchanged: nobody pinned
    assert rank_env(1)["JAX_PLATFORMS"] == "cpu"
    assert rank_env(1, cpu=False).get("JAX_PLATFORMS") == os.environ.get("JAX_PLATFORMS")
    del cpu_platform_env  # imported for documentation of the contract


def _stub_gcloud(tmp_path, monkeypatch, script: str):
    """Install a fake `gcloud` at the front of PATH; returns its call log."""
    log = tmp_path / "calls.log"
    exe = tmp_path / "bin" / "gcloud"
    exe.parent.mkdir()
    exe.write_text("#!/bin/sh\n" f'echo "$@" >> {log}\n' + script)
    exe.chmod(0o755)
    monkeypatch.setenv("PATH", f"{exe.parent}:{os.environ['PATH']}")
    return log


def test_cloud_submit_executes_against_stubbed_gcloud(tmp_path, monkeypatch, capsys):
    """VERDICT r1 missing #3: the real (non-dry-run) submission path must
    execute — create then run — when a gcloud binary exists."""
    log = _stub_gcloud(tmp_path, monkeypatch, "exit 0\n")
    spec = TPUJobSpec(script_args=["--epochs", "1"])
    url = submit(spec)
    calls = log.read_text().splitlines()
    assert len(calls) == 2
    assert calls[0].startswith("compute tpus tpu-vm create distbelief-single")
    assert calls[1].startswith("compute tpus tpu-vm ssh distbelief-single")
    assert "--epochs 1" in calls[1]
    assert url in capsys.readouterr().out


def test_cloud_submit_tolerates_existing_target(tmp_path, monkeypatch):
    """create failing with 'already exists' is resubmission, not an error."""
    log = _stub_gcloud(
        tmp_path, monkeypatch,
        'case "$@" in *create*) echo "ERROR: already exists" >&2; exit 1;;\n'
        "*) exit 0;; esac\n",
    )
    submit(TPUJobSpec())
    assert len(log.read_text().splitlines()) == 2  # ssh still ran


def test_cloud_submit_raises_on_fatal_create_error(tmp_path, monkeypatch):
    import subprocess

    _stub_gcloud(
        tmp_path, monkeypatch,
        'case "$@" in *create*) echo "ERROR: quota exceeded" >&2; exit 1;;\n'
        "*) exit 0;; esac\n",
    )
    with pytest.raises(subprocess.CalledProcessError):
        submit(TPUJobSpec())


def test_launch_world_rejects_non_worker_tpu_rank():
    from distributed_ml_pytorch_tpu.launch import launch_world

    for bad in (0, 3, -1):
        with pytest.raises(ValueError, match="worker rank"):
            launch_world(3, [], tpu_worker_rank=bad)
