"""ISSUE 20 — the gray-failure plane.

Four layers:

- **chaos**: ``GrayRule`` (partition / lossy / stall) is schedulable in
  ChaosPlan JSON, windowed on per-channel send indices, drawn from its
  own seeded stream — adding gray rules (or flipping the imperative
  ``partition``/``heal`` switch mid-plan) never perturbs an existing
  plan's fault/weather/SDC decisions;
- **wire**: the LeaseRenew gray tail is back-compatible in both
  directions — pre-ISSUE-20 frames decode with neutral gray defaults,
  the evolved frame is a pure extension of the pre-ISSUE-20 prefix, and
  malformed tails are length-gated away;
- **ladder**: ``GrayHealth`` confirms suspicion with hysteresis, degrades
  probation -> quarantine (checkpoint-park, lease exempt) -> eviction
  only for confirmed-gray, and a resumed member re-enters at PROBATION;
- **acceptance** (slow): the mid-training gray drill contains a windowed
  one-way partition without killing anyone, byte-identical chaos logs 3x.

``make gray`` selects exactly these (plus the gray distmodel replays in
tests/test_distmodel.py carrying their own markers).
"""

import json

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosLog,
    ChaosPlan,
    FaultRule,
    FaultyTransport,
    GrayRule,
    plan_from_json,
    plan_to_json,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)

pytestmark = pytest.mark.gray


def _pump(t, n=1000):
    out = []
    while True:
        m = t.recv(timeout=0.05)
        if m is None or len(out) >= n:
            return out
        out.append(m)


# ---------------------------------------------------------------------------
# chaos: GrayRule scheduling + determinism
# ---------------------------------------------------------------------------

def test_gray_rules_roundtrip_chaos_plan_json():
    """ISSUE 13 interchange: all three gray kinds survive the ChaosPlan
    JSON round trip exactly — counterexamples from the gray distmodel
    plane travel as runnable schedules, like every other rule family."""
    plan = ChaosPlan(
        seed=7,
        rules=[FaultRule(drop=0.1)],
        gray=(
            GrayRule(kind="partition", src=1, dst=2,
                     code=int(MessageCode.ParameterRequest),
                     after=3, until=9),
            GrayRule(kind="lossy", src=2, dst=0, p=0.4, after=1),
            GrayRule(kind="stall", src=1, site="fsync", p=1.0,
                     stall_ms=5.0, until=4),
        ))
    data = plan_to_json(plan)
    assert plan_from_json(json.loads(json.dumps(data))) == plan
    # defaults are omitted on the wire, and typo'd fields fail loudly
    assert "gray" not in plan_to_json(ChaosPlan(seed=7))
    with pytest.raises(ValueError, match="unknown GrayRule fields"):
        plan_from_json({"gray": [{"knid": "partition"}]})
    with pytest.raises(ValueError, match="unknown gray kind"):
        GrayRule(kind="flaky")


def test_gray_partition_rule_is_windowed_and_one_way():
    """A scheduled one-way partition: matching sends in the index window
    vanish (logged ``gray-partition``); other codes, other indices and
    the REVERSE direction are untouched."""
    plan = ChaosPlan(seed=0, gray=(
        GrayRule(kind="partition", src=1, dst=0,
                 code=int(MessageCode.GradientUpdate), after=1, until=3),))
    world = InProcessTransport.create_world(2)
    fw, log = FaultyTransport.wrap_world(world, plan)
    for i in range(5):
        fw[1].send(MessageCode.GradientUpdate, np.full(1, i, np.float32))
        fw[1].send(MessageCode.Heartbeat, np.full(1, i, np.float32))
        fw[0].send(MessageCode.GradientUpdate, np.full(1, i, np.float32),
                   dst=1)
    inbound = _pump(fw[0])
    grads = [int(m[2][0]) for m in inbound
             if m[1] == MessageCode.GradientUpdate]
    beats = [int(m[2][0]) for m in inbound
             if m[1] == MessageCode.Heartbeat]
    reverse = [int(m[2][0]) for m in _pump(fw[1])]
    assert grads == [0, 3, 4]          # sends #1, #2 vanished
    assert beats == list(range(5))     # other code untouched
    assert reverse == list(range(5))   # reverse direction untouched
    assert log.counts() == {"gray-partition": 2}


def test_gray_lossy_rule_is_seeded_and_deterministic():
    """kind="lossy" drops each matching frame with probability p on the
    gray stream — run-to-run byte-identical log AND deliveries."""
    plan = ChaosPlan(seed=5, gray=(
        GrayRule(kind="lossy", src=1, dst=0, p=0.5),))

    def run():
        world = InProcessTransport.create_world(2)
        fw, log = FaultyTransport.wrap_world(world, plan)
        for i in range(40):
            fw[1].send(MessageCode.GradientUpdate, np.full(1, i, np.float32))
        return [int(m[2][0]) for m in _pump(fw[0])], log.lines()

    got_a, log_a = run()
    got_b, log_b = run()
    assert got_a == got_b and log_a == log_b
    assert 0 < len(got_a) < 40          # flaky, not dead, not clean
    assert set(log_a.split()) >= {"gray-drop"} or "gray-drop" in log_a


def test_gray_rules_do_not_perturb_existing_streams():
    """The back-compat contract baked into ``_Channel``: gray draws ride
    their own namespaced stream, so ADDING gray rules to a plan leaves
    every fault/SDC decision of the original seed byte-identical."""
    base = ChaosPlan([FaultRule(drop=0.3, dup=0.2)], seed=11)
    grayed = ChaosPlan(
        [FaultRule(drop=0.3, dup=0.2)], seed=11,
        gray=(GrayRule(kind="lossy", src=1, dst=0, p=1.0,
                       after=10**6),))  # present but out of window

    def run(plan):
        world = InProcessTransport.create_world(2)
        fw, log = FaultyTransport.wrap_world(world, plan)
        for i in range(50):
            fw[1].send(MessageCode.GradientUpdate, np.full(1, i, np.float32))
        return [int(m[2][0]) for m in _pump(fw[0])], log.lines()

    got_base, log_base = run(base)
    got_gray, log_gray = run(grayed)
    assert got_base == got_gray
    assert log_base == log_gray and "drop" in log_base


def test_gray_stall_is_deterministic_and_windowed():
    """kind="stall" matches per-(rank, site) op counters via
    ``gray_stall``: inside the window each op sleeps the scripted
    quantum, other sites and out-of-window ops return 0.0, and the log
    records ``gray-stall-<site>`` — replayed exactly run-to-run."""
    plan = ChaosPlan(seed=3, gray=(
        GrayRule(kind="stall", src=0, site="fsync", p=1.0, stall_ms=2.0,
                 after=1, until=3),))

    def run():
        world = InProcessTransport.create_world(2)
        ft = FaultyTransport(world[0], plan, log=ChaosLog())
        sleeps = [ft.gray_stall("fsync") for _ in range(5)]
        other = [ft.gray_stall("serve") for _ in range(3)]
        for t in world.values():
            t.close()
        return sleeps, other, ft.log.lines()

    sleeps_a, other_a, log_a = run()
    sleeps_b, other_b, log_b = run()
    assert sleeps_a == sleeps_b == [0.0, 0.002, 0.002, 0.0, 0.0]
    assert other_a == other_b == [0.0, 0.0, 0.0]
    assert log_a == log_b
    assert log_a.count("gray-stall-fsync") == 2


def test_probabilistic_stall_draws_are_seeded_per_site():
    """p < 1 stalls draw from a per-(rank, site) seeded stream: the fire
    pattern is a pure function of the seed, and distinct sites get
    independent streams off the same seed."""
    plan = ChaosPlan(seed=9, gray=(
        GrayRule(kind="stall", site="fsync", p=0.5, stall_ms=1.0),
        GrayRule(kind="stall", site="serve", p=0.5, stall_ms=1.0),))

    def run(site):
        world = InProcessTransport.create_world(2)
        ft = FaultyTransport(world[0], plan, log=ChaosLog())
        fired = [ft.gray_stall(site) > 0 for _ in range(64)]
        for t in world.values():
            t.close()
        return fired

    fsync_a, fsync_b = run("fsync"), run("fsync")
    assert fsync_a == fsync_b
    assert 0 < sum(fsync_a) < 64        # probabilistic, not all-or-nothing
    assert run("serve") != fsync_a      # independent per-site streams


def test_imperative_partition_heal_mid_plan_preserves_rng_streams():
    """Flipping ``partition``/``heal`` mid-plan must not shift any seeded
    decision: draws are consumed BEFORE the partition check, so outside
    the partitioned window the fault log and deliveries are identical to
    the never-partitioned run, and inside it every send is logged
    ``partition-drop`` at its true channel index."""
    plan = ChaosPlan([FaultRule(drop=0.3, dup=0.2)], seed=11)

    def run(window=None):
        world = InProcessTransport.create_world(2)
        fw, log = FaultyTransport.wrap_world(world, plan)
        for i in range(40):
            if window and i == window[0]:
                fw[1].partition(0)
            if window and i == window[1]:
                fw[1].heal(0)
            fw[1].send(MessageCode.GradientUpdate, np.full(1, i, np.float32))
        return [int(m[2][0]) for m in _pump(fw[0])], log.events()

    got_base, ev_base = run()
    got_part, ev_part = run((10, 20))
    part_drops = sorted(e[3] for e in ev_part if e[4] == "partition-drop")
    assert part_drops == list(range(10, 20))
    # outside the window: identical decisions, identical deliveries
    assert [e for e in ev_part if e[4] != "partition-drop"] \
        == [e for e in ev_base if not 10 <= e[3] < 20]
    assert got_part == [v for v in got_base if not 10 <= v < 20]


# ---------------------------------------------------------------------------
# wire: the LeaseRenew gray tail is back-compatible both ways
# ---------------------------------------------------------------------------

def _coord_rig():
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        KIND_SHARD,
        Coordinator,
        encode_join,
    )
    from distributed_ml_pytorch_tpu.coord.grayhealth import GrayHealth

    world = InProcessTransport.create_world(3)
    fake_now = [100.0]
    coord = Coordinator(world[0], 8, lease=8.0, speculation=False,
                        clock=lambda: fake_now[0])
    gray = GrayHealth(coord, raise_threshold=2.5)
    for r in (1, 2):
        coord.handle(r, MessageCode.CoordJoin, encode_join(KIND_SHARD, 0))
    return world, coord, gray, fake_now


def _close(world):
    for t in world.values():
        t.close()


def test_renew_frame_is_a_pure_extension_of_the_old_layout():
    """Forward direction: a new sender's frame read by a pre-ISSUE-20
    receiver (which consumes only the first 10 floats) sees EXACTLY the
    frame the old encoder would have produced — the gray tail is
    appended, never interleaved."""
    from distributed_ml_pytorch_tpu.coord.coordinator import encode_renew

    old = encode_renew(3, push_count=2, step=7, ewma_ms=1.5, wire_open=1,
                       nacks=4, bad_loss=1, loss_ewma=0.9, gnorm_ewma=2.0)
    new = encode_renew(3, push_count=2, step=7, ewma_ms=1.5, wire_open=1,
                       nacks=4, bad_loss=1, loss_ewma=0.9, gnorm_ewma=2.0,
                       retrans_rate=0.25, blocked_s=0.5,
                       links=((2, 0.5, 0.1), (4, 0.0, 0.0)))
    assert np.array_equal(new[:10], old[:10])
    assert new.size == 15 + 3 * 2


def test_old_renew_frames_decode_with_neutral_gray_defaults():
    """Reverse direction: 5-field (pre-ISSUE-7) and 10-field
    (pre-ISSUE-20) renewals stay FULL renewals — accepted, liveness
    refreshed, gray evidence left neutral ("didn't say" is not
    "gray")."""
    from distributed_ml_pytorch_tpu.coord.coordinator import encode_renew

    world, coord, gray, fake_now = _coord_rig()
    try:
        full = encode_renew(0, push_count=6, step=9, ewma_ms=2.0,
                            retrans_rate=0.8, blocked_s=1.0)
        fake_now[0] += 1.0
        coord.handle(1, MessageCode.LeaseRenew, full[:5])
        m = coord.members[1]
        assert m.push_count == 6 and m.step == 9
        assert m.last_seen == fake_now[0]
        assert m.retrans_rate == 0.0 and m.blocked_s == 0.0
        fake_now[0] += 1.0
        coord.handle(1, MessageCode.LeaseRenew, full[:10])
        assert coord.members[1].retrans_rate == 0.0
        # the full frame finally lands the gray tail
        fake_now[0] += 1.0
        coord.handle(1, MessageCode.LeaseRenew, full)
        assert coord.members[1].retrans_rate == pytest.approx(0.8)
        # every form fed the suspicion plane's arrival history
        assert len(gray._tracks[1].gaps) == 2
    finally:
        _close(world)


def test_renew_link_triples_decode_and_malformed_tails_are_gated():
    """The per-directed-link evidence triples reach GrayHealth keyed
    (suspect, reporter); nonfinite renewals are dropped whole, and a
    truncated trailing triple is length-gated away instead of shifting
    the decode."""
    from distributed_ml_pytorch_tpu.coord.coordinator import encode_renew

    world, coord, gray, fake_now = _coord_rig()
    try:
        fake_now[0] += 1.0
        coord.handle(2, MessageCode.LeaseRenew,
                     encode_renew(0, links=((1, 0.5, 0.25),)))
        assert (1, 2) in gray._links
        assert gray._links[(1, 2)].latest > 0
        # nonfinite fixed fields: the whole renewal is dropped
        seen = coord.members[2].last_seen
        bad = np.full(15, np.nan, np.float32)
        fake_now[0] += 1.0
        coord.handle(2, MessageCode.LeaseRenew, bad)
        assert coord.members[2].last_seen == seen
        # a truncated trailing triple decodes as "no link evidence"
        frame = np.concatenate([encode_renew(0),
                                np.asarray([1.0, 0.5], np.float32)])
        before = len(gray._links)
        coord.handle(2, MessageCode.LeaseRenew, frame)
        assert len(gray._links) == before
        # a self-report (peer == sender) is ignored, not an indictment
        coord.handle(2, MessageCode.LeaseRenew,
                     encode_renew(0, links=((2, 9.0, 9.0),)))
        assert (2, 2) not in gray._links
    finally:
        _close(world)


# ---------------------------------------------------------------------------
# ladder: GrayHealth probation -> quarantine -> evict, and the way back
# ---------------------------------------------------------------------------

class _FakeMember:
    def __init__(self, rank):
        self.rank = rank
        self.kind = 99            # not KIND_WORKER: no speculation paths
        self.kind_name = "shard"
        self.incarnation = 0
        self.retrans_rate = 0.0
        self.nack_rate = 0.0
        self.blocked_s = 0.0
        self.fsync_p95_ms = 0.0
        self.busy_ratio = 0.0


class _FakeCoord:
    """The duck-typed coordinator surface GrayHealth actuates against —
    a ledger of what the plane DID (logs, frames, park tickets,
    revocations) without a serve thread in the way."""

    def __init__(self, ranks=(1,)):
        self.members = {r: _FakeMember(r) for r in ranks}
        self.speculation = False
        self.manifests_written = 0
        self.last_manifest = None
        self.logs = []
        self.sent = []
        self.parked = {}
        self.revoked = []
        self.gray = None

    def _log(self, msg):
        self.logs.append(msg)

    def _send(self, rank, code, payload):
        self.sent.append((rank, MessageCode(code), np.asarray(payload)))

    def trigger_snapshot(self):
        import types

        self.manifests_written += 1
        self.last_manifest = types.SimpleNamespace(snapshot_id=7)

    def note_parked(self, rank, ticket):
        self.parked[rank] = ticket

    def note_unparked(self, rank):
        self.parked.pop(rank, None)

    def revoke_member(self, rank, why, cooldown=0.0):
        self.revoked.append((rank, why))
        self.members.pop(rank, None)


def _ladder(**knobs):
    from distributed_ml_pytorch_tpu.coord import grayhealth

    coord = _FakeCoord()
    kw = dict(raise_threshold=2.5, confirm_ticks=2, clear_ticks=2,
              quarantine_after=3, quarantine_cooldown=0.5,
              actuator_rank=9)
    kw.update(knobs)
    gray = grayhealth.GrayHealth(coord, **kw)
    return coord, gray


def _round(coord, gray, now, rank=1, x=0.01):
    """One renew-then-tick round at a 0.25s cadence (the drills' lease/4).
    ``x`` is the member's own retransmit-rate evidence."""
    now[0] += 0.25
    coord.members[rank].retrans_rate = x
    gray.on_renew(coord.members[rank], now[0])
    gray.tick(now[0])


def test_ladder_probation_entry_and_hysteresis_clear():
    from distributed_ml_pytorch_tpu.coord import grayhealth

    coord, gray = _ladder()
    now = [100.0]
    for _ in range(10):
        _round(coord, gray, now)                   # warm the baseline
    assert gray.state_of(1) == grayhealth.OK
    _round(coord, gray, now, x=2.0)                # suspicious tick 1
    assert gray.state_of(1) == grayhealth.OK       # not confirmed yet
    _round(coord, gray, now, x=2.0)                # tick 2: confirmed
    assert gray.state_of(1) == grayhealth.PROBATION
    assert gray.probations == 1 and gray.flaps_of(1) == 1
    assert gray.detection_latencies and gray.detection_latencies[0] >= 0
    assert 1 in coord.members                      # nobody dies
    # hysteresis on the way down: one calm tick is not enough
    _round(coord, gray, now)
    assert gray.state_of(1) == grayhealth.PROBATION
    _round(coord, gray, now)
    assert gray.state_of(1) == grayhealth.OK       # clear_ticks=2 reached
    assert gray.suspect_count() == 0
    assert not coord.revoked and not coord.parked


def test_evict_on_first_suspicion_knob_kills_the_flap_victim():
    """The distmodel mutation's real-stack surface: with the ladder
    disabled, the first confirmed suspicion revokes a member a blip
    would have cleared."""
    from distributed_ml_pytorch_tpu.coord import grayhealth

    coord, gray = _ladder(evict_on_first_suspicion=True)
    now = [100.0]
    for _ in range(10):
        _round(coord, gray, now)
    _round(coord, gray, now, x=2.0)
    _round(coord, gray, now, x=2.0)
    assert gray.state_of(1) == grayhealth.EVICTED
    assert gray.evictions == 1
    assert coord.revoked and coord.revoked[0][0] == 1


def test_quarantine_parks_resumes_and_reenters_probation():
    """The full degrade-don't-kill arc: sustained suspicion drives a
    snapshot barrier then a gray-granted PreemptRequest; PreemptDone
    parks the member (ticket tagged gray); the cooldown sends a
    ResumeRequest to the node agent; the resumed life's first renewal
    unparks it INTO probation, and clean windows clear it to OK."""
    from distributed_ml_pytorch_tpu.coord import grayhealth

    coord, gray = _ladder()
    now = [100.0]
    for _ in range(10):
        _round(coord, gray, now)
    for _ in range(2):
        _round(coord, gray, now, x=2.0)            # -> PROBATION
    assert gray.state_of(1) == grayhealth.PROBATION
    # still suspect: probation_ticks accumulate to quarantine_after=3,
    # then one tick arms the barrier and the next sends the park
    for _ in range(6):
        _round(coord, gray, now, x=2.0)
    preempts = [s for s in coord.sent
                if s[1] == MessageCode.PreemptRequest]
    assert preempts and preempts[0][0] == 1
    assert coord.manifests_written == 1            # barrier came first
    gid = gray._tracks[1].grant_id
    assert gray.owns_grant(gid) and gid >= grayhealth.GRAY_GRANT_BASE
    gray.on_preempt_done(1, grant_id=gid, snap_id=7, lo=0, hi=8,
                         apply_seq=5, now=now[0])
    assert gray.state_of(1) == grayhealth.QUARANTINED
    assert gray.quarantines == 1
    assert coord.parked[1]["gray"] is True
    assert gray.containment_mttrs
    # cooldown expires -> resume goes to the actuator rank
    now[0] += 1.0
    gray.tick(now[0])
    resumes = [s for s in coord.sent if s[1] == MessageCode.ResumeRequest]
    assert resumes and resumes[0][0] == 9
    # the resumed life renews: unparked, back on the ladder at PROBATION
    _round(coord, gray, now)
    assert gray.state_of(1) == grayhealth.PROBATION
    assert gray.recoveries == 1 and 1 not in coord.parked
    for _ in range(2):
        _round(coord, gray, now)
    assert gray.state_of(1) == grayhealth.OK
    assert not coord.revoked                       # contained, never killed
    s = gray.stats()
    assert s["probations"] >= 1 and s["quarantines"] == 1 \
        and s["evictions"] == 0 and s["recoveries"] == 1


def test_asymmetric_link_evidence_convicts_a_clean_tailed_suspect():
    """The one-way-partition witness: the suspect's own tail stays calm,
    but distinct reporters' link triples (suspect -> reporter) spike —
    with ``asymmetric=True`` that alone confirms suspicion; with the
    mutation knob off the plane is blind to it."""
    from distributed_ml_pytorch_tpu.coord import grayhealth

    def play(asymmetric):
        coord = _FakeCoord(ranks=(1, 2, 3))
        gray = grayhealth.GrayHealth(
            coord, raise_threshold=2.5, confirm_ticks=2,
            asymmetric=asymmetric)
        now = [100.0]

        def round_(link_rate):
            now[0] += 0.25
            gray.on_renew(coord.members[1], now[0])       # suspect: calm
            for rep in (2, 3):
                gray.on_renew(coord.members[rep], now[0],
                              links=((1, link_rate, 0.0),))
            gray.tick(now[0])

        for _ in range(10):
            round_(0.01)
        for _ in range(4):
            round_(1.0)
        return gray.state_of(1)

    assert play(True) == grayhealth.PROBATION
    assert play(False) == grayhealth.OK


# ---------------------------------------------------------------------------
# fleet: probation bends routing without marking the engine down
# ---------------------------------------------------------------------------

def test_fleet_gray_penalty_routes_around_without_removal():
    from distributed_ml_pytorch_tpu.serving.fleet import FleetRouter
    from distributed_ml_pytorch_tpu.serving.frontend import _Route

    class _M:
        def __init__(self, eid, slots):
            self.engine_id = eid
            self._slots = slots

        def pressure(self):
            return (0, self._slots, 0)

    a, b = _M(0, 4), _M(1, 4)
    router = FleetRouter.__new__(FleetRouter)
    router.members = {0: a, 1: b}
    router._member_up = {0: True, 1: True}
    router._gray_penalized = set()
    router.session_affinity = False
    route = _Route(rank=1, rid=1)
    assert router._pick_engine(route) is a     # tie -> lowest engine id
    router.note_gray(0)
    assert router._pick_engine(route) is b     # penalty bends the tie
    router._member_up[1] = False
    assert router._pick_engine(route) is a     # degraded, NOT removed
    router._member_up[1] = True
    router.clear_gray(0)
    assert router._pick_engine(route) is a     # penalty is reversible


# ---------------------------------------------------------------------------
# acceptance (slow): the mid-training drill, byte-identical 3x
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.drill
def test_gray_drill_contains_without_killing_three_runs_byte_identical(
        tmp_path):
    """ISSUE 20 tentpole acceptance: a windowed one-way partition grays
    shard server 0 mid-training; the plane detects on renew-tail link
    evidence, checkpoint-parks, resumes bit-identically, and the ladder
    clears — zero evictions, zero lease expiries, and the chaos log is
    byte-identical across three runs."""
    from distributed_ml_pytorch_tpu.coord.drill import gray_drill

    outs = []
    for k in range(3):
        d = tmp_path / f"run{k}"
        d.mkdir()
        out = gray_drill(base_dir=str(d), seed=0)
        assert out["ok"], (out["violations"], out["errors"],
                           out["events"][-8:])
        outs.append(out)
    first = outs[0]
    assert first["detect_latency_s"] is not None
    assert first["containment_mttr_s"] is not None
    assert first["bit_identical"] is True
    assert first["gray"]["evictions"] == 0
    assert first["gray"]["quarantines"] >= 1
    assert first["gray"]["recoveries"] >= 1
    assert first["chaos_counts"].get("gray-partition", 0) > 0
    assert len({o["chaos_lines"] for o in outs}) == 1
