"""Regenerate tests/slow_tests.txt — the measured >=4s cut for `make test`.

Runs the FULL suite (no -m filter, so already-listed tests are re-timed
rather than deselected), parses pytest's --durations output, drops tests
from the subprocess-world modules (those are marked wholesale via
SLOW_MODULES in conftest.py), and rewrites slow_tests.txt with its header.

    python tests/regen_slow_tests.py          # ~45 min on this 1-core host

Incremental mode re-measures ONLY the given test files and merges their
>=4s node IDs into the existing list (entries for other files are kept
verbatim) — the cheap path when a PR adds new test modules:

    python tests/regen_slow_tests.py --paths tests/test_serving.py ...

The conftest marks listed node IDs slow; while this sweep runs they are
still executed (nothing passes -m "not slow" here), so the regenerated
list is a complete re-measurement, not an increment.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

THRESHOLD_S = 4.0
HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "slow_tests.txt")

HEADER = """# Tests deselected from `make test` (the fast core signal) because one run
# costs >= 4 s on this 1-core host — almost all of it XLA compile time of
# heavyweight equality programs. They all still run in `make test-all`.
#
# GENERATED — do not hand-edit. Regenerate (full re-measurement) with:
#   python tests/regen_slow_tests.py
# (whole modules that spawn real processes are marked via SLOW_MODULES in
#  conftest.py instead and are not listed here)
"""


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "--paths", nargs="+", default=None, metavar="TEST_FILE",
        help="re-measure only these test files and merge their >=4s node "
             "IDs into the existing list (default: full re-measurement)")
    args = parser.parse_args(argv)
    sys.path.insert(0, HERE)
    from conftest import SLOW_MODULES

    targets = args.paths or ["tests/"]
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *targets, "-q", "--durations=0"],
        cwd=os.path.dirname(HERE), capture_output=True, text=True,
    )
    sys.stdout.write(proc.stdout[-2000:])
    rows = []
    for line in proc.stdout.splitlines():
        m = re.match(r"([\d.]+)s call\s+(\S+)", line.strip())
        if m:
            rows.append((float(m.group(1)), m.group(2)))
    if not rows:
        print("no duration lines parsed — did the suite run?", file=sys.stderr)
        return 1
    slow = sorted(
        name for t, name in rows
        if t >= THRESHOLD_S
        and name.split("::")[0].rsplit("/", 1)[-1][:-3] not in SLOW_MODULES
    )
    if args.paths:
        # merge: keep every existing entry that is NOT under a re-measured
        # file, then add the fresh measurements. Normalize each given path
        # to the repo-root-relative spelling pytest uses in node IDs, so
        # absolute and ../-style spellings prune correctly too.
        root = os.path.dirname(HERE)
        measured = {
            os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
            for p in args.paths
        }
        kept = []
        if os.path.exists(OUT):
            with open(OUT, encoding="utf-8") as fh:
                kept = [
                    line.strip() for line in fh
                    if line.strip() and not line.startswith("#")
                    and line.split("::")[0] not in measured
                ]
        slow = sorted(set(kept) | set(slow))
    with open(OUT, "w", encoding="utf-8") as fh:
        fh.write(HEADER)
        for name in slow:
            fh.write(name + "\n")
    print(f"wrote {len(slow)} node IDs to {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
