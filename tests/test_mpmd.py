"""MPMD pipeline plane (ISSUE 10): stages as independently compiled fleet
members that die, restart from per-stage checkpoints, and catch up by
watermark-bounded microbatch replay.

THE acceptance scenario: a 4-stage MPMD pipeline under seeded drop/dup +
network weather, the middle stage killed mid-schedule -> lease-expiry
detection, restart from its stage checkpoint, replay of only the in-flight
microbatches past the watermark (no microbatch applied twice), a loss
trajectory EQUAL to the fault-free corridor, byte-identical chaos logs
across 3 runs, and a measured stage-restart MTTR.
"""

import os
import tempfile

import jax
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.coord.stages import (
    StageEntry,
    StagePlacement,
    default_mpmd_plan,
    mpmd_scenario,
)
from distributed_ml_pytorch_tpu.parallel.mpmd import (
    MpmdLocal,
    MpmdStage,
    load_stage_checkpoint,
    save_stage_checkpoint,
    stage_param_ranges,
)
from distributed_ml_pytorch_tpu.parallel.pipeline import PipelineLMConfig
from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    _split16,
)

pytestmark = pytest.mark.mpmd


def cfg4():
    return PipelineLMConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_len=128)


def small_cfg(n_stages=4, seq=8):
    """The scenario's config (matches mpmd_scenario's default so the
    process-wide program cache is shared across the fleet tests)."""
    return PipelineLMConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=n_stages, d_ff=32,
        max_len=max(64, seq))


def make_batch(batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, 64, size=(batch, seq)).astype(np.int32)
    return tokens, np.asarray(next_token_targets(tokens))


# --------------------------------------------------------------- exactness

def test_mpmd_local_matches_single_stage_reference():
    """The burn-down proof behind tests/test_pipeline.py: each stage
    compiled STANDALONE (plain jit + vjp, no shard_map) makes the 4-stage
    pipeline's loss and updated params equal the single-stage reference on
    every runtime — the legacy shard_map transpose semantics never enter
    the program."""
    cfg = cfg4()
    tokens, targets = make_batch()

    ref = MpmdLocal(cfg, 1, 1, 0.1, jax.random.key(0))
    ref_losses = [ref.step(tokens[None], targets[None]) for _ in range(2)]

    pp = MpmdLocal(cfg, 4, 4, 0.1, jax.random.key(0))
    tok_mb, tgt_mb = tokens.reshape(4, 2, 16), targets.reshape(4, 2, 16)
    pp_losses = [pp.step(tok_mb, tgt_mb) for _ in range(2)]

    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref.full_params()),
                    jax.tree.leaves(pp.full_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=1e-6)


def test_mpmd_schedules_identical():
    """gpipe vs 1f1b execution order: same per-microbatch values, same
    mb-ordered accumulation — value-identical updates by construction."""
    cfg = cfg4()
    tokens, targets = make_batch()
    tok_mb, tgt_mb = tokens.reshape(4, 2, 16), targets.reshape(4, 2, 16)
    g = MpmdLocal(cfg, 4, 4, 0.1, jax.random.key(0))
    f = MpmdLocal(cfg, 4, 4, 0.1, jax.random.key(0), schedule="1f1b")
    lg = [g.step(tok_mb, tgt_mb) for _ in range(2)]
    lf = [f.step(tok_mb, tgt_mb) for _ in range(2)]
    np.testing.assert_allclose(lf, lg, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g.full_params()),
                    jax.tree.leaves(f.full_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-8)


def test_mpmd_local_loss_matches_shard_map_step():
    """Cross-validation against the in-process shard_map schedule: losses
    are exact on every runtime (the dryrun asserts the same), so the two
    pipeline planes must agree on the forward."""
    import optax
    from jax.sharding import Mesh

    from distributed_ml_pytorch_tpu.parallel.pipeline import (
        create_pp_train_state,
        make_pp_train_step,
    )

    cfg = cfg4()
    tokens, targets = make_batch()
    tok_mb, tgt_mb = tokens.reshape(4, 2, 16), targets.reshape(4, 2, 16)
    mesh = Mesh(np.array(jax.devices()[:4]), ("stage",))
    tx = optax.sgd(0.1)
    st = create_pp_train_state(cfg, jax.random.key(0), tx, mesh)
    _, loss_sm = make_pp_train_step(cfg, tx, mesh, n_microbatches=4)(
        st, tok_mb, tgt_mb)
    local = MpmdLocal(cfg, 4, 4, 0.1, jax.random.key(0))
    np.testing.assert_allclose(local.step(tok_mb, tgt_mb), float(loss_sm),
                               rtol=1e-5)


def test_stage_param_ranges_tile():
    from jax.flatten_util import ravel_pytree

    from distributed_ml_pytorch_tpu.parallel.mpmd import (
        init_stage_params,
    )

    cfg = small_cfg()
    ranges = stage_param_ranges(cfg, 4)
    assert ranges[0][0] == 0
    for (lo, hi), (lo2, _hi2) in zip(ranges, ranges[1:]):
        assert hi == lo2 and hi > lo
    for s, (lo, hi) in enumerate(ranges):
        flat, _ = ravel_pytree(
            init_stage_params(cfg, jax.random.key(0), s, 4))
        assert flat.size == hi - lo


# ------------------------------------------------------------- durability

def test_stage_checkpoint_roundtrip_and_refusals(tmp_path):
    d = str(tmp_path / "ck")
    p = np.arange(10, dtype=np.float32)
    o = np.arange(4, dtype=np.float32)
    save_stage_checkpoint(d, stage=2, step=7, watermark=28, lo=5, hi=15,
                          params_flat=p, opt_flat=o)
    meta, p2, o2 = load_stage_checkpoint(d)
    assert (meta["stage"], meta["step"], meta["watermark"]) == (2, 7, 28)
    np.testing.assert_array_equal(p, p2)
    np.testing.assert_array_equal(o, o2)
    # CRC damage (a flipped byte in the state blob) is refused loudly
    path = os.path.join(d, "stage.ckpt")
    raw = bytearray(open(path, "rb").read())
    raw[-3] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(ValueError, match="CRC"):
        load_stage_checkpoint(d)
    # missing checkpoint is refused loudly
    with pytest.raises(ValueError, match="unreadable"):
        load_stage_checkpoint(str(tmp_path / "nope"))


class _StubCoord:
    """Just enough CoordClient surface for a transport-level MpmdStage
    unit (no coordinator, no threads)."""

    def __init__(self):
        self.on_stage_assign = None
        self.on_snapshot = None
        self._on_speculate = None
        self.incarnation = 1

    def report(self, *a, **k):
        pass

    def stage_ready(self, *a, **k):
        pass

    def snapshot_done(self, *a, **k):
        pass

    def stop(self):
        pass

    def close(self):
        pass


def _unit_stage(tmp_path, stage=1, n_stages=4, M=2, mb=2, seq=8):
    cfg = small_cfg(n_stages, seq)
    world = InProcessTransport.create_world(1 + n_stages)
    srv = MpmdStage(stage, cfg, n_stages, M, world[1 + stage], _StubCoord(),
                    mb_size=mb, seq_len=seq, lr=0.1,
                    ckpt_dir=str(tmp_path / f"stage{stage}"))
    placement = StagePlacement(1, stage_param_ranges(cfg, n_stages)[-1][1], [
        StageEntry(stage=s, rank=1 + s, inc=100 + s, lo=lo, hi=hi)
        for s, (lo, hi) in enumerate(stage_param_ranges(cfg, n_stages))])
    srv._note_placement(placement)
    srv._drain_mailboxes()
    return cfg, world, srv


def _ship_frame(step, mbi, kind, body):
    # head: step(2) mb kind ver(2) codec — codec 0 = dense (ISSUE 18)
    return np.concatenate([
        np.asarray([*_split16(step), float(mbi), float(kind), 0.0, 0.0,
                    0.0],
                   np.float32),
        np.asarray(body, np.float32).ravel()])


def _grad_frame(step, mbi, body):
    return np.concatenate([
        np.asarray([*_split16(step), float(mbi), 0.0, 0.0, 0.0], np.float32),
        np.asarray(body, np.float32).ravel()])


def test_duplicate_grad_applied_once(tmp_path):
    """The no-microbatch-applied-twice core: a duplicated ActivationGrad
    (chaos dup, reliability redelivery, or replay re-ship) accumulates
    into the stage's update exactly once."""
    cfg, world, srv = _unit_stage(tmp_path)
    act = np.zeros((2, 8, cfg.d_model), np.float32)
    srv.handle(1, MessageCode.ActivationShip, _ship_frame(0, 0, 0, act))
    srv._pump()
    assert srv.stats["fwd"] == 1
    g = np.ones((2, 8, cfg.d_model), np.float32)
    srv.handle(3, MessageCode.ActivationGrad, _grad_frame(0, 0, g))
    srv._pump()
    assert srv.stats["bwd"] == 1
    # the dup arrives after the backward was applied: dropped, not redone
    srv.handle(3, MessageCode.ActivationGrad, _grad_frame(0, 0, g))
    srv._pump()
    assert srv.stats["bwd"] == 1
    assert srv.stats["dup_grads_dropped"] == 1
    # a duplicated INPUT is dropped the same way
    srv.handle(1, MessageCode.ActivationShip, _ship_frame(0, 0, 0, act))
    assert srv.stats["dup_inputs_dropped"] == 1
    # traffic for an already-applied step is stale
    srv.handle(1, MessageCode.ActivationShip, _ship_frame(0, 1, 0, act))
    srv.handle(3, MessageCode.ActivationGrad, _grad_frame(0, 1, g))
    srv._pump()
    assert srv.step == 1 and srv.stats["updates"] == 1
    assert sorted(srv.applied_log) == [(0, 0), (0, 1)]
    srv.handle(1, MessageCode.ActivationShip, _ship_frame(0, 0, 0, act))
    assert srv.stats["stale_dropped"] == 1


def test_stage_restore_refuses_bad_state(tmp_path):
    """The manifest restore contract for stages: range mismatch and a
    checkpoint BEHIND the manifest's promised apply seq are refused."""
    from distributed_ml_pytorch_tpu.coord.manifest import (
        FleetManifest,
        ManifestError,
        ShardRecord,
    )

    cfg, world, srv = _unit_stage(tmp_path)
    act = np.zeros((2, 8, cfg.d_model), np.float32)
    g = np.ones((2, 8, cfg.d_model), np.float32)
    for mbi in range(2):
        srv.handle(1, MessageCode.ActivationShip, _ship_frame(0, mbi, 0, act))
        srv.handle(3, MessageCode.ActivationGrad, _grad_frame(0, mbi, g))
    srv._pump()
    assert srv.step == 1 and srv.watermark == 2  # checkpoint written

    ranges = stage_param_ranges(cfg, 4)

    def manifest(apply_seq, lo, hi):
        shards = []
        for s, (slo, shi) in enumerate(ranges):
            rec_lo, rec_hi = (lo, hi) if s == 1 else (slo, shi)
            shards.append(ShardRecord(
                server_id=1 + s, lo=rec_lo, hi=rec_hi, map_version=4,
                apply_seq=apply_seq if s == 1 else 0, push_count=1))
        return FleetManifest(snapshot_id=1, map_version=4,
                             n_params=ranges[-1][1], shards=tuple(shards))

    fresh = MpmdStage(1, cfg, 4, 2, world[2], _StubCoord(),
                      mb_size=2, seq_len=8, lr=0.1,
                      ckpt_dir=str(tmp_path / "stage1"))
    # a checkpoint BEHIND the promised apply seq is refused
    with pytest.raises(ValueError, match="BEHIND"):
        fresh.restore(manifest(apply_seq=99, lo=ranges[1][0],
                               hi=ranges[1][1]))
    # a range mismatch is refused
    with pytest.raises(ManifestError, match="range"):
        fresh.restore(manifest(apply_seq=0, lo=0, hi=1))
    # the good path restores the promised watermark
    fresh.restore(manifest(apply_seq=2, lo=ranges[1][0], hi=ranges[1][1]))
    assert fresh.step == 1 and fresh.watermark == 2


def test_stage_placement_codec_roundtrip():
    p = StagePlacement(7, 999, [
        StageEntry(stage=0, rank=1, inc=0x12345, lo=0, hi=400, watermark=8),
        StageEntry(stage=1, rank=-1, inc=0, lo=400, hi=999, watermark=12),
    ])
    q = StagePlacement.decode(p.encode())
    assert q.version == 7 and q.n_params == 999
    assert q.entries == p.entries
    assert q.entries[1].vacant and not q.assigned
    with pytest.raises(ValueError, match="malformed"):
        StagePlacement.decode(np.zeros(3, np.float32))
    with pytest.raises(ValueError, match="entries"):
        StagePlacement.decode(
            np.asarray([*_split16(1), 5.0, *_split16(10)], np.float32))


# ------------------------------------------------------------ fleet (slow)

def _scenario_data(seed, steps, M, mb, seq, vocab):
    rng = np.random.default_rng(seed)
    toks, tgts = [], []
    for _t in range(steps):
        t = rng.integers(0, vocab, size=(M * mb, seq)).astype(np.int32)
        toks.append(t.reshape(M, mb, seq))
        tgts.append(np.asarray(next_token_targets(t)).reshape(M, mb, seq))
    return toks, tgts


def test_mpmd_fleet_matches_local_runner():
    """The distributed fleet computes exactly what the loopback runner
    computes: same per-step losses on identical data."""
    steps = 4
    out = mpmd_scenario(base_dir=tempfile.mkdtemp(prefix="mpmd_t_"),
                        seed=3, steps=steps)
    assert out["ok"], (out["errors"], out["events"])
    cfg = small_cfg()
    local = MpmdLocal(cfg, 4, 4, 0.1, jax.random.key(3))
    toks, tgts = _scenario_data(3, steps, 4, 4, 8, cfg.vocab_size)
    local_losses = [local.step(t, g) for t, g in zip(toks, tgts)]
    np.testing.assert_allclose(out["losses"], local_losses, rtol=1e-5)


def test_mpmd_acceptance_stage_death_under_chaos(lock_witness):
    """THE ISSUE 10 acceptance: 4-stage MPMD pipeline under seeded
    drop/dup + weather, middle stage killed mid-schedule -> lease-expiry
    detection, restart from its stage checkpoint, watermark-bounded
    replay with no microbatch applied twice, a loss trajectory EQUAL to
    the fault-free corridor, 3x byte-identical chaos logs, and a
    measured stage-restart MTTR."""
    steps = 8
    # corridor first: the fault-free trajectory AND the program-cache
    # warmup (a cold jit compile stalls serve loops long enough to fire
    # timing-driven retransmits, which would perturb the chaos log)
    corridor = mpmd_scenario(
        base_dir=tempfile.mkdtemp(prefix="mpmd_c_"), seed=0, steps=steps)
    assert corridor["ok"], (corridor["errors"], corridor["events"])

    logs = []
    for _rep in range(3):
        out = mpmd_scenario(
            base_dir=tempfile.mkdtemp(prefix="mpmd_a_"), seed=0,
            steps=steps, kill_stage=1, kill_at_step=3, snapshot_at_step=1,
            plan=default_mpmd_plan(0))
        assert out["ok"], (out["errors"], out["events"])
        # the kill really happened and really was detected + restored
        assert out["stage_restarts"] == 1
        assert out["stage_mttr_s"] is not None and out["stage_mttr_s"] > 0
        assert any("lease expired" in e for e in out["events"])
        assert any("restored by rank" in e for e in out["events"])
        # accounting: every (step, mb) applied exactly once per stage
        assert out["applied_ok"]
        assert out["discarded_applies"] == 0
        # the faults genuinely fired
        assert out["chaos_counts"].get("drop", 0) > 0
        assert out["chaos_counts"].get("dup", 0) > 0
        assert any(k.startswith("weather") for k in out["chaos_counts"])
        # loss-trajectory equivalence to the fault-free corridor: replay
        # reconstructs the SAME updates, so the trajectory is numerically
        # the corridor trajectory, not merely near it
        np.testing.assert_allclose(out["losses"], corridor["losses"],
                                   rtol=1e-5, atol=1e-6)
        logs.append(out["chaos_lines"])
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "mpmd chaos log not byte-identical across runs")


@pytest.mark.drill
def test_mpmd_stage_drill_manifest_restore():
    """The drill satellite: the snapshot barrier covers STAGE checkpoints
    (a FleetManifest whose shard records are the stage ranges), a killed
    stage restores THROUGH the manifest (range + apply-seq validated),
    and drill accounting proves no microbatch applied twice."""
    from distributed_ml_pytorch_tpu.coord.manifest import FleetManifest

    base = tempfile.mkdtemp(prefix="mpmd_d_")
    out = mpmd_scenario(
        base_dir=base, seed=0, steps=8, kill_stage=2, kill_at_step=4,
        snapshot_at_step=1, restore_via_manifest=True)
    assert out["ok"], (out["errors"], out["events"])
    manifest = FleetManifest.load(os.path.join(base, "fleet_manifest.json"))
    ranges = stage_param_ranges(small_cfg(), 4)
    assert [(r.lo, r.hi) for r in manifest.shards] == ranges
    assert out["stage_restarts"] == 1 and out["applied_ok"]
    assert any("snapshot 1 complete" in e for e in out["events"])
    # the restored member's checkpoint covered the manifest's promise
    # (restore() would have refused otherwise) and replay filled the gap
    victim_stats = out["stats"]["stage2"]
    assert victim_stats["updates"] >= 4  # steps 4..7 rebuilt after restore


def test_mpmd_speculation_standby_takeover():
    """Sandblaster speculation applied to stages: a throttled (straggler)
    stage member is raced by a standby that loads its checkpoint; the
    placement flips to the winner, the victim goes passive, and the
    loser's racing applications are DISCARDED work, never double-applied."""
    # warm the program cache first: cold jit compiles stall every stage
    # for seconds, which drowns the busy-ms contrast the straggler
    # detector needs (same discipline as the acceptance's corridor run)
    warm = mpmd_scenario(base_dir=tempfile.mkdtemp(prefix="mpmd_w_"),
                         seed=0, steps=2)
    assert warm["ok"], (warm["errors"], warm["events"])
    out = mpmd_scenario(
        base_dir=tempfile.mkdtemp(prefix="mpmd_s_"), seed=0, steps=12,
        throttle_stage=1, throttle=0.2, standby=True,
        straggler_factor=5.0, lease=0.5)
    assert out["ok"], (out["errors"], out["events"])
    assert any("stage straggler" in e for e in out["events"])
    # the flip is logged as a TAKEOVER when the victim's lease is still
    # live, or as a restore when the overloaded victim's lease expired
    # first — either way the standby must now OWN the stage and have done
    # the work, with the victim passive and nothing double-applied
    assert any("TAKEOVER" in e
               or ("restored by rank" in e and "stage 1" in e)
               for e in out["events"]), out["events"]
    assert out["standby"].stats["updates"] > 0
    assert out["placement"].entries[1].rank == out["standby"].rank
    assert out["applied_ok"]


def test_mpmd_acceptance_int8_activation_corridor(lock_witness):
    """THE ISSUE 18 acceptance for the activation plane: the same 4-stage
    chaos + stage-death scenario as above, but with activations and
    activation-grads riding the registry's int8 rung — the loss
    trajectory stays inside a tight corridor around the dense run, every
    lossy frame ships >= 3x fewer floats (the analyzer's own wire-stats
    counters, not an estimate), the 3 chaos logs are byte-identical, and
    the 3 quantized trajectories are bitwise EQUAL to each other (the
    codec is deterministic, so replay-after-death reconstructs the same
    updates it would have applied fault-free)."""
    steps = 8
    corridor = mpmd_scenario(
        base_dir=tempfile.mkdtemp(prefix="mpmd_qc_"), seed=0, steps=steps)
    assert corridor["ok"], (corridor["errors"], corridor["events"])

    logs, trajectories = [], []
    for _rep in range(3):
        out = mpmd_scenario(
            base_dir=tempfile.mkdtemp(prefix="mpmd_q_"), seed=0,
            steps=steps, kill_stage=1, kill_at_step=3, snapshot_at_step=1,
            plan=default_mpmd_plan(0), act_codec="int8")
        assert out["ok"], (out["errors"], out["events"])
        assert out["stage_restarts"] == 1
        assert out["applied_ok"]
        assert out["chaos_counts"].get("drop", 0) > 0
        # int8 is bounded-lossy, so the trajectory is NEAR the dense
        # corridor rather than equal to it; the probe deviation is
        # ~4e-4 relative, so 5e-3 catches a broken codec without
        # flaking on quantization noise
        np.testing.assert_allclose(out["losses"], corridor["losses"],
                                   rtol=5e-3)
        # the bytes actually dropped: every stage that shipped lossy
        # frames shipped them >= 3x smaller than the dense bodies
        lossy = {k: s for k, s in out["stats"].items()
                 if isinstance(s, dict) and s.get("act_dense_floats")}
        assert lossy, out["stats"]
        for name, s in lossy.items():
            assert s["act_wire_floats"] * 3 <= s["act_dense_floats"], (
                name, s["act_wire_floats"], s["act_dense_floats"])
        logs.append(out["chaos_lines"])
        trajectories.append(list(out["losses"]))
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "mpmd chaos log not byte-identical across int8 runs")
    assert trajectories[0] == trajectories[1] == trajectories[2], (
        "int8 activation codec is not deterministic across replays")
