"""Optimizer-plane numerics + durability (ISSUE 14, parallel/optplane.py).

- sharded step == dense step on the same range (the ZeRO contract);
- Adasum algebra: orthogonal -> plain sum, identical -> one copy,
  anti-aligned -> plain sum (the documented deliberate deviation);
- PS integration: adasum combine de-weights redundant concurrent pushes,
  is mutually exclusive with staleness damping;
- state persistence: checkpoint + WAL replay reproduces BOTH the central
  vector and the optimizer moments bit-for-bit across a crash, including
  a crash torn between the checkpoint's renames (the two-generation
  state file), and elastic resizes keep the overlap's moments.
"""

import os
import threading

import numpy as np
import pytest

from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer
from distributed_ml_pytorch_tpu.parallel.optplane import (
    ShardedOptimizer,
    adasum,
    adasum_adjust,
    optimizer_from_args,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
)


# --------------------------------------------------------------- numerics

@pytest.mark.parametrize("kind", ["sgdm", "adam"])
def test_sharded_step_equals_dense_step_on_the_same_range(kind):
    rng = np.random.default_rng(0)
    dense = ShardedOptimizer(kind, 0, 24, lr=0.1, momentum=0.7)
    parts = [ShardedOptimizer(kind, lo, hi, lr=0.1, momentum=0.7)
             for lo, hi in ((0, 7), (7, 16), (16, 24))]
    for _ in range(6):
        u = rng.normal(size=24).astype(np.float32)
        d = dense.step(u)
        ds = np.concatenate([p.step(u[p.lo:p.hi]) for p in parts])
        np.testing.assert_array_equal(d, ds)
    # the 1/shards state claim: each shard holds exactly its range's words
    assert sum(p.size for p in parts) == dense.size
    assert all(p.state_floats() == 2 * p.size for p in parts)


def test_sgdm_identity_configuration_reproduces_plain_add():
    """lr=1, momentum=0: the optimizer plane degenerates to the exact
    reference server behavior (central += payload)."""
    opt = ShardedOptimizer("sgdm", 0, 5, lr=1.0, momentum=0.0)
    u = np.asarray([1, -2, 3, -4, 5], np.float32)
    np.testing.assert_array_equal(opt.step(u), u)


def test_adasum_orthogonal_reduces_to_plain_sum():
    a = np.asarray([1.0, 0.0, 0.0, 2.0], np.float32)
    b = np.asarray([0.0, 3.0, -1.0, 0.0], np.float32)
    assert float(a @ b) == 0.0
    np.testing.assert_allclose(adasum(a, b), a + b)
    np.testing.assert_allclose(adasum_adjust(a, b), b)


def test_adasum_identical_updates_apply_once():
    a = np.asarray([2.0, -1.0, 0.5], np.float32)
    np.testing.assert_allclose(adasum(a, a), a, rtol=1e-6)


def test_adasum_anti_aligned_falls_back_to_plain_sum():
    a = np.asarray([1.0, 0.0], np.float32)
    np.testing.assert_allclose(adasum(a, -a), a + (-a))


def test_adasum_zero_overlap_is_the_identity():
    z = np.zeros(3, np.float32)
    b = np.asarray([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(adasum_adjust(z, b), b)


# ----------------------------------------------------------- PS integration

def _pull(ps, world, rank):
    ps.handle(rank, MessageCode.ParameterRequest, np.zeros(0, np.float32))
    world[rank].recv(timeout=1.0)  # drain the reply


def test_ps_adasum_deweights_redundant_concurrent_pushes():
    world = InProcessTransport.create_world(3)
    ps = ParameterServer(params=np.zeros(4, np.float32),
                         transport=world[0], combine="adasum")
    _pull(ps, world, 1)
    _pull(ps, world, 2)
    d = np.asarray([2.0, 0.0, 0.0, 0.0], np.float32)
    ps.handle(1, MessageCode.GradientUpdate, d)
    # worker 2's identical concurrent push: overlap == push -> applies ~0
    ps.handle(2, MessageCode.GradientUpdate, d)
    assert ps.central[0] < 3.0, ps.central  # plain add would give 4.0
    np.testing.assert_allclose(ps.central[0], 2.0, atol=1e-5)
    for t in world.values():
        t.close()


def test_ps_adasum_orthogonal_pushes_apply_in_full():
    world = InProcessTransport.create_world(3)
    ps = ParameterServer(params=np.zeros(4, np.float32),
                         transport=world[0], combine="adasum")
    _pull(ps, world, 1)
    _pull(ps, world, 2)
    ps.handle(1, MessageCode.GradientUpdate,
              np.asarray([1.0, 0.0, 0.0, 0.0], np.float32))
    ps.handle(2, MessageCode.GradientUpdate,
              np.asarray([0.0, 1.0, 0.0, 0.0], np.float32))
    np.testing.assert_allclose(ps.central, [1.0, 1.0, 0.0, 0.0], atol=1e-6)
    for t in world.values():
        t.close()


def test_ps_adasum_and_staleness_damping_are_mutually_exclusive():
    with pytest.raises(ValueError, match="adasum"):
        ParameterServer(params=np.zeros(2, np.float32),
                        combine="adasum", staleness_damping=0.5)
    with pytest.raises(ValueError, match="combine"):
        ParameterServer(params=np.zeros(2, np.float32), combine="vibes")


# -------------------------------------------------------------- durability

def _mk_ps(tmp_path, n=16, momentum=0.5, **kw):
    opt = ShardedOptimizer("sgdm", 0, n, lr=1.0, momentum=momentum)
    return ParameterServer(params=np.zeros(n, np.float32),
                           ckpt_dir=str(tmp_path), ckpt_every=0, wal=True,
                           optimizer=opt, **kw)


def _push_n(ps, k, n=16, seed=0, sender=1):
    rng = np.random.default_rng(seed)
    for _ in range(k):
        ps.handle(sender, MessageCode.GradientUpdate,
                  rng.normal(size=n).astype(np.float32))


@pytest.mark.drill
def test_optimizer_state_survives_crash_restore_exactly(tmp_path):
    """checkpoint + WAL replay reproduces the central vector AND the
    momentum bit-for-bit — the state really rides checkpoints/WAL."""
    ps = _mk_ps(tmp_path)
    _push_n(ps, 3)
    ps.commit()
    ps.save_checkpoint()
    _push_n(ps, 4, seed=1)  # live only in the WAL
    ps.commit()
    live_c, live_m, live_t = (ps.central.copy(), ps.optimizer.m.copy(),
                              ps.optimizer.t)
    ps.wal.close()

    ps2 = _mk_ps(tmp_path)
    assert ps2.maybe_restore()
    np.testing.assert_array_equal(ps2.central, live_c)
    np.testing.assert_array_equal(ps2.optimizer.m, live_m)
    assert ps2._apply_seq == 7


@pytest.mark.drill
def test_optimizer_state_pairs_with_the_adopted_generation_on_a_torn_crash(
        tmp_path):
    """Crash between the checkpoint's renames: maybe_restore resolves the
    vector to the PREVIOUS generation — the optimizer state must follow
    it (the two-generation file), never pair new moments with the old
    vector."""
    import json

    ps = _mk_ps(tmp_path)
    _push_n(ps, 3)
    ps.commit()
    ps.save_checkpoint()
    gen1_m = ps.optimizer.m.copy()
    gen1_meta = json.load(open(ps._meta_path()))
    _push_n(ps, 2, seed=1)
    ps.commit()
    # simulate the tear: write generation-2 OPT STATE (it goes first in
    # save_checkpoint) and then "crash" before the meta/vector renames
    ps.optimizer.save_state(ps._opt_path(),
                            central_crc=12345, apply_seq=5)
    ps.wal.close()

    ps2 = _mk_ps(tmp_path)
    assert ps2.maybe_restore()
    # vector = gen1; the opt file's CURRENT gen is the orphan (crc 12345)
    # so the PREVIOUS generation (bound to gen1's CRC) must be adopted,
    # and WAL replay then advances both identically to the live run
    assert ps2._apply_seq == 5
    rng = np.random.default_rng(1)
    expect_m = gen1_m.copy()
    for _ in range(2):
        u = rng.normal(size=16).astype(np.float32)
        expect_m = (0.5 * expect_m + u).astype(np.float32)
    np.testing.assert_array_equal(ps2.optimizer.m, expect_m)
    assert int(gen1_meta["apply_seq"]) == 3


@pytest.mark.drill
def test_orphan_generation_from_a_torn_save_never_evicts_the_live_one(
        tmp_path):
    """Two torn crashes in a row (review hardening): save of G2 dies
    after the opt-state write (orphan cur=G2, vector stays G1); the
    restarted server later checkpoints G3 — the promoted prev slot must
    be the ADOPTED G1, not the orphan G2, so a tear in G3's renames
    still resolves to a (vector, optimizer) pair from one generation."""
    ps = _mk_ps(tmp_path)
    _push_n(ps, 2)
    ps.commit()
    ps.save_checkpoint()  # G1 completes
    g1_m = ps.optimizer.m.copy()
    _push_n(ps, 2, seed=1)
    ps.commit()
    # G2's save dies right after the opt-state write: only the orphan
    # cur generation lands (bound to a CRC no on-disk vector ever gets)
    ps.optimizer.save_state(ps._opt_path(), central_crc=0xDEAD,
                            apply_seq=4, prev_crc=None)
    ps.wal.close()

    ps2 = _mk_ps(tmp_path)
    assert ps2.maybe_restore()  # adopts G1 (+ WAL replay to seq 4)
    _push_n(ps2, 1, seed=2)
    ps2.commit()
    ps2.save_checkpoint()  # G3: must promote G1 into prev, not the orphan
    import numpy as _np

    with _np.load(ps2._opt_path()) as data:
        assert int(data["prev_seq"]) == 2  # G1's apply seq
        _np.testing.assert_array_equal(data["prev_m"], g1_m)


def test_missing_state_file_resets_moments_loudly(tmp_path):
    ps = _mk_ps(tmp_path)
    _push_n(ps, 2)
    ps.commit()
    ps.save_checkpoint()
    os.unlink(ps._opt_path())
    ps.wal.close()
    ps2 = _mk_ps(tmp_path)
    assert ps2.maybe_restore()
    np.testing.assert_array_equal(
        ps2.optimizer.m, np.zeros(16, np.float32))


def test_rollback_restore_rolls_the_optimizer_state_back_too(tmp_path):
    ps = _mk_ps(tmp_path)
    _push_n(ps, 3)
    ps.commit()
    ps.save_checkpoint()
    m_at_ckpt = ps.optimizer.m.copy()
    target = ps._apply_seq
    _push_n(ps, 3, seed=2)
    ps.commit()
    discarded = ps.rollback_restore(target)
    assert discarded == 3
    np.testing.assert_array_equal(ps.optimizer.m, m_at_ckpt)


def test_resize_keeps_overlap_moments_and_zeroes_fresh_range():
    opt = ShardedOptimizer("sgdm", 0, 8, momentum=0.9)
    opt.step(np.arange(8, dtype=np.float32))
    opt.resize(4, 12)
    np.testing.assert_array_equal(opt.m[:4],
                                  np.arange(4, 8, dtype=np.float32))
    np.testing.assert_array_equal(opt.m[4:], np.zeros(4, np.float32))
    assert (opt.lo, opt.hi) == (4, 12)


def test_optimizer_from_args_cli_face():
    class A:
        server_opt = "adam"
        server_lr = 0.01
        server_momentum = 0.9

    opt = optimizer_from_args(A(), 10)
    assert opt.kind == "adam" and opt.size == 10 and opt.lr == 0.01
    A.server_opt = "none"
    assert optimizer_from_args(A(), 10) is None
    A.server_opt = "vibes"
    with pytest.raises(ValueError, match="kind"):
        optimizer_from_args(A(), 10)
