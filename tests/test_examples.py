"""The LM example must run every parallelism mode end-to-end and learn."""

import pytest

from examples.train_lm import main


@pytest.mark.parametrize(
    "mode", ["single", "sp", "ulysses", "fsdp", "tp", "pp", "moe", "composite"]
)
def test_train_lm_example_runs(mode, capsys):
    rc = main([
        "--mode", mode, "--steps", "4", "--batch", "4", "--seq", "32",
        "--vocab", "64", "--d-model", "32", "--n-heads", "8",
        "--n-layers", "1", "--d-ff", "64",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "final loss" in out


@pytest.mark.parametrize("mode", ["single", "tp", "pp", "moe"])
def test_train_lm_chunked_dispatch(mode, capsys):
    rc = main([
        "--mode", mode, "--steps", "4", "--steps-per-dispatch", "2",
        "--batch", "4", "--seq", "32", "--vocab", "64", "--d-model", "32",
        "--n-heads", "8", "--n-layers", "1", "--d-ff", "64",
    ])
    assert rc == 0
    assert "final loss" in capsys.readouterr().out


@pytest.mark.parametrize("mode", ["fsdp", "moe"])
def test_train_lm_checkpoint_resume(mode, tmp_path, capsys):
    base = ["--mode", mode, "--batch", "4", "--seq", "32", "--vocab", "64",
            "--d-model", "32", "--n-heads", "8", "--n-layers", "1",
            "--d-ff", "64", "--ckpt-dir", str(tmp_path / "ckpt"),
            "--ckpt-every", "1"]
    assert main(base + ["--steps", "3"]) == 0
    capsys.readouterr()
    assert main(base + ["--steps", "2", "--resume"]) == 0
    out = capsys.readouterr().out
    assert "resumed from checkpoint step 3" in out


def test_train_lm_example_loss_decreases(capsys):
    main(["--mode", "single", "--steps", "10", "--batch", "8", "--seq", "32",
          "--vocab", "64", "--d-model", "32", "--n-heads", "4",
          "--n-layers", "1", "--d-ff", "64", "--lr", "0.1"])
    out = capsys.readouterr().out
    losses = [float(l.split("loss")[-1]) for l in out.splitlines() if "  step" in l]
    assert losses[-1] < losses[0]


def test_train_lm_chunked_loss_matches_dense(capsys):
    """--loss-chunk must train to the same losses as the dense loss (same
    seed/data) — the CLI-reachable face of chunked_lm_loss's exactness."""
    import re

    outs = []
    for extra in ([], ["--loss-chunk", "64"]):
        rc = main([
            "--mode", "single", "--steps", "3", "--batch", "4",
            "--seq", "256", "--vocab", "64", "--d-model", "32",
            "--n-heads", "8", "--n-layers", "1", "--d-ff", "64",
        ] + extra)
        assert rc == 0
        m = re.search(r"final loss ([0-9.]+)", capsys.readouterr().out)
        assert m
        outs.append(float(m.group(1)))
    assert abs(outs[0] - outs[1]) < 1e-3, outs


def test_generate_text_example_greedy_and_sampled(capsys):
    from examples.generate_text import main as gen_main

    small = ["--batch", "1", "--prompt-len", "8", "--vocab", "64",
             "--d-model", "32", "--n-heads", "4", "--n-layers", "1",
             "--d-ff", "64"]
    assert gen_main(small + ["--new-tokens", "20"]) == 0
    out = capsys.readouterr().out
    assert "sampled:" in out and "decode (" in out
    assert gen_main(small + ["--new-tokens", "20", "--temperature", "0.9",
                             "--top-k", "10", "--top-p", "0.9"]) == 0
    assert gen_main(small + ["--new-tokens", "20", "--kv-quant"]) == 0
    out = capsys.readouterr().out
    assert "int8 KV cache" in out


def test_generate_text_restores_train_lm_checkpoint(tmp_path, capsys):
    from examples.generate_text import main as gen_main

    model = ["--vocab", "64", "--d-model", "32", "--n-heads", "4",
             "--n-layers", "1", "--d-ff", "64"]
    assert main(["--mode", "single", "--steps", "2", "--batch", "4",
                 "--seq", "32",
                 "--ckpt-dir", str(tmp_path / "c"), "--ckpt-every", "1"]
                + model) == 0
    capsys.readouterr()
    assert gen_main(model + ["--batch", "1", "--prompt-len", "8",
                             "--new-tokens", "12",
                             "--ckpt-dir", str(tmp_path / "c")]) == 0
    # a table-size mismatch must fail loudly, and --max-len must fix it
    with pytest.raises(Exception):
        gen_main(model + ["--batch", "1", "--prompt-len", "8",
                          "--new-tokens", "12", "--max-len", "300",
                          "--ckpt-dir", str(tmp_path / "c")])
    out = capsys.readouterr().out
    assert "restored params from step 2" in out


def test_generate_text_rejects_bad_flags(capsys):
    from examples.generate_text import main as gen_main

    with pytest.raises(SystemExit):
        gen_main(["--top-k", "5"])  # sampling knobs without temperature
    with pytest.raises(SystemExit):
        gen_main(["--d-model", "30", "--n-heads", "4"])
    with pytest.raises(SystemExit):
        gen_main(["--tp", "2", "--kv-quant"])  # silently-exact combination


def test_train_lm_dp_pp_composite():
    """--mode pp --pp-dp 2 runs the dp x pp composition (2 pipeline
    replicas x 2 stages on the 8-device mesh) and learns."""
    rc = main([
        "--mode", "pp", "--pp-dp", "2", "--steps", "4", "--batch", "8",
        "--seq", "32", "--vocab", "64", "--d-model", "32", "--n-heads", "8",
        "--n-layers", "2", "--d-ff", "64",
    ])
    assert rc == 0


def test_train_lm_pp_tp_and_3d_composite():
    """--pp-tp shards blocks inside each stage (pp x tp); with --pp-dp the
    full dp x pp x tp 3-D layout runs on the 8-device mesh."""
    args = ["--mode", "pp", "--steps", "3", "--batch", "8",
            "--seq", "32", "--vocab", "64", "--d-model", "32", "--n-heads",
            "8", "--n-layers", "2", "--d-ff", "64"]
    assert main(args + ["--pp-tp", "2"]) == 0
    assert main(args + ["--pp-dp", "2", "--pp-tp", "2"]) == 0


def test_train_lm_pp_tp_rejects_indivisible_heads(capsys):
    with pytest.raises(SystemExit):
        main(["--mode", "pp", "--pp-tp", "3", "--n-heads", "8",
              "--steps", "1"])
