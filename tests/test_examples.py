"""The LM example must run every parallelism mode end-to-end and learn."""

import pytest

from examples.train_lm import main


@pytest.mark.parametrize(
    "mode", ["single", "sp", "ulysses", "fsdp", "tp", "composite"]
)
def test_train_lm_example_runs(mode, capsys):
    rc = main([
        "--mode", mode, "--steps", "4", "--batch", "4", "--seq", "32",
        "--vocab", "64", "--d-model", "32", "--n-heads", "8",
        "--n-layers", "1", "--d-ff", "64",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "final loss" in out


def test_train_lm_example_loss_decreases(capsys):
    main(["--mode", "single", "--steps", "10", "--batch", "8", "--seq", "32",
          "--vocab", "64", "--d-model", "32", "--n-heads", "4",
          "--n-layers", "1", "--d-ff", "64", "--lr", "0.1"])
    out = capsys.readouterr().out
    losses = [float(l.split("loss")[-1]) for l in out.splitlines() if "  step" in l]
    assert losses[-1] < losses[0]
