"""WIRE_SCHEMAS totality fast-tests (ISSUE 13 satellite).

Until now only ``make lint`` (the full analyzer run) checked that the
declarative schema table stays total over the ``MessageCode`` enum; these
plain tier-1 units fail a schema drift in milliseconds:

- every enum member has a schema entry, and no schema names a ghost code;
- code values are collision-free (IntEnum would silently alias);
- every schema's ``handled_by`` plane names at least one real handler
  site in the package source;
- the ISSUE 13 protocol annotations are complete and vocabulary-valid:
  every reliably-delivered code declares its dedup key, durability only
  decorates reliable codes, ``delivery='best_effort'`` agrees exactly
  with ``ReliableTransport.unreliable_codes``, and an evolved
  multi-section tail declares its separator.
"""

import inspect
import os

import pytest

from distributed_ml_pytorch_tpu.utils.messaging import (
    DEDUP_KEYS,
    DELIVERY,
    DURABILITY,
    MessageCode,
    PayloadSchema,
    ReliableTransport,
    WIRE_SCHEMAS,
)


def test_every_code_has_a_schema_and_no_ghosts():
    missing = [c.name for c in MessageCode if c not in WIRE_SCHEMAS]
    assert not missing, f"codes without a WIRE_SCHEMAS entry: {missing}"
    ghosts = [c for c in WIRE_SCHEMAS if not isinstance(c, MessageCode)]
    assert not ghosts, f"schemas for non-enum keys: {ghosts}"


def test_code_values_are_collision_free():
    # IntEnum aliases a colliding member silently: __members__ keeps the
    # alias name, so a collision shows up as more names than values
    members = MessageCode.__members__
    assert len({int(v) for v in members.values()}) == len(members), (
        "MessageCode values collide — IntEnum aliased a member and its "
        "frames dispatch to the wrong handler")


def test_handled_by_planes_are_known_and_nonempty():
    valid = {"ps", "serving", "coord", "transport"}
    for code, schema in WIRE_SCHEMAS.items():
        assert schema.handled_by, f"{code.name}: empty handled_by"
        assert set(schema.handled_by) <= valid, (
            f"{code.name}: unknown plane(s) {schema.handled_by}")


@pytest.fixture(scope="module")
def handler_sites():
    """Positive dispatch sites per (code name, plane), from the same AST
    extraction the analyzer uses — parsing only, no checker run."""
    import distributed_ml_pytorch_tpu as pkg
    from distributed_ml_pytorch_tpu.analysis.core import load_package
    from distributed_ml_pytorch_tpu.analysis.wire import extract_handlers

    tree = load_package(os.path.dirname(os.path.abspath(pkg.__file__)))
    return extract_handlers(tree)


def test_every_schema_plane_names_a_real_handler(handler_sites):
    by_code = {}
    for h in handler_sites:
        by_code.setdefault(h.code, set()).add(h.plane)
    orphans = []
    for code, schema in WIRE_SCHEMAS.items():
        if not by_code.get(code.name, set()) & set(schema.handled_by):
            orphans.append((code.name, schema.handled_by))
    assert not orphans, (
        "schemas whose declared plane has no real handler site: "
        f"{orphans}")


# --------------------------------------- ISSUE 13 protocol annotations

def test_annotation_vocabularies_are_enforced_at_construction():
    with pytest.raises(ValueError, match="dedup_key"):
        PayloadSchema(dedup_key="vibes")
    with pytest.raises(ValueError, match="durability"):
        PayloadSchema(durability="hopes")
    with pytest.raises(ValueError, match="delivery"):
        PayloadSchema(delivery="carrier-pigeon")
    with pytest.raises(ValueError, match="rest_separator"):
        PayloadSchema(rest="tail", rest_sections=("a", "b"))


def test_every_reliable_code_declares_a_dedup_key():
    bare = [c.name for c, s in WIRE_SCHEMAS.items()
            if s.delivery == "reliable" and s.dedup_key is None]
    assert not bare, (
        "reliably-delivered codes with no dedup_key (at-least-once "
        f"redelivery with no exactly-once guard): {bare}")


def test_annotations_stay_inside_their_vocabularies():
    for code, s in WIRE_SCHEMAS.items():
        assert s.dedup_key is None or s.dedup_key in DEDUP_KEYS, code.name
        assert s.durability in DURABILITY, code.name
        assert s.delivery in DELIVERY, code.name


def test_durability_only_decorates_reliable_wal_codes():
    for code, s in WIRE_SCHEMAS.items():
        if s.durability == "wal_before_ack":
            assert s.delivery == "reliable", (
                f"{code.name}: WAL-before-ack is meaningless without "
                "reliable delivery (nothing withholds the ack)")
            assert s.dedup_key == "env_seq", (
                f"{code.name}: WAL'd codes dedup by the envelope "
                "identity the WAL records (seed_dedup)")


def test_best_effort_annotation_matches_unreliable_codes_default():
    sig = inspect.signature(ReliableTransport.__init__)
    default = {MessageCode(int(c))
               for c in sig.parameters["unreliable_codes"].default}
    annotated = {c for c, s in WIRE_SCHEMAS.items()
                 if s.delivery == "best_effort"}
    assert annotated == default, (
        f"delivery='best_effort' annotations {sorted(c.name for c in annotated)} "
        "disagree with ReliableTransport.unreliable_codes "
        f"{sorted(c.name for c in default)}")


def test_envelope_codes_are_exactly_the_reliability_wire():
    annotated = {c.name for c, s in WIRE_SCHEMAS.items()
                 if s.delivery == "envelope"}
    assert annotated == {"ReliableFrame", "ReliableAck", "CumAck"}


def test_multi_section_tails_declare_rest_and_separator():
    for code, s in WIRE_SCHEMAS.items():
        if s.rest_sections:
            assert s.rest is not None, code.name
            assert len(s.rest_sections) >= 2, code.name
            assert s.rest_separator is not None, code.name
    fleet = WIRE_SCHEMAS[MessageCode.FleetState]
    assert fleet.rest_sections == ("engine_ranks", "fleet_metrics")
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        FLEET_TAIL_SEPARATOR,
    )

    assert fleet.rest_separator == FLEET_TAIL_SEPARATOR
