"""The decode-attention kernel (ops/decode_attention.py — a measured
record, not integrated) must compute exactly the XLA blocked-decode
attention math: masked live-prefix scores (+ int8 per-key scales), masked
ring scores, fresh-token score, f32 softmax, three-part value sum."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.ops.decode_attention import (
    decode_attention_step,
    kernel_supported,
)
from distributed_ml_pytorch_tpu.ops.fused_update import force_pallas_interpret
from distributed_ml_pytorch_tpu.models.transformer import quantize_kv


def _xla_reference(q, k_new, v_new, big_k, big_v, ring_k, ring_v, t,
                   ring_base, scale_k=None, scale_v=None):
    """The transformer.py blocked-path math, extracted."""
    d = q.shape[-1]
    scale = jnp.sqrt(jnp.asarray(d, jnp.float32))
    C, T = big_k.shape[2], ring_k.shape[2]
    s_big = jnp.einsum("bhsd,bhcd->bhsc", q, big_k.astype(q.dtype),
                       preferred_element_type=jnp.float32)
    if scale_k is not None:
        s_big = s_big * scale_k[:, :, None, :]
    s_big = jnp.where((jnp.arange(C) < ring_base)[None, None, None, :],
                      s_big, -jnp.inf)
    s_ring = jnp.einsum("bhsd,bhtd->bhst", q, ring_k,
                        preferred_element_type=jnp.float32)
    s_ring = jnp.where((jnp.arange(T) < t)[None, None, None, :],
                       s_ring, -jnp.inf)
    s_self = jnp.einsum("bhsd,bhsd->bhs", q, k_new,
                        preferred_element_type=jnp.float32)
    scores = jnp.concatenate([s_big, s_ring, s_self[..., None]],
                             axis=-1) / scale
    probs = jax.nn.softmax(scores, axis=-1)
    p_big = probs[..., :C]
    if scale_v is not None:
        p_big = p_big * scale_v[:, :, None, :]
    out = (
        jnp.einsum("bhsc,bhcd->bhsd", p_big.astype(q.dtype),
                   big_v.astype(q.dtype), preferred_element_type=jnp.float32)
        + jnp.einsum("bhst,bhtd->bhsd",
                     probs[..., C:C + T].astype(q.dtype), ring_v,
                     preferred_element_type=jnp.float32)
        + probs[..., C + T:].astype(jnp.float32) * v_new
    )
    return out.astype(q.dtype)


@pytest.mark.parametrize("quant", [False, True])
def test_decode_attention_kernel_matches_xla_math(quant):
    rng = np.random.default_rng(0)
    b, h, C, T, d = 3, 4, 40, 16, 32
    q, k_new, v_new = (
        jnp.asarray(rng.normal(size=(b, h, 1, d)), jnp.float32)
        for _ in range(3))
    ring_k, ring_v = (
        jnp.asarray(rng.normal(size=(b, h, T, d)), jnp.float32)
        for _ in range(2))
    big_k_f = jnp.asarray(rng.normal(size=(b, h, C, d)), jnp.float32)
    big_v_f = jnp.asarray(rng.normal(size=(b, h, C, d)), jnp.float32)
    if quant:
        big_k, scale_k = quantize_kv(big_k_f)
        big_v, scale_v = quantize_kv(big_v_f)
    else:
        big_k, big_v, scale_k, scale_v = big_k_f, big_v_f, None, None
    t, ring_base = jnp.asarray(5), jnp.asarray(32)

    want = _xla_reference(q, k_new, v_new, big_k, big_v, ring_k, ring_v,
                          t, ring_base, scale_k, scale_v)
    with force_pallas_interpret():
        assert kernel_supported(big_k)
        got = decode_attention_step(q, k_new, v_new, big_k, big_v,
                                    ring_k, ring_v, t, ring_base,
                                    scale_k, scale_v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_kernel_supported_gates_context_and_backend():
    big = jnp.zeros((1, 2, 8192, 16), jnp.bfloat16)
    with force_pallas_interpret():
        assert not kernel_supported(big)  # context beyond the VMEM gate
        assert kernel_supported(jnp.zeros((1, 2, 64, 16), jnp.bfloat16))
