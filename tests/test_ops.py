"""Kernel tests: Pallas flat-axpy + flash attention vs. naive references.

Pallas kernels run in interpreter mode on the CPU test mesh (Mosaic only
compiles on real TPU); the wrappers auto-select that, and
``force_pallas_interpret`` drives the flat-update kernel's Pallas path
explicitly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.ops import (
    attention_reference,
    blockwise_attention,
    downpour_accumulate,
    flash_attention,
    flat_axpy,
)
from distributed_ml_pytorch_tpu.ops.attention import finalize_attention
from distributed_ml_pytorch_tpu.ops.fused_update import force_pallas_interpret


@pytest.mark.parametrize("n", [128 * 256, 1000, 7])
def test_flat_axpy_pallas_matches_reference(n):
    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.normal(size=n).astype(np.float32))
    x = jnp.asarray(rng.normal(size=n).astype(np.float32))
    with force_pallas_interpret():
        got = flat_axpy(y, x, -0.05)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(y) - 0.05 * np.asarray(x), rtol=1e-5, atol=1e-6
    )


def test_flat_axpy_fallback_path():
    y = jnp.arange(10, dtype=jnp.float32)
    x = jnp.ones(10, jnp.float32)
    np.testing.assert_allclose(np.asarray(flat_axpy(y, x, 2.0)), np.arange(10) + 2.0)


def test_downpour_accumulate_prescales_by_neg_lr():
    accum = jnp.zeros(5, jnp.float32)
    grads = jnp.ones(5, jnp.float32)
    out = downpour_accumulate(accum, grads, lr=0.1)
    np.testing.assert_allclose(np.asarray(out), -0.1 * np.ones(5), rtol=1e-6)


def _qkv(b=2, h=2, sq=256, sk=256, d=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda s: jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    return mk(sq), mk(sk), mk(sk)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    want = attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sk", [256, 300])  # 300 exercises the ragged-pad path
def test_blockwise_attention_matches_reference(causal, sk):
    q, k, v = _qkv(sq=256 if causal else 128, sk=sk)
    if causal and sk != q.shape[2]:
        pytest.skip("causal is defined for sq == sk")
    want = attention_reference(q, k, v, causal=causal)
    acc, _m, l = blockwise_attention(q, k, v, causal=causal, block_k=128)
    got = finalize_attention(acc, l)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_blockwise_attention_bf16_accumulates_in_f32():
    q, k, v = _qkv(sq=128, sk=256)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    acc, m, l = blockwise_attention(qb, kb, vb, block_k=64)
    assert acc.dtype == jnp.float32 and l.dtype == jnp.float32
    got = finalize_attention(acc, l)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-2, rtol=3e-2)


def test_blockwise_attention_is_differentiable():
    q, k, v = _qkv(b=1, h=1, sq=128, sk=128, d=32)

    def loss(q, k, v):
        acc, _m, l = blockwise_attention(q, k, v, causal=True, block_k=64)
        return jnp.sum(finalize_attention(acc, l) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0.0


def test_blockwise_attention_fully_masked_rows_are_empty():
    """A chunk whose keys are all causally after the queries must contribute
    nothing: acc == 0 and l == 0 (ring attention's not-yet-arrived case)."""
    q, k, v = _qkv(b=1, h=1, sq=64, sk=128)
    acc, _m, l = blockwise_attention(q, k, v, causal=True, q_offset=0, k_offset=64)
    assert float(jnp.abs(acc).max()) == 0.0
    assert float(jnp.abs(l).max()) == 0.0


def test_flash_attention_rejects_causal_cross_lengths():
    q, k, v = _qkv(sq=128, sk=256)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, causal=True)


def test_blockwise_attention_offsets_shift_causal_mask():
    """With q_offset = sk (queries globally after all keys), causal masking
    must reduce to full attention over the keys — the invariant ring
    attention relies on for later-arriving chunks."""
    q, k, v = _qkv(b=1, h=1, sq=64, sk=128)
    acc, _m, l = blockwise_attention(q, k, v, causal=True, q_offset=128, k_offset=0)
    got = finalize_attention(acc, l)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bwd_impl", ["fused", "split"])
def test_flash_attention_gradients_match_reference(causal, bwd_impl):
    """Both backward implementations (the one-recompute fused kernel and the
    two-kernel split) must agree with autodiff through the dense reference —
    including with backward blocking different from the forward's (the
    production default) so the dq-partials layout is exercised."""
    rng = np.random.default_rng(5)
    b, h, s, d = 2, 2, 256, 64
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
               for _ in range(3))

    def f(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=128,
                               block_k=128, block_q_bwd=256, block_k_bwd=128,
                               bwd_impl=bwd_impl).sum()

    def r(q, k, v):
        return attention_reference(q, k, v, causal=causal).sum()

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_lse_matches_reference(causal):
    """flash_attention_lse must return the dense output AND the per-row
    natural logsumexp of the scaled (masked) scores."""
    from distributed_ml_pytorch_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(11)
    b, h, s, d = 2, 2, 256, 64
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
               for _ in range(3))
    out, lse = flash_attention_lse(q, k, v, causal=causal,
                                   block_q=128, block_k=128)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * d**-0.5
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    want_lse = jax.scipy.special.logsumexp(scores, axis=-1)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(q, k, v, causal=causal)),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want_lse),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("bwd_impl", ["fused", "split"])
def test_flash_attention_lse_cotangent_reaches_inputs(bwd_impl):
    """A loss that consumes BOTH outputs (as ring attention's combine does)
    must produce reference gradients — the dlse cotangent folds into the
    backward delta."""
    from distributed_ml_pytorch_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(12)
    b, h, s, d = 1, 2, 256, 64
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
               for _ in range(3))

    def f(q, k, v):
        out, lse = flash_attention_lse(q, k, v, causal=True, block_q=128,
                                       block_k=128, bwd_impl=bwd_impl)
        return jnp.sum(out**2) + jnp.sum(jnp.sin(lse))

    def r(q, k, v):
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * d**-0.5
        scores = jnp.where(jnp.tril(jnp.ones((s, s), bool)), scores, -jnp.inf)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        return jnp.sum(out**2) + jnp.sum(jnp.sin(lse))

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-2, atol=2e-2)


def test_flash_bwd_impl_auto_selects_split_at_extreme_length(monkeypatch):
    """Beyond FUSED_BWD_PARTIALS_CAP the lean split backward must be chosen
    so extreme-length gradients stay compilable (code-review r3 finding)."""
    from distributed_ml_pytorch_tpu.ops import attention as A

    chosen = []
    real = A._flash

    def spy(causal, blocks, bwd_blocks, interpret, bwd_impl, q, k, v):
        chosen.append(bwd_impl)
        return real(causal, blocks, bwd_blocks, interpret, bwd_impl, q, k, v)

    monkeypatch.setattr(A, "_flash", spy)
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 1, 256, 64)), jnp.float32)
               for _ in range(3))
    A.flash_attention(q, k, v, causal=True)
    assert chosen[-1] == "fused"
    monkeypatch.setattr(A, "FUSED_BWD_PARTIALS_CAP", 1)  # force the cap
    A.flash_attention(q, k, v, causal=True)
    assert chosen[-1] == "split"


def test_flash_block_choice_prefers_large_and_falls_back():
    from distributed_ml_pytorch_tpu.ops.attention import flash_block_choice

    assert flash_block_choice(2048, 2048) == (1024, 1024)
    assert flash_block_choice(512, 256) == (512, 256)
    assert flash_block_choice(384, 384) == (128, 128)
    assert flash_block_choice(200, 512) is None  # no divisor → scan path


def test_auto_attention_matches_reference_off_tpu():
    """On the CPU test backend auto_attention takes the scan path and must
    equal the dense reference (the flash path's numerics are covered by the
    kernel tests above)."""
    from distributed_ml_pytorch_tpu.ops.attention import auto_attention

    rng = np.random.default_rng(6)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 2, 128, 32)), jnp.float32)
               for _ in range(3))
    got = auto_attention(q, k, v, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gspmd_safe_lm_pins_sharded_island_on_multidevice_mesh():
    """GSPMD step factories must not embed a bare pallas custom call (no
    SPMD partitioning rule) — models with a default attn_fn get a
    shard_map attention island on multi-device meshes, stay untouched on
    1-device meshes, and injected attn_fns are never overridden."""
    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.models.moe import MoETransformerLM
    from distributed_ml_pytorch_tpu.ops.attention import gspmd_safe_lm
    from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

    mesh8 = make_mesh({"data": 8})
    mesh1 = make_mesh({"data": 1}, devices=jax.devices()[:1])
    for cls in (TransformerLM, MoETransformerLM):
        m = cls(vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64)
        pinned = gspmd_safe_lm(m, mesh8)
        assert pinned is not m and pinned.attn_fn is not None
        assert gspmd_safe_lm(m, mesh1) is m
        injected = m.clone(attn_fn=attention_reference)
        assert gspmd_safe_lm(injected, mesh8).attn_fn is attention_reference


def test_sharded_attn_island_matches_reference():
    """The shard_map attention island (batch over data, heads over model)
    must reproduce dense causal attention exactly — attention is parallel
    over (batch, heads), so sharding adds nothing numerically."""
    from distributed_ml_pytorch_tpu.ops.attention import make_sharded_attn_fn
    from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

    mesh = make_mesh({"data": 2, "model": 4})
    attn = make_sharded_attn_fn(mesh, batch_axes=("data",), head_axis="model")
    rng = np.random.default_rng(8)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 4, 128, 32)), jnp.float32)
               for _ in range(3))
    got = jax.jit(attn)(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # and it is differentiable end-to-end (the GSPMD step trains through it)
    g = jax.jit(jax.grad(lambda q: attn(q, k, v).sum()))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_flash_bwd_block_choice_gates_long_key_blocks():
    """The (·, 2048) backward key block applies at sk == 8192 EXACTLY:
    measured faster there (and it halves the dq-partials reduce), but
    slower at 4096 and scoped-vmem-OOM at >= 16384 (see the docstring's
    measurements) — the gate must not widen silently."""
    from distributed_ml_pytorch_tpu.ops.attention import (
        flash_bwd_block_choice,
    )

    assert flash_bwd_block_choice(8192, 8192) == (1024, 2048)
    assert flash_bwd_block_choice(2048, 2048) == (1024, 1024)
    assert flash_bwd_block_choice(4096, 4096) == (1024, 1024)
    assert flash_bwd_block_choice(16384, 16384) == (1024, 1024)
    assert flash_bwd_block_choice(32768, 32768) == (1024, 1024)


def test_flash_bwd_2048_key_block_grads_match_reference():
    """The sk=8192 backward blocking computes the same gradients as the
    square blocking (interpret mode, small head count)."""
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 1, 8192, 8)), jnp.float32)
               for _ in range(3))

    def loss(blocks):
        def f(q, k, v):
            return flash_attention(
                q, k, v, causal=True, block_q_bwd=blocks[0],
                block_k_bwd=blocks[1], interpret=True).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_a = loss((1024, 1024))
    g_b = loss((1024, 2048))
    for a, b in zip(g_a, g_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_attention_default_blocks_adapt_to_sequence():
    """Default (unspecified) blocks must derive from flash_block_choice so
    lengths like 1536 — divisible by 512 but not 1024 — still work."""
    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 1, 1536, 8)), jnp.float32)
               for _ in range(3))
    got = flash_attention(q, k, v, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=2e-4)


def test_sharded_attn_island_runs_pallas_kernel():
    """The island's purpose is hosting the Pallas kernel under shard_map —
    exercised here with interpret-mode flash (the CPU analog of the TPU
    path auto_attention takes), guarding against shard_map/pallas interop
    regressions (e.g. the check_vma rejection of custom-call bodies)."""
    from distributed_ml_pytorch_tpu.ops.attention import make_sharded_attn_fn
    from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

    mesh = make_mesh({"data": 2, "model": 4})
    attn = make_sharded_attn_fn(
        mesh, batch_axes=("data",), head_axis="model",
        local_attn=lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=True),
    )
    rng = np.random.default_rng(9)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 4, 128, 32)), jnp.float32)
               for _ in range(3))
    got = jax.jit(attn)(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    g = jax.jit(jax.grad(lambda q: attn(q, k, v).sum()))(q)
    assert np.isfinite(np.asarray(g)).all()
