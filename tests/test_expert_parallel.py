"""Switch-MoE + dp×ep expert parallelism: routing invariants and sharded vs
unsharded numerical equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_ml_pytorch_tpu.models.moe import (
    MoETransformerLM,
    switch_route,
)
from distributed_ml_pytorch_tpu.parallel.expert_parallel import (
    create_ep_train_state,
    ep_param_specs,
    make_ep_train_step,
    shard_ep_batch,
)
from distributed_ml_pytorch_tpu.parallel.seq_parallel import next_token_targets
from distributed_ml_pytorch_tpu.training.trainer import TrainState


def tiny_moe():
    return MoETransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, capacity_factor=2.0, max_len=128,
    )


def dp_ep_mesh(dp=2, ep=4):
    devs = np.array(jax.devices()[: dp * ep]).reshape(dp, ep)
    return Mesh(devs, ("data", "expert"))


def make_batch(batch=4, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, 64, size=(batch, seq)).astype(np.int32)
    return tokens, next_token_targets(tokens)


def test_switch_route_respects_capacity_and_slots():
    rng = jax.random.key(0)
    probs = jax.nn.softmax(jax.random.normal(rng, (2, 16, 4)), axis=-1)
    capacity = 3
    dispatch, combine = switch_route(probs, capacity)
    assert dispatch.shape == (2, 16, 4, 3)
    # each token goes to at most one (expert, slot)
    assert float(jnp.max(jnp.sum(dispatch, axis=(2, 3)))) <= 1.0 + 1e-6
    # each (expert, slot) holds at most one token per batch row
    assert float(jnp.max(jnp.sum(dispatch, axis=1))) <= 1.0 + 1e-6
    # combine carries the router prob on dispatched tokens only
    gate = jnp.sum(combine, axis=(2, 3))
    kept = jnp.sum(dispatch, axis=(2, 3))
    assert float(jnp.max(gate - kept)) <= 0.0 + 1e-6  # gate <= 1 where kept


def test_switch_route_ample_capacity_drops_nothing():
    probs = jax.nn.softmax(jax.random.normal(jax.random.key(1), (2, 8, 4)), axis=-1)
    dispatch, _ = switch_route(probs, capacity=8)  # capacity = full seq
    np.testing.assert_allclose(np.asarray(jnp.sum(dispatch, axis=(2, 3))), 1.0, rtol=1e-6)


def test_moe_lm_forward_and_aux_loss():
    model = tiny_moe()
    tokens, _ = make_batch()
    params = model.init(jax.random.key(0), jnp.asarray(tokens))["params"]
    logits, sown = model.apply({"params": params}, jnp.asarray(tokens), mutable=["losses"])
    assert logits.shape == (4, 16, 64)
    aux = [float(jnp.sum(v)) for v in jax.tree.leaves(sown["losses"])]
    assert len(aux) == 2  # one per layer
    # balanced-uniform routing gives aux ≈ 1.0; any routing keeps it finite ≥ 1-ish
    assert all(np.isfinite(a) and a > 0.5 for a in aux)


def test_ep_param_specs_shard_only_expert_stacks():
    model = tiny_moe()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    specs = ep_param_specs(params)
    moe = specs["block_0"]["moe"]
    assert moe["w_up"] == P("expert", None, None)
    assert moe["b_down"] == P("expert", None)
    assert moe["router"]["kernel"] == P()
    assert specs["block_0"]["attn"]["q"]["kernel"] == P()


def test_ep_training_matches_unsharded_exactly():
    model = tiny_moe()
    mesh = dp_ep_mesh()
    tx = optax.sgd(0.1)
    tokens, targets = make_batch()

    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    ref_state = TrainState.create(params, tx)
    ref_step = make_ep_train_step(model, tx, mesh)  # same code, unsharded args

    ep_state = create_ep_train_state(model, jax.random.key(0), tx, mesh)
    ep_step = make_ep_train_step(model, tx, mesh)
    stok, stgt = shard_ep_batch(mesh, tokens, targets)

    for _ in range(3):
        ref_state, (ref_loss, ref_aux) = ref_step(
            ref_state, jnp.asarray(tokens), jnp.asarray(targets)
        )
        ep_state, (ep_loss, ep_aux) = ep_step(ep_state, stok, stgt)
        np.testing.assert_allclose(float(ep_loss), float(ref_loss), rtol=2e-5)
        np.testing.assert_allclose(float(ep_aux), float(ref_aux), rtol=2e-5)
    for a, b in zip(
        jax.tree.leaves(ref_state.params), jax.tree.leaves(jax.device_get(ep_state.params))
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-5, atol=1e-6)


def test_ep_state_is_actually_sharded():
    mesh = dp_ep_mesh()
    state = create_ep_train_state(
        tiny_moe(), jax.random.key(0), optax.sgd(0.1, momentum=0.9), mesh
    )
    w = state.params["block_0"]["moe"]["w_up"]
    assert w.sharding.spec == P("expert", None, None)
    mom = state.opt_state[0].trace["block_0"]["moe"]["w_up"]
    assert mom.sharding.spec == P("expert", None, None)


def test_ep_rejects_indivisible_experts():
    mesh = dp_ep_mesh(dp=2, ep=4)
    bad = MoETransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64, n_experts=3
    )
    with pytest.raises(ValueError, match="not divisible"):
        make_ep_train_step(bad, optax.sgd(0.1), mesh)


def test_moe_remat_matches_no_remat():
    model = tiny_moe()
    remat_model = MoETransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, capacity_factor=2.0, max_len=128, remat=True,
    )
    tokens, _ = make_batch()
    params = model.init(jax.random.key(0), jnp.asarray(tokens))["params"]
    a, sown_a = model.apply({"params": params}, jnp.asarray(tokens), mutable=["losses"])
    b, sown_b = remat_model.apply({"params": params}, jnp.asarray(tokens), mutable=["losses"])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    la = sum(float(jnp.sum(v)) for v in jax.tree.leaves(sown_a["losses"]))
    lb = sum(float(jnp.sum(v)) for v in jax.tree.leaves(sown_b["losses"]))
    np.testing.assert_allclose(la, lb, rtol=1e-6)
