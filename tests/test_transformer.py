"""Transformer LM + sequence-parallel training on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
    create_lm_train_state,
    make_sp_train_step,
    next_token_targets,
    shard_lm_batch,
    sp_eval_loss,
)
from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh
from distributed_ml_pytorch_tpu.training.trainer import TrainState


CFG = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64, max_len=256)


def _batch(b=4, s=32, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, CFG["vocab_size"], size=(b, s)).astype(np.int32)
    return tokens, next_token_targets(tokens)


def test_forward_shapes_and_finiteness():
    model = TransformerLM(**CFG)
    tokens, _ = _batch(b=2, s=16)
    params = model.init(jax.random.key(0), jnp.asarray(tokens))["params"]
    logits = model.apply({"params": params}, jnp.asarray(tokens))
    assert logits.shape == (2, 16, CFG["vocab_size"])
    assert bool(jnp.isfinite(logits).all())


def test_next_token_targets_shift():
    tokens = np.array([[1, 2, 3, 4]], np.int32)
    np.testing.assert_array_equal(next_token_targets(tokens), [[2, 3, 4, 0]])


def _single_device_loss(model, params, tokens, targets):
    logits = model.apply({"params": params}, jnp.asarray(tokens))
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, jnp.asarray(targets))
    mask = (jnp.arange(tokens.shape[1]) < tokens.shape[1] - 1).astype(ce.dtype)[None, :]
    return jnp.sum(ce * mask) / jnp.sum(jnp.broadcast_to(mask, ce.shape))


def test_sp_step_matches_single_device():
    """One dp×sp step over 2×4 devices == one full-sequence step on one."""
    model = TransformerLM(**CFG)
    mesh = make_mesh({"data": 2, "seq": 4})
    tx = optax.sgd(0.1)
    state = create_lm_train_state(model, jax.random.key(0), tx)
    tokens, targets = _batch(b=4, s=32)

    # single-device reference step on the same global batch
    loss_ref, grads = jax.value_and_grad(
        lambda p: _single_device_loss(model, p, tokens, targets)
    )(state.params)
    updates, _ = tx.update(grads, state.opt_state, state.params)
    params_ref = optax.apply_updates(state.params, updates)

    step = make_sp_train_step(model, tx, mesh)
    tok_s, tgt_s = shard_lm_batch(mesh, tokens, targets)
    state2, loss_sp = step(state, tok_s, tgt_s)

    np.testing.assert_allclose(float(loss_sp), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state2.params), jax.tree.leaves(params_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)


def test_sp_training_reduces_loss():
    model = TransformerLM(**CFG)
    mesh = make_mesh({"data": 2, "seq": 4})
    tx = optax.adam(1e-2)
    state = create_lm_train_state(model, jax.random.key(0), tx)
    tokens, targets = _batch(b=8, s=32, seed=3)
    step = make_sp_train_step(model, tx, mesh)
    tok_s, tgt_s = shard_lm_batch(mesh, tokens, targets)
    first = None
    for _ in range(20):
        state, loss = step(state, tok_s, tgt_s)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.8, (first, float(loss))
    eval_loss, n_tok = sp_eval_loss(model, mesh, state, tok_s, tgt_s)
    assert np.isfinite(eval_loss) and n_tok == tokens.size


def test_sp_step_rejects_sequences_beyond_max_len():
    model = TransformerLM(**{**CFG, "max_len": 16})
    mesh = make_mesh({"data": 1, "seq": 8})
    tx = optax.sgd(0.01)
    state = create_lm_train_state(model, jax.random.key(0), tx, sample_len=8)
    tokens, targets = _batch(b=1, s=64)  # 64 > max_len=16
    step = make_sp_train_step(model, tx, mesh)
    tok_s, tgt_s = shard_lm_batch(mesh, tokens, targets)
    with pytest.raises(ValueError, match="max_len"):
        step(state, tok_s, tgt_s)


def test_sp_step_long_sequence_smoke():
    """4k tokens over the seq axis — each device holds 512."""
    model = TransformerLM(**{**CFG, "max_len": 8192})
    mesh = make_mesh({"data": 1, "seq": 8})
    tx = optax.sgd(0.01)
    state = create_lm_train_state(model, jax.random.key(0), tx)
    tokens, targets = _batch(b=1, s=4096, seed=5)
    step = make_sp_train_step(model, tx, mesh)
    tok_s, tgt_s = shard_lm_batch(mesh, tokens, targets)
    state, loss = step(state, tok_s, tgt_s)
    assert np.isfinite(float(loss))
    assert int(state.step) == 1


def test_remat_matches_no_remat_exactly():
    """remat=True must change memory, not math: same loss and grads."""
    import optax
    from distributed_ml_pytorch_tpu.models.transformer import TransformerLM

    cfg = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=64)
    base = TransformerLM(**cfg)
    remat = TransformerLM(**cfg, remat=True)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 16)), jnp.int32
    )
    params = base.init(jax.random.key(0), tokens)["params"]

    def loss(model, p):
        logits = model.apply({"params": p}, tokens)
        tgt = jnp.roll(tokens, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(logits, tgt).mean()

    l1, g1 = jax.value_and_grad(lambda p: loss(base, p))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss(remat, p))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_chunked_lm_loss_matches_dense_loss_and_grads():
    """chunked_lm_loss (no full-logits materialization) must equal the
    dense masked-mean CE exactly — values and gradients."""
    import optax

    from distributed_ml_pytorch_tpu.training.trainer import chunked_lm_loss

    lm = TransformerLM(vocab_size=97, d_model=32, n_heads=4, n_layers=2,
                       d_ff=64, max_len=64)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 97, (2, 32)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, 97, (2, 32)), jnp.int32)
    params = lm.init(jax.random.key(0), tokens)["params"]

    def dense(params):
        logits = lm.apply({"params": params}, tokens)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        mask = jnp.ones_like(ce).at[:, -1].set(0.0)
        return jnp.sum(ce * mask) / jnp.sum(mask)

    def chunked(params):
        return chunked_lm_loss(lm, params, tokens, targets, chunk=8)

    ld, gd = jax.value_and_grad(dense)(params)
    lc, gc = jax.value_and_grad(chunked)(params)
    np.testing.assert_allclose(float(lc), float(ld), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gc)):
        # f32 reassociation across the chunked sum: tight but not bitwise
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_chunked_lm_loss_bf16_ce_tracks_f32_ce_training():
    """Measured justification for the bf16-CE default (ADVICE r4): train a
    bf16-activation LM at a 16k vocabulary for 60 SGD steps with the
    chunked loss twice — CE on bf16 logits (default) vs CE on per-chunk
    f32-upcast logits (``ce_dtype=jnp.float32``) — from identical init on
    the identical batch stream. The trajectories must track: same descent,
    final-loss delta within noise. This is the loss-quality evidence the
    +3.7% bf16-CE change rests on."""
    import optax

    from distributed_ml_pytorch_tpu.training.trainer import chunked_lm_loss

    vocab = 16384
    lm = TransformerLM(vocab_size=vocab, d_model=64, n_heads=4, n_layers=2,
                       d_ff=128, max_len=64, dtype=jnp.bfloat16)
    rng = np.random.default_rng(1)
    init_tokens = jnp.zeros((2, 32), jnp.int32)
    params0 = lm.init(jax.random.key(0), init_tokens)["params"]
    tx = optax.sgd(0.05)
    # one fixed batch, memorized over 60 steps — random next-token targets
    # are unlearnable (loss pinned at log vocab), memorization descends,
    # and a fixed batch makes the two runs exactly comparable
    tok = jnp.asarray(rng.integers(0, vocab, (2, 32)), jnp.int32)
    tgt = jnp.roll(tok, -1, axis=1)

    def run(ce_dtype):
        params = params0
        opt_state = tx.init(params)
        losses = []
        loss_fn = jax.jit(jax.value_and_grad(
            lambda p: chunked_lm_loss(
                lm, p, tok, tgt, chunk=8, ce_dtype=ce_dtype)))
        for _ in range(60):
            loss, grads = loss_fn(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(loss))
        return losses

    bf16_losses = run(None)
    f32_losses = run(jnp.float32)

    # both must memorize: ~log(16384)=9.7 down to < 1 nat
    assert bf16_losses[0] > 9.0 and bf16_losses[-1] < 1.0, bf16_losses[-1]
    assert f32_losses[0] > 9.0 and f32_losses[-1] < 1.0, f32_losses[-1]
    # trajectories track: the gap oscillates in BOTH directions (bf16
    # activations make each run jittery; measured max one-step gap ~0.56
    # on a 9.5-nat descent, crossing sign repeatedly) but the mean gap and
    # the final losses stay within a few % of the descent
    descent = bf16_losses[0] - bf16_losses[-1]
    gaps = [abs(a - b) for a, b in zip(bf16_losses, f32_losses)]
    # measured on this CPU backend: max one-step gap ~0.059*descent, mean
    # ~0.011*descent, final ~0.008*descent; bounds leave >2.5x headroom
    # for backend-dependent bf16 accumulation order
    assert max(gaps) < 0.15 * descent, (max(gaps), descent)
    assert sum(gaps) / len(gaps) < 0.05 * descent, sum(gaps) / len(gaps)
    assert abs(bf16_losses[-1] - f32_losses[-1]) < 0.04 * descent


def test_chunked_lm_loss_rejects_indivisible_chunk():
    from distributed_ml_pytorch_tpu.training.trainer import chunked_lm_loss

    lm = TransformerLM(vocab_size=97, d_model=32, n_heads=4, n_layers=1,
                       d_ff=64, max_len=64)
    tokens = jnp.zeros((1, 30), jnp.int32)
    params = lm.init(jax.random.key(0), tokens)["params"]
    with pytest.raises(ValueError, match="divide"):
        chunked_lm_loss(lm, params, tokens, tokens, chunk=8)
