"""MFU numerator audit (VERDICT r2 #8): the hybrid FLOPs count and the
scaling-book 6ND analytic count are independent methods and must agree."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.utils.flops import (
    check_flops_agreement,
    compiled_flops,
    flash_attention_train_flops,
    lm_train_flops_6nd,
)


def test_flash_flops_fused_vs_split_ratio():
    # fused backward recomputes scores once: 7 matmuls vs split's 9
    fused = flash_attention_train_flops(2, 4, 512, 64, 3, bwd_impl="fused")
    split = flash_attention_train_flops(2, 4, 512, 64, 3, bwd_impl="split")
    assert split / fused == pytest.approx(9 / 7)
    # remat adds the 2 forward matmuls
    remat = flash_attention_train_flops(2, 4, 512, 64, 3, remat=True)
    assert remat / fused == pytest.approx(9 / 7)


def test_check_flops_agreement_boundaries():
    assert check_flops_agreement(1.0e12, 1.1e12) is None  # ~9% apart: ok
    warn = check_flops_agreement(1.0e12, 2.0e12)
    assert warn is not None and "cross-check FAILED" in warn
    assert check_flops_agreement(None, 1.0e12) is None  # no hybrid count


def test_xla_count_agrees_with_6nd_for_a_real_lm_step():
    """End-to-end: XLA's cost_analysis over a full LM train step (scan
    attention on CPU — visible to the compiler) must land within 15% of
    the 6ND analytic count, the assertion bench_lm runs at bench time."""
    from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
    from distributed_ml_pytorch_tpu.parallel.fsdp import lm_loss_builder
    from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
        create_lm_train_state,
        next_token_targets,
    )

    lm = TransformerLM(vocab_size=512, d_model=256, n_heads=4, n_layers=4,
                       d_ff=1024, max_len=256, pos_encoding="rope")
    tx = optax.sgd(1e-3)
    state = create_lm_train_state(lm, jax.random.key(0), tx)
    tokens = np.random.default_rng(0).integers(0, 512, size=(4, 256)).astype(np.int32)
    targets = jnp.asarray(next_token_targets(tokens))
    tokens = jnp.asarray(tokens)
    loss_builder = lm_loss_builder(lm)

    @jax.jit
    def step(state, tokens, targets):
        loss, grads = jax.value_and_grad(
            loss_builder(state, tokens, targets))(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(params=params, opt_state=opt_state), loss

    hybrid = compiled_flops(step, state, tokens, targets)
    assert hybrid is not None
    n_params = sum(p.size for p in jax.tree.leaves(state.params))
    embed = sum(
        leaf.size
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
        if any("embed" in str(getattr(k, "key", k)).lower() for k in path)
    )
    analytic = lm_train_flops_6nd(n_params - embed, 4, 256, 4, 64, 4)
    assert check_flops_agreement(hybrid, analytic) is None, (
        f"hybrid {hybrid:.3e} vs analytic {analytic:.3e}")
