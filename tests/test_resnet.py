"""ResNet-18/50 (BASELINE.md configs #4/#5 — no reference counterpart; the
reference's models stop at LeNet/AlexNet, ``example/models.py:5-49``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.models import get_model
from distributed_ml_pytorch_tpu.training.trainer import create_train_state, make_train_step


def _n_params(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


@pytest.mark.parametrize("name,expected_m", [("resnet18", 11.2), ("resnet50", 23.5)])
def test_forward_shape_and_param_count(name, expected_m):
    model = get_model(name)
    x = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.key(0), x)["params"]
    logits = model.apply({"params": params}, x)
    assert logits.shape == (2, 10)
    # within 5% of the canonical torchvision count (GN vs BN shifts it slightly)
    assert abs(_n_params(params) / 1e6 - expected_m) / expected_m < 0.05


def test_imagenet_stem_selected_for_large_inputs():
    model = get_model("resnet18")
    x = jnp.zeros((1, 224, 224, 3))
    params = model.init(jax.random.key(0), x)["params"]
    assert model.apply({"params": params}, x).shape == (1, 10)
    # imagenet stem: 7x7 conv kernel
    assert params["stem_conv"]["kernel"].shape[:2] == (7, 7)
    # cifar stem on 32x32: 3x3
    p32 = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    assert p32["stem_conv"]["kernel"].shape[:2] == (3, 3)


def test_resnet18_train_step_decreases_loss():
    model = get_model("resnet18")
    state, tx = create_train_state(model, jax.random.key(0), lr=0.01)
    step = make_train_step(model, tx)
    rng = jax.random.key(1)
    x = np.random.default_rng(0).normal(size=(16, 32, 32, 3)).astype(np.float32)
    y = (np.arange(16) % 10).astype(np.int32)
    losses = []
    for _ in range(10):
        state, loss = step(state, x, y, rng)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_unknown_resnet_rejected():
    with pytest.raises(ValueError):
        get_model("resnet1000")
