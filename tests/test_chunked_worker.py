"""Chunked DownPour dispatch (VERDICT r3): schedule, device math, cadence.

The chunked worker compiles each between-comm run of local SGD into one
``lax.scan`` dispatch. These tests pin the three claims that make it safe:
the schedule cuts exactly at the comm gaps (including the +1 offset of
pushes), the fused scan reproduces the per-step device math bit-for-bit
(same op sequence), and ``boundary()``-driven communication emits the same
message sequence as the per-step ``step()`` client.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ml_pytorch_tpu.models import get_model
from distributed_ml_pytorch_tpu.parallel.async_ps import (
    Asynchronous,
    default_downpour_tx,
    downpour_chunk_schedule,
    init_downpour_accumulator,
    make_downpour_chunk_step,
    make_downpour_device_step,
)
from distributed_ml_pytorch_tpu.utils.messaging import InProcessTransport, MessageCode


def test_chunk_schedule_cuts_exactly_at_comm_gaps():
    # n_push = n_pull = 5: pulls open steps {0,5,10,15}; pushes close steps
    # {0,5,10,15} i.e. live at gaps {1,6,11,16} — the +1 offset
    sched = downpour_chunk_schedule(5, 5, 0, 20)
    assert sched == [(0, 1), (1, 4), (5, 1), (6, 4), (10, 1), (11, 4),
                     (15, 1), (16, 4)]
    assert sum(length for _, length in sched) == 20


def test_chunk_schedule_nonzero_start_and_cap():
    sched = downpour_chunk_schedule(4, 6, 12, 24, max_chunk=2)
    assert sum(length for _, length in sched) == 12
    gaps = [g for g, _ in sched]
    # every true comm gap in (12, 24) must be a cut: pulls {12, 18},
    # pushes at {13, 17, 21}
    for need in (12, 13, 17, 18, 21):
        assert need in gaps
    assert all(length <= 2 for _, length in sched)


def test_chunk_schedule_coprime_cadence():
    sched = downpour_chunk_schedule(3, 2, 0, 12)
    assert sum(length for _, length in sched) == 12
    gaps = {g for g, _ in sched}
    assert {0, 1, 2, 4, 6, 7, 8, 10}.issubset(gaps)


def test_chunk_step_matches_per_step_device_math():
    model = get_model("lenet")
    rng = np.random.default_rng(0)
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    _, n, pad, accum = init_downpour_accumulator(params)
    lr = 0.05
    L = 5
    bxs = jnp.asarray(rng.normal(size=(L, 8, 32, 32, 3)), jnp.float32)
    bys = jnp.asarray(rng.integers(0, 10, (L, 8)))
    key = jax.random.key(7)

    # per-step reference: the worker's grad_fn + make_downpour_device_step
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    tx = default_downpour_tx(lr)
    device_step = make_downpour_device_step(tx, pad)

    def grad_fn(p, bx, by, idx):
        def loss_fn(q):
            logits = model.apply(
                {"params": q}, bx, train=True,
                rngs={"dropout": jax.random.fold_in(key, idx)},
            )
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    p_ref, a_ref, s_ref = params, accum, tx.init(params)
    losses_ref = []
    for i in range(L):
        loss, grads = grad_fn(p_ref, bxs[i], bys[i], i)
        p_ref, s_ref, a_ref = device_step(p_ref, s_ref, grads, a_ref)
        losses_ref.append(float(loss))

    chunk_step = make_downpour_chunk_step(model, tx, pad)
    _, _, pad2, accum2 = init_downpour_accumulator(params)
    p_chk, _, a_chk, losses = chunk_step(
        params, tx.init(params), accum2, bxs, bys, key, 0
    )

    np.testing.assert_allclose(np.asarray(losses), losses_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_chk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a_ref), np.asarray(a_chk),
                               rtol=1e-5, atol=1e-6)


def test_boundary_send_sequence_matches_per_step_client():
    """Driving boundary() at the schedule's gaps must emit the same message
    codes as N per-step step() calls + finish(), in the same PER-KIND order.

    Pushes ride the background flusher (overlap with compute — VERDICT r4
    #5) while pull requests go out from the training thread, so the
    interleaving BETWEEN the two kinds is intentionally unordered (the
    async-DownPour contract); the cadence guarantee is that each kind's
    own sequence — and hence its count and payload schedule — is
    identical. finish() drains the flusher, so capture is complete."""
    model = get_model("lenet")
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    zero_grads = jax.tree.map(jnp.zeros_like, params)
    N, n_push, n_pull = 9, 3, 2

    def capture_client():
        world = InProcessTransport.create_world(2)
        opt = Asynchronous(params, lr=0.1, n_push=n_push, n_pull=n_pull,
                           transport=world[1])
        sent = []
        opt._send = lambda code, payload: sent.append(code)
        return opt, sent

    opt_a, sent_a = capture_client()
    for _ in range(N):
        params = opt_a.step(params, zero_grads)
    opt_a.finish()

    opt_b, sent_b = capture_client()
    for gap, length in downpour_chunk_schedule(n_push, n_pull, 0, N):
        opt_b.boundary(gap)
        opt_b.idx = gap + length  # the compiled chunk advances the steps
    opt_b.finish()

    def by_kind(sent):
        return ([c for c in sent if c == MessageCode.GradientUpdate],
                [c for c in sent if c != MessageCode.GradientUpdate])

    assert by_kind(sent_a) == by_kind(sent_b)
    assert MessageCode.GradientUpdate in sent_a
    assert MessageCode.ParameterRequest in sent_a
